"""Scatter-gather decomposition of a Computation DAG over sharded sets.

The reference's master never executes a pipeline itself: TCAPAnalyzer
cuts the plan into JobStages and ``QuerySchedulerServer`` schedules
each stage across the workers holding the set's partitions, merging
bounded aggregation state at the master
(``QuerySchedulerServer.cc:216-330``; the partial-merge shape also
follows *Large Scale Distributed Linear Algebra With TPUs*, arXiv
2112.09017 — each worker computes over only its panel and the
coordinator merges bounded partials). This module is that analysis
for the serve layer's sharded worker pool: given a sink DAG and a
predicate "is this set partitioned?", it recognizes the pushable
shapes and produces a :class:`ScatterSpec` the coordinator
(``serve/shard.py``) executes:

* ``fold_state`` — ``Scan(sharded) → [rowwise chain] → Apply(fold)``
  where the single-pass fold declares ``state_merge``: every shard
  folds its LOCAL pages to the bounded partial state (running the
  shipped subplan through its own executor, so staging, the devcache
  and PR 10's fusion regions all apply per shard), the coordinator
  merges states in slot order and runs ``finalize`` once. The q01/q06
  family.
* ``group_partial`` — ``Scan(sharded) → {Filter|Flatten|rowwise
  Apply}* → Aggregate(key, value, combine)``: shards return partial
  group dicts, the coordinator merges them with the node's own
  ``combine`` (associative by the Aggregate contract).
* ``shuffle_join`` — ``Join(Scan(sharded), Scan(sharded), fold with
  probe_key/build_key/merge)``: the grace-hash partition step becomes
  a genuine DISTRIBUTED shuffle — every shard hash-partitions both
  local sides by the join key and ships bucket *j* to the daemon
  owning slot *j* over the v3 vectored wire, then folds its own
  bucket; the coordinator merges the per-slot outputs with the fold's
  declared ``merge``. Keys co-locate whole, so no group is ever split
  across partials.
* ``tensor_chain`` — a layer-chain sink DAG (the FF/conv inference
  shape) whose ONLY sharded leaf is the batch-partitioned input
  tensor set; every other input subtree scans sets mirrored on each
  daemon (the model's weights). Each shard runs the WHOLE chain over
  its local batch partition through its own executor — so PR 10's
  region mapper compiles the layer chain as ONE fused program per
  shard, not per-row pre-chains — and the coordinator concatenates
  the dense per-slot outputs along the batch axis in slot order. The
  shape is opted into by the sink's ``scatter_gather`` declaration
  (``{"axis": batch_axis, "block": out_block}``, set by the serving
  layer — ``models/serving.py``): the declaration IS the contract
  that the chain is batch-decomposable along that axis, exactly as a
  fold's ``state_merge`` declares mergeability.

Anything else touching a sharded set is refused typed (the
coordinator raises; mirrored/local sets are untouched by all of
this). Determinism: shards are always visited in slot order and every
merge is a left fold over that order, so repeated runs merge in one
canonical order.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

from netsdb_tpu.plan.computations import (
    Aggregate,
    Apply,
    Computation,
    Filter,
    Join,
    MultiApply,
    ScanSet,
    WriteSet,
)
from netsdb_tpu.plan.fold import FoldSpec


@dataclasses.dataclass
class ScatterSpec:
    """One sink's scatter decomposition (see module docstring)."""

    kind: str  # "fold_state" | "group_partial" | "shuffle_join" | "tensor_chain"
    sink: WriteSet
    node: Computation
    #: sharded (db, set) leaves the spec scans, in deterministic order
    scan_sets: Tuple[Tuple[str, str], ...]
    fold: Optional[FoldSpec] = None
    #: shuffle_join: (db, set) of the streamed/probe and build sides
    probe: Optional[Tuple[str, str]] = None
    build: Optional[Tuple[str, str]] = None
    #: tensor_chain: the sink's ``scatter_gather`` declaration —
    #: ``{"axis": batch_axis, "block": out_block_shape | None}``
    gather: Optional[dict] = None


@dataclasses.dataclass
class MultiScatterSpec:
    """A dashboard-style fan of N ``fold_state`` sinks over ONE shared
    sharded scan set (the PR 13 multi-sink carry-over): the pool ships
    ONE subplan per shard whose combined tuple-state fold runs every
    component's (grafted pre-chain + step) over each streamed chunk in
    one compiled program, and the coordinator splits the tuple and
    merges+finalizes every component — also as one program
    (:func:`merge_fold_states_compiled`).  Byte-equal to running the
    sinks separately: each component's math is unchanged, only the
    dispatch seams fuse."""

    kind: str  # "multi_fold"
    components: Tuple[ScatterSpec, ...]
    #: the ONE sharded (db, set) every component scans
    scan_sets: Tuple[Tuple[str, str], ...]


#: node types that are row-decomposable over object/table partitions —
#: a chain of these between the sharded scan and the aggregating node
#: ships to the shards unchanged
def _rowwise_chain_ok(node: Computation) -> bool:
    if isinstance(node, (Filter, MultiApply)):
        return True
    return isinstance(node, Apply) and getattr(node, "rowwise", False) \
        and node.fold is None


def _scan_leaf(node: Computation) -> Optional[ScanSet]:
    """Follow a pure rowwise chain down to its scan leaf (None when
    the chain holds anything else)."""
    while not isinstance(node, ScanSet):
        if not _rowwise_chain_ok(node) or len(node.inputs) != 1:
            return None
        node = node.inputs[0]
    return node


def _subtree_touches_sharded(node: Computation,
                             is_sharded: Callable[[str, str], bool]
                             ) -> bool:
    seen, stack = set(), [node]
    while stack:
        n = stack.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        if isinstance(n, ScanSet) and is_sharded(n.db, n.set_name):
            return True
        stack.extend(n.inputs)
    return False


def _tensor_chain_leaf(node: Computation,
                       is_sharded: Callable[[str, str], bool]
                       ) -> Optional[ScanSet]:
    """Follow the batch spine from the sink's input to its sharded
    scan leaf: every chain node must have EXACTLY ONE input whose
    subtree touches a sharded set (the spine — the batch-partitioned
    activations); all other input subtrees scan only sets mirrored on
    each daemon (the weights), so the chain ships to the shards
    unchanged. None when the spine forks or dead-ends."""
    cur = node
    while not isinstance(cur, ScanSet):
        spine = [i for i in cur.inputs
                 if _subtree_touches_sharded(i, is_sharded)]
        if len(spine) != 1:
            return None
        cur = spine[0]
    return cur if is_sharded(cur.db, cur.set_name) else None


def sharded_scan_sets(sinks, is_sharded: Callable[[str, str], bool]
                      ) -> List[Tuple[str, str]]:
    """Every sharded (db, set) any sink's DAG scans, sorted."""
    out = set()
    seen = set()
    stack = list(sinks)
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        if isinstance(node, ScanSet) and is_sharded(node.db,
                                                    node.set_name):
            out.add((node.db, node.set_name))
        stack.extend(node.inputs)
    return sorted(out)


def analyze_sinks(sinks, is_sharded: Callable[[str, str], bool]
                  ) -> Optional[ScatterSpec]:
    """The scatter decomposition of ``sinks``, or None when the DAG
    either touches no sharded set (callers then run the unchanged
    local path) or touches one in a shape this module cannot push
    (callers raise typed — a sharded set's pages live only on its
    shards, so there is no local fallback)."""
    touched = sharded_scan_sets(sinks, is_sharded)
    if not touched:
        return None
    if len(sinks) != 1:
        return analyze_multi_sinks(sinks, is_sharded, touched)
    sink = sinks[0]
    if not isinstance(sink, WriteSet):
        return None
    node = sink.inputs[0]

    # shuffle_join: Join over two sharded scans with a grace-capable
    # fold (declared keys + output merge)
    if isinstance(node, Join) and node.fold is not None \
            and node.fold.probe_key and node.fold.build_key \
            and node.fold.merge is not None \
            and len(node.fold.passes) == 1:
        probe_in = node.inputs[node.fold_src]
        build_in = node.inputs[1 - node.fold_src]
        if isinstance(probe_in, ScanSet) and isinstance(build_in, ScanSet) \
                and is_sharded(probe_in.db, probe_in.set_name) \
                and is_sharded(build_in.db, build_in.set_name):
            return ScatterSpec(
                kind="shuffle_join", sink=sink, node=node,
                scan_sets=tuple(touched), fold=node.fold,
                probe=(probe_in.db, probe_in.set_name),
                build=(build_in.db, build_in.set_name))

    # fold_state: single-pass fold with a declared state_merge over a
    # (possibly rowwise-prefixed) sharded scan
    if isinstance(node, Apply) and node.fold is not None \
            and node.fold.state_merge is not None \
            and len(node.fold.passes) == 1:
        scan = _scan_leaf(node.inputs[0])
        if scan is not None and is_sharded(scan.db, scan.set_name):
            return ScatterSpec(kind="fold_state", sink=sink, node=node,
                               scan_sets=tuple(touched), fold=node.fold)

    # group_partial: dict group-by whose combine IS the partial merge
    if isinstance(node, Aggregate) and node.fn is None \
            and node.combine is not None:
        scan = _scan_leaf(node.inputs[0])
        if scan is not None and is_sharded(scan.db, scan.set_name):
            return ScatterSpec(kind="group_partial", sink=sink,
                               node=node, scan_sets=tuple(touched))

    # tensor_chain: sink-declared batch-decomposable layer chain over
    # ONE sharded input tensor set (module docstring) — opted in via
    # the sink's scatter_gather attribute, never inferred
    gather = getattr(sink, "scatter_gather", None)
    if gather is not None and len(touched) == 1 \
            and _tensor_chain_leaf(node, is_sharded) is not None:
        return ScatterSpec(kind="tensor_chain", sink=sink, node=node,
                           scan_sets=tuple(touched),
                           gather=dict(gather))

    return None


def _bakeable_prechain(node: Computation) -> Optional[List[Apply]]:
    """The rowwise Apply chain between a fold node's stream input and
    its scan leaf, scan→fold order — the shape the combined multi-sink
    fold can bake into its chunk steps (exactly what the fusion mapper
    grafts: Filter/MultiApply chains cannot bake, their evaluation is
    not a chunk→chunk callable). None when anything else sits on the
    chain; ``[]`` when the input IS the scan."""
    chain: List[Apply] = []
    cur = node
    while not isinstance(cur, ScanSet):
        if not (isinstance(cur, Apply)
                and getattr(cur, "rowwise", False)
                and cur.fn is not None
                and getattr(cur, "traceable", True)
                and cur.fold is None and len(cur.inputs) == 1):
            return None
        chain.append(cur)
        cur = cur.inputs[0]
    chain.reverse()
    return chain


def analyze_multi_sinks(sinks, is_sharded: Callable[[str, str], bool],
                        touched: List[Tuple[str, str]]
                        ) -> Optional[MultiScatterSpec]:
    """The multi-sink decomposition: every sink must independently be
    a pushable ``fold_state`` over the SAME single sharded set, with a
    pre-chain the combined fold can bake into its steps. None
    otherwise — callers keep the typed refusal a lone unpushable shape
    already gets (a partitioned set's pages live only on its
    shards)."""
    if len(sinks) < 2 or len(touched) != 1:
        return None
    comps: List[ScatterSpec] = []
    for s in sinks:
        spec = analyze_sinks([s], is_sharded)
        if spec is None or spec.kind != "fold_state" \
                or spec.scan_sets != tuple(touched) \
                or len(spec.node.inputs) != 1 \
                or spec.fold.probe_key is not None \
                or spec.fold.build_key is not None \
                or _bakeable_prechain(spec.node.inputs[0]) is None:
            return None
        comps.append(spec)
    return MultiScatterSpec(kind="multi_fold", components=tuple(comps),
                            scan_sets=tuple(touched))


# --- shard-side sink construction ------------------------------------

def _state_finalize(state, src, *resident):
    """The partial sink's finalize: return the fold state itself (the
    bounded partial the coordinator merges)."""
    del src, resident
    return state


def _max_node_id(root: Computation) -> int:
    out = root.node_id
    seen = set()
    stack = [root]
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        out = max(out, node.node_id)
        stack.extend(node.inputs)
    return out


def partial_sink(spec: ScatterSpec) -> WriteSet:
    """The sink a shard executes for a ``fold_state``/``group_partial``
    spec: identical plan, but a fold's finalize is replaced with the
    state-returning stub (distinct label — the jit cache must never
    alias the partial step with the full fold's).

    The wrapper nodes take ids ABOVE the decoded DAG's maximum: the
    original nodes carry the CLIENT's process-local ids, and a
    coordinator-minted id colliding with one of them would corrupt
    the id-keyed topo sort (a false cycle — the cross-process hazard
    the in-process tests can never see)."""
    node = spec.node
    if spec.kind in ("group_partial", "tensor_chain"):
        # the chain runs unchanged over the shard's local partition;
        # its output (group dict / local-batch tensor) IS the partial
        sink = WriteSet(node, spec.sink.db, "__scatter_partial__")
        sink.node_id = _max_node_id(node) + 1
        sink.output_name = f"{sink.op_kind}_{sink.node_id}"
        return sink
    fold = spec.fold
    pf = FoldSpec(fold.passes, _state_finalize,
                  probe_columns=fold.probe_columns)
    partial = Apply(node.inputs[0], fold=pf,
                    label=f"{node.label}::partial",
                    traceable=node.traceable)
    partial.node_id = _max_node_id(node.inputs[0]) + 1
    partial.output_name = f"{partial.op_kind}_{partial.node_id}"
    # the marker the fusion mapper keys distributed regions on: a
    # scatter partial fold IS the shard's one compiled program, so the
    # optimal mapper forms its region even with nothing local to graft
    partial.scatter_partial = True
    sink = WriteSet(partial, spec.sink.db, "__scatter_partial__")
    sink.node_id = partial.node_id + 1
    sink.output_name = f"{sink.op_kind}_{sink.node_id}"
    return sink


def _combined_fold(comps: Tuple[ScatterSpec, ...]) -> FoldSpec:
    """ONE FoldSpec whose state is the tuple of every component's
    state: each streamed chunk runs every component's (baked pre-chain
    + step) inside one compiled step, ``state_merge`` is
    componentwise, finalize returns the tuple itself (the multi
    partial the coordinator splits)."""
    from netsdb_tpu.plan import fusion as _fusion

    wrapped = []
    for c in comps:
        chain = _bakeable_prechain(c.node.inputs[0]) or []
        f = c.fold
        if chain:
            f = _fusion.wrap_fold_prechain(f, [a.fn for a in chain])
        wrapped.append(f)
    folds = tuple(wrapped)

    def init(prev, src, *resident):
        del prev
        return tuple(f.passes[0][0](None, src, *resident)
                     for f in folds)

    def step(state, chunk, *resident):
        return tuple(f.passes[0][1](state[i], chunk, *resident)
                     for i, f in enumerate(folds))

    def state_merge(a, b):
        return tuple(c.fold.state_merge(a[i], b[i])
                     for i, c in enumerate(comps))

    return FoldSpec(((init, step),), _state_finalize,
                    state_merge=state_merge)


def multi_partial_sink(mspec: MultiScatterSpec) -> WriteSet:
    """The ONE sink a shard executes for a ``multi_fold`` spec:
    ``Scan(shared set) → Apply(combined tuple-state fold) → partial
    write`` — fresh coordinator-minted nodes throughout (no client ids
    to collide with).  The combined label keys the shard's compiled
    step apart from every component's own jit entries, so a fan and
    its separately-run components never alias cache entries."""
    db, set_name = mspec.scan_sets[0]
    scan = ScanSet(db, set_name)
    label = "multi::" + "+".join(
        (getattr(c.node, "label", "") or c.node.op_kind)
        for c in mspec.components) + "::partial"
    partial = Apply(scan, fold=_combined_fold(mspec.components),
                    label=label,
                    traceable=all(getattr(c.node, "traceable", True)
                                  for c in mspec.components))
    partial.scatter_partial = True
    return WriteSet(partial, mspec.components[0].sink.db,
                    "__scatter_partial__")


# --- coordinator-side merges -----------------------------------------

class SchemaProxy:
    """What a scatterable fold's ``finalize`` may read of its source:
    the schema surface (dictionaries + total row count), never pages —
    the coordinator holds none."""

    __slots__ = ("dicts", "num_rows")

    def __init__(self, dicts: Dict[str, list], num_rows: int):
        self.dicts = dict(dicts)
        self.num_rows = int(num_rows)


def merge_fold_states(fold: FoldSpec, states: List[Any],
                      dicts: Dict[str, list], num_rows: int) -> Any:
    """Left-fold the per-slot states in slot order, then finalize over
    the schema proxy — ONE canonical merge order, so repeated runs
    are bit-identical to each other."""
    merged = states[0]
    for s in states[1:]:
        merged = fold.state_merge(merged, s)
    return fold.finalize(merged, SchemaProxy(dicts, num_rows))


class MultiFoldMerge:
    """The merge/finalize surface of a ``multi_fold`` coordinator: the
    shards' tuple states merge componentwise and each component's own
    ``finalize`` runs over the shared schema proxy, yielding the tuple
    of per-sink results in sink order.  Duck-types FoldSpec's
    state_merge/finalize so both merge paths (compiled and eager)
    treat a fan exactly like a single fold."""

    def __init__(self, components: Tuple[ScatterSpec, ...]):
        self.components = tuple(components)
        self.state_merge = self._state_merge  # FoldSpec surface

    def _state_merge(self, a, b):
        return tuple(c.fold.state_merge(a[i], b[i])
                     for i, c in enumerate(self.components))

    def finalize(self, merged, src):
        return tuple(c.fold.finalize(merged[i], src)
                     for i, c in enumerate(self.components))


def merge_fold_states_compiled(fold, states: List[Any],
                               dicts: Dict[str, list], num_rows: int,
                               job_name: str, label: str,
                               traceable: bool = True) -> Any:
    """:func:`merge_fold_states` through ONE compiled program
    (``fusion.compile_scatter_merge``) when the fold and the shards'
    states are jit-safe; the eager left-fold otherwise — a counted
    fallback (``fusion.fallbacks``), never an error.  Both paths share
    the same canonical slot-order left fold, so results are
    bit-identical either way."""
    from netsdb_tpu.plan import executor as _executor
    from netsdb_tpu.plan import fusion

    if traceable and getattr(fold, "state_merge", None) is not None \
            and _executor._jit_safe_values(states):
        try:
            prog = fusion.compile_scatter_merge(
                fold, len(states), SchemaProxy(dicts, num_rows),
                job_name, label)
            return prog(tuple(states))
        except Exception as e:  # noqa: BLE001 — counted fallback
            fusion.fallback("scatter merge+finalize fell back eager: "
                            f"{type(e).__name__}: {e}")
    merged = states[0]
    for s in states[1:]:
        merged = fold.state_merge(merged, s)
    return fold.finalize(merged, SchemaProxy(dicts, num_rows))


def merge_group_dicts(node: Aggregate, parts: List[dict]) -> dict:
    """Merge per-slot group dicts with the Aggregate's own combine
    (slot order; first occurrence seeds the key, like the single-node
    fold's first item)."""
    out: dict = {}
    for part in parts:
        for k, v in part.items():
            out[k] = node.combine(out[k], v) if k in out else v
    return out


def merge_join_outputs(fold: FoldSpec, parts: List[Any]) -> Any:
    """Merge per-slot shuffle-join outputs with the fold's declared
    output merge (the grace-hash partition-merge rule, applied across
    daemons instead of arena spill partitions)."""
    merged = parts[0]
    for p in parts[1:]:
        merged = fold.merge(merged, p)
    return merged


def merge_tensor_chain(gather: dict, parts: List[Any]) -> Any:
    """Assemble the per-slot outputs in slot order — slot order equals
    ingest partition order (range slices are contiguous and
    ascending), so the assembled batch is byte-identical to a
    single-daemon run: every output element is computed from exactly
    one shard's batch rows, never summed across shards.

    ``mode="concat"`` (default) concatenates dense arrays along the
    declared batch ``axis``, re-blocking with ``block`` when declared
    so downstream padded shapes match the local engine's;
    ``mode="items"`` chains per-slot item LISTS (the conv2d shape —
    one output tensor per input image)."""
    import numpy as np

    if gather.get("mode") == "items":
        out: List[Any] = []
        for p in parts:
            out.extend(p)
        return out
    dense = np.concatenate([np.asarray(p) for p in parts],
                           axis=int(gather.get("axis", 0)))
    block = gather.get("block")
    if block:
        from netsdb_tpu.core.blocked import BlockedTensor

        return BlockedTensor.from_dense(dense, tuple(block))
    return dense
