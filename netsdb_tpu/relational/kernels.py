"""Vectorized relational kernels over device arrays.

Each kernel is the TPU-native form of one of the reference's executor /
processor families (``src/queryExecution``):

- group-by + aggregate → masked scatter-add segments
  (reference: CombinerProcessor / AggregationProcessor hash maps,
  ``src/queryExecution/headers/CombinerProcessor.h:20``);
- equi-join → sort the build side once, ``searchsorted`` probes, gather
  (reference: JoinMap build + probe,
  ``src/builtInPDBObjects/headers/JoinPairArray.h:122``);
- semi/anti-join → membership probe with a sentinel for masked rows;
- top-k → ``lax.top_k`` over masked scores
  (reference: TopK aggregation, ``src/sharedLibraries/headers/TopKTest.h``).

All kernels take/return fixed-shape arrays and are jit-safe; dynamic
cardinalities (number of groups, join fan-out) are bounded by host-side
static metadata (key-space size), which the caller reads off table
shapes/dictionaries before tracing.

Masked rows are handled with identity elements (0 for sum/count,
±inf for min/max) or key sentinels that can never match — never with
shape-changing compaction.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

_I32_SENTINEL = jnp.int32(-2147483648)


def _masked(values: jnp.ndarray, mask: Optional[jnp.ndarray],
            identity) -> jnp.ndarray:
    if mask is None:
        return values
    return jnp.where(mask, values, jnp.asarray(identity, values.dtype))


# --- group-by aggregates ---------------------------------------------

def _in_range(segment_ids: jnp.ndarray, num_segments: int,
              mask: Optional[jnp.ndarray]) -> jnp.ndarray:
    """Rows whose segment id is outside [0, num_segments) are dropped,
    not clipped — an orphan key (e.g. an order whose customer was not
    loaded) must not be credited to the last segment."""
    ok = (segment_ids >= 0) & (segment_ids < num_segments)
    return ok if mask is None else (ok & mask)


def _use_dense(num_segments: int, method: Optional[str]) -> bool:
    """Dense (broadcast-compare + column reduce) vs scatter dispatch.

    Below the crossover a dense pass beats the scatter-add: TPU
    scatters with millions of colliding updates serialize, while the
    dense form is one fused streaming pass (measured on Q01 @ SF1, 12
    groups: 52.6 ms scatter → ~2 ms dense). Above it the O(N*G) dense
    work loses; large-G queries (Q13's per-customer counts) keep the
    scatter. The crossover is measured per device kind
    (:mod:`netsdb_tpu.relational.tuning`), not frozen; ``method``
    ("dense"/"scatter") forces a strategy (tests, autotune probes).
    """
    if method is not None:
        return method == "dense"
    from netsdb_tpu.relational import planner

    return planner.segment_method(num_segments) == "dense"


def _dense_segment_reduce(v: jnp.ndarray, segment_ids: jnp.ndarray,
                          num_segments: int, identity, reduce_axis0):
    """(N,) → (G,) via broadcast-compare + column reduce; ``v`` must
    already carry ``identity`` in masked rows."""
    eq = segment_ids[:, None] == jnp.arange(num_segments,
                                            dtype=segment_ids.dtype)
    return reduce_axis0(jnp.where(eq, v[:, None],
                                  jnp.asarray(identity, v.dtype)))


def segment_sum(values: jnp.ndarray, segment_ids: jnp.ndarray,
                num_segments: int,
                mask: Optional[jnp.ndarray] = None,
                method: Optional[str] = None) -> jnp.ndarray:
    """Per-segment sum; masked and out-of-range rows contribute 0."""
    v = _masked(values, _in_range(segment_ids, num_segments, mask), 0)
    if _use_dense(num_segments, method):
        return _dense_segment_reduce(v, segment_ids, num_segments, 0,
                                     lambda m: m.sum(axis=0))
    ids = jnp.clip(segment_ids, 0, num_segments - 1)
    return jnp.zeros((num_segments,), v.dtype).at[ids].add(v)


def segment_count(segment_ids: jnp.ndarray, num_segments: int,
                  mask: Optional[jnp.ndarray] = None,
                  method: Optional[str] = None) -> jnp.ndarray:
    """Per-segment counts. Three strategies, chosen by the planner's
    measured thresholds when ``method`` is None: "dense" (tiny G),
    "grid" (mid-range G — one-hot int8 MXU matmuls, measured 0.67 ms vs
    6.9 ms scatter at G=50k/1M rows on v5e; linear in G/128, losing to
    scatter again near G~590k — `tuning` key ``count_grid_limit``),
    "scatter" (large G)."""
    if method is None:
        from netsdb_tpu.relational import planner

        method = planner.count_method(num_segments)
    if method == "grid":
        return count_grid(segment_ids, num_segments, mask)
    ones = jnp.ones(segment_ids.shape, jnp.int32)
    return segment_sum(ones, segment_ids, num_segments, mask, method)


def _grid_reduce(folded_ids: jnp.ndarray, key_space: int,
                 block: int, chunk: int) -> jnp.ndarray:
    """Shared core of the grid kernels: per-key occurrence counts of
    ``folded_ids`` (already masked: dropped rows hold -1) as one-hot
    int8 matmuls over an (H, block) key grid — the MXU accumulates, no
    scatter. ``folded_ids`` must already be padded to a multiple of
    ``chunk``. Returns the (H, block) int32 count grid."""
    H = (key_space + block - 1) // block
    hi, lo = folded_ids // block, folded_ids % block

    def step(acc, xs):
        h, l = xs
        m2 = (h[None, :] == jnp.arange(H, dtype=jnp.int32)[:, None]
              ).astype(jnp.int8)
        m1 = (l[:, None] == jnp.arange(block, dtype=jnp.int32)[None, :]
              ).astype(jnp.int8)
        return acc + jax.lax.dot(m2, m1,
                                 preferred_element_type=jnp.int32), None

    # carry init derives from the data so it inherits its varying manual
    # axes under shard_map (a plain zeros const is unvarying and fails
    # the scan carry typecheck there; no-op elsewhere)
    init = jnp.zeros((H, block), jnp.int32) + folded_ids.sum() * 0
    grid, _ = jax.lax.scan(step, init,
                           (hi.reshape(-1, chunk), lo.reshape(-1, chunk)))
    return grid


def _pad_to(x: jnp.ndarray, chunk: int, fill) -> jnp.ndarray:
    pad = (-x.shape[0]) % chunk
    if not pad:
        return x
    return jnp.concatenate([x, jnp.full((pad,), fill, x.dtype)])


def count_grid(segment_ids: jnp.ndarray, num_segments: int,
               mask: Optional[jnp.ndarray] = None,
               block: int = 128, chunk: int = 4096) -> jnp.ndarray:
    """Exact per-segment counts on the grid path (`_grid_reduce`).
    Masked and out-of-range rows fold into the index (-1 matches no
    cell)."""
    a = _pad_to(segment_ids, chunk, -1)
    ok = (a >= 0) & (a < num_segments)
    if mask is not None:
        ok = ok & _pad_to(mask, chunk, False)
    am = jnp.where(ok, a, jnp.int32(-1))
    grid = _grid_reduce(am, num_segments, block, chunk)
    return grid.reshape(-1)[:num_segments]


def segment_min(values: jnp.ndarray, segment_ids: jnp.ndarray,
                num_segments: int,
                mask: Optional[jnp.ndarray] = None,
                method: Optional[str] = None) -> jnp.ndarray:
    """Per-segment min; empty segments hold +inf (f32) / max (i32)."""
    big = jnp.inf if values.dtype.kind == "f" else jnp.iinfo(values.dtype).max
    v = _masked(values, _in_range(segment_ids, num_segments, mask), big)
    if _use_dense(num_segments, method):
        return _dense_segment_reduce(v, segment_ids, num_segments, big,
                                     lambda m: m.min(axis=0))
    ids = jnp.clip(segment_ids, 0, num_segments - 1)
    init = jnp.full((num_segments,), big, values.dtype)
    return init.at[ids].min(v)


def segment_max(values: jnp.ndarray, segment_ids: jnp.ndarray,
                num_segments: int,
                mask: Optional[jnp.ndarray] = None,
                method: Optional[str] = None) -> jnp.ndarray:
    small = (-jnp.inf if values.dtype.kind == "f"
             else jnp.iinfo(values.dtype).min)
    v = _masked(values, _in_range(segment_ids, num_segments, mask), small)
    if _use_dense(num_segments, method):
        return _dense_segment_reduce(v, segment_ids, num_segments, small,
                                     lambda m: m.max(axis=0))
    ids = jnp.clip(segment_ids, 0, num_segments - 1)
    init = jnp.full((num_segments,), small, values.dtype)
    return init.at[ids].max(v)


def segment_mean(values: jnp.ndarray, segment_ids: jnp.ndarray,
                 num_segments: int,
                 mask: Optional[jnp.ndarray] = None,
                 method: Optional[str] = None) -> jnp.ndarray:
    """Per-segment mean; empty segments yield 0."""
    s = segment_sum(values.astype(jnp.float32), segment_ids, num_segments,
                    mask, method)
    c = segment_count(segment_ids, num_segments, mask, method)
    return s / jnp.maximum(c, 1).astype(jnp.float32)


def bincount_masked(values: jnp.ndarray, length: int,
                    mask: Optional[jnp.ndarray] = None,
                    method: Optional[str] = None) -> jnp.ndarray:
    """Histogram of small non-negative ints (Q13's count-of-counts)."""
    return segment_count(values, length, mask, method)


# --- joins ------------------------------------------------------------

def _sentineled(keys: jnp.ndarray, mask: Optional[jnp.ndarray]) -> jnp.ndarray:
    if mask is None:
        return keys
    return jnp.where(mask, keys, _I32_SENTINEL)


def pk_fk_join(pk_keys: jnp.ndarray, fk_keys: jnp.ndarray,
               pk_mask: Optional[jnp.ndarray] = None,
               fk_mask: Optional[jnp.ndarray] = None,
               key_space: Optional[int] = None,
               plan=None,
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Equi-join a unique-key (primary) side into a foreign-key side.

    Returns ``(gather_idx, match_mask)`` both shaped like ``fk_keys``:
    row i of the probe side matches row ``gather_idx[i]`` of the build
    side iff ``match_mask[i]``. Columns of the build side are then
    brought over with ``jnp.take(col, gather_idx)`` — the vectorized
    JoinMap probe.

    ``plan`` (a :class:`netsdb_tpu.relational.planner.JoinPlan`,
    produced from ingest-time column statistics) selects the physical
    strategy; it is the stats-driven replacement for the round-1
    caller-supplied ``key_space=`` (still accepted: it forces the LUT
    path, which the autotune probes and legacy callers use).

    LUT strategy — dense lookup table over [0, key_space): one scatter
    to build, one gather to probe. Measured ~19x faster than
    sort+binary-search at SF-1 TPC-H scale (49 ms vs 947 ms for 6M
    probes into 1.5M build rows) — TPU binary search serializes,
    gathers stream. Sort strategy — argsort +
    ``searchsorted(method="sort")``; wins when the key space is sparse
    enough that the LUT is mostly padding (TPU's while-loop "scan"
    searchsorted is another ~8x slower, so "sort" here always means the
    vectorized sort-based probe).
    """
    if plan is not None:
        key_space = plan.key_space if plan.strategy == "lut" else None
    if key_space is not None:
        p = pk_keys.shape[0]
        valid_pk = (pk_keys >= 0) & (pk_keys < key_space)
        if pk_mask is not None:
            valid_pk = valid_pk & pk_mask
        # invalid build rows route to an extra trash slot
        slot = jnp.where(valid_pk, pk_keys, jnp.int32(key_space))
        lut = jnp.full((key_space + 1,), jnp.int32(-1)).at[slot].set(
            jnp.arange(p, dtype=jnp.int32), mode="drop")
        fk_in = (fk_keys >= 0) & (fk_keys < key_space)
        pos = jnp.take(lut, jnp.clip(fk_keys, 0, key_space - 1))
        hit = fk_in & (pos >= 0)
        if fk_mask is not None:
            hit = hit & fk_mask
        return jnp.maximum(pos, 0), hit
    pk = _sentineled(pk_keys, pk_mask)
    order = jnp.argsort(pk)
    pk_sorted = pk[order]
    pos = jnp.searchsorted(pk_sorted, fk_keys, method="sort")
    pos_c = jnp.clip(pos, 0, pk.shape[0] - 1)
    hit = pk_sorted[pos_c] == fk_keys
    if fk_mask is not None:
        hit = hit & fk_mask
    # masked build rows carry the sentinel key; a probe key equal to the
    # sentinel would false-match, so exclude it explicitly
    hit = hit & (fk_keys != _I32_SENTINEL)
    return order[pos_c], hit


def member(build_keys: jnp.ndarray, probe_keys: jnp.ndarray,
           build_mask: Optional[jnp.ndarray] = None,
           probe_mask: Optional[jnp.ndarray] = None,
           key_space: Optional[int] = None,
           plan=None) -> jnp.ndarray:
    """Semi-join membership: for each probe row, does any valid build
    row share its key? (Q04 EXISTS, Q22 NOT EXISTS.) Build keys need
    not be unique."""
    _, hit = pk_fk_join(
        # duplicates are fine for membership: any representative row
        # (leftmost via searchsorted, last-writer via the LUT) works
        build_keys, probe_keys, build_mask, probe_mask, key_space, plan)
    return hit


def any_by_key(keys: jnp.ndarray, flag: jnp.ndarray, key_space: int,
               block: int = 128, chunk: int = 4096) -> jnp.ndarray:
    """Per row: does ANY row sharing its key have ``flag`` set?
    (Self-semi-join — reddit label propagation,
    ref ``src/reddit/headers/RedditCommentLabelJoin.h``.)

    Scatter-free formulation, measured on v5e at 1M rows / 50k keys
    (2026-07, netsdb bench harness):

    - the naive scatter-max + flat gather costs 13.6 ms — colliding
      scatter updates serialize on TPU (see ``_use_dense``), and a flat
      1M-row gather from a 50k table alone costs 6.7 ms;
    - this kernel reshapes the key space into an (H, block) grid.
      REDUCE: flagged keys become (hi, lo) one-hot int8 matrices whose
      product accumulates the mark grid on the MXU (~0.7 ms — flag
      folded into the index, so unflagged rows match no grid cell).
      GATHER: per-row lookup = a row gather on ``hi`` (vectorized,
      lane-wide) + a one-hot lane select on ``lo`` (~2.7 ms vs 6.7 for
      the flat gather).
    - total 3.45 ms = 3.9× over the scatter form. ``block=128`` (one
      lane register) measured best; larger blocks only move cost from
      rows to lanes.

    Out-of-range keys return 0 and contribute nothing (orphan-key rule
    of `_in_range`). Rows are padded to ``chunk`` internally.
    """
    n = keys.shape[0]
    a = _pad_to(keys, chunk, -1)
    f = _pad_to(flag, chunk, 0)
    # flag folds into the index: unflagged rows match no grid cell
    am = jnp.where((f != 0) & (a >= 0) & (a < key_space), a, jnp.int32(-1))
    grid = _grid_reduce(am, key_space, block, chunk)
    gridb = (grid > 0).astype(jnp.int8)  # marks, not counts
    # gather phase chunked too: the (rows, block) select intermediate
    # must stay VMEM-sized — unchunked it is N*block bytes (25 GB at
    # 50M rows, an HBM OOM)
    kin = (a >= 0) & (a < key_space)
    kc = jnp.clip(a, 0, key_space - 1)
    gchunk = 65536
    gpad = (-kc.shape[0]) % gchunk
    if gpad:
        kc = jnp.concatenate([kc, jnp.zeros((gpad,), jnp.int32)])
        kin = jnp.concatenate([kin, jnp.zeros((gpad,), jnp.bool_)])

    def gstep(carry, xs):
        k, k_ok = xs
        rows = jnp.take(gridb, k // block, axis=0)
        oneh = ((k % block)[:, None]
                == jnp.arange(block, dtype=jnp.int32)[None, :])
        got = jnp.where(oneh, rows, 0).sum(axis=1)
        return carry, ((got > 0) & k_ok).astype(jnp.int32)

    _, out = jax.lax.scan(gstep, jnp.zeros((), jnp.int32) + am.sum() * 0,
                          (kc.reshape(-1, gchunk),
                           kin.reshape(-1, gchunk)))
    return out.reshape(-1)[:n]


def top_k_masked(scores: jnp.ndarray, k: int,
                 mask: Optional[jnp.ndarray] = None,
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Indices of the k largest valid scores. Returns ``(idx, valid)``;
    ``valid[j]`` is False when fewer than j+1 rows were valid."""
    neg = jnp.asarray(-jnp.inf, jnp.float32)
    s = scores.astype(jnp.float32)
    if mask is not None:
        s = jnp.where(mask, s, neg)
    vals, idx = jax.lax.top_k(s, k)
    return idx, vals > neg
