"""Hash-repartition shuffle with ROW outputs — distributed joins whose
result is a sharded table, not just a psum-able aggregate.

The reference's partitioned join materializes distributed row sets:
each node's pipeline hashes join keys, per-destination combiner threads
stream rows to the owning node, and the joined tuples land in a
partitioned set a downstream stage scans
(``src/queryExecution/source/PipelineStage.cc:1652-1728``,
``src/serverFunctionalities/source/HermesExecutionServer.cc:901``).
Round 1's :mod:`netsdb_tpu.relational.sharded` covered only the
aggregate-output case (psum of fixed-shape partials); this module adds
the row-output case the TPU way:

- the shuffle is ONE ``all_to_all`` collective over the mesh axis
  (replacing per-node combiner threads + snappy + TCP streams);
- destination buckets are fixed-capacity (static shapes for XLA) with a
  validity mask and a psum'd overflow counter — the caller sizes slack
  and can verify nothing was dropped (:func:`check_overflow`);
- co-location is by ``key % n_shards``, so every row of one key lands
  on shard ``key % n`` and local per-key work uses the COMPRESSED key
  ``key // n`` over a key space n× smaller — the LUT-join and
  segment-reduce kernels get cheaper per shard as the mesh grows.

The result type :class:`ShardedRows` is a first-class distributed
table: its columns are global jax.Arrays sharded ``P(axis)`` over the
mesh, directly consumable by a downstream shard_map stage (see
``shuffle_q03`` — repartitioned join feeding a per-order aggregate
feeding a distributed top-k).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from netsdb_tpu.relational import kernels as K
from netsdb_tpu.relational import tuning
from netsdb_tpu.relational.planner import JoinPlan
from netsdb_tpu.relational.sharded import shard_fact_columns


@dataclasses.dataclass
class ShardedRows:
    """A distributed row set: each column sharded ``P(axis)`` over
    ``mesh``; ``valid`` marks live rows (bucket padding is False).
    ``overflow`` counts rows dropped because a destination bucket
    filled — always verify it is 0 (:func:`check_overflow`) or re-run
    with more ``slack``."""

    cols: Dict[str, jax.Array]
    valid: jax.Array
    mesh: Mesh
    axis: str
    overflow: jax.Array

    @property
    def rows_per_shard(self) -> int:
        return self.valid.shape[0] // self.mesh.shape[self.axis]


def check_overflow(t: ShardedRows) -> None:
    n = int(t.overflow)
    if n:
        raise ValueError(
            f"hash shuffle dropped {n} rows (bucket capacity too small);"
            " re-run with a larger slack factor")


def _bucket_local(cols: Dict[str, jnp.ndarray], key: jnp.ndarray,
                  valid: jnp.ndarray, n_shards: int, cap: int):
    """Pack one shard's rows into (n_shards, cap) destination buckets
    (the per-destination page queues of the reference's shuffle sink),
    dropping overflow with a count."""
    dest = key % n_shards
    # stable sort: valid rows grouped by destination, invalid at the end
    sort_key = jnp.where(valid, dest, n_shards)
    order = jnp.argsort(sort_key, stable=True)
    dest_s = jnp.where(valid, dest, n_shards)[order]
    first = jnp.searchsorted(dest_s, jnp.arange(n_shards), side="left")
    n = dest.shape[0]
    rank = jnp.arange(n) - jnp.take(first, jnp.clip(dest_s, 0, n_shards - 1))
    ok = (dest_s < n_shards) & (rank < cap)
    slot = jnp.where(ok, dest_s * cap + rank, n_shards * cap)
    out = {}
    for name, c in cols.items():
        cs = c[order]
        out[name] = jnp.zeros((n_shards * cap,), c.dtype).at[slot].set(
            cs, mode="drop")
    vout = jnp.zeros((n_shards * cap,), jnp.bool_).at[slot].set(
        ok, mode="drop")
    overflow = jnp.sum((dest_s < n_shards) & (rank >= cap)
                       ).astype(jnp.int32)
    reshape = lambda a: a.reshape(n_shards, cap)
    return ({k: reshape(v) for k, v in out.items()}, reshape(vout),
            overflow)


def _exchange(bucketed: Dict[str, jnp.ndarray], valid: jnp.ndarray,
              axis: str):
    """The shuffle itself: one all_to_all moves bucket i of every shard
    to shard i."""
    ex = lambda a: jax.lax.all_to_all(a, axis, split_axis=0,
                                      concat_axis=0, tiled=True)
    return {k: ex(v) for k, v in bucketed.items()}, ex(valid)


def hash_repartition(mesh: Mesh, axis: str,
                     cols: Dict[str, jnp.ndarray], key_col: str,
                     slack: float = 2.0,
                     valid: Optional[jnp.ndarray] = None) -> ShardedRows:
    """Repartition a row-sharded table so that all rows with equal
    ``cols[key_col]`` land on shard ``key % n_shards``.

    Every output column keeps its input name; rows are padded to the
    static bucket capacity ``cap = slack * mean_bucket + 16``.
    ``valid`` marks live input rows (e.g. a ShardedRows result being
    re-shuffled — its padding rows must not travel, or their sentinel
    keys pile into one bucket).
    """
    if "__valid__" in cols:
        raise ValueError("column name '__valid__' is reserved by "
                         "hash_repartition (internal validity mask)")
    n_shards = mesh.shape[axis]
    payload = dict(cols)
    if valid is not None:
        payload["__valid__"] = valid
    fact, pad_valid = shard_fact_columns(payload, n_shards)
    in_valid = fact.pop("__valid__", None)
    per_shard = pad_valid.shape[0] // n_shards
    cap = int(slack * (per_shard / n_shards)) + 16
    names = tuple(sorted(fact))
    fn = _repartition_prog(mesh, axis, names, key_col, n_shards, cap,
                           in_valid is not None)
    varg = pad_valid if in_valid is None else (pad_valid, in_valid)
    out_cols, out_valid, overflow = fn(varg, *[fact[n] for n in names])
    return ShardedRows(out_cols, out_valid, mesh, axis, overflow)


@functools.lru_cache(maxsize=128)
def _repartition_prog(mesh: Mesh, axis: str, names: Tuple[str, ...],
                      key_col: str, n_shards: int, cap: int,
                      has_valid: bool):
    """Compiled-program cache: one jitted shard_map per (mesh, columns,
    capacity) signature — repeated shuffles reuse the XLA executable
    the way queries.py's module-level cores do."""

    def body(valid_s, *arrs):
        if has_valid:
            valid_s, vin = valid_s
            valid_s = valid_s & vin
        c = dict(zip(names, arrs))
        bucketed, bvalid, overflow = _bucket_local(
            c, c[key_col], valid_s, n_shards, cap)
        ex_cols, ex_valid = _exchange(bucketed, bvalid, axis)
        flat = {k: v.reshape(-1) for k, v in ex_cols.items()}
        return flat, ex_valid.reshape(-1), jax.lax.psum(overflow, axis)

    vspec = (P(axis), P(axis)) if has_valid else P(axis)
    return jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(vspec,) + (P(axis),) * len(names),
        out_specs=({k: P(axis) for k in names}, P(axis), P())))


def compressed_key_space(global_key_space: int, n_shards: int) -> int:
    """Per-shard key-space bound after modulo placement: local key is
    ``key // n_shards``."""
    return -(-global_key_space // n_shards) + 1


def hash_join(mesh: Mesh, axis: str,
              build: Dict[str, jnp.ndarray], build_key: str,
              probe: Dict[str, jnp.ndarray], probe_key: str,
              key_space: int,
              build_mask_fn: Optional[Callable] = None,
              slack: float = 2.0,
              build_valid: Optional[jnp.ndarray] = None,
              probe_valid: Optional[jnp.ndarray] = None) -> ShardedRows:
    """Distributed hash-partitioned equi-join with row output.

    Both sides are repartitioned by key (two all_to_alls), then each
    shard LUT-joins its co-located partitions over the COMPRESSED key
    space. The result carries every probe column plus every build
    column (gathered through the join) plus the ``hit`` validity —
    a sharded joined table for downstream stages, exactly the
    partitioned-join row sets of the reference
    (``PipelineStage.cc:1652-1728``).

    ``build_mask_fn(cols) -> bool array`` optionally filters build rows
    (selection pushed below the join). Build keys must be unique among
    surviving rows (primary-key side).
    """
    clash = (set(build) - {build_key}) & set(probe)
    if clash:
        raise ValueError(
            f"hash_join column name collision {sorted(clash)}: rename a "
            "side's columns (build columns would silently shadow probe)")
    b = hash_repartition(mesh, axis, build, build_key, slack, build_valid)
    p = hash_repartition(mesh, axis, probe, probe_key, slack, probe_valid)
    nb = next(iter(build.values())).shape[0]
    npr = next(iter(probe.values())).shape[0]
    return local_join(b, p, build_key, probe_key, key_space, nb, npr,
                      build_mask_fn)


def local_join(b: ShardedRows, p: ShardedRows, build_key: str,
               probe_key: str, key_space: int, build_rows: int,
               probe_rows: int,
               build_mask_fn: Optional[Callable] = None) -> ShardedRows:
    """Per-shard LUT/sort join of two ALREADY co-partitioned row sets
    (both repartitioned on the same key, e.g. by ``hash_repartition``
    or a ``Partition`` Computation node) over the compressed key space.
    This is the local half of :func:`hash_join`, exposed so a
    Partition-node DAG can compose shuffle and join as separate stages
    — the reference's partition-stage → join-stage pipeline
    (``PipelineStage.cc:1652-1728``)."""
    mesh, axis = b.mesh, b.axis
    n_shards = mesh.shape[axis]
    local_ks = compressed_key_space(key_space, n_shards)
    # the per-shard join strategy comes from the SAME cost model as the
    # single-chip planner (tuned LUT density factor + byte cap), fed
    # REAL per-shard row counts (from the pre-shuffle inputs — the
    # post-shuffle buckets are slack-padded) and the compressed key space
    from netsdb_tpu.relational.planner import plan_join_from_stats
    from netsdb_tpu.relational.stats import ColumnStats

    local_build = ColumnStats(build_rows // n_shards + 1, 0,
                              local_ks - 1, -1)
    jp = plan_join_from_stats(local_build, probe_rows // n_shards + 1)
    jp = JoinPlan(jp.strategy, local_ks)
    fn = _join_prog(mesh, axis, tuple(sorted(b.cols)),
                    tuple(sorted(p.cols)), build_key, probe_key, jp,
                    n_shards, build_mask_fn)
    cols, hit = fn(b.valid, p.valid,
                   *[b.cols[n] for n in sorted(b.cols)],
                   *[p.cols[n] for n in sorted(p.cols)])
    return ShardedRows(cols, hit, mesh, axis, b.overflow + p.overflow)


@functools.lru_cache(maxsize=128)
def _join_prog(mesh: Mesh, axis: str, bnames: Tuple[str, ...],
               pnames: Tuple[str, ...], build_key: str, probe_key: str,
               jp: JoinPlan, n_shards: int,
               build_mask_fn: Optional[Callable]):
    """Compiled local-join program per (mesh, schema, plan) signature.
    ``build_mask_fn`` participates in the cache key by identity — pass
    a module-level function (not a fresh lambda) to hit the cache."""

    def body(bvalid, pvalid, *arrs):
        bc = dict(zip(bnames, arrs[:len(bnames)]))
        pc = dict(zip(pnames, arrs[len(bnames):]))
        bmask = bvalid
        if build_mask_fn is not None:
            bmask = bmask & build_mask_fn(bc)
        bk = bc[build_key] // n_shards
        pk = pc[probe_key] // n_shards
        idx, hit = K.pk_fk_join(bk, pk, bmask, pvalid, plan=jp)
        out = dict(pc)
        for name in bnames:
            if name != build_key:
                out[name] = jnp.take(bc[name], idx)
        return out, hit

    out_names = sorted(set(pnames) | set(n for n in bnames
                                         if n != build_key))
    return jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(axis),) * (2 + len(bnames) + len(pnames)),
        out_specs=({k: P(axis) for k in out_names}, P(axis))))


def segment_sum_by_key(t: ShardedRows, key_col: str, value_col: str,
                       key_space: int,
                       extra_min_col: Optional[str] = None):
    """Downstream-stage demo primitive: per-key sums over a repartition
    result, computed PURELY LOCALLY per shard (keys are co-located, so
    no collective is needed — the payoff of the row shuffle). Returns
    per-shard segment arrays sharded ``P(axis)`` with global key
    ``local_index * n_shards + shard_id``."""
    n_shards = t.mesh.shape[t.axis]
    local_ks = compressed_key_space(key_space, n_shards)
    names = tuple(sorted(t.cols))
    fn = _segment_prog(t.mesh, t.axis, names, key_col, value_col,
                       local_ks, n_shards, extra_min_col)
    return fn(t.valid, *[t.cols[n] for n in names])


@functools.lru_cache(maxsize=128)
def _segment_prog(mesh: Mesh, axis: str, names: Tuple[str, ...],
                  key_col: str, value_col: str, local_ks: int,
                  n_shards: int, extra_min_col: Optional[str]):
    def body(valid, *arrs):
        c = dict(zip(names, arrs))
        ck = c[key_col] // n_shards
        sums = K.segment_sum(c[value_col], ck, local_ks, valid)
        if extra_min_col is None:
            return sums
        mins = K.segment_min(c[extra_min_col], ck, local_ks, valid)
        return sums, mins

    specs = (P(axis),) * (1 + len(names))
    out_specs = P(axis) if extra_min_col is None else (P(axis), P(axis))
    return jax.jit(jax.shard_map(body, mesh=mesh, in_specs=specs,
                                 out_specs=out_specs))


def distributed_top_k(mesh: Mesh, axis: str, scores: jax.Array, k: int,
                      mask: Optional[jax.Array] = None
                      ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Global top-k over a ``P(axis)``-sharded score vector whose
    global position encodes the key as ``local_index * n + shard``:
    local top-k per shard, all_gather of the n*k candidates, final
    top-k replicated (the reference's TopK aggregation combine,
    ``src/sharedLibraries/headers/TopKTest.h``). Always returns k
    entries; slots past the number of available rows hold -inf."""
    fn = _topk_prog(mesh, axis, k, mask is not None)
    args = (scores, mask) if mask is not None else (scores,)
    vals, keys = fn(*args)
    return vals, keys, vals > -jnp.inf


@functools.lru_cache(maxsize=64)
def _topk_prog(mesh: Mesh, axis: str, k: int, has_mask: bool):
    n_shards = mesh.shape[axis]

    def body(s, m):
        sm = jnp.where(m, s, -jnp.inf) if m is not None else s
        # a shard may hold fewer than k rows: clamp the local pick and
        # pad the merged result back to k with -inf
        kk = min(k, sm.shape[0])
        vals, idx = jax.lax.top_k(sm, kk)
        shard = jax.lax.axis_index(axis)
        gkey = idx * n_shards + shard
        allv = jax.lax.all_gather(vals, axis, tiled=True)
        allk = jax.lax.all_gather(gkey, axis, tiled=True)
        fk = min(k, allv.shape[0])
        fv, fi = jax.lax.top_k(allv, fk)
        fkeys = jnp.take(allk, fi)
        if fk < k:
            fv = jnp.pad(fv, (0, k - fk), constant_values=-jnp.inf)
            fkeys = jnp.pad(fkeys, (0, k - fk), constant_values=-1)
        return fv, fkeys

    in_specs = (P(axis), P(axis)) if has_mask else (P(axis),)
    # check_vma=False: the post-all_gather top_k is replicated by
    # construction (same candidates on every shard), which the static
    # varying-axes inference cannot see through lax.top_k.
    if has_mask:
        return jax.jit(jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                                     out_specs=(P(), P()),
                                     check_vma=False))
    return jax.jit(jax.shard_map(lambda s: body(s, None), mesh=mesh,
                                 in_specs=in_specs, out_specs=(P(), P()),
                                 check_vma=False))


# ------------------------------------------------------------------ Q03
def _mask_o_ok(c):
    return c["o_ok"]


def _mask_c_ok(c):
    return c["c_ok"]


def shuffle_q03(tables, mesh: Mesh, axis: str = "data",
                segment: str = "BUILDING", date: str = "1995-03-15",
                k: int = 10, slack: float = 2.0):
    """Hand-mesh form of the row-output Q03 — kept as the kernel-layer
    driver and benchmark; APPLICATION code should use
    :func:`q03_row_sink_for`, the same plan as a Partition-node DAG
    over PLACED sets with no mesh argument (round 4 retired this
    surface from the dryrun/client paths).

    Q03 through the ROW-OUTPUT distributed plan — the reference's
    actual shape for this query (partitioned join materializing row
    sets, then aggregation, then top-k) rather than round 1's
    replicate-the-dimensions shortcut:

    1. customer ⋈ orders on the controller (customer is small → the
       planner's broadcast side);
    2. orders and lineitem hash-REPARTITIONED on orderkey — two
       all_to_alls — and LUT-joined per shard over compressed keys,
       yielding a sharded joined row table;
    3. a purely LOCAL per-order revenue + order-date aggregate over the
       co-located rows (no collective — the repartition bought this);
    4. distributed top-k merge.

    Returns the same row dicts as ``queries.cq03`` (cross-checked in
    tests/test_shuffle.py).
    """
    from netsdb_tpu.relational import planner as PLN
    from netsdb_tpu.relational.stats import key_space as ks_of
    from netsdb_tpu.relational.table import date_to_int, int_to_date

    cust, orders, li = (tables["customer"], tables["orders"],
                        tables["lineitem"])
    d = date_to_int(date)
    n_shards = mesh.shape[axis]
    gks = max(ks_of(orders, "o_orderkey"), ks_of(li, "l_orderkey"))
    seg_code = cust.code("c_mktsegment", segment)
    cust_ok = cust["c_mktsegment"] == seg_code

    # phase 1: customer ⋈ orders — the planner picks the side placement
    # from the build side's bytes (broadcast for a dimension-sized
    # customer table, hash-repartition when it is fact-scale)
    cust_bytes = 8 * cust.num_rows  # the two columns the join carries
    if PLN.plan_distribution(cust_bytes,
                             n_shards).strategy == "broadcast":
        jp_cust = PLN.plan_join(cust, "c_custkey", orders, "o_custkey")
        _, chit = K.pk_fk_join(cust["c_custkey"], orders["o_custkey"],
                               cust_ok, plan=jp_cust)
        o_ok = chit & (orders["o_orderdate"] < d)
    else:
        j1 = hash_join(
            mesh, axis,
            build={"c_custkey": cust["c_custkey"], "c_ok": cust_ok},
            build_key="c_custkey",
            probe={"o_orderkey": orders["o_orderkey"],
                   "o_custkey": orders["o_custkey"],
                   "o_orderdate": orders["o_orderdate"]},
            probe_key="o_custkey",
            key_space=max(ks_of(cust, "c_custkey"),
                          ks_of(orders, "o_custkey")),
            build_mask_fn=_mask_c_ok, slack=slack)
        check_overflow(j1)
        orders = None  # the sharded join result replaces the table
        o_ok = j1.valid & j1.cols["c_ok"] & (j1.cols["o_orderdate"] < d)

    # phase 2: repartition + row-output join. In the partition branch
    # the build side is already a sharded join result — its global
    # arrays feed the next shuffle directly (a downstream stage
    # consuming a ShardedRows, the point of row outputs).
    if orders is not None:
        build = {"o_orderkey": orders["o_orderkey"],
                 "o_orderdate": orders["o_orderdate"], "o_ok": o_ok}
    else:
        build = {"o_orderkey": j1.cols["o_orderkey"],
                 "o_orderdate": j1.cols["o_orderdate"], "o_ok": o_ok}
    joined = hash_join(
        mesh, axis,
        build=build,
        build_key="o_orderkey",
        probe={"l_orderkey": li["l_orderkey"],
               "l_shipdate": li["l_shipdate"],
               "l_extendedprice": li["l_extendedprice"],
               "l_discount": li["l_discount"]},
        probe_key="l_orderkey", key_space=gks,
        build_mask_fn=_mask_o_ok, slack=slack,
        build_valid=None if orders is not None else j1.valid)
    check_overflow(joined)

    return q03_finish(joined, gks, d, k)


def q03_finish(joined: ShardedRows, gks: int, d: int, k: int):
    """Phases 3–4 of the row-output Q03 over an already-joined
    ShardedRows: local per-order aggregate (no collective — the
    repartition bought co-location), distributed top-k, host decode.
    Shared by the hand-mesh driver (:func:`shuffle_q03`) and the
    Partition-node DAG (:func:`q03_row_sink_for`)."""
    from netsdb_tpu.relational.table import int_to_date

    mesh, axis = joined.mesh, joined.axis
    n_shards = mesh.shape[axis]
    local_ks = compressed_key_space(gks, n_shards)
    agg_in = ShardedRows(
        {"l_orderkey": joined.cols["l_orderkey"],
         "o_orderdate": joined.cols["o_orderdate"],
         "rev": joined.cols["l_extendedprice"]
         * (1.0 - joined.cols["l_discount"])},
        joined.valid & (joined.cols["l_shipdate"] > d),
        mesh, axis, joined.overflow)
    rev_sh, od_sh = segment_sum_by_key(agg_in, "l_orderkey", "rev", gks,
                                       extra_min_col="o_orderdate")

    vals, gkeys, _ = distributed_top_k(mesh, axis, rev_sh, k,
                                       mask=rev_sh > 0)
    import numpy as np

    vals, gkeys = np.asarray(vals), np.asarray(gkeys)
    od = np.asarray(od_sh)  # global layout: shard * local_ks + ck
    rows = []
    for j in range(k):
        if not np.isfinite(vals[j]) or vals[j] <= 0:
            continue
        okey = int(gkeys[j])
        pos = (okey % n_shards) * local_ks + okey // n_shards
        rows.append({"okey": okey, "odate": int_to_date(int(od[pos])),
                     "revenue": float(vals[j])})
    rows.sort(key=lambda r: (-r["revenue"], r["odate"]))
    return rows


def q03_row_sink_for(client, db: str, segment: str = "BUILDING",
                     date: str = "1995-03-15", k: int = 10,
                     slack: float = 2.0, n_parts: Optional[int] = None):
    """The row-output shuffle Q03 as a PARTITION-NODE DAG over placed
    sets — no hand mesh anywhere: the mesh comes off the stored
    columns' placement shardings, statistics come from
    ``client.analyze_set`` summaries, and the plan is
    SCAN→JOIN(filter)→PARTITION ×2 →JOIN(local)→OUTPUT, the reference's
    partition-stage → join-stage pipeline shape
    (``PipelineStage.cc:1652-1728``) expressed in Computation nodes.
    Retires ``shuffle_q03(tables, mesh)``'s hand-mesh surface from
    client code paths."""
    from netsdb_tpu.plan.computations import (Apply, Join, Partition,
                                              ScanSet, WriteSet)
    from netsdb_tpu.relational.dag import _fold_mask
    from netsdb_tpu.relational.table import ColumnTable, date_to_int
    from netsdb_tpu.storage.store import SetIdentifier

    info = {n: client.analyze_set(db, n)
            for n in ("customer", "orders", "lineitem")}
    gks = max(info["orders"]["stats"]["o_orderkey"].key_space,
              info["lineitem"]["stats"]["l_orderkey"].key_space)
    cust_ks = max(info["customer"]["stats"]["c_custkey"].key_space,
                  info["orders"]["stats"]["o_custkey"].key_space)
    seg_dict = info["customer"]["dicts"]["c_mktsegment"]
    # -1 for an unknown segment → empty result, not a build-time crash
    seg_code = seg_dict.index(segment) if segment in seg_dict else -1
    d = date_to_int(date)
    if n_parts is None:
        # in-process: read the shard count off the set's placement;
        # RemoteClients (no local store) pass n_parts explicitly
        store = getattr(client, "store", None)
        pl = (store.placement_of(SetIdentifier(db, "lineitem"))
              if store is not None else None)
        if pl is None:
            raise ValueError(
                "q03_row_sink_for needs a placed lineitem set (the "
                "Partition nodes shuffle on its mesh) — or pass n_parts "
                "explicitly when building from a RemoteClient")
        n_parts = pl.axis_size()
    jp_cust = JoinPlan("lut", cust_ks)

    def filter_orders(orders: ColumnTable, cust: ColumnTable) -> ColumnTable:
        orders, cust = _fold_mask(orders), _fold_mask(cust)
        cust_ok = cust["c_mktsegment"] == seg_code
        _, chit = K.pk_fk_join(cust["c_custkey"], orders["o_custkey"],
                               cust_ok, plan=jp_cust)
        return ColumnTable({"o_orderkey": orders["o_orderkey"],
                            "o_orderdate": orders["o_orderdate"],
                            "o_ok": chit & (orders["o_orderdate"] < d)})

    def project_li(t: ColumnTable) -> ColumnTable:
        return t.select(["l_orderkey", "l_shipdate", "l_extendedprice",
                         "l_discount"])

    build = Join(ScanSet(db, "orders"), ScanSet(db, "customer"),
                 fn=filter_orders, label=f"q03rows-filter:{seg_code}:{d}")
    probe = Apply(ScanSet(db, "lineitem"), project_li,
                  label="q03rows-project", traceable=False)
    pb = Partition(build, "o_orderkey", n_parts, label="part-orders")
    pp = Partition(probe, "l_orderkey", n_parts, label="part-lineitem")

    def join_and_finish(p: ShardedRows, b: ShardedRows):
        j = local_join(b, p, "o_orderkey", "l_orderkey", gks,
                       build_rows=info["orders"]["num_rows"],
                       probe_rows=info["lineitem"]["num_rows"],
                       build_mask_fn=_mask_o_ok)
        check_overflow(j)
        return q03_finish(j, gks, d, k)

    out = Join(pp, pb, fn=join_and_finish,
               label=f"q03rows-join:{gks}:{d}:{k}")
    return WriteSet(out, db, "q03_rows_out")
