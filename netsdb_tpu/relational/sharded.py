"""Mesh-distributed relational execution — the reference's multi-node
query plan, re-expressed as shardings + collectives.

The reference scales queries by partitioning sets across workers and
running the same pipeline on each node's partition, with two data
movements (SURVEY §2.6):

- **local pre-aggregation + hash-repartition shuffle**: each node's
  ``CombinerProcessor`` folds its partition, then partial aggregates
  stream to the owning node where ``AggregationProcessor`` merges them
  (``src/queryExecution/headers/CombinerProcessor.h:20``,
  ``PipelineStage.cc:1215-1516``). TPU form: row-shard the fact table
  over a mesh axis, run the SAME per-shard kernels as the single-chip
  engine, and ``psum`` the fixed-shape partial aggregates over ICI —
  the shuffle is one collective.
- **broadcast join**: the small side is replicated to every node as a
  ``SharedHashSet`` (``BroadcastJoinBuildHTJobStage``,
  ``HermesExecutionServer.cc:172-369``). TPU form: dimension-table
  columns replicated in the shard_map (``P(None)``); each shard probes
  its rows against the full build LUT locally.

Any query whose result is a fixed-shape aggregate distributes this way;
``sharded_query`` wraps a local kernel accordingly, and the concrete
``sharded_q01`` / ``sharded_q06`` / ``sharded_q04`` bodies below REUSE
the single-chip query cores' logic so the distributed answers are
bit-comparable to the local engine (tests cross-check both on the
virtual 8-device CPU mesh).

LAYERING (round 4): this module is the shard_map KERNEL layer. The
user-facing distribution surface is the SET API — create the sets with
a Placement and run ``relational.dag.suite_sink_for`` (aggregate form)
or ``relational.shuffle.q03_row_sink_for`` (row-output form); those
DAGs reach the same physics with the mesh taken from the stored
columns' shardings. Call these functions directly only when you hold
raw arrays and a mesh (benchmarks, library composition) — application
code should not hand-shard.

Row padding: a sharded axis must divide the device count, so fact
columns are padded and a validity mask rides along (the mask approach
every tensor op in this framework uses).
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from netsdb_tpu.relational import kernels as K
import re

from netsdb_tpu.relational import planner as PLN
from netsdb_tpu.relational.queries import Tables, _lut, q22_code_lut
from netsdb_tpu.relational.stats import key_space
from netsdb_tpu.relational.table import date_to_int


def shard_fact_columns(cols: Dict[str, jnp.ndarray], n_shards: int,
                       ) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """Pad each column to a multiple of ``n_shards`` and return the
    validity mask (False on padding rows) — the dispatcher's
    round-robin row partitioning (``PartitionPolicy.h:29``) with the
    remainder handled by masking instead of ragged partitions."""
    n = next(iter(cols.values())).shape[0]
    padded = -(-n // n_shards) * n_shards
    out = {}
    for name, c in cols.items():
        pad = padded - n
        out[name] = jnp.pad(c, (0, pad)) if pad else c
    valid = jnp.arange(padded) < n
    return out, valid


def sharded_query(local_kernel: Callable[..., jax.Array], mesh: Mesh,
                  axis: str, fact: Dict[str, jnp.ndarray],
                  replicated: Sequence[jax.Array] = (),
                  combine: Optional[Callable] = None) -> jax.Array:
    """Run ``local_kernel(valid, fact_cols..., replicated...)``
    per shard and combine its fixed-shape partial aggregate over
    ``axis`` (default ``psum``; pass ``jax.lax.pmin``/``pmax`` for
    min/max merges — the reference's AggregationProcessor runs the
    aggregate's own combine the same way).

    ``local_kernel`` must return per-shard PARTIAL aggregates whose
    combine over shards is the global answer. The result may be a
    pytree (e.g. ``(sums, counts)``) — each leaf is combined.
    """
    n_shards = mesh.shape[axis]
    fact_p, valid = shard_fact_columns(fact, n_shards)
    names = sorted(fact_p)
    combine = combine or jax.lax.psum

    def body(valid_s, *args):
        k = len(names)
        cols = dict(zip(names, args[:k]))
        rep = args[k:k + len(replicated)]
        partial = local_kernel(valid_s, cols, *rep)
        return jax.tree_util.tree_map(lambda x: combine(x, axis), partial)

    fn = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(axis),) + (P(axis),) * len(names)
        + (P(),) * len(replicated),
        out_specs=P(),
    )
    return fn(valid, *[fact_p[n] for n in names], *replicated)


def sharded_key_marks(mesh: Mesh, axis: str, key_col: jnp.ndarray,
                      n_keys: int,
                      row_mask: Optional[jnp.ndarray] = None,
                      extra_cols: Optional[Dict[str, jnp.ndarray]] = None,
                      mask_fn: Optional[Callable] = None) -> jax.Array:
    """0/1 existence marks per key, psum-merged over shards — the
    build-HT half of a distributed semi/anti-join (Q04's late-order
    set, Q22's has-orders set). ``mask_fn(valid, cols)`` may narrow
    which rows mark (cols include ``key`` plus ``extra_cols``)."""
    fact = {"key": key_col}
    if row_mask is not None:
        fact["row_mask"] = row_mask
    fact.update(extra_cols or {})

    def local(valid, c):
        m = valid if row_mask is None else (valid & c["row_mask"])
        if mask_fn is not None:
            m = m & mask_fn(valid, c)
        return jnp.minimum(K.segment_count(c["key"], n_keys, m), 1)

    return sharded_query(local, mesh, axis, fact)


def probe_marks(marks: jnp.ndarray, keys: jnp.ndarray,
                n_keys: int) -> jnp.ndarray:
    """Per-row membership against a psum-merged mark table (the probe
    half; out-of-space keys are non-members)."""
    in_space = (keys >= 0) & (keys < n_keys)
    return in_space & (jnp.take(marks, jnp.clip(keys, 0, n_keys - 1)) > 0)


# ------------------------------------------------------------------ Q01
_Q01_COLS = ("l_shipdate", "l_returnflag", "l_linestatus", "l_quantity",
             "l_extendedprice", "l_discount", "l_tax")


def _q01_local(valid, li, n_groups: int, n_ls: int, delta: int):
    mask = valid & (li["l_shipdate"] <= delta)
    seg = li["l_returnflag"] * n_ls + li["l_linestatus"]
    qty = li["l_quantity"].astype(jnp.float32)
    disc_price = li["l_extendedprice"] * (1.0 - li["l_discount"])
    charge = disc_price * (1.0 + li["l_tax"])
    rows = [K.segment_sum(v, seg, n_groups, mask)
            for v in (qty, li["l_extendedprice"], disc_price, charge,
                      li["l_discount"])]
    # counts stay int32 through the psum — f32 partials would absorb
    # +1 increments past 2^24 rows/group (same guard as the single-chip
    # core, queries.py _q01_core)
    return jnp.stack(rows), K.segment_count(seg, n_groups, mask)


def sharded_q01(tables: Tables, mesh: Mesh, axis: str = "data",
                delta_date: str = "1998-09-02"):
    """Distributed pricing-summary → (sums (5, groups) f32,
    counts (groups,) i32), identical to the single-chip core's."""
    li = tables["lineitem"]
    n_ls = len(li.dicts["l_linestatus"])
    n_groups = len(li.dicts["l_returnflag"]) * n_ls
    kern = functools.partial(_q01_local, n_groups=n_groups, n_ls=n_ls,
                             delta=date_to_int(delta_date))
    return sharded_query(kern, mesh, axis,
                         {k: li.cols[k] for k in _Q01_COLS})


# ------------------------------------------------------------------ Q06
def _q06_local(valid, li, a, b, disc, qty):
    c = li
    mask = (valid & (c["l_shipdate"] >= a) & (c["l_shipdate"] < b)
            & (c["l_discount"] >= disc - 0.011)
            & (c["l_discount"] <= disc + 0.011)
            & (c["l_quantity"] < qty))
    return jnp.sum(jnp.where(mask, c["l_extendedprice"] * c["l_discount"],
                             0.0))


def sharded_q06(tables: Tables, mesh: Mesh, axis: str = "data",
                d0: str = "1994-01-01", d1: str = "1995-01-01",
                discount: float = 0.06, quantity: int = 24) -> jax.Array:
    li = tables["lineitem"]
    cols = {k: li.cols[k] for k in ("l_shipdate", "l_discount",
                                    "l_quantity", "l_extendedprice")}

    def local(valid, c):
        return _q06_local(valid, c, date_to_int(d0), date_to_int(d1),
                          discount, quantity)

    return sharded_query(local, mesh, axis, cols)


# ------------------------------------------------------------------ Q04
def sharded_q04(tables: Tables, mesh: Mesh, axis: str = "data",
                d0: str = "1993-07-01",
                d1: str = "1993-10-01") -> jax.Array:
    """Distributed EXISTS semi-join + count in two collective phases —
    the reference's plan shape exactly:

    1. lineitem row-sharded: each shard marks the order keys for which
       it holds a late item; ``psum`` merges the marks (combiner →
       shuffle → aggregator).
    2. orders row-sharded, the merged mark table REPLICATED — the
       broadcast-join build side (``BroadcastJoinBuildHTJobStage``) —
       and probed per shard; the per-priority counts psum again.
    """
    orders, li = tables["orders"], tables["lineitem"]
    n_pri = len(orders.dicts["o_orderpriority"])
    n_okey = key_space(li, "l_orderkey")
    a, b = date_to_int(d0), date_to_int(d1)

    marks = sharded_key_marks(
        mesh, axis, li["l_orderkey"], n_okey,
        extra_cols={"l_commitdate": li["l_commitdate"],
                    "l_receiptdate": li["l_receiptdate"]},
        mask_fn=lambda valid, c: c["l_commitdate"] < c["l_receiptdate"])

    def count_local(valid, o, marks_rep):
        has_late = valid & probe_marks(marks_rep, o["o_orderkey"], n_okey)
        in_q = (o["o_orderdate"] >= a) & (o["o_orderdate"] < b)
        return K.segment_count(o["o_orderpriority"], n_pri,
                               has_late & in_q)

    return sharded_query(
        count_local, mesh, axis,
        {k: orders.cols[k] for k in
         ("o_orderkey", "o_orderdate", "o_orderpriority")},
        replicated=(marks,))


# ------------------------------------------------------------------ Q12
def sharded_q12(tables: Tables, mesh: Mesh, axis: str = "data",
                mode1: str = "MAIL", mode2: str = "SHIP",
                d0: str = "1994-01-01", d1: str = "1995-01-01") -> jax.Array:
    """Late-shipmode counts: lineitem sharded, orders replicated (the
    broadcast-join side feeding the priority lookup)."""
    li, orders = tables["lineitem"], tables["orders"]
    n_modes = len(li.dicts["l_shipmode"])
    jp_orders = PLN.plan_join(orders, "o_orderkey", li, "l_orderkey")
    m1, m2 = li.code("l_shipmode", mode1), li.code("l_shipmode", mode2)
    hi = _lut(orders.dicts["o_orderpriority"],
              lambda s: s in ("1-URGENT", "2-HIGH"))
    a, b = date_to_int(d0), date_to_int(d1)

    def local(valid, c, o_key, o_pri, hi_lut):
        mask = (valid & ((c["l_shipmode"] == m1) | (c["l_shipmode"] == m2))
                & (c["l_commitdate"] < c["l_receiptdate"])
                & (c["l_shipdate"] < c["l_commitdate"])
                & (c["l_receiptdate"] >= a) & (c["l_receiptdate"] < b))
        oidx, ohit = K.pk_fk_join(o_key, c["l_orderkey"], plan=jp_orders)
        mask = mask & ohit
        high = jnp.take(hi_lut, jnp.take(o_pri, oidx))
        return jnp.stack([
            K.segment_count(c["l_shipmode"], n_modes, mask & high),
            K.segment_count(c["l_shipmode"], n_modes, mask & ~high)])

    return sharded_query(
        local, mesh, axis,
        {k: li.cols[k] for k in ("l_orderkey", "l_shipmode", "l_shipdate",
                                 "l_commitdate", "l_receiptdate")},
        replicated=(orders["o_orderkey"], orders["o_orderpriority"], hi))


# ------------------------------------------------------------------ Q13
def sharded_q13(tables: Tables, mesh: Mesh, axis: str = "data",
                word1: str = "special",
                word2: str = "requests") -> jax.Array:
    """Per-customer order counts (n_cust,) int32, psum-merged; the
    histogram finishes on the merged vector exactly as the single-chip
    query does."""
    cust, orders = tables["customer"], tables["orders"]
    n_cust = key_space(cust, "c_custkey")
    if "o_comment" in orders.dicts:
        pat = re.compile(f"{re.escape(word1)}.*{re.escape(word2)}")
        keep_lut = _lut(orders.dicts["o_comment"],
                        lambda s: not pat.search(s))
        keep = jnp.take(keep_lut, orders["o_comment"])
    else:
        keep = jnp.ones((orders["o_custkey"].shape[0],), jnp.bool_)

    def local(valid, c):
        return K.segment_count(c["o_custkey"], n_cust, valid & c["keep"])

    counts = sharded_query(local, mesh, axis,
                           {"o_custkey": orders["o_custkey"],
                            "keep": keep})
    return jnp.take(counts, cust["c_custkey"])  # per-customer, zeros kept


# ------------------------------------------------------------------ Q14
def sharded_q14(tables: Tables, mesh: Mesh, axis: str = "data",
                d0: str = "1995-09-01",
                d1: str = "1995-10-01") -> jax.Array:
    """(promo_revenue, total_revenue): lineitem sharded, part replicated."""
    li, part = tables["lineitem"], tables["part"]
    jp_part = PLN.plan_join(part, "p_partkey", li, "l_partkey")
    promo = _lut(part.dicts["p_type"], lambda s: s.startswith("PROMO"))
    a, b = date_to_int(d0), date_to_int(d1)

    def local(valid, c, p_key, p_type, promo_lut):
        mask = valid & (c["l_shipdate"] >= a) & (c["l_shipdate"] < b)
        pidx, phit = K.pk_fk_join(p_key, c["l_partkey"], plan=jp_part)
        mask = mask & phit
        rev = jnp.where(mask, c["l_extendedprice"] * (1.0 - c["l_discount"]),
                        0.0)
        is_promo = jnp.take(promo_lut, jnp.take(p_type, pidx))
        return jnp.stack([jnp.sum(jnp.where(is_promo, rev, 0.0)),
                          jnp.sum(rev)])

    return sharded_query(
        local, mesh, axis,
        {k: li.cols[k] for k in ("l_partkey", "l_shipdate",
                                 "l_extendedprice", "l_discount")},
        replicated=(part["p_partkey"], part["p_type"], promo))


# ------------------------------------------------------------------ Q17
def sharded_q17(tables: Tables, mesh: Mesh, axis: str = "data",
                brand: str = "Brand#23",
                container: str = "MED BOX") -> jax.Array:
    """Small-quantity revenue, two phases: (1) per-part qty sums+counts
    psum (the global avg needs every shard's rows), (2) the avg table
    replicated back and the below-avg revenue summed per shard."""
    li, part = tables["lineitem"], tables["part"]
    jp_part = PLN.plan_join(part, "p_partkey", li, "l_partkey")
    n_part = jp_part.key_space
    brand_code = part.code("p_brand", brand)
    cont_code = part.code("p_container", container)
    li_cols = {k: li.cols[k] for k in ("l_partkey", "l_quantity",
                                       "l_extendedprice")}

    def phase1(valid, c, p_key, p_brand, p_cont):
        part_ok = (p_brand == brand_code) & (p_cont == cont_code)
        _, phit = K.pk_fk_join(p_key, c["l_partkey"], part_ok,
                               plan=jp_part)
        phit = phit & valid
        qty = c["l_quantity"].astype(jnp.float32)
        return (K.segment_sum(qty, c["l_partkey"], n_part, phit),
                K.segment_count(c["l_partkey"], n_part, phit))

    sums, cnts = sharded_query(
        phase1, mesh, axis, li_cols,
        replicated=(part["p_partkey"], part["p_brand"],
                    part["p_container"]))
    avg = sums / jnp.maximum(cnts, 1).astype(jnp.float32)

    def phase2(valid, c, p_key, p_brand, p_cont, avg_rep):
        part_ok = (p_brand == brand_code) & (p_cont == cont_code)
        _, phit = K.pk_fk_join(p_key, c["l_partkey"], part_ok,
                               plan=jp_part)
        phit = phit & valid
        qty = c["l_quantity"].astype(jnp.float32)
        small = phit & (qty < 0.2 * jnp.take(avg_rep, c["l_partkey"]))
        return jnp.sum(jnp.where(small, c["l_extendedprice"], 0.0))

    total = sharded_query(
        phase2, mesh, axis, li_cols,
        replicated=(part["p_partkey"], part["p_brand"],
                    part["p_container"], avg))
    return total / 7.0


# ------------------------------------------------------------------ Q22
def sharded_q22(tables: Tables, mesh: Mesh, axis: str = "data",
                prefixes: Tuple[str, ...] = ("13", "31", "23", "29", "30",
                                             "18", "17")) -> jax.Array:
    """Anti-join in three collective phases: order marks psum; global
    positive-balance average psum; per-prefix counts/sums psum with the
    marks replicated (broadcast anti-join probe)."""
    cust, orders = tables["customer"], tables["orders"]
    pref_list, code_lut = q22_code_lut(cust.dicts["c_phone"], prefixes)
    n_pref = len(pref_list)
    n_ckey = key_space(orders, "o_custkey")

    marks = sharded_key_marks(mesh, axis, orders["o_custkey"], n_ckey)

    cust_cols = {k: cust.cols[k] for k in ("c_custkey", "c_phone",
                                           "c_acctbal")}

    def avg_local(valid, c, lut):
        pref = jnp.take(lut, c["c_phone"])
        pos = valid & (pref >= 0) & (c["c_acctbal"] > 0)
        return (jnp.sum(jnp.where(pos, c["c_acctbal"], 0.0)),
                jnp.sum(pos.astype(jnp.int32)))

    bal_sum, bal_cnt = sharded_query(avg_local, mesh, axis, cust_cols,
                                     replicated=(code_lut,))
    avg = bal_sum / jnp.maximum(bal_cnt, 1).astype(jnp.float32)

    def count_local(valid, c, lut, marks_rep, avg_rep):
        pref = jnp.take(lut, c["c_phone"])
        has_orders = probe_marks(marks_rep, c["c_custkey"], n_ckey)
        sel = (valid & (pref >= 0) & (c["c_acctbal"] > avg_rep)
               & ~has_orders)
        seg = jnp.clip(pref, 0, n_pref - 1)
        return jnp.stack([
            K.segment_count(seg, n_pref, sel).astype(jnp.float32),
            K.segment_sum(c["c_acctbal"], seg, n_pref, sel)])

    return sharded_query(count_local, mesh, axis, cust_cols,
                         replicated=(code_lut, marks, avg))


# ------------------------------------------------------------------ Q03
def sharded_q03(tables: Tables, mesh: Mesh, axis: str = "data",
                segment: str = "BUILDING", date: str = "1995-03-15",
                k: int = 10):
    """Top unshipped orders: lineitem sharded, customer/orders
    replicated; per-order revenue psum-merged, top-k on the merged
    vector (small) outside the map."""
    cust, orders, li = tables["customer"], tables["orders"], tables["lineitem"]
    jp_orders = PLN.plan_join(orders, "o_orderkey", li, "l_orderkey")
    jp_cust = PLN.plan_join(cust, "c_custkey", orders, "o_custkey")
    n_orders = jp_orders.key_space
    seg_code = cust.code("c_mktsegment", segment)
    d = date_to_int(date)

    def local(valid, c, c_key, c_seg, o_key, o_cust, o_date):
        cust_ok = c_seg == seg_code
        _, chit = K.pk_fk_join(c_key, o_cust, cust_ok, plan=jp_cust)
        order_ok = chit & (o_date < d)
        oidx, ohit = K.pk_fk_join(o_key, c["l_orderkey"], order_ok,
                                  plan=jp_orders)
        li_ok = valid & ohit & (c["l_shipdate"] > d)
        rev = c["l_extendedprice"] * (1.0 - c["l_discount"])
        return K.segment_sum(rev, c["l_orderkey"], n_orders, li_ok)

    rev = sharded_query(
        local, mesh, axis,
        {q: li.cols[q] for q in ("l_orderkey", "l_shipdate",
                                 "l_extendedprice", "l_discount")},
        replicated=(cust["c_custkey"], cust["c_mktsegment"],
                    orders["o_orderkey"], orders["o_custkey"],
                    orders["o_orderdate"]))
    top_idx, top_ok = K.top_k_masked(rev, k, rev > 0)
    # order date lookup for the winners — the same guarded LUT probe as
    # every other join in this module
    oidx, ohit = K.pk_fk_join(orders["o_orderkey"], top_idx,
                              plan=jp_orders)
    odate = jnp.where(ohit, jnp.take(orders["o_orderdate"], oidx), 0)
    return top_idx, top_ok, odate, jnp.take(rev, top_idx)


# ------------------------------------------------------------------ Q02
def sharded_q02(tables: Tables, mesh: Mesh, axis: str = "data",
                size: int = 15, type_suffix: str = "BRUSHED",
                region: str = "EUROPE"):
    """Min-cost supplier per part: partsupp sharded, the entire
    dimension chain (part/supplier/nation/region) replicated; the
    per-part min cost merges with ``pmin`` (the aggregate's own
    combine), then a second pmin pass picks the global winner row."""
    part, ps = tables["part"], tables["partsupp"]
    sup, nat, reg = tables["supplier"], tables["nation"], tables["region"]
    jp_part = PLN.plan_join(part, "p_partkey", ps, "ps_partkey")
    jp_sup = PLN.plan_join(sup, "s_suppkey", ps, "ps_suppkey")
    jp_nat = PLN.plan_join(nat, "n_nationkey", sup, "s_nationkey")
    jp_reg = PLN.plan_join(reg, "r_regionkey", nat, "n_regionkey")
    n_part = jp_part.key_space
    type_ok = _lut(part.dicts["p_type"], lambda s: s.endswith(type_suffix))
    region_code = reg.code("r_name", region)
    ps_cols = {q: ps.cols[q] for q in ("ps_partkey", "ps_suppkey",
                                       "ps_supplycost")}
    dims = (part["p_partkey"], part["p_size"], part["p_type"],
            sup["s_suppkey"], sup["s_nationkey"],
            nat["n_nationkey"], nat["n_regionkey"],
            reg["r_regionkey"], reg["r_name"], type_ok)

    def valid_mask(valid, c, p_key, p_size, p_type, s_key, s_nat, n_key,
                   n_regk, r_key, r_name, tok):
        part_ok = (p_size == size) & jnp.take(tok, p_type)
        _, phit = K.pk_fk_join(p_key, c["ps_partkey"], part_ok,
                               plan=jp_part)
        nidx, nhit = K.pk_fk_join(n_key, s_nat, plan=jp_nat)
        sup_region = jnp.take(n_regk, nidx)
        ridx, rhit = K.pk_fk_join(r_key, sup_region, plan=jp_reg)
        in_region = nhit & rhit & (jnp.take(r_name, ridx) == region_code)
        _, shit = K.pk_fk_join(s_key, c["ps_suppkey"], in_region,
                               plan=jp_sup)
        return valid & phit & shit

    def phase1(valid, c, *dims_r):
        ok = valid_mask(valid, c, *dims_r)
        return K.segment_min(c["ps_supplycost"], c["ps_partkey"], n_part,
                             ok)

    cost_min = sharded_query(phase1, mesh, axis, ps_cols,
                             replicated=dims, combine=jax.lax.pmin)

    def phase2(valid, c, *args):
        *dims_r, cmin = args
        ok = valid_mask(valid, c, *dims_r)
        at_min = ok & (c["ps_supplycost"] == jnp.take(cmin,
                                                      c["ps_partkey"]))
        # global row ids travel as a fact column so winner correctness
        # does not depend on shard_fact_columns' internal row layout
        return K.segment_min(c["row_id"], c["ps_partkey"], n_part, at_min)

    winner = sharded_query(
        phase2, mesh, axis,
        {**ps_cols,
         "row_id": jnp.arange(ps.num_rows, dtype=jnp.int32)},
        replicated=dims + (cost_min,), combine=jax.lax.pmin)
    return winner, cost_min
