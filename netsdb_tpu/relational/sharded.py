"""Mesh-distributed relational execution — the reference's multi-node
query plan, re-expressed as shardings + collectives.

The reference scales queries by partitioning sets across workers and
running the same pipeline on each node's partition, with two data
movements (SURVEY §2.6):

- **local pre-aggregation + hash-repartition shuffle**: each node's
  ``CombinerProcessor`` folds its partition, then partial aggregates
  stream to the owning node where ``AggregationProcessor`` merges them
  (``src/queryExecution/headers/CombinerProcessor.h:20``,
  ``PipelineStage.cc:1215-1516``). TPU form: row-shard the fact table
  over a mesh axis, run the SAME per-shard kernels as the single-chip
  engine, and ``psum`` the fixed-shape partial aggregates over ICI —
  the shuffle is one collective.
- **broadcast join**: the small side is replicated to every node as a
  ``SharedHashSet`` (``BroadcastJoinBuildHTJobStage``,
  ``HermesExecutionServer.cc:172-369``). TPU form: dimension-table
  columns replicated in the shard_map (``P(None)``); each shard probes
  its rows against the full build LUT locally.

LAYERING (round 5): this module is the shard_map KERNEL layer
(``sharded_query`` and friends, consumed by ``relational.shuffle``)
plus thin mesh wrappers ``sharded_qXX`` over the ONE set of query
decompositions in :mod:`netsdb_tpu.relational.folds` — the same
FoldSpecs the paged/streamed engine runs, here in whole-table form
under jit with sharded inputs (XLA inserts the collectives). The
user-facing distribution surface is the SET API — create the sets with
a Placement and run ``relational.dag.suite_sink_for`` (aggregate form)
or ``relational.shuffle.q03_row_sink_for`` (row-output form). Call
these functions directly only when you hold raw arrays and a mesh
(benchmarks, library composition) — application code should not
hand-shard.

Row padding: a sharded axis must divide the device count, so fact
columns are padded and a validity mask rides along (the mask approach
every tensor op in this framework uses).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from netsdb_tpu.relational import kernels as K
from netsdb_tpu.relational.queries import Tables


def shard_fact_columns(cols: Dict[str, jnp.ndarray], n_shards: int,
                       ) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """Pad each column to a multiple of ``n_shards`` and return the
    validity mask (False on padding rows) — the dispatcher's
    round-robin row partitioning (``PartitionPolicy.h:29``) with the
    remainder handled by masking instead of ragged partitions."""
    n = next(iter(cols.values())).shape[0]
    padded = -(-n // n_shards) * n_shards
    out = {}
    for name, c in cols.items():
        pad = padded - n
        out[name] = jnp.pad(c, (0, pad)) if pad else c
    valid = jnp.arange(padded) < n
    return out, valid


def sharded_query(local_kernel: Callable[..., jax.Array], mesh: Mesh,
                  axis: str, fact: Dict[str, jnp.ndarray],
                  replicated: Sequence[jax.Array] = (),
                  combine: Optional[Callable] = None) -> jax.Array:
    """Run ``local_kernel(valid, fact_cols..., replicated...)``
    per shard and combine its fixed-shape partial aggregate over
    ``axis`` (default ``psum``; pass ``jax.lax.pmin``/``pmax`` for
    min/max merges — the reference's AggregationProcessor runs the
    aggregate's own combine the same way).

    ``local_kernel`` must return per-shard PARTIAL aggregates whose
    combine over shards is the global answer. The result may be a
    pytree (e.g. ``(sums, counts)``) — each leaf is combined.
    """
    n_shards = mesh.shape[axis]
    fact_p, valid = shard_fact_columns(fact, n_shards)
    names = sorted(fact_p)
    combine = combine or jax.lax.psum

    def body(valid_s, *args):
        k = len(names)
        cols = dict(zip(names, args[:k]))
        rep = args[k:k + len(replicated)]
        partial = local_kernel(valid_s, cols, *rep)
        return jax.tree_util.tree_map(lambda x: combine(x, axis), partial)

    fn = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(axis),) + (P(axis),) * len(names)
        + (P(),) * len(replicated),
        out_specs=P(),
    )
    return fn(valid, *[fact_p[n] for n in names], *replicated)


def sharded_key_marks(mesh: Mesh, axis: str, key_col: jnp.ndarray,
                      n_keys: int,
                      row_mask: Optional[jnp.ndarray] = None,
                      extra_cols: Optional[Dict[str, jnp.ndarray]] = None,
                      mask_fn: Optional[Callable] = None) -> jax.Array:
    """0/1 existence marks per key, psum-merged over shards — the
    build-HT half of a distributed semi/anti-join (Q04's late-order
    set, Q22's has-orders set). ``mask_fn(valid, cols)`` may narrow
    which rows mark (cols include ``key`` plus ``extra_cols``)."""
    fact = {"key": key_col}
    if row_mask is not None:
        fact["row_mask"] = row_mask
    fact.update(extra_cols or {})

    def local(valid, c):
        m = valid if row_mask is None else (valid & c["row_mask"])
        if mask_fn is not None:
            m = m & mask_fn(valid, c)
        return jnp.minimum(K.segment_count(c["key"], n_keys, m), 1)

    return sharded_query(local, mesh, axis, fact)


def probe_marks(marks: jnp.ndarray, keys: jnp.ndarray,
                n_keys: int) -> jnp.ndarray:
    """Per-row membership against a psum-merged mark table (the probe
    half; out-of-space keys are non-members)."""
    in_space = (keys >= 0) & (keys < n_keys)
    return in_space & (jnp.take(marks, jnp.clip(keys, 0, n_keys - 1)) > 0)


# ---------------------------------------------------- the query cores
# ONE code path per query core (round 5): every sharded_qXX is a thin
# wrapper over the SAME FoldSpec the set-API DAG streams for paged sets
# (``relational.folds``) — the whole-table form of the fold runs under
# jit with the fact columns mesh-sharded and the dimensions replicated,
# and XLA inserts the psum the retired hand-written shard_map bodies
# (round 1-4) expressed explicitly. The kernel layer above
# (``sharded_query`` etc.) remains for library composition
# (``relational.shuffle``); query logic lives in the folds only.

_FOLD_JIT: Dict[tuple, Callable] = {}


def fold_sharded(qname: str, tables: Tables, mesh: Mesh,
                 axis: str = "data", **params):
    """Run one suite query's fold distributed over ``(mesh, axis)``:
    fact rows sharded, dimensions replicated (broadcast join), output
    the fold's finalize tuple — matching the resident engine's suite
    outputs elementwise (the equivalence the paged tests pin)."""
    from jax.sharding import NamedSharding

    from netsdb_tpu.relational.dag import _QUERY_TABLES
    from netsdb_tpu.relational.folds import SUITE_FOLDS
    from netsdb_tpu.relational.stats import analyze_table
    from netsdb_tpu.relational.table import ColumnTable

    names = _QUERY_TABLES[qname]
    fact, builder = SUITE_FOLDS[qname]
    cap = {n: analyze_table(tables[n]) for n in names}
    dicts = {n: tables[n].dicts for n in names}
    nrows = {n: tables[n].num_rows for n in names}
    fold = builder(cap, dicts, nrows, **params)

    div = mesh.shape[axis]
    placed = {}
    for n in names:
        t = tables[n]
        if n == fact:
            pad = (-t.num_rows) % div
            sh = NamedSharding(mesh, P(axis))
            cols = {}
            for k, c in t.cols.items():
                c = jnp.asarray(c)
                if pad:
                    c = jnp.concatenate(
                        [c, jnp.zeros((pad,) + c.shape[1:], c.dtype)])
                cols[k] = jax.device_put(c, sh)
            nr = t.num_rows + pad
            # global row ids: folds arbitrate ties on them (q02)
            cols.setdefault("_rowid", jax.device_put(
                jnp.arange(nr, dtype=jnp.int32), sh))
            valid = t.mask()
            if pad:
                valid = jnp.concatenate(
                    [valid, jnp.zeros((pad,), jnp.bool_)])
            placed[n] = ColumnTable(cols, t.dicts,
                                    jax.device_put(valid, sh))
        else:
            sh = NamedSharding(mesh, P())
            cols = {k: jax.device_put(jnp.asarray(c), sh)
                    for k, c in t.cols.items()}
            valid = (jax.device_put(t.mask(), sh)
                     if t.valid is not None else None)
            placed[n] = ColumnTable(cols, t.dicts, valid)

    fact_t = placed[fact]
    resident = tuple(placed[n] for n in names if n != fact)
    # one jitted runner per equivalent fold build (same query, params,
    # row counts, key spaces AND dictionary contents ⇒ deterministic
    # identical closures): fold builders bake dict-derived codes/LUTs
    # into the closure (q12's shipmode codes, q13's comment regex LUT),
    # so two datasets differing only in dict encoding must not share a
    # runner — same hazard class as the transformer DAG's mesh tag.
    # Jitting per call would recompile every time (env gotcha).
    import hashlib

    dict_tag = hashlib.blake2s(repr(sorted(
        (n, c, tuple(d)) for n in names
        for c, d in tables[n].dicts.items())).encode()).hexdigest()[:12]
    key = (qname, repr(sorted(params.items())),
           tuple(sorted(nrows.items())),
           tuple(sorted((n, c, s.key_space)
                        for n, cs in cap.items()
                        for c, s in cs.items())), dict_tag)
    fn = _FOLD_JIT.get(key)
    if fn is None:
        fn = jax.jit(
            lambda ft, res, _fold=fold: _fold.whole(ft, *res))
        if len(_FOLD_JIT) > 64:
            _FOLD_JIT.clear()  # unbounded-growth guard
        _FOLD_JIT[key] = fn
    return fn(fact_t, resident)


def _wrap(qname: str):
    def runner(tables: Tables, mesh: Mesh, axis: str = "data",
               **params):
        return fold_sharded(qname, tables, mesh, axis, **params)

    runner.__name__ = f"sharded_{qname}"
    runner.__doc__ = (
        f"Thin wrapper: {qname} distributed over a mesh via "
        f"``fold_sharded`` — same fold as the paged/streamed path "
        f"(``relational.folds.fold_{qname}``), whole-table under jit.")
    return runner


sharded_q01 = _wrap("q01")
sharded_q02 = _wrap("q02")
sharded_q03 = _wrap("q03")
sharded_q04 = _wrap("q04")
sharded_q06 = _wrap("q06")
sharded_q12 = _wrap("q12")
sharded_q13 = _wrap("q13")
sharded_q14 = _wrap("q14")
sharded_q17 = _wrap("q17")
sharded_q22 = _wrap("q22")
