"""Mesh-distributed relational execution — the reference's multi-node
query plan, re-expressed as shardings + collectives.

The reference scales queries by partitioning sets across workers and
running the same pipeline on each node's partition, with two data
movements (SURVEY §2.6):

- **local pre-aggregation + hash-repartition shuffle**: each node's
  ``CombinerProcessor`` folds its partition, then partial aggregates
  stream to the owning node where ``AggregationProcessor`` merges them
  (``src/queryExecution/headers/CombinerProcessor.h:20``,
  ``PipelineStage.cc:1215-1516``). TPU form: row-shard the fact table
  over a mesh axis, run the SAME per-shard kernels as the single-chip
  engine, and ``psum`` the fixed-shape partial aggregates over ICI —
  the shuffle is one collective.
- **broadcast join**: the small side is replicated to every node as a
  ``SharedHashSet`` (``BroadcastJoinBuildHTJobStage``,
  ``HermesExecutionServer.cc:172-369``). TPU form: dimension-table
  columns replicated in the shard_map (``P(None)``); each shard probes
  its rows against the full build LUT locally.

Any query whose result is a fixed-shape aggregate distributes this way;
``sharded_query`` wraps a local kernel accordingly, and the concrete
``sharded_q01`` / ``sharded_q06`` / ``sharded_q04`` bodies below REUSE
the single-chip query cores' logic so the distributed answers are
bit-comparable to the local engine (tests cross-check both on the
virtual 8-device CPU mesh).

Row padding: a sharded axis must divide the device count, so fact
columns are padded and a validity mask rides along (the mask approach
every tensor op in this framework uses).
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from netsdb_tpu.relational import kernels as K
from netsdb_tpu.relational.queries import Tables, key_space
from netsdb_tpu.relational.table import date_to_int


def shard_fact_columns(cols: Dict[str, jnp.ndarray], n_shards: int,
                       ) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """Pad each column to a multiple of ``n_shards`` and return the
    validity mask (False on padding rows) — the dispatcher's
    round-robin row partitioning (``PartitionPolicy.h:29``) with the
    remainder handled by masking instead of ragged partitions."""
    n = next(iter(cols.values())).shape[0]
    padded = -(-n // n_shards) * n_shards
    out = {}
    for name, c in cols.items():
        pad = padded - n
        out[name] = jnp.pad(c, (0, pad)) if pad else c
    valid = jnp.arange(padded) < n
    return out, valid


def sharded_query(local_kernel: Callable[..., jax.Array], mesh: Mesh,
                  axis: str, fact: Dict[str, jnp.ndarray],
                  replicated: Sequence[jax.Array] = (),
                  scalars: Sequence = ()) -> jax.Array:
    """Run ``local_kernel(valid, fact_cols..., replicated..., scalars...)``
    per shard and psum its fixed-shape aggregate over ``axis``.

    ``local_kernel`` must return per-shard PARTIAL aggregates whose sum
    over shards is the global answer (the combiner/aggregator contract).
    """
    n_shards = mesh.shape[axis]
    fact_p, valid = shard_fact_columns(fact, n_shards)
    names = sorted(fact_p)

    def body(valid_s, *args):
        k = len(names)
        cols = dict(zip(names, args[:k]))
        rep = args[k:k + len(replicated)]
        partial = local_kernel(valid_s, cols, *rep, *scalars)
        return jax.lax.psum(partial, axis)

    fn = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(axis),) + (P(axis),) * len(names)
        + (P(),) * len(replicated),
        out_specs=P(),
    )
    return fn(valid, *[fact_p[n] for n in names], *replicated)


# ------------------------------------------------------------------ Q01
_Q01_COLS = ("l_shipdate", "l_returnflag", "l_linestatus", "l_quantity",
             "l_extendedprice", "l_discount", "l_tax")


def _q01_local(valid, li, n_groups: int, n_ls: int, delta: int):
    mask = valid & (li["l_shipdate"] <= delta)
    seg = li["l_returnflag"] * n_ls + li["l_linestatus"]
    qty = li["l_quantity"].astype(jnp.float32)
    disc_price = li["l_extendedprice"] * (1.0 - li["l_discount"])
    charge = disc_price * (1.0 + li["l_tax"])
    rows = [K.segment_sum(v, seg, n_groups, mask)
            for v in (qty, li["l_extendedprice"], disc_price, charge,
                      li["l_discount"])]
    # counts stay int32 through the psum — f32 partials would absorb
    # +1 increments past 2^24 rows/group (same guard as the single-chip
    # core, queries.py _q01_core)
    return jnp.stack(rows), K.segment_count(seg, n_groups, mask)


def sharded_q01(tables: Tables, mesh: Mesh, axis: str = "data",
                delta_date: str = "1998-09-02"):
    """Distributed pricing-summary → (sums (5, groups) f32,
    counts (groups,) i32), identical to the single-chip core's."""
    li = tables["lineitem"]
    n_ls = len(li.dicts["l_linestatus"])
    n_groups = len(li.dicts["l_returnflag"]) * n_ls
    kern = functools.partial(_q01_local, n_groups=n_groups, n_ls=n_ls,
                             delta=date_to_int(delta_date))
    return sharded_query(kern, mesh, axis,
                         {k: li.cols[k] for k in _Q01_COLS})


# ------------------------------------------------------------------ Q06
def _q06_local(valid, li, a, b, disc, qty):
    c = li
    mask = (valid & (c["l_shipdate"] >= a) & (c["l_shipdate"] < b)
            & (c["l_discount"] >= disc - 0.011)
            & (c["l_discount"] <= disc + 0.011)
            & (c["l_quantity"] < qty))
    return jnp.sum(jnp.where(mask, c["l_extendedprice"] * c["l_discount"],
                             0.0))


def sharded_q06(tables: Tables, mesh: Mesh, axis: str = "data",
                d0: str = "1994-01-01", d1: str = "1995-01-01",
                discount: float = 0.06, quantity: int = 24) -> jax.Array:
    li = tables["lineitem"]
    cols = {k: li.cols[k] for k in ("l_shipdate", "l_discount",
                                    "l_quantity", "l_extendedprice")}

    def local(valid, c):
        return _q06_local(valid, c, date_to_int(d0), date_to_int(d1),
                          discount, quantity)

    return sharded_query(local, mesh, axis, cols)


# ------------------------------------------------------------------ Q04
def sharded_q04(tables: Tables, mesh: Mesh, axis: str = "data",
                d0: str = "1993-07-01",
                d1: str = "1993-10-01") -> jax.Array:
    """Distributed EXISTS semi-join + count in two collective phases —
    the reference's plan shape exactly:

    1. lineitem row-sharded: each shard marks the order keys for which
       it holds a late item; ``psum`` merges the marks (combiner →
       shuffle → aggregator).
    2. orders row-sharded, the merged mark table REPLICATED — the
       broadcast-join build side (``BroadcastJoinBuildHTJobStage``) —
       and probed per shard; the per-priority counts psum again.
    """
    orders, li = tables["orders"], tables["lineitem"]
    n_pri = len(orders.dicts["o_orderpriority"])
    n_okey = key_space(li, "l_orderkey")
    a, b = date_to_int(d0), date_to_int(d1)

    def mark_local(valid, c):
        late = valid & (c["l_commitdate"] < c["l_receiptdate"])
        marks = K.segment_count(c["l_orderkey"], n_okey, late)
        return jnp.minimum(marks, 1)

    marks = sharded_query(
        mark_local, mesh, axis,
        {k: li.cols[k] for k in
         ("l_orderkey", "l_commitdate", "l_receiptdate")})

    def count_local(valid, o, marks_rep):
        ok = o["o_orderkey"]
        in_space = (ok >= 0) & (ok < n_okey)
        has_late = valid & in_space & (
            jnp.take(marks_rep, jnp.clip(ok, 0, n_okey - 1)) > 0)
        in_q = (o["o_orderdate"] >= a) & (o["o_orderdate"] < b)
        return K.segment_count(o["o_orderpriority"], n_pri,
                               has_late & in_q)

    return sharded_query(
        count_local, mesh, axis,
        {k: orders.cols[k] for k in
         ("o_orderkey", "o_orderdate", "o_orderpriority")},
        replicated=(marks,))
