"""Per-table / per-column statistics — the planner's input.

The reference collects per-set statistics (page/byte/tuple counts) on
demand and feeds them to its greedy physical planner
(``src/queryPlanning/headers/TCAPAnalyzer.h:20-40``; ``Statistics``
populated via ``StorageCollectStats`` in
``src/serverFunctionalities/source/QuerySchedulerServer.cc:1332-1420``).
Here the analogous facts are column-level — row count, key min/max,
distinct count — because the physical choices they drive are different:
LUT-vs-sort joins, dense-vs-scatter segment reductions, and
broadcast-vs-repartition distribution (see
:mod:`netsdb_tpu.relational.planner`).

Stats are computed host-side in one numpy pass per column and cached
PER TABLE INSTANCE, so the cost is paid once at ingest (loaders call
:func:`analyze_table`) and every subsequent plan decision is a dict
lookup. Instance keying is load-bearing: anything shared by schema
equality (e.g. the pytree aux key) aliases across DISTINCT same-schema
tables — jax reuses output treedefs, so one table's key_space would
silently apply to another's data. Traced clones therefore start with
EMPTY caches; code that needs stats inside a jit trace must inject
host-computed stats explicitly (`inject_stats`, used by the set-API DAG
builders in relational/dag.py — a cold cache under trace would need a
host read of a traced array and raise).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, Optional

import numpy as np

from netsdb_tpu.relational.table import ColumnTable




@dataclasses.dataclass(frozen=True)
class ColumnStats:
    """Host-side facts about one integer column (keys, codes, dates).

    ``n_distinct`` is -1 until someone asks for it: the distinct count
    needs an O(N log N) sort that no current plan decision consumes, so
    ingest pays only the O(N) min/max pass
    (``column_stats(..., distinct=True)`` fills it in).
    """

    n_rows: int
    min_val: int
    max_val: int
    n_distinct: int = -1

    @property
    def key_space(self) -> int:
        """Static dense-key bound: every value lies in
        ``[0, key_space)``. Clamped to >= 1 so downstream static shapes
        stay positive for empty or all-negative columns — and so a
        merged record whose ``max_val`` was widened past its own rows
        (planner.plan_join covering the probe column) keeps the widened
        bound."""
        return max(self.max_val + 1, 1)

    @property
    def density(self) -> float:
        """Fraction of the key space actually occupied — the signal that
        separates dense surrogate keys (dbgen: ~1.0) from sparse ids
        where a LUT would be mostly padding. Requires the distinct
        count to have been computed."""
        if self.n_distinct < 0:
            raise ValueError("distinct count not computed; use "
                             "column_stats(table, col, distinct=True)")
        return self.n_distinct / max(self.key_space, 1)


def analyze_array(arr, distinct: bool = False) -> ColumnStats:
    """Min/max in one O(N) host pass; the sort-based distinct count
    only when asked for."""
    a = np.asarray(arr)
    if a.size == 0:
        return ColumnStats(0, 0, -1, 0 if distinct else -1)
    if a.dtype.kind == "b":
        a = a.astype(np.int32)
    nd = int(np.unique(a).size) if distinct else -1
    return ColumnStats(int(a.size), int(a.min()), int(a.max()), nd)


_CACHE_ATTR = "_column_stats"


def _stats_cache(table: ColumnTable) -> Dict[str, ColumnStats]:
    cache = table.__dict__.get(_CACHE_ATTR)
    if cache is None:
        cache = {}
        table.__dict__[_CACHE_ATTR] = cache
    return cache


def inject_stats(table: ColumnTable,
                 stats: Dict[str, ColumnStats]) -> ColumnTable:
    """Seed ``table``'s per-instance cache with host-precomputed stats —
    the bridge that lets planner decisions run inside a jit trace (where
    computing stats from traced arrays is impossible). Returns the same
    table."""
    _stats_cache(table).update(stats)
    return table


def column_stats(table: ColumnTable, col: str,
                 distinct: bool = False) -> ColumnStats:
    """Stats for ``table.cols[col]``, cached on the table instance (the
    same idiom the old per-query ``key_space`` helper used, widened to
    the full stats record)."""
    cache = _stats_cache(table)
    if col not in cache or (distinct and cache[col].n_distinct < 0):
        cache[col] = analyze_array(table[col], distinct)
    return cache[col]


def key_space(table: ColumnTable, col: str) -> int:
    """Static key-space bound (max key + 1) — the group-cardinality
    metadata every segment reduction needs."""
    return column_stats(table, col).key_space


def analyze_table(table: ColumnTable,
                  cols: Optional[Iterable[str]] = None) -> Dict[str, ColumnStats]:
    """Warm the stats cache at ingest. ``cols`` defaults to every
    integer column (keys, dictionary codes, dates); float measure
    columns carry no planning signal and are skipped."""
    if cols is None:
        cols = [n for n, c in table.cols.items()
                if np.asarray(c).dtype.kind in "ib"]
    return {c: column_stats(table, c) for c in cols}
