"""Measured physical-strategy thresholds, keyed on device kind.

Round-1 froze two crossovers as constants measured once on TPU v5e
(`_DENSE_SEGMENT_LIMIT = 64`, LUT-always joins). This module makes the
thresholds a three-level lookup:

1. a persisted autotune file (``$NETSDB_TPU_HOME/autotune.json``),
   written by :func:`autotune` after actually measuring the crossovers
   on the live backend;
2. a built-in table of measured values per device kind;
3. conservative defaults.

The reference's analogue is the compile-time ``-D`` knobs in
``SConstruct:67-100`` (batch sizes, join ratios) that its authors
measured on their cluster and froze; here the same numbers re-measure
themselves per device generation.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

# Measured tables. "segment_dense_limit": largest group count where the
# broadcast-compare dense segment reduce still beats the scatter-add
# (measured on Q01-shaped data: 6M rows). "join_lut_factor": LUT join
# wins while key_space <= factor * (build_rows + probe_rows); beyond it
# the LUT is mostly padding and the sort path's N log N beats the
# key_space-sized init+scatter. "join_lut_max_bytes": absolute LUT size
# cap so a pathological key range cannot OOM HBM.
_MEASURED: Dict[str, Dict[str, float]] = {
    # v5e, measured via `python -m netsdb_tpu autotune` on the live
    # chip with SCAN-SLOPE timing (r3 — the r2 values 64/128 were
    # per-dispatch walls, which the ~65 ms controller RTT reduced to
    # noise): scatter serializes on colliding updates (55.7 ms vs
    # below-noise dense at 12 groups / 6M rows), and dense keeps
    # winning through the whole measured range (G<=512 @1M rows). The
    # LUT join wins through a 64x-sparse key space (gathers stream;
    # sort+searchsorted serializes); the byte cap retires it beyond.
    "TPU v5 lite": {"segment_dense_limit": 512, "join_lut_factor": 64.0,
                    "join_lut_max_bytes": 1 << 28,
                    # grid one-hot count beats scatter up to 256k groups
                    # (0.67 vs 6.9 ms at 50k; linear in G/128 — kernels.py)
                    "count_grid_limit": float(1 << 18)},
    # CPU (tests, virtual mesh): XLA's CPU scatter is cheap and the
    # dense O(N*G) pass loses earlier.
    "cpu": {"segment_dense_limit": 32, "join_lut_factor": 16.0,
            "join_lut_max_bytes": 1 << 27,
            "count_grid_limit": float(1 << 18),
            "device_hbm_bytes": 4 * 1024**3},
}

_DEFAULTS: Dict[str, float] = {
    "segment_dense_limit": 64,
    "count_grid_limit": float(1 << 18),
    "join_lut_factor": 32.0,
    "join_lut_max_bytes": 1 << 28,
    # fallback per-device memory for broadcast-vs-repartition planning
    # when the backend reports no bytes_limit (v5e HBM; the cpu entry
    # models a test-mesh host share)
    "device_hbm_bytes": 16 * 1024**3,
}

_cache: Dict[str, Dict[str, float]] = {}


def _tuning_path() -> str:
    root = os.environ.get("NETSDB_TPU_HOME", "/tmp/netsdb_tpu")
    return os.path.join(root, "autotune.json")


def device_kind() -> str:
    try:
        return jax.devices()[0].device_kind
    except Exception:  # no backend yet (import-time use)
        return "cpu"


def _load(kind: str) -> Dict[str, float]:
    if kind in _cache:
        return _cache[kind]
    table = dict(_DEFAULTS)
    table.update(_MEASURED.get(kind, {}))
    try:
        with open(_tuning_path()) as f:
            persisted = json.load(f)
        table.update(persisted.get(kind, {}))
    except (OSError, ValueError):
        pass
    _cache[kind] = table
    return table


def get(name: str, kind: Optional[str] = None) -> float:
    """Threshold ``name`` for ``kind`` (default: the live backend)."""
    return _load(kind or device_kind())[name]


def set_override(name: str, value: float,
                 kind: Optional[str] = None) -> None:
    """In-process override (tests force strategies through this).

    Thresholds are read at TRACE time, so already-compiled programs
    have the old choice baked in — clear jit caches so the next call
    re-traces under the new threshold.
    """
    kind = kind or device_kind()
    _load(kind)[name] = value
    jax.clear_caches()


def clear_overrides() -> None:
    _cache.clear()
    jax.clear_caches()


# --------------------------------------------------------------- autotune

def _scan_time(step_fn, lo: int = 8, hi: int = 64) -> Optional[float]:
    """Seconds/iteration of ``step_fn(carry) -> carry`` folded inside
    ONE jitted lax.scan — the tunnel-safe timing protocol every bench
    in this repo uses (`utils.timing.scan_slope_seconds`): loop lengths
    escalate until the delta clears controller noise. ``step_fn`` must
    thread a live int32 carry through the computation so XLA can
    neither hoist nor DCE the body. Returns None when the kernel is
    below timing noise even after escalation."""
    import functools

    from netsdb_tpu.utils.timing import device_seconds

    @functools.partial(jax.jit, static_argnums=(0,))
    def loop(n):
        def step(c, _):
            return step_fn(c), None

        c, _ = jax.lax.scan(step, jnp.zeros((), jnp.int32), None, length=n)
        return c

    # autotune sweeps dozens of (strategy, size) points and each
    # escalation recompiles two loop lengths — cap the retries and
    # accept a coarser (but still RTT-immune) delta than the benches use.
    # NEVER time per-dispatch walls here: over the axon tunnel each
    # dispatch pays ~65 ms RTT and the r2 autotune recorded pure noise.
    return device_seconds(lambda n: float(loop(n)), lo=lo, hi=hi,
                          repeats=2, max_escalations=2,
                          min_delta_seconds=0.1)


def _faster(ta: Optional[float], tb: Optional[float]) -> Optional[bool]:
    """Compare two `_scan_time` results where None means BELOW NOISE —
    i.e. faster than the measurement floor, which must count as a WIN,
    not a failure (treating it as undecidable once made autotune record
    'dense never wins' for the strategy that was too fast to time).
    Returns None only when both sides are below noise (undecidable)."""
    if ta is None and tb is None:
        return None
    if ta is None:
        return True
    if tb is None:
        return False
    return ta <= tb


def measure_segment_crossover(n_rows: int = 1 << 20,
                              candidates=(8, 16, 32, 64, 128, 256, 512),
                              ) -> Optional[int]:
    """Measure the dense-vs-scatter segment-sum crossover on the live
    backend: the largest G where dense still wins. 0 means dense LOST
    at the smallest candidate; None means nothing was decidable (both
    strategies below timing noise everywhere) — callers must keep their
    prior threshold rather than record "never wins"."""
    from netsdb_tpu.relational import kernels as K

    rng = np.random.default_rng(0)
    vals = jnp.asarray(rng.standard_normal(n_rows).astype(np.float32))
    best = 0
    for g in candidates:
        seg = jnp.asarray(rng.integers(0, g, n_rows).astype(np.int32))

        def step(method):
            def run(c):
                s_ = (seg + c) % g  # carry-coupled: no hoisting
                out = K.segment_sum(vals, s_, g, method=method)
                return (c + out[0].astype(jnp.int32)) % 127

            return run

        win = _faster(_scan_time(step("dense")), _scan_time(step("scatter")))
        if win is None:
            if best == 0:
                return None  # nothing decidable: caller keeps prior value
            break  # keep the last decidable crossover
        if win:
            best = g
        else:
            break
    # best == 0 ⇒ dense LOST at the smallest G (decided): record "never"
    return best


def measure_count_grid_crossover(n_rows: int = 1 << 20,
                                 candidates=(1 << 12, 1 << 14, 1 << 16,
                                             1 << 18, 1 << 20),
                                 ) -> Optional[int]:
    """Measure the grid-vs-scatter segment-count crossover: the largest
    group count where the one-hot int8 MXU grid formulation still beats
    the scatter-add (`kernels.count_grid`)."""
    from netsdb_tpu.relational import kernels as K

    rng = np.random.default_rng(0)
    best = 0
    for g in candidates:
        seg = jnp.asarray(rng.integers(0, g, n_rows).astype(np.int32))

        def step(method):
            def run(c):
                s_ = (seg + c) % g  # carry-coupled: no hoisting
                out = K.segment_count(s_, g, method=method)
                return (c + out[0]) % 127

            return run

        win = _faster(_scan_time(step("grid")), _scan_time(step("scatter")))
        if win is None:
            if best == 0:
                return None  # undecidable ≠ "grid never wins"
            break
        if win:
            best = g
        else:
            break
    return best


def measure_join_crossover(n_build: int = 1 << 17, n_probe: int = 1 << 19,
                           factors=(2, 4, 8, 16, 32, 64, 128),
                           ) -> Optional[float]:
    """Measure the LUT-vs-sort join crossover: the largest
    ``key_space / (build + probe)`` ratio where the LUT still wins."""
    from netsdb_tpu.relational import kernels as K
    from netsdb_tpu.relational.planner import JoinPlan

    rng = np.random.default_rng(0)
    # never probe a LUT bigger than the byte cap the planner enforces —
    # the probe itself must not OOM measuring the guard
    cap = _load(device_kind())["join_lut_max_bytes"]
    factors = [f for f in factors
               if f * (n_build + n_probe) * 4 <= cap]
    if not factors:  # every probe would breach the cap: LUT never legal
        return 0.0
    best = 0.0  # stays 0 if the LUT never wins, recording "sort always"
    for f in factors:
        ks = int(f * (n_build + n_probe))
        # unique build keys WITHOUT materializing a ks-sized permutation
        # (Generator.choice(replace=False) builds one — ~670 MB at the
        # largest factor): oversample with replacement, dedup, trim.
        # Only uniqueness among the n_build keys matters.
        draw = rng.integers(0, ks, int(n_build * 1.3) + 16)
        pk_u = np.unique(draw)[:n_build]
        while len(pk_u) < n_build:  # sparse-collision retry, ~never loops
            extra = rng.integers(0, ks, n_build)
            pk_u = np.unique(np.concatenate([pk_u, extra]))[:n_build]
        pk = jnp.asarray(rng.permutation(pk_u).astype(np.int32))
        fk = jnp.asarray(rng.integers(0, ks, n_probe).astype(np.int32))

        def step(strategy, ks=ks, pk=pk, fk=fk):
            def run(c):
                probe = (fk + c) % ks  # perturb the probe side only:
                # build keys must stay unique
                idx, hit = K.pk_fk_join(pk, probe,
                                        plan=JoinPlan(strategy, ks))
                return (c + idx[0] + hit[0].astype(jnp.int32)) % 127

            return run

        win = _faster(_scan_time(step("lut")), _scan_time(step("sort")))
        if win is None:
            if best == 0.0:
                return None  # undecidable ≠ "LUT never wins"
            break
        if win:
            best = float(f)
        else:
            break
    return best


def autotune(persist: bool = True) -> Dict[str, float]:
    """Measure both crossovers on the live backend and (optionally)
    persist them for this device kind. Run via
    ``python -m netsdb_tpu autotune``."""
    kind = device_kind()
    raw = {
        "segment_dense_limit": measure_segment_crossover(),
        "count_grid_limit": measure_count_grid_crossover(),
        "join_lut_factor": measure_join_crossover(),
    }
    # None = the sweep was undecidable (everything below timing noise):
    # keep the existing threshold instead of persisting "never wins"
    measured = {k: float(v) for k, v in raw.items() if v is not None}
    measured["join_lut_max_bytes"] = float(_load(kind)["join_lut_max_bytes"])
    _load(kind).update(measured)
    jax.clear_caches()  # compiled programs have the old thresholds baked in
    if persist:
        path = _tuning_path()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            data = {}
        data[kind] = measured
        with open(path, "w") as f:
            json.dump(data, f, indent=2, sort_keys=True)
    return measured
