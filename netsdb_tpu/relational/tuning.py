"""Measured physical-strategy thresholds, keyed on device kind.

Round-1 froze two crossovers as constants measured once on TPU v5e
(`_DENSE_SEGMENT_LIMIT = 64`, LUT-always joins). This module makes the
thresholds a three-level lookup:

1. a persisted autotune file (``$NETSDB_TPU_HOME/autotune.json``),
   written by :func:`autotune` after actually measuring the crossovers
   on the live backend;
2. a built-in table of measured values per device kind;
3. conservative defaults.

The reference's analogue is the compile-time ``-D`` knobs in
``SConstruct:67-100`` (batch sizes, join ratios) that its authors
measured on their cluster and froze; here the same numbers re-measure
themselves per device generation.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

# Measured tables. "segment_dense_limit": largest group count where the
# broadcast-compare dense segment reduce still beats the scatter-add
# (measured on Q01-shaped data: 6M rows). "join_lut_factor": LUT join
# wins while key_space <= factor * (build_rows + probe_rows); beyond it
# the LUT is mostly padding and the sort path's N log N beats the
# key_space-sized init+scatter. "join_lut_max_bytes": absolute LUT size
# cap so a pathological key range cannot OOM HBM.
_MEASURED: Dict[str, Dict[str, float]] = {
    # v5e, measured via `python -m netsdb_tpu autotune` on the live
    # chip: scatter serializes on colliding updates (52.6 ms vs ~2 ms at
    # 12 groups, BASELINE.md); dense loses past G=64 at 1M rows. The
    # LUT join keeps winning through a 128x-sparse key space (gathers
    # stream; sort+searchsorted serializes), so only the byte cap
    # retires it.
    "TPU v5 lite": {"segment_dense_limit": 64, "join_lut_factor": 128.0,
                    "join_lut_max_bytes": 1 << 28},
    # CPU (tests, virtual mesh): XLA's CPU scatter is cheap and the
    # dense O(N*G) pass loses earlier.
    "cpu": {"segment_dense_limit": 32, "join_lut_factor": 16.0,
            "join_lut_max_bytes": 1 << 27,
            "device_hbm_bytes": 4 * 1024**3},
}

_DEFAULTS: Dict[str, float] = {
    "segment_dense_limit": 64,
    "join_lut_factor": 32.0,
    "join_lut_max_bytes": 1 << 28,
    # fallback per-device memory for broadcast-vs-repartition planning
    # when the backend reports no bytes_limit (v5e HBM; the cpu entry
    # models a test-mesh host share)
    "device_hbm_bytes": 16 * 1024**3,
}

_cache: Dict[str, Dict[str, float]] = {}


def _tuning_path() -> str:
    root = os.environ.get("NETSDB_TPU_HOME", "/tmp/netsdb_tpu")
    return os.path.join(root, "autotune.json")


def device_kind() -> str:
    try:
        return jax.devices()[0].device_kind
    except Exception:  # no backend yet (import-time use)
        return "cpu"


def _load(kind: str) -> Dict[str, float]:
    if kind in _cache:
        return _cache[kind]
    table = dict(_DEFAULTS)
    table.update(_MEASURED.get(kind, {}))
    try:
        with open(_tuning_path()) as f:
            persisted = json.load(f)
        table.update(persisted.get(kind, {}))
    except (OSError, ValueError):
        pass
    _cache[kind] = table
    return table


def get(name: str, kind: Optional[str] = None) -> float:
    """Threshold ``name`` for ``kind`` (default: the live backend)."""
    return _load(kind or device_kind())[name]


def set_override(name: str, value: float,
                 kind: Optional[str] = None) -> None:
    """In-process override (tests force strategies through this).

    Thresholds are read at TRACE time, so already-compiled programs
    have the old choice baked in — clear jit caches so the next call
    re-traces under the new threshold.
    """
    kind = kind or device_kind()
    _load(kind)[name] = value
    jax.clear_caches()


def clear_overrides() -> None:
    _cache.clear()
    jax.clear_caches()


# --------------------------------------------------------------- autotune

def _time_fn(fn, *args, reps: int = 5) -> float:
    jax.block_until_ready(fn(*args))  # compile + warm
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def measure_segment_crossover(n_rows: int = 1 << 20,
                              candidates=(8, 16, 32, 64, 128, 256, 512),
                              ) -> int:
    """Measure the dense-vs-scatter segment-sum crossover on the live
    backend: the largest G where dense still wins."""
    from netsdb_tpu.relational import kernels as K

    rng = np.random.default_rng(0)
    vals = jnp.asarray(rng.standard_normal(n_rows).astype(np.float32))
    best = 0
    for g in candidates:
        seg = jnp.asarray(rng.integers(0, g, n_rows).astype(np.int32))

        def dense(v, s, g=g):
            return K.segment_sum(v, s, g, method="dense")

        def scatter(v, s, g=g):
            return K.segment_sum(v, s, g, method="scatter")

        td = _time_fn(jax.jit(dense), vals, seg)
        ts = _time_fn(jax.jit(scatter), vals, seg)
        if td <= ts:
            best = g
        else:
            break
    # best == 0 ⇒ dense lost even at the smallest G: record "never"
    return best


def measure_join_crossover(n_build: int = 1 << 17, n_probe: int = 1 << 19,
                           factors=(2, 4, 8, 16, 32, 64, 128),
                           ) -> float:
    """Measure the LUT-vs-sort join crossover: the largest
    ``key_space / (build + probe)`` ratio where the LUT still wins."""
    from netsdb_tpu.relational import kernels as K
    from netsdb_tpu.relational.planner import JoinPlan

    rng = np.random.default_rng(0)
    # never probe a LUT bigger than the byte cap the planner enforces —
    # the probe itself must not OOM measuring the guard
    cap = _load(device_kind())["join_lut_max_bytes"]
    factors = [f for f in factors
               if f * (n_build + n_probe) * 4 <= cap]
    if not factors:  # every probe would breach the cap: LUT never legal
        return 0.0
    best = 0.0  # stays 0 if the LUT never wins, recording "sort always"
    for f in factors:
        ks = int(f * (n_build + n_probe))
        # unique build keys WITHOUT materializing a ks-sized permutation
        # (Generator.choice(replace=False) builds one — ~670 MB at the
        # largest factor): oversample with replacement, dedup, trim.
        # Only uniqueness among the n_build keys matters.
        draw = rng.integers(0, ks, int(n_build * 1.3) + 16)
        pk_u = np.unique(draw)[:n_build]
        while len(pk_u) < n_build:  # sparse-collision retry, ~never loops
            extra = rng.integers(0, ks, n_build)
            pk_u = np.unique(np.concatenate([pk_u, extra]))[:n_build]
        pk = jnp.asarray(rng.permutation(pk_u).astype(np.int32))
        fk = jnp.asarray(rng.integers(0, ks, n_probe).astype(np.int32))

        def lut(p, q, ks=ks):
            return K.pk_fk_join(p, q, plan=JoinPlan("lut", ks))

        def srt(p, q, ks=ks):
            return K.pk_fk_join(p, q, plan=JoinPlan("sort", ks))

        tl = _time_fn(jax.jit(lut), pk, fk)
        tsort = _time_fn(jax.jit(srt), pk, fk)
        if tl <= tsort:
            best = float(f)
        else:
            break
    return best


def autotune(persist: bool = True) -> Dict[str, float]:
    """Measure both crossovers on the live backend and (optionally)
    persist them for this device kind. Run via
    ``python -m netsdb_tpu autotune``."""
    kind = device_kind()
    measured = {
        "segment_dense_limit": float(measure_segment_crossover()),
        "join_lut_factor": measure_join_crossover(),
        "join_lut_max_bytes": float(_load(kind)["join_lut_max_bytes"]),
    }
    _load(kind).update(measured)
    jax.clear_caches()  # compiled programs have the old thresholds baked in
    if persist:
        path = _tuning_path()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            data = {}
        data[kind] = measured
        with open(path, "w") as f:
            json.dump(data, f, indent=2, sort_keys=True)
    return measured
