"""Streamable decompositions of the TPC-H suite cores.

The reference's pipelines are *born* decomposed: every stage consumes
its source page-by-page and merges per-page partial state through a
combiner (``src/storage/headers/PageScanner.h:25-34``,
``HermesExecutionServer.cc:49-93``), so out-of-core execution is not a
special mode — it is the only mode. The round-3 engine here had the
opposite shape: whole-table jitted cores (``relational/queries.py``)
with three bespoke out-of-core drivers bolted on. This module closes
that gap: each suite query gets a :class:`~netsdb_tpu.plan.fold.FoldSpec`
— init / per-chunk step / finalize — over its FACT table stream, with
the dimension tables resident, so the SAME ``suite_sink_for`` DAG runs
whole-table or streamed depending only on how the fact set was created
(``create_set(storage="paged")``).

Semantics discipline: every step first folds validity into columns with
``relational.dag._fold_mask`` (invalid rows → -1 keys / 0 measures,
dropped everywhere by the kernels' orphan-key rule) and then runs the
SAME expressions as the whole-table core, accumulating instead of
reducing once — so streamed results match the resident engine to float
summation order. Join plans come from ingest-time statistics
(:func:`plan_from_captured`), never from streamed arrays: the planner
consumes summaries collected where the data lives
(``client.analyze_set``; ref ``StorageCollectStats``,
``PangeaStorageServer.h:48``).

Multi-pass note: Q17 needs the per-part average *before* it can price
small-quantity rows, so its fold has two passes (aggregate pass, probe
pass) — the stream is read twice, the reference's
aggregate-stage-then-probe-stage sequence.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import jax.numpy as jnp
import numpy as np

from netsdb_tpu.plan.fold import FoldSpec, single_pass
from netsdb_tpu.relational import kernels as K
from netsdb_tpu.relational.planner import JoinPlan, plan_join_from_stats
from netsdb_tpu.relational.stats import ColumnStats
from netsdb_tpu.relational.table import date_to_int

Captured = Dict[str, Dict[str, ColumnStats]]


def plan_from_captured(cap: Captured, nrows: Dict[str, int],
                       build_tab: str, build_col: str,
                       probe_tab: str, probe_col: str) -> JoinPlan:
    """`planner.plan_join` computed from captured summaries instead of
    live tables — same widening rule (the plan's key_space bounds both
    columns, so orphan foreign keys stay in range)."""
    bs = cap[build_tab][build_col]
    ks = max(bs.key_space, cap[probe_tab][probe_col].key_space)
    merged = ColumnStats(bs.n_rows, bs.min_val, max(bs.max_val, ks - 1),
                         bs.n_distinct)
    return plan_join_from_stats(merged, nrows[probe_tab])


def _fm(t):
    from netsdb_tpu.relational.dag import _fold_mask

    return _fold_mask(t)


def _lut(dictionary, pred) -> jnp.ndarray:
    return jnp.asarray(np.fromiter((pred(s) for s in dictionary),
                                   np.bool_, len(dictionary)))


# ---------------------------------------------------------------- Q01
def fold_q01(cap: Captured, dicts, nrows, *, delta_date: str = "1998-09-02"
             ) -> FoldSpec:
    from netsdb_tpu.relational.queries import _q01_fold

    delta = date_to_int(delta_date)

    def shape(src):
        n_ls = len(src.dicts["l_linestatus"])
        return n_ls, len(src.dicts["l_returnflag"]) * n_ls

    def init(prev, src):
        n_ls, g = shape(src)
        return (jnp.zeros((5, g), jnp.float32), jnp.zeros((g,), jnp.int32))

    def step(st, t):
        t = _fm(t)
        n_ls, g = shape(t)
        s, c = _q01_fold(g, n_ls, t["l_returnflag"], t["l_linestatus"],
                         t["l_quantity"], t["l_extendedprice"],
                         t["l_discount"], t["l_tax"],
                         t["l_shipdate"] <= delta)
        return (st[0] + s, st[1] + c)

    return single_pass(init, step, lambda st, src: (st[0], st[1]))


# ---------------------------------------------------------------- Q06
def fold_q06(cap: Captured, dicts, nrows, *, d0: str = "1994-01-01",
             d1: str = "1995-01-01", disc: float = 0.06, qty: int = 24
             ) -> FoldSpec:
    a, b = date_to_int(d0), date_to_int(d1)

    def step(st, t):
        t = _fm(t)
        ship, discount = t["l_shipdate"], t["l_discount"]
        mask = ((ship >= a) & (ship < b)
                & (discount >= disc - 0.011) & (discount <= disc + 0.011)
                & (t["l_quantity"] < qty))
        return st + jnp.sum(jnp.where(mask, t["l_extendedprice"] * discount,
                                      0.0))

    return single_pass(lambda prev, src: jnp.zeros((), jnp.float32),
                       step, lambda st, src: (st,))


# ---------------------------------------------------------------- Q02
def fold_q02(cap: Captured, dicts, nrows, *, size: int = 15,
             type_suffix: str = "BRUSHED", region: str = "EUROPE"
             ) -> FoldSpec:
    """Min-cost supplier per part over a STREAMED partsupp. The
    cross-chunk arbitration is lexicographic on (cost, global row id):
    the chunk winner's ``_rowid`` breaks cost ties exactly like the
    whole-table core's first-row-wins ``segment_min`` over row
    indices, so streamed and resident outputs match array-for-array.
    The supplier-side region chain is loop-invariant — computed once
    in init and carried in state."""
    jp_part = plan_from_captured(cap, nrows, "part", "p_partkey",
                                 "partsupp", "ps_partkey")
    jp_sup = plan_from_captured(cap, nrows, "supplier", "s_suppkey",
                                "partsupp", "ps_suppkey")
    jp_nat = plan_from_captured(cap, nrows, "nation", "n_nationkey",
                                "supplier", "s_nationkey")
    jp_reg = plan_from_captured(cap, nrows, "region", "r_regionkey",
                                "nation", "n_regionkey")
    n_part = jp_part.key_space
    IMAX = jnp.iinfo(jnp.int32).max

    def init(prev, src, part, sup, nat, reg):
        part, sup, nat, reg = _fm(part), _fm(sup), _fm(nat), _fm(reg)
        type_ok = _lut(part.dicts["p_type"],
                       lambda s: s.endswith(type_suffix))
        part_ok = ((part["p_size"] == size)
                   & jnp.take(type_ok, part["p_type"]))
        nidx, nhit = K.pk_fk_join(nat["n_nationkey"], sup["s_nationkey"],
                                  plan=jp_nat)
        sup_region = jnp.take(nat["n_regionkey"], nidx)
        ridx, rhit = K.pk_fk_join(reg["r_regionkey"], sup_region,
                                  plan=jp_reg)
        sup_ok = (nhit & rhit
                  & (jnp.take(reg["r_name"], ridx)
                     == reg.code("r_name", region)))
        return {"has": jnp.zeros((n_part,), jnp.bool_),
                "cmin": jnp.full((n_part,), jnp.inf, jnp.float32),
                "rowid": jnp.full((n_part,), IMAX, jnp.int32),
                "sup_row": jnp.zeros((n_part,), jnp.int32),
                "part_ok": part_ok, "sup_ok": sup_ok, "nidx": nidx}

    def step(st, t, part, sup, nat, reg):
        t, part, sup = _fm(t), _fm(part), _fm(sup)
        ps_part, ps_cost = t["ps_partkey"], t["ps_supplycost"]
        _, phit = K.pk_fk_join(part["p_partkey"], ps_part,
                               st["part_ok"], plan=jp_part)
        sidx, shit = K.pk_fk_join(sup["s_suppkey"], t["ps_suppkey"],
                                  st["sup_ok"], plan=jp_sup)
        valid = phit & shit
        cmin_c = K.segment_min(ps_cost, ps_part, n_part, valid)
        at_min = valid & (ps_cost == jnp.take(cmin_c, ps_part))
        local = jnp.arange(ps_part.shape[0], dtype=jnp.int32)
        win_local = K.segment_min(local, ps_part, n_part, at_min)
        has_c = win_local < IMAX
        wl = jnp.clip(win_local, 0, ps_part.shape[0] - 1)
        rowid_c = jnp.where(has_c, jnp.take(t["_rowid"], wl), IMAX)
        sup_row_c = jnp.where(has_c, jnp.take(sidx, wl), 0)
        better = has_c & (~st["has"] | (cmin_c < st["cmin"])
                          | ((cmin_c == st["cmin"])
                             & (rowid_c < st["rowid"])))
        return {"has": st["has"] | has_c,
                "cmin": jnp.where(better, cmin_c, st["cmin"]),
                "rowid": jnp.where(better, rowid_c, st["rowid"]),
                "sup_row": jnp.where(better, sup_row_c, st["sup_row"]),
                "part_ok": st["part_ok"], "sup_ok": st["sup_ok"],
                "nidx": st["nidx"]}

    def fin(st, src, part, sup, nat, reg):
        has = st["has"]
        nat_row = jnp.where(has, jnp.take(st["nidx"], st["sup_row"]), 0)
        ints = jnp.stack([has.astype(jnp.int32), st["sup_row"], nat_row])
        return (ints, st["cmin"])

    def merge(a, b):
        # grace partitions hold DISJOINT part-key sets (both sides
        # hashed on partkey), so per-key winners never conflict: where
        # b found a winner, take b, else a
        ai, ac = a
        bi, bc = b
        bhas = bi[0] > 0
        return (jnp.where(bhas[None, :], bi, ai),
                jnp.where(bhas, bc, ac))

    return single_pass(init, step, fin, merge,
                       probe_key="ps_partkey", build_key="p_partkey",
                       probe_columns=("ps_suppkey", "ps_supplycost"))


# ---------------------------------------------------------------- Q03
def fold_q03(cap: Captured, dicts, nrows, *, segment: str = "BUILDING",
             date: str = "1995-03-15", k: int = 10) -> FoldSpec:
    """Streamed lineitem against resident customer/orders; state is the
    core's own (key_space,) revenue/odate accumulators, so finalize's
    top-k packs the identical raw output."""
    d = date_to_int(date)
    jp_cust = plan_from_captured(cap, nrows, "customer", "c_custkey",
                                 "orders", "o_custkey")
    jp_orders = plan_from_captured(cap, nrows, "orders", "o_orderkey",
                                   "lineitem", "l_orderkey")
    n_orders = jp_orders.key_space

    def init(prev, src, cust, orders):
        # the customer⋈orders qualification is loop-invariant: compute
        # it ONCE here and carry it in the fold state, instead of
        # rebuilding the customer LUT inside every chunk's step
        cust, orders = _fm(cust), _fm(orders)
        cust_ok = cust["c_mktsegment"] == cust.code("c_mktsegment",
                                                    segment)
        _, chit = K.pk_fk_join(cust["c_custkey"], orders["o_custkey"],
                               cust_ok, plan=jp_cust)
        order_ok = chit & (orders["o_orderdate"] < d)
        return (jnp.zeros((n_orders,), jnp.float32),
                jnp.full((n_orders,), jnp.iinfo(jnp.int32).max, jnp.int32),
                order_ok)

    def step(st, t, cust, orders):
        t, orders = _fm(t), _fm(orders)
        rev_acc, od_acc, order_ok = st
        l_okey = t["l_orderkey"]
        oidx, ohit = K.pk_fk_join(orders["o_orderkey"], l_okey,
                                  order_ok, plan=jp_orders)
        li_ok = ohit & (t["l_shipdate"] > d)
        rev_acc = rev_acc + K.segment_sum(
            t["l_extendedprice"] * (1.0 - t["l_discount"]), l_okey,
            n_orders, li_ok)
        od_acc = jnp.minimum(od_acc, K.segment_min(
            jnp.take(orders["o_orderdate"], oidx), l_okey, n_orders, li_ok))
        return (rev_acc, od_acc, order_ok)

    def fin(st, src, cust, orders):
        rev, odate = st[0], st[1]
        top_idx, top_ok = K.top_k_masked(rev, k, rev > 0)
        ints = jnp.stack([top_idx, top_ok.astype(jnp.int32),
                          jnp.take(odate, top_idx)])
        return (ints, jnp.take(rev, top_idx))

    return single_pass(init, step, fin)


# ---------------------------------------------------------------- Q04
def fold_q04(cap: Captured, dicts, nrows, *, d0: str = "1993-07-01",
             d1: str = "1993-10-01") -> FoldSpec:
    a, b = date_to_int(d0), date_to_int(d1)
    jp_li = plan_from_captured(cap, nrows, "lineitem", "l_orderkey",
                               "orders", "o_orderkey")

    def init(prev, src, orders):
        return jnp.zeros((nrows["orders"],), jnp.bool_)

    def step(st, t, orders):
        t, orders = _fm(t), _fm(orders)
        late = t["l_commitdate"] < t["l_receiptdate"]
        return st | K.member(t["l_orderkey"], orders["o_orderkey"], late,
                             plan=jp_li).astype(jnp.bool_)

    def fin(st, src, orders):
        orders = _fm(orders)
        n_pri = len(orders.dicts["o_orderpriority"])
        o_date = orders["o_orderdate"]
        in_q = (o_date >= a) & (o_date < b)
        return (K.segment_count(orders["o_orderpriority"], n_pri,
                                st & in_q),)

    return single_pass(init, step, fin)


# ---------------------------------------------------------------- Q12
def fold_q12(cap: Captured, dicts, nrows, *, mode1: str = "MAIL",
             mode2: str = "SHIP", d0: str = "1994-01-01",
             d1: str = "1995-01-01") -> FoldSpec:
    a, b = date_to_int(d0), date_to_int(d1)
    jp_orders = plan_from_captured(cap, nrows, "orders", "o_orderkey",
                                   "lineitem", "l_orderkey")
    li_dicts = dicts["lineitem"]
    n_modes = len(li_dicts["l_shipmode"])
    m1 = li_dicts["l_shipmode"].index(mode1)
    m2 = li_dicts["l_shipmode"].index(mode2)

    def init(prev, src, orders):
        return jnp.zeros((2, n_modes), jnp.int32)

    def step(st, t, orders):
        t, orders = _fm(t), _fm(orders)
        l_mode = t["l_shipmode"]
        mask = (((l_mode == m1) | (l_mode == m2))
                & (t["l_commitdate"] < t["l_receiptdate"])
                & (t["l_shipdate"] < t["l_commitdate"])
                & (t["l_receiptdate"] >= a) & (t["l_receiptdate"] < b))
        oidx, ohit = K.pk_fk_join(orders["o_orderkey"], t["l_orderkey"],
                                  plan=jp_orders)
        mask = mask & ohit
        hi = _lut(orders.dicts["o_orderpriority"],
                  lambda s: s in ("1-URGENT", "2-HIGH"))
        high = jnp.take(hi, jnp.take(orders["o_orderpriority"], oidx))
        return st + jnp.stack(
            [K.segment_count(l_mode, n_modes, mask & high),
             K.segment_count(l_mode, n_modes, mask & ~high)])

    # paged-orders build: partitions hold disjoint order-key ranges, so
    # per-mode counts simply add across partition outputs
    return single_pass(init, step, lambda st, src, orders: (st,),
                       merge=lambda a, b: (a[0] + b[0],),
                       probe_key="l_orderkey", build_key="o_orderkey",
                       probe_columns=("l_shipmode", "l_shipdate",
                                      "l_commitdate", "l_receiptdate"))


# ---------------------------------------------------------------- Q13
_Q13_CAP = 256  # mirrors queries._Q13_CAP (orders/customer is spec-fixed)


def fold_q13(cap: Captured, dicts, nrows, *, word1: str = "special",
             word2: str = "requests") -> FoldSpec:
    import re

    n_cust = cap["customer"]["c_custkey"].key_space
    pat = re.compile(f"{re.escape(word1)}.*{re.escape(word2)}")

    def init(prev, src, cust):
        return jnp.zeros((n_cust,), jnp.int32)

    def step(st, t, cust):
        t = _fm(t)
        if "o_comment" in t.dicts:
            keep = jnp.take(_lut(t.dicts["o_comment"],
                                 lambda s: not pat.search(s)),
                            t["o_comment"])
        else:
            keep = t["o_custkey"] >= 0
        return st + K.segment_count(t["o_custkey"], n_cust, keep)

    def fin(st, src, cust):
        cust = _fm(cust)
        c_key = cust["c_custkey"]
        real = c_key >= 0  # grace partitions pad with invalid rows
        # (key -1 after the mask fold); they must not count as
        # zero-order customers
        per_cust = jnp.where(real, jnp.take(st, c_key), 0)
        hist = K.bincount_masked(jnp.minimum(per_cust, _Q13_CAP - 1),
                                 _Q13_CAP, real)
        return (hist, jnp.max(per_cust, initial=0))

    # paged-customer build: every customer lives in exactly ONE key
    # partition and its orders are routed to the same one, so the
    # count histograms add (zero-order customers contribute to hist[0]
    # in their own partition) and the max is the max of maxes
    return single_pass(init, step, fin,
                       merge=lambda a, b: (a[0] + b[0],
                                           jnp.maximum(a[1], b[1])),
                       probe_key="o_custkey", build_key="c_custkey",
                       probe_columns=("o_comment",))


# ---------------------------------------------------------------- Q14
def fold_q14(cap: Captured, dicts, nrows, *, d0: str = "1995-09-01",
             d1: str = "1995-10-01") -> FoldSpec:
    a, b = date_to_int(d0), date_to_int(d1)
    jp_part = plan_from_captured(cap, nrows, "part", "p_partkey",
                                 "lineitem", "l_partkey")

    def init(prev, src, part):
        return jnp.zeros((2,), jnp.float32)

    def step(st, t, part):
        t, part = _fm(t), _fm(part)
        mask = (t["l_shipdate"] >= a) & (t["l_shipdate"] < b)
        pidx, phit = K.pk_fk_join(part["p_partkey"], t["l_partkey"],
                                  plan=jp_part)
        mask = mask & phit
        rev = jnp.where(mask, t["l_extendedprice"] * (1.0 - t["l_discount"]),
                        0.0)
        promo = _lut(part.dicts["p_type"], lambda s: s.startswith("PROMO"))
        is_promo = jnp.take(promo, jnp.take(part["p_type"], pidx))
        return st + jnp.stack([jnp.sum(jnp.where(is_promo, rev, 0.0)),
                               jnp.sum(rev)])

    return single_pass(init, step, lambda st, src, part: (st,))


# ---------------------------------------------------------------- Q17
def fold_q17(cap: Captured, dicts, nrows, *, brand: str = "Brand#23",
             container: str = "MED BOX") -> FoldSpec:
    jp_part = plan_from_captured(cap, nrows, "part", "p_partkey",
                                 "lineitem", "l_partkey")
    ks = jp_part.key_space

    def part_hit(t, part):
        part_ok = ((part["p_brand"] == part.code("p_brand", brand))
                   & (part["p_container"] == part.code("p_container",
                                                       container)))
        _, phit = K.pk_fk_join(part["p_partkey"], t["l_partkey"], part_ok,
                               plan=jp_part)
        return phit

    # pass 1: per-part quantity sum/count over qualifying rows
    def init1(prev, src, part):
        return (jnp.zeros((ks,), jnp.float32), jnp.zeros((ks,), jnp.int32))

    def step1(st, t, part):
        t, part = _fm(t), _fm(part)
        phit = part_hit(t, part)
        qty = t["l_quantity"].astype(jnp.float32)
        return (st[0] + K.segment_sum(qty, t["l_partkey"], ks, phit),
                st[1] + K.segment_count(t["l_partkey"], ks, phit))

    # pass 2: price rows below 0.2 * the pass-1 average
    def init2(prev, src, part):
        s, c = prev
        avg = s / jnp.maximum(c, 1).astype(jnp.float32)
        return (avg, jnp.zeros((), jnp.float32))

    def step2(st, t, part):
        t, part = _fm(t), _fm(part)
        avg, acc = st
        phit = part_hit(t, part)
        qty = t["l_quantity"].astype(jnp.float32)
        small = phit & (qty < 0.2 * jnp.take(avg, t["l_partkey"]))
        return (avg, acc + jnp.sum(jnp.where(small, t["l_extendedprice"],
                                             0.0)))

    def fin(st, src, part):
        return (st[1] / 7.0,)

    return FoldSpec(((init1, step1), (init2, step2)), fin)


# ---------------------------------------------------------------- Q22
def fold_q22(cap: Captured, dicts, nrows,
             *, prefixes: Tuple[str, ...] = ("13", "31", "23", "29", "30",
                                             "18", "17")) -> FoldSpec:
    from netsdb_tpu.relational.queries import q22_code_lut

    jp_cust = plan_from_captured(cap, nrows, "orders", "o_custkey",
                                 "customer", "c_custkey")
    n_pref = len(sorted(set(prefixes)))

    def init(prev, src, cust):
        return jnp.zeros((nrows["customer"],), jnp.bool_)

    def step(st, t, cust):
        t, cust = _fm(t), _fm(cust)
        return st | K.member(t["o_custkey"], cust["c_custkey"],
                             t["o_custkey"] >= 0,
                             plan=jp_cust).astype(jnp.bool_)

    def fin(st, src, cust):
        cust = _fm(cust)
        _, code_lut = q22_code_lut(cust.dicts["c_phone"], prefixes)
        pref = jnp.take(code_lut, cust["c_phone"])
        in_pref = pref >= 0
        c_bal = cust["c_acctbal"]
        pos = in_pref & (c_bal > 0)
        avg = (jnp.sum(jnp.where(pos, c_bal, 0.0))
               / jnp.maximum(jnp.sum(pos.astype(jnp.int32)), 1))
        sel = in_pref & (c_bal > avg) & ~st
        seg = jnp.clip(pref, 0, n_pref - 1)
        return (jnp.stack(
            [K.segment_count(seg, n_pref, sel).astype(jnp.float32),
             K.segment_sum(c_bal, seg, n_pref, sel)]),)

    return single_pass(init, step, fin)


# ---------------------------------------------------- registry
# qname -> (fact set name streamed when paged, fold builder). All ten
# suite queries decompose; fold-less consumers of a paged set (host
# DAGs, custom nodes) take the executor's materialize fallback.
SUITE_FOLDS: Dict[str, Tuple[str, Callable[..., FoldSpec]]] = {
    "q01": ("lineitem", fold_q01),
    "q02": ("partsupp", fold_q02),
    "q03": ("lineitem", fold_q03),
    "q04": ("lineitem", fold_q04),
    "q06": ("lineitem", fold_q06),
    "q12": ("lineitem", fold_q12),
    "q13": ("orders", fold_q13),
    "q14": ("lineitem", fold_q14),
    "q17": ("lineitem", fold_q17),
    "q22": ("orders", fold_q22),
}
