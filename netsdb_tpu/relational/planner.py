"""Statistics-driven physical planning for the columnar engine.

The reference's ``TCAPAnalyzer`` greedily picks sources and stage cuts
from runtime set statistics and re-plans after every stage
(``src/queryPlanning/headers/TCAPAnalyzer.h:20-40``,
``src/serverFunctionalities/source/QuerySchedulerServer.cc:1332-1420``).
On a single-controller JAX stack the stage-cutting half is absorbed by
XLA (stages = jit boundaries), but three physical choices remain that
XLA cannot make because they change the *algorithm*, not the schedule:

- **LUT vs sort equi-join** (:func:`plan_join`) — a dense lookup table
  is ~19x faster when keys are dense surrogate ints, but is mostly
  padding (and eventually HBM-prohibitive) for sparse key ranges;
- **dense vs scatter segment reduction** (:func:`segment_method`) —
  broadcast-compare wins for small group counts where TPU scatter-adds
  serialize, loses O(N*G) above the crossover;
- **broadcast vs repartition distribution** (:func:`plan_distribution`)
  — replicate the small join side to every shard, or all-to-all both
  sides by key hash.

Each chooser reads column statistics collected at ingest
(:mod:`netsdb_tpu.relational.stats`) and thresholds measured per device
kind (:mod:`netsdb_tpu.relational.tuning`), so the decisions follow the
data and the hardware instead of the round-1 hand-tuned call sites.

A :class:`JoinPlan` is a hashable NamedTuple so it rides through
``jax.jit`` static arguments — the physical choice is fixed at trace
time, exactly like the reference fixing a stage's algorithm before
shipping it to workers.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

from netsdb_tpu.relational import tuning
from netsdb_tpu.relational.stats import ColumnStats, column_stats
from netsdb_tpu.relational.table import ColumnTable


class JoinPlan(NamedTuple):
    """Physical equi-join choice.

    ``key_space`` is always the stats-derived dense bound (segment
    reductions keyed on the same column reuse it); ``strategy`` selects
    the join implementation: ``"lut"`` (scatter build / gather probe)
    or ``"sort"`` (argsort + searchsorted).
    """

    strategy: str
    key_space: int

    @property
    def is_lut(self) -> bool:
        return self.strategy == "lut"


def plan_join_from_stats(build: ColumnStats,
                         n_probe: int,
                         kind: Optional[str] = None) -> JoinPlan:
    """Cost-model core, exposed for tests: LUT wins while the key space
    is within ``join_lut_factor`` of the touched rows AND the LUT fits
    the byte cap; otherwise sort."""
    ks = build.key_space
    factor = tuning.get("join_lut_factor", kind)
    max_bytes = tuning.get("join_lut_max_bytes", kind)
    touched = build.n_rows + n_probe
    if ks <= factor * max(touched, 1) and ks * 4 <= max_bytes:
        return JoinPlan("lut", ks)
    return JoinPlan("sort", ks)


def plan_join(build: ColumnTable, build_col: str,
              probe: ColumnTable, probe_col: Optional[str] = None,
              kind: Optional[str] = None) -> JoinPlan:
    """Choose the physical join of ``build[build_col]`` (unique or
    representative keys) probed by ``probe[probe_col]``.

    The plan's ``key_space`` bounds BOTH columns (with ``probe_col``
    given), so a query reusing it as a segment-reduction cardinality
    over the foreign-key column stays in range even when the data has
    orphan foreign keys.
    """
    bs = column_stats(build, build_col)
    ks = bs.key_space
    if probe_col is not None:
        ks = max(ks, column_stats(probe, probe_col).key_space)
    merged = ColumnStats(bs.n_rows, bs.min_val, max(bs.max_val, ks - 1),
                         bs.n_distinct)
    return plan_join_from_stats(merged, probe.num_rows, kind)


def segment_method(num_segments: int, kind: Optional[str] = None) -> str:
    """``"dense"`` (broadcast-compare + column reduce) or ``"scatter"``
    (indexed add) for a ``num_segments``-group reduction."""
    limit = tuning.get("segment_dense_limit", kind)
    return "dense" if num_segments <= limit else "scatter"


def count_method(num_segments: int, kind: Optional[str] = None) -> str:
    """Strategy for a pure COUNT reduction, which has a third option:
    the grid one-hot int8 MXU formulation (`kernels.count_grid`) — exact
    for counts, and measured faster than the scatter-add through
    mid-range cardinalities (`count_grid_limit`, autotuned)."""
    if num_segments <= tuning.get("segment_dense_limit", kind):
        return "dense"
    if num_segments <= tuning.get("count_grid_limit", kind):
        return "grid"
    return "scatter"


class DistPlan(NamedTuple):
    """Distributed join-side placement: replicate the build side to all
    shards (``"broadcast"``) or hash-repartition both sides
    (``"partition"``)."""

    strategy: str


# Broadcast while the replicated build side stays under this fraction of
# per-device HBM (the reference's analogue: BroadcastJoinBuildHTJobStage
# is chosen for sides that fit one SharedHashSet,
# src/serverFunctionalities/source/HermesExecutionServer.cc:172-369).
_BROADCAST_HBM_FRACTION = 0.10


def device_memory_bytes() -> int:
    """Per-device memory for distribution planning: the live backend's
    own number when it reports one, else the per-device-kind table."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
        if stats and stats.get("bytes_limit"):
            return int(stats["bytes_limit"])
    except Exception:
        pass
    return int(tuning.get("device_hbm_bytes"))


def plan_distribution(build_bytes: int, n_devices: int,
                      device_bytes: Optional[int] = None,
                      ) -> DistPlan:
    """Broadcast-vs-repartition: replicating costs ``build_bytes`` on
    EVERY device plus one all-gather; repartitioning moves each row once
    but needs the all-to-all machinery. Broadcast wins while the build
    side is small relative to HBM (dimension tables); repartition when
    both sides are fact-scale."""
    if device_bytes is None:
        device_bytes = device_memory_bytes()
    if build_bytes <= _BROADCAST_HBM_FRACTION * device_bytes:
        return DistPlan("broadcast")
    return DistPlan("partition")
