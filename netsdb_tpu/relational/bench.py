"""TPC-H device benchmark: columnar queries at dbgen-like scale.

The reference's only published end-to-end numbers are TPC-H query
times on its CPU cluster (SURVEY.md §6 / BASELINE.md: Q01 13.4-17.9 s,
Q02 77-94 s, Q04 188-210 s, RUN_STAT traces in
``/root/reference/model-inference/../gen_trace.sql``). This module
generates SF-scaled columnar tables directly (dbgen row counts:
lineitem ≈ 6M·SF, orders = 1.5M·SF, customer = 150k·SF, part = 200k·SF)
and times the jitted columnar queries on the attached device.

Timing protocol (axon tunnel): scalar-pull sync, RTT-subtracted —
``jax.block_until_ready`` is not a reliable barrier over the tunnel.
"""

from __future__ import annotations

import time
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from netsdb_tpu.relational.queries import COLUMNAR_QUERIES, Tables
from netsdb_tpu.relational.table import ColumnTable

# reference-published wall times (seconds) — BASELINE.md §6
PUBLISHED = {"q01": 13.4, "q02": 77.4, "q04": 188.5}


def generate_columnar(sf: float = 0.1, seed: int = 0) -> Tables:
    """dbgen-shaped synthetic tables, built directly as columns (no row
    dicts — row generation at SF≥0.1 would dominate the benchmark).
    Distributions follow dbgen's ranges; string domains are the real
    TPC-H enumerations, dictionary-encoded. Covers all eight tables so
    every columnar query (incl. Q02's five-way join and Q22's
    anti-join) benches at dbgen scale: supplier 10k·SF, partsupp =
    4 suppliers per part, nation 25, region 5."""
    rng = np.random.default_rng(seed)
    n_li = int(6_000_000 * sf)
    n_ord = int(1_500_000 * sf)
    n_cust = int(150_000 * sf)
    n_part = int(200_000 * sf)
    n_sup = max(int(10_000 * sf), 1)

    def dates(n):
        return (rng.integers(1992, 1999, n) * 10000
                + rng.integers(1, 13, n) * 100
                + rng.integers(1, 29, n)).astype(np.int32)

    flags = ["A", "N", "R"]
    status = ["F", "O"]
    modes = ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"]
    prios = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
    segs = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]
    brands = sorted(f"Brand#{i}{j}" for i in range(1, 6) for j in range(1, 6))
    containers = sorted(["SM CASE", "MED BOX", "LG JAR", "WRAP PACK",
                         "JUMBO PKG"])
    types = sorted(["PROMO BURNISHED", "STANDARD POLISHED",
                    "ECONOMY ANODIZED", "PROMO PLATED", "MEDIUM BRUSHED"])

    commit = dates(n_li)
    lineitem = ColumnTable(
        cols={
            "l_orderkey": rng.integers(0, n_ord, n_li).astype(np.int32),
            "l_partkey": rng.integers(0, n_part, n_li).astype(np.int32),
            "l_quantity": rng.integers(1, 51, n_li).astype(np.int32),
            "l_extendedprice": (rng.uniform(1000, 100000, n_li)
                                .astype(np.float32)),
            "l_discount": np.round(rng.uniform(0.0, 0.1, n_li), 2)
            .astype(np.float32),
            "l_tax": np.round(rng.uniform(0.0, 0.08, n_li), 2)
            .astype(np.float32),
            "l_returnflag": rng.integers(0, 3, n_li).astype(np.int32),
            "l_linestatus": rng.integers(0, 2, n_li).astype(np.int32),
            "l_shipmode": rng.integers(0, 7, n_li).astype(np.int32),
            "l_shipdate": dates(n_li),
            "l_commitdate": commit,
            "l_receiptdate": (commit
                              + rng.integers(-5, 15, n_li).astype(np.int32)),
        },
        dicts={"l_returnflag": flags, "l_linestatus": status,
               "l_shipmode": modes},
    )
    orders = ColumnTable(
        cols={
            "o_orderkey": np.arange(n_ord, dtype=np.int32),
            "o_custkey": rng.integers(0, n_cust, n_ord).astype(np.int32),
            "o_orderdate": dates(n_ord),
            "o_orderpriority": rng.integers(0, 5, n_ord).astype(np.int32),
        },
        dicts={"o_orderpriority": prios},
    )
    # dbgen phone country codes are 10..34; Q22 groups by the 2-char
    # prefix, so a 25-entry dictionary of representative numbers suffices
    phones = [f"{cc}-555-{cc:03d}-{cc * 37 % 10000:04d}"
              for cc in range(10, 35)]
    customer = ColumnTable(
        cols={
            "c_custkey": np.arange(n_cust, dtype=np.int32),
            "c_mktsegment": rng.integers(0, 5, n_cust).astype(np.int32),
            "c_acctbal": rng.uniform(-999, 9999, n_cust).astype(np.float32),
            "c_phone": rng.integers(0, len(phones), n_cust).astype(np.int32),
        },
        dicts={"c_mktsegment": segs, "c_phone": phones},
    )
    part = ColumnTable(
        cols={
            "p_partkey": np.arange(n_part, dtype=np.int32),
            "p_brand": rng.integers(0, len(brands), n_part).astype(np.int32),
            "p_container": rng.integers(0, len(containers), n_part)
            .astype(np.int32),
            "p_size": rng.integers(1, 51, n_part).astype(np.int32),
            "p_type": rng.integers(0, len(types), n_part).astype(np.int32),
        },
        dicts={"p_brand": brands, "p_container": containers,
               "p_type": types},
    )
    n_ps = 4 * n_part  # dbgen: four suppliers per part
    partsupp = ColumnTable(cols={
        "ps_partkey": np.repeat(np.arange(n_part, dtype=np.int32), 4),
        "ps_suppkey": rng.integers(0, n_sup, n_ps).astype(np.int32),
        "ps_supplycost": rng.uniform(1, 1000, n_ps).astype(np.float32),
    })
    sup_names = [f"Supplier#{i:09d}" for i in range(n_sup)]
    supplier = ColumnTable(
        cols={
            "s_suppkey": np.arange(n_sup, dtype=np.int32),
            "s_nationkey": rng.integers(0, 25, n_sup).astype(np.int32),
            "s_name": np.arange(n_sup, dtype=np.int32),
        },
        dicts={"s_name": sup_names},
    )
    regions = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
    nat_names = [f"NATION{i:02d}" for i in range(25)]
    nation = ColumnTable(
        cols={
            "n_nationkey": np.arange(25, dtype=np.int32),
            "n_regionkey": (np.arange(25, dtype=np.int32) % 5),
            "n_name": np.arange(25, dtype=np.int32),
        },
        dicts={"n_name": nat_names},
    )
    region = ColumnTable(
        cols={
            "r_regionkey": np.arange(5, dtype=np.int32),
            "r_name": np.arange(5, dtype=np.int32),
        },
        dicts={"r_name": regions},
    )
    tables = {"lineitem": lineitem, "orders": orders, "customer": customer,
              "part": part, "partsupp": partsupp, "supplier": supplier,
              "nation": nation, "region": region}
    for t in tables.values():
        t.cols = {k: jnp.asarray(v) for k, v in t.cols.items()}
    return tables


def _rtt() -> float:
    g = jax.jit(lambda v: v + 1)
    float(g(jnp.float32(0)))
    t0 = time.perf_counter()
    for _ in range(5):
        float(g(jnp.float32(0)))
    return (time.perf_counter() - t0) / 5


def bench_queries(tables: Tables,
                  names=("q01", "q02", "q03", "q04", "q06", "q12", "q13",
                         "q14", "q17", "q22"),
                  iters: int = 10) -> Dict[str, Dict[str, float]]:
    """Steady-state per-query seconds (compile excluded — the compiled-
    plan cache is the reference's PreCompiledWorkload, so steady state
    is the honest comparison; compile time is reported separately)."""
    out: Dict[str, Dict[str, float]] = {}
    rtt = _rtt()
    n_li = tables["lineitem"].num_rows
    for name in names:
        fn = COLUMNAR_QUERIES[name]
        t0 = time.perf_counter()
        fn(tables)  # compile + first run (result pull syncs)
        first = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(iters):
            fn(tables)
        wall = (time.perf_counter() - t0) / iters
        dev = wall - rtt
        entry = {"seconds_wall": wall, "first_run_seconds": first,
                 "controller_rtt": rtt,
                 "lineitem_rows_per_sec": n_li / wall}
        if dev > 0.2 * rtt:
            entry["seconds_device"] = dev
        else:
            # query finishes inside controller-RTT noise; wall time is
            # an upper bound and the device time is unresolvable
            entry["seconds_device_below_rtt"] = True
        out[name] = entry
    return out


def bench_suite(tables: Tables, iters: int = 10) -> Dict[str, float]:
    """The whole ten-query suite as ONE fused jitted program (see
    queries.compile_suite): wall seconds for all ten queries per call,
    one controller round-trip total."""
    from netsdb_tpu.relational.queries import compile_suite

    suite = compile_suite(tables)

    def sync(out):
        leaves = jax.tree_util.tree_leaves(out)
        return float(jnp.sum(leaves[-1].astype(jnp.float32)))

    t0 = time.perf_counter()
    sync(suite())  # compile + first run
    first = time.perf_counter() - t0
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        sync(suite())
        times.append(time.perf_counter() - t0)
    wall = sorted(times)[len(times) // 2]
    return {"all_ten_queries_wall_seconds": wall,
            "first_run_seconds": first}


def main(sf: float = 0.1, iters: int = 10):
    tables = generate_columnar(sf)
    res = bench_queries(tables, iters=iters)
    res["suite_fused"] = bench_suite(tables, iters=iters)
    # published-baseline comparison only at SF 1: the reference's scale
    # factor is unrecorded, and dividing its full-scale wall time by a
    # smaller run's would inflate the ratio by the scale difference
    if sf >= 1.0:
        for name, secs in PUBLISHED.items():
            if name in res:
                res[name]["published_baseline_seconds"] = secs
                res[name]["speedup_vs_published"] = \
                    secs / res[name]["seconds_wall"]
    return {"scale_factor": sf,
            "lineitem_rows": tables["lineitem"].num_rows,
            "queries": res}
