"""Columnar device-relational engine — the TPU-native redesign of the
reference's relational core.

The reference executes relational plans row-at-a-time over 64 MB pages
with hand-written executors (``src/lambdas/headers/Pipeline.h``,
``src/queryExecution``); its headline numbers are TPC-H query times.
On TPU the same queries become vectorized array programs: columns are
device arrays, filters are masks (static shapes — XLA requirement),
group-by is ``segment_sum``, equi-joins are sort+searchsorted gathers.
Everything jit-compiles to a single fused XLA program per query.

``netsdb_tpu.workloads.tpch`` (host row DAGs) remains the capability-
parity path; this package is the performance path.
"""

from netsdb_tpu.relational.table import ColumnTable, date_to_int
from netsdb_tpu.relational import kernels

__all__ = ["ColumnTable", "date_to_int", "kernels"]
