"""Automatic device equi-joins for string-keyed host data.

Round 2's rule: host-object workloads with non-integer join keys ran on
the interpreter path unless someone hand-built a columnar twin
(``workloads/reddit_columnar.py``'s author→id maps). This module makes
the device LUT-join path automatic:

- :func:`table_from_objects` ingests arbitrary record objects
  (dataclasses, namedtuples, plain attribute objects) through
  ``ColumnTable.from_rows`` — string columns dictionary-encode exactly
  as TPC-H columns do (``relational/table.py`` design rules).
- :func:`equijoin` joins two tables on a (possibly string) key: the
  two tables' dictionaries are UNIFIED host-side — the right table's
  codes are remapped into the left's code space in O(|dict|), the same
  division of labor as the LIKE-predicate LUTs — and the join itself
  is one ``kernels.pk_fk_join`` gather on device.

This is the reference's per-tuple hash join on ``String`` keys
(``src/builtInPDBObjects/headers/JoinPairArray.h:122`` probing hashed
``Handle<String>``) re-priced: strings hash once at ingest into dense
codes, every probe is an int gather on the MXU-fed LUT path.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from netsdb_tpu.relational import kernels as K
from netsdb_tpu.relational.table import ColumnTable


def _record_to_row(obj: Any) -> Dict[str, Any]:
    if isinstance(obj, dict):
        return obj
    if dataclasses.is_dataclass(obj):
        return dataclasses.asdict(obj)
    if hasattr(obj, "_asdict"):  # namedtuple
        return obj._asdict()
    return {k: v for k, v in vars(obj).items() if not k.startswith("_")}


def table_from_objects(objs: Sequence[Any],
                       date_cols: Sequence[str] = ()) -> ColumnTable:
    """Host records → ColumnTable, strings dictionary-encoded at
    ingest. The automatic columnarizer for object sets."""
    return ColumnTable.from_rows([_record_to_row(o) for o in objs],
                                 date_cols)


def merge_dicts(base: Sequence[str], other: Sequence[str]
                ) -> Tuple[List[str], np.ndarray]:
    """Merge two column dictionaries: ``other``'s entries extend
    ``base``'s, and the returned remap LUT (len(other) int32) carries
    each ``other`` code into the merged space. The ONE place append
    (``concat_tables``) and join (``unify_key_codes``) agree on merge
    semantics."""
    merged = {s: i for i, s in enumerate(base)}
    remap = np.empty(len(other), np.int32)
    for code, s in enumerate(other):
        if s not in merged:
            merged[s] = len(merged)
        remap[code] = merged[s]
    return list(merged), remap


def unify_key_codes(left: ColumnTable, left_key: str,
                    right: ColumnTable, right_key: str
                    ) -> Tuple[jnp.ndarray, jnp.ndarray, int]:
    """Key columns of both tables in ONE integer code space.

    Plain int keys pass through. Dictionary-encoded keys are unified
    host-side: the merged dictionary extends the left table's, and the
    right table's codes remap through an O(|dict|) LUT gather on
    device. Returns (left_codes, right_codes, key_space)."""
    l_dict = left.dicts.get(left_key)
    r_dict = right.dicts.get(right_key)
    if (l_dict is None) != (r_dict is None):
        raise ValueError(
            f"join key type mismatch: {left_key!r} "
            f"{'string' if l_dict else 'int'} vs {right_key!r} "
            f"{'string' if r_dict else 'int'}")
    lc, rc = left[left_key], right[right_key]
    if l_dict is None:
        space = int(max(int(jnp.max(lc)) if lc.shape[0] else 0,
                        int(jnp.max(rc)) if rc.shape[0] else 0)) + 1
        return lc, rc, space
    merged, remap = merge_dicts(l_dict, r_dict)
    rc = jnp.take(jnp.asarray(remap), rc)
    return lc, rc, len(merged)


def concat_tables(a: ColumnTable, b: ColumnTable) -> ColumnTable:
    """Row-append two same-schema tables on device: ``b``'s dictionary
    codes remap into ``a``'s merged dictionaries (the same O(|dict|)
    host unification as :func:`unify_key_codes`), columns concatenate,
    validity masks concatenate. The append path for ``objects`` sets —
    O(batch + copy), no row re-encoding."""
    if set(a.cols) != set(b.cols):
        raise ValueError(f"schema mismatch: {sorted(a.cols)} vs "
                         f"{sorted(b.cols)}")
    cols: Dict[str, jnp.ndarray] = {}
    dicts: Dict[str, List[str]] = {}
    for name in a.cols:
        ca, cb = a[name], b[name]
        da, db = a.dicts.get(name), b.dicts.get(name)
        if (da is None) != (db is None):
            raise ValueError(f"column {name!r}: dictionary-encoded on "
                             f"one side only")
        if da is not None:
            merged, remap = merge_dicts(da, db)
            cb = jnp.take(jnp.asarray(remap), cb)
            dicts[name] = merged
        cols[name] = jnp.concatenate([ca, cb])
    valid = None
    if a.valid is not None or b.valid is not None:
        valid = jnp.concatenate([a.mask(), b.mask()])
    return ColumnTable(cols, dicts, valid)


def equijoin(left: ColumnTable, left_key: str,
             right: ColumnTable, right_key: str,
             take: Optional[Sequence[str]] = None,
             prefix: str = "r_") -> ColumnTable:
    """Inner PK-FK equi-join on device: ``right`` is the build side
    (unique keys — dimension table), ``left`` the probe. Returns the
    left table extended with ``take`` columns gathered from the right
    (named ``prefix+col`` on collision), validity ANDed with the hit
    mask. String keys ride automatically via dictionary unification."""
    lc, rc, space = unify_key_codes(left, left_key, right, right_key)
    ridx, hit = K.pk_fk_join(rc, lc, pk_mask=right.valid,
                             fk_mask=left.valid, key_space=space)
    out = left.filter(hit)
    for col in (take if take is not None else right.cols):
        if col == right_key:
            continue
        name = col if col not in out.cols else prefix + col
        out = out.with_column(name, jnp.take(right[col], ridx),
                              right.dicts.get(col))
    return out
