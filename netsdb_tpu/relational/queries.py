"""Columnar TPC-H queries — the device-side counterparts of
``netsdb_tpu.workloads.tpch``.

Same ten queries as the reference (``src/tpch/source/Query01..22``) and
as the host row engine, but each query body is one (or two) jitted
array programs: filters are masks, group-bys are segment reductions,
joins are searchsorted gathers (see :mod:`netsdb_tpu.relational.kernels`).
String/LIKE predicates are evaluated once on the host dictionary and
broadcast to rows as code lookups — dictionary encoding turns the
reference's per-row string compares into O(|dict|) host work plus an
int gather on device.

Two controller-latency rules shape the code (the controller⇄device
round-trip is ~65 ms over a tunnel, and remote compiles cost seconds):

- every jitted core is a **module-level** function, so ``jax.jit``'s
  cache hits across calls — a core defined inside the query wrapper
  would recompile on every invocation (this is the same economics that
  makes the reference cache physical plans in PreCompiledWorkload,
  ``src/queryPlanning/headers/PreCompiledWorkload.h``);
- each core packs its results into as few arrays as possible, because
  every host pull is one round-trip. Scalar predicate parameters
  (dates, codes) are passed as traced scalars, not baked constants, so
  changing a parameter does not retrace.

Every query function takes ``tables`` (dict of ColumnTable) and returns
the same Python result structure as the row engine's query, so the two
engines are cross-checkable on identical data (tests/test_relational.py).

Group cardinalities (static ``num_segments``) come from host-side key
maxima, computed once per table load and cached on the ColumnTable —
the role the reference's ``Statistics`` set-size metadata plays for its
planner (``src/queryPlanning/headers/TCAPAnalyzer.h``).
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from netsdb_tpu.relational import kernels as K
from netsdb_tpu.relational import planner as P
from netsdb_tpu.relational.stats import analyze_table, key_space
from netsdb_tpu.relational.table import ColumnTable, date_to_int, int_to_date

Tables = Dict[str, ColumnTable]

# Join strategies are chosen by the statistics-driven planner
# (`P.plan_join` reading ingest-time column stats), not by per-call
# `key_space=` arguments as in round 1 — the choice follows the data.
# The resulting JoinPlan is a hashable static argument, so each
# (strategy, key_space) pair compiles once and is cached like any other
# static shape.


def _lut(dictionary: List[str], pred: Callable[[str], bool]) -> jnp.ndarray:
    """Host-evaluated string predicate → device bool LUT over codes."""
    return jnp.asarray(np.fromiter((pred(s) for s in dictionary),
                                   np.bool_, len(dictionary)))


def _ct(tables: Tables, name: str) -> ColumnTable:
    """Fetch a table for the direct columnar path, compacting away any
    validity mask first (placement row-padding, applied filters): the
    jitted cores below predate table masks and assume every row is real.
    ``compact()`` is identity for mask-free tables, so the common path
    costs one dict lookup. The set-API DAG path (relational/dag.py)
    instead keeps the mask and ANDs it — static shapes for jit."""
    return tables[name].compact()


# ---------------------------------------------------------------- Q01
def _q01_fold(n_groups, n_ls, rf, ls, qty, price, disc, tax, mask):
    """Shared Q01 reduction body — used by the direct columnar path
    (`_q01_core`) and by the set-API DAG (`relational.dag.q01_sink`),
    which ANDs the table validity mask in (placement row-padding)."""
    seg = rf * n_ls + ls
    qty = qty.astype(jnp.float32)
    disc_price = price * (1.0 - disc)
    charge = disc_price * (1.0 + tax)
    rows = [K.segment_sum(v, seg, n_groups, mask)
            for v in (qty, price, disc_price, charge, disc)]
    # counts stay int32: a float32 count saturates at 2^24 rows/group
    return jnp.stack(rows), K.segment_count(seg, n_groups, mask)


@functools.partial(jax.jit, static_argnums=(0, 1))
def _q01_core(n_groups, n_ls, ship, rf, ls, qty, price, disc, tax, delta):
    return _q01_fold(n_groups, n_ls, rf, ls, qty, price, disc, tax,
                     ship <= delta)


def _args_q01(tables: Tables, delta_date: str = "1998-09-02"):
    li = _ct(tables, "lineitem")
    n_ls = len(li.dicts["l_linestatus"])
    n_groups = len(li.dicts["l_returnflag"]) * n_ls
    return (n_groups, n_ls, li["l_shipdate"], li["l_returnflag"],
            li["l_linestatus"], li["l_quantity"], li["l_extendedprice"],
            li["l_discount"], li["l_tax"], date_to_int(delta_date))


def cq01(tables: Tables, delta_date: str = "1998-09-02"):
    """Pricing summary report. One segment-reduction pass over lineitem."""
    li = _ct(tables, "lineitem")
    n_ls = len(li.dicts["l_linestatus"])
    n_groups = len(li.dicts["l_returnflag"]) * n_ls
    sums, counts = jax.device_get(_q01_core(*_args_q01(tables, delta_date)))
    names = ("sum_qty", "sum_base_price", "sum_disc_price", "sum_charge",
             "sum_disc")
    out = []
    for g in range(n_groups):
        cnt = int(counts[g])
        if cnt == 0:
            continue
        key = (li.decode("l_returnflag", g // n_ls),
               li.decode("l_linestatus", g % n_ls))
        v = {names[i]: float(sums[i, g]) for i in range(5)}
        v["count"] = cnt
        v["avg_qty"] = v["sum_qty"] / cnt
        v["avg_price"] = v["sum_base_price"] / cnt
        v["avg_disc"] = v["sum_disc"] / cnt
        out.append((key, v))
    out.sort(key=lambda kv: kv[0])
    return out


# ---------------------------------------------------------------- Q02
@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3))
def _q02_core(jp_part, jp_sup, jp_nat, jp_reg,
              p_key, p_size, p_type, ps_part, ps_supp, ps_cost,
              s_key, s_nat, r_key, r_name, n_key, n_reg,
              type_ok, size, region_code):
    n_part = jp_part.key_space
    part_ok = (p_size == size) & jnp.take(type_ok, p_type)
    # partsupp ⋈ part (restrict to qualifying parts)
    _, phit = K.pk_fk_join(p_key, ps_part, part_ok, plan=jp_part)
    # supplier ⋈ nation ⋈ region chain, evaluated on the supplier side;
    # nation columns come through the join's row index (keys need not
    # equal row positions)
    nidx, nhit = K.pk_fk_join(n_key, s_nat, plan=jp_nat)
    sup_region = jnp.take(n_reg, nidx)
    ridx, rhit = K.pk_fk_join(r_key, sup_region, plan=jp_reg)
    in_region = nhit & rhit & (jnp.take(r_name, ridx) == region_code)
    sup_ok = in_region
    # partsupp ⋈ supplier
    sidx, shit = K.pk_fk_join(s_key, ps_supp, sup_ok, plan=jp_sup)
    valid = phit & shit
    # min cost per part, then the first row achieving it (the row
    # engine's combine keeps the earlier row on ties)
    cost_min = K.segment_min(ps_cost, ps_part, n_part, valid)
    at_min = valid & (ps_cost == jnp.take(cost_min, ps_part))
    rows = jnp.arange(ps_part.shape[0], dtype=jnp.int32)
    winner = K.segment_min(rows, ps_part, n_part, at_min)
    has = winner < jnp.iinfo(jnp.int32).max
    winner_c = jnp.clip(winner, 0, ps_part.shape[0] - 1)
    # non-qualifying parts hold deterministic zeros (not clip garbage):
    # the streamed fold produces the same, so whole-table and paged
    # outputs compare array-for-array
    sup_row = jnp.where(has, jnp.take(sidx, winner_c), 0)
    nat_row = jnp.where(has, jnp.take(nidx, sup_row), 0)
    ints = jnp.stack([has.astype(jnp.int32), sup_row, nat_row])
    return ints, cost_min


def _args_q02(tables: Tables, size: int = 15, type_suffix: str = "BRUSHED",
              region: str = "EUROPE"):
    part, ps = _ct(tables, "part"), _ct(tables, "partsupp")
    sup, nat, reg = _ct(tables, "supplier"), _ct(tables, "nation"), _ct(tables, "region")
    type_ok = _lut(part.dicts["p_type"], lambda s: s.endswith(type_suffix))
    return (P.plan_join(part, "p_partkey", ps, "ps_partkey"),
            P.plan_join(sup, "s_suppkey", ps, "ps_suppkey"),
            P.plan_join(nat, "n_nationkey", sup, "s_nationkey"),
            P.plan_join(reg, "r_regionkey", nat, "n_regionkey"),
            part["p_partkey"], part["p_size"], part["p_type"],
            ps["ps_partkey"], ps["ps_suppkey"], ps["ps_supplycost"],
            sup["s_suppkey"], sup["s_nationkey"],
            reg["r_regionkey"], reg["r_name"],
            nat["n_nationkey"], nat["n_regionkey"],
            type_ok, size, reg.code("r_name", region))


def cq02(tables: Tables, size: int = 15, type_suffix: str = "BRUSHED",
         region: str = "EUROPE"):
    """Minimum-cost supplier per qualifying part."""
    sup, nat = _ct(tables, "supplier"), _ct(tables, "nation")
    ints, cost_min = _q02_core(*_args_q02(tables, size, type_suffix, region))
    ints, cost_min = np.asarray(ints), np.asarray(cost_min)
    s_names = np.asarray(sup["s_name"])
    n_names = np.asarray(nat["n_name"])
    out = []
    for pk in np.nonzero(ints[0])[0]:  # only qualifying parts
        pk = int(pk)
        out.append((pk, {"partkey": pk, "cost": float(cost_min[pk]),
                         "s_name": sup.decode(
                             "s_name", int(s_names[ints[1, pk]])),
                         "n_name": nat.decode(
                             "n_name", int(n_names[ints[2, pk]]))}))
    return out


# ---------------------------------------------------------------- Q03
@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def _q03_core(jp_orders, k, jp_cust, c_key, c_seg, o_key, o_cust, o_date,
              l_okey, l_ship, l_price, l_disc, seg_code, d):
    n_orders = jp_orders.key_space
    cust_ok = c_seg == seg_code
    _, chit = K.pk_fk_join(c_key, o_cust, cust_ok, plan=jp_cust)
    order_ok = chit & (o_date < d)
    oidx, ohit = K.pk_fk_join(o_key, l_okey, order_ok, plan=jp_orders)
    li_ok = ohit & (l_ship > d)
    rev = K.segment_sum(l_price * (1.0 - l_disc), l_okey, n_orders, li_ok)
    odate_per_order = K.segment_min(
        jnp.take(o_date, oidx), l_okey, n_orders, li_ok)
    top_idx, top_ok = K.top_k_masked(rev, k, rev > 0)
    ints = jnp.stack([top_idx, top_ok.astype(jnp.int32),
                      jnp.take(odate_per_order, top_idx)])
    return ints, jnp.take(rev, top_idx)


def _args_q03(tables: Tables, segment: str = "BUILDING",
              date: str = "1995-03-15", k: int = 10):
    cust, orders, li = (_ct(tables, "customer"), _ct(tables, "orders"),
                        _ct(tables, "lineitem"))
    return (P.plan_join(orders, "o_orderkey", li, "l_orderkey"), k,
            P.plan_join(cust, "c_custkey", orders, "o_custkey"),
            cust["c_custkey"],
            cust["c_mktsegment"], orders["o_orderkey"], orders["o_custkey"],
            orders["o_orderdate"], li["l_orderkey"], li["l_shipdate"],
            li["l_extendedprice"], li["l_discount"],
            cust.code("c_mktsegment", segment), date_to_int(date))


def cq03(tables: Tables, segment: str = "BUILDING",
         date: str = "1995-03-15", k: int = 10):
    """Top unshipped orders by revenue."""
    ints, rev = _q03_core(*_args_q03(tables, segment, date, k))
    ints, rev = np.asarray(ints), np.asarray(rev)
    rows = [{"okey": int(ints[0, j]), "odate": int_to_date(int(ints[2, j])),
             "revenue": float(rev[j])}
            for j in range(ints.shape[1]) if ints[1, j]]
    rows.sort(key=lambda r: (-r["revenue"], r["odate"]))
    return rows


# ---------------------------------------------------------------- Q04
@functools.partial(jax.jit, static_argnums=(0, 1))
def _q04_core(n_pri, jp_li, o_key, o_date, o_pri, l_okey, l_commit,
              l_receipt, a, b):
    late = l_commit < l_receipt
    has_late = K.member(l_okey, o_key, late, plan=jp_li)
    in_q = (o_date >= a) & (o_date < b)
    return K.segment_count(o_pri, n_pri, has_late & in_q)


def _args_q04(tables: Tables, d0: str = "1993-07-01",
              d1: str = "1993-10-01"):
    orders, li = _ct(tables, "orders"), _ct(tables, "lineitem")
    n_pri = len(orders.dicts["o_orderpriority"])
    return (n_pri, P.plan_join(li, "l_orderkey", orders, "o_orderkey"),
            orders["o_orderkey"], orders["o_orderdate"],
            orders["o_orderpriority"], li["l_orderkey"], li["l_commitdate"],
            li["l_receiptdate"], date_to_int(d0), date_to_int(d1))


def cq04(tables: Tables, d0: str = "1993-07-01", d1: str = "1993-10-01"):
    """Orders with ≥1 late lineitem, counted per priority."""
    orders = _ct(tables, "orders")
    n_pri = len(orders.dicts["o_orderpriority"])
    counts = np.asarray(_q04_core(*_args_q04(tables, d0, d1)))
    out = [(orders.decode("o_orderpriority", i), int(counts[i]))
           for i in range(n_pri) if counts[i]]
    out.sort(key=lambda kv: kv[0])
    return out


# ---------------------------------------------------------------- Q06
@jax.jit
def _q06_core(ship, discount, quantity, price, a, b, disc, qty):
    mask = ((ship >= a) & (ship < b)
            & (discount >= disc - 0.011) & (discount <= disc + 0.011)
            & (quantity < qty))
    return jnp.sum(jnp.where(mask, price * discount, 0.0))


def _args_q06(tables: Tables, d0: str = "1994-01-01",
              d1: str = "1995-01-01", disc: float = 0.06, qty: int = 24):
    li = _ct(tables, "lineitem")
    return (li["l_shipdate"], li["l_discount"],
            li["l_quantity"], li["l_extendedprice"],
            date_to_int(d0), date_to_int(d1), disc, qty)


def cq06(tables: Tables, d0: str = "1994-01-01", d1: str = "1995-01-01",
         disc: float = 0.06, qty: int = 24):
    """Revenue-change forecast: one fused filtered reduction."""
    rev = float(_q06_core(*_args_q06(tables, d0, d1, disc, qty)))
    return [("revenue", rev)]


# ---------------------------------------------------------------- Q12
@functools.partial(jax.jit, static_argnums=(0, 1))
def _q12_core(n_modes, jp_orders, o_key, o_pri, l_okey, l_mode, l_ship,
              l_commit, l_receipt, hi_lut, m1, m2, a, b):
    mask = (((l_mode == m1) | (l_mode == m2))
            & (l_commit < l_receipt) & (l_ship < l_commit)
            & (l_receipt >= a) & (l_receipt < b))
    oidx, ohit = K.pk_fk_join(o_key, l_okey, plan=jp_orders)
    mask = mask & ohit
    high = jnp.take(hi_lut, jnp.take(o_pri, oidx))
    return jnp.stack([K.segment_count(l_mode, n_modes, mask & high),
                      K.segment_count(l_mode, n_modes, mask & ~high)])


def _args_q12(tables: Tables, mode1: str = "MAIL", mode2: str = "SHIP",
              d0: str = "1994-01-01", d1: str = "1995-01-01"):
    orders, li = _ct(tables, "orders"), _ct(tables, "lineitem")
    n_modes = len(li.dicts["l_shipmode"])
    m1, m2 = li.code("l_shipmode", mode1), li.code("l_shipmode", mode2)
    hi = _lut(orders.dicts["o_orderpriority"],
              lambda s: s in ("1-URGENT", "2-HIGH"))
    return (n_modes, P.plan_join(orders, "o_orderkey", li, "l_orderkey"),
            orders["o_orderkey"], orders["o_orderpriority"],
            li["l_orderkey"], li["l_shipmode"], li["l_shipdate"],
            li["l_commitdate"], li["l_receiptdate"], hi, m1, m2,
            date_to_int(d0), date_to_int(d1))


def cq12(tables: Tables, mode1: str = "MAIL", mode2: str = "SHIP",
         d0: str = "1994-01-01", d1: str = "1995-01-01"):
    """High/low-priority lineitems per ship mode."""
    li = _ct(tables, "lineitem")
    m1, m2 = li.code("l_shipmode", mode1), li.code("l_shipmode", mode2)
    packed = np.asarray(_q12_core(*_args_q12(tables, mode1, mode2, d0, d1)))
    out = [(li.decode("l_shipmode", m),
            {"high": int(packed[0, m]), "low": int(packed[1, m])})
           for m in (m1, m2)
           if m >= 0 and packed[0, m] + packed[1, m] > 0]
    out.sort(key=lambda kv: kv[0])
    return out


# ---------------------------------------------------------------- Q13
# Static histogram domain: per-customer order counts are ~10-40 at any
# dbgen scale factor (orders/customer is fixed by the spec), so a
# generous static cap keeps n_buckets host-static — no mid-query host
# pull of max(counts) and no per-dataset recompile. Overflow (counts
# >= cap) is detected on device and handled by an exact host fallback.
_Q13_CAP = 256


@functools.partial(jax.jit, static_argnums=(0, 1))
def _q13_core(n_cust, cap, o_cust, keep, c_key):
    counts = K.segment_count(o_cust, n_cust, keep)
    per_cust = jnp.take(counts, c_key)
    hist = K.bincount_masked(jnp.minimum(per_cust, cap - 1), cap)
    return hist, jnp.max(per_cust, initial=0)


@functools.partial(jax.jit, static_argnums=(0,))
def _q13_per_cust(n_cust, o_cust, keep, c_key):
    return jnp.take(K.segment_count(o_cust, n_cust, keep), c_key)


def _q13_keep(tables: Tables, word1: str, word2: str) -> jnp.ndarray:
    import re

    orders = _ct(tables, "orders")
    if "o_comment" in orders.dicts:
        pat = re.compile(f"{re.escape(word1)}.*{re.escape(word2)}")
        keep_lut = _lut(orders.dicts["o_comment"],
                        lambda s: not pat.search(s))
        return jnp.take(keep_lut, orders["o_comment"])
    return jnp.ones((orders.num_rows,), jnp.bool_)


def _args_q13(tables: Tables, word1: str = "special",
              word2: str = "requests"):
    cust, orders = _ct(tables, "customer"), _ct(tables, "orders")
    return (key_space(cust, "c_custkey"), _Q13_CAP, orders["o_custkey"],
            _q13_keep(tables, word1, word2), cust["c_custkey"])


def cq13(tables: Tables, word1: str = "special", word2: str = "requests"):
    """Histogram of per-customer order counts (zero included — the
    left-outer-join semantics)."""
    cust, orders = _ct(tables, "customer"), _ct(tables, "orders")
    n_cust = key_space(cust, "c_custkey")
    args = _args_q13(tables, word1, word2)
    keep = args[3]  # reused by the over-cap exact fallback below
    hist, maxc = jax.device_get(_q13_core(*args))
    maxc = int(maxc)
    if maxc >= _Q13_CAP:  # beyond any dbgen shape: exact host fallback
        per = np.asarray(_q13_per_cust(n_cust, orders["o_custkey"], keep,
                                       cust["c_custkey"]))
        hist = np.bincount(per, minlength=maxc + 1)
    return [(i, int(hist[i])) for i in range(maxc + 1) if hist[i]]


# ---------------------------------------------------------------- Q14
@functools.partial(jax.jit, static_argnums=(0,))
def _q14_core(jp_part, p_key, p_type, l_part, l_ship, l_price, l_disc,
              promo_lut, a, b):
    mask = (l_ship >= a) & (l_ship < b)
    pidx, phit = K.pk_fk_join(p_key, l_part, plan=jp_part)
    mask = mask & phit
    rev = jnp.where(mask, l_price * (1.0 - l_disc), 0.0)
    is_promo = jnp.take(promo_lut, jnp.take(p_type, pidx))
    return jnp.stack([jnp.sum(jnp.where(is_promo, rev, 0.0)), jnp.sum(rev)])


def _args_q14(tables: Tables, d0: str = "1995-09-01",
              d1: str = "1995-10-01"):
    li, part = _ct(tables, "lineitem"), _ct(tables, "part")
    promo = _lut(part.dicts["p_type"], lambda s: s.startswith("PROMO"))
    return (P.plan_join(part, "p_partkey", li, "l_partkey"),
            part["p_partkey"], part["p_type"], li["l_partkey"],
            li["l_shipdate"], li["l_extendedprice"], li["l_discount"],
            promo, date_to_int(d0), date_to_int(d1))


def cq14(tables: Tables, d0: str = "1995-09-01", d1: str = "1995-10-01"):
    """% of revenue from promo parts."""
    pr, total = np.asarray(_q14_core(*_args_q14(tables, d0, d1)))
    pct = 100.0 * float(pr) / float(total) if total else 0.0
    return [("promo_revenue_pct", pct)]


# ---------------------------------------------------------------- Q17
@functools.partial(jax.jit, static_argnums=(0,))
def _q17_core(jp_part, p_key, p_brand, p_cont, l_part, l_qty, l_price,
              brand_code, cont_code):
    part_ok = (p_brand == brand_code) & (p_cont == cont_code)
    _, phit = K.pk_fk_join(p_key, l_part, part_ok, plan=jp_part)
    qty = l_qty.astype(jnp.float32)
    avg = K.segment_mean(qty, l_part, jp_part.key_space, phit)
    small = phit & (qty < 0.2 * jnp.take(avg, l_part))
    return jnp.sum(jnp.where(small, l_price, 0.0)) / 7.0


def _args_q17(tables: Tables, brand: str = "Brand#23",
              container: str = "MED BOX"):
    li, part = _ct(tables, "lineitem"), _ct(tables, "part")
    return (P.plan_join(part, "p_partkey", li, "l_partkey"),
            part["p_partkey"],
            part["p_brand"], part["p_container"], li["l_partkey"],
            li["l_quantity"], li["l_extendedprice"],
            part.code("p_brand", brand),
            part.code("p_container", container))


def cq17(tables: Tables, brand: str = "Brand#23", container: str = "MED BOX"):
    """Revenue from small-quantity orders of one brand/container."""
    total = float(_q17_core(*_args_q17(tables, brand, container)))
    return [("avg_yearly", total)] if total else []


# ---------------------------------------------------------------- Q22
@functools.partial(jax.jit, static_argnums=(0, 1))
def _q22_core(n_pref, jp_cust, c_key, c_phone, c_bal, o_cust, code_lut):
    pref = jnp.take(code_lut, c_phone)
    in_pref = pref >= 0
    pos = in_pref & (c_bal > 0)
    avg = (jnp.sum(jnp.where(pos, c_bal, 0.0))
           / jnp.maximum(jnp.sum(pos.astype(jnp.int32)), 1))
    rich = in_pref & (c_bal > avg)
    has_orders = K.member(o_cust, c_key, plan=jp_cust)
    sel = rich & ~has_orders
    seg = jnp.clip(pref, 0, n_pref - 1)
    return jnp.stack([K.segment_count(seg, n_pref, sel).astype(jnp.float32),
                      K.segment_sum(c_bal, seg, n_pref, sel)])


def q22_code_lut(phone_dict: List[str], prefixes: Sequence[str]
                 ) -> Tuple[List[str], jnp.ndarray]:
    """Phone-dictionary → prefix-group code LUT (-1 = no group). Shared
    by the local and sharded Q22 engines so prefix semantics cannot
    diverge."""
    pref_list = sorted(set(prefixes))
    pref_idx = {p: i for i, p in enumerate(pref_list)}
    lut = jnp.asarray(np.fromiter(
        (pref_idx.get(s[:2], -1) for s in phone_dict), np.int32,
        len(phone_dict)))
    return pref_list, lut


def _args_q22(tables: Tables,
              prefixes: Sequence[str] = ("13", "31", "23", "29", "30",
                                         "18", "17")):
    cust, orders = _ct(tables, "customer"), _ct(tables, "orders")
    pref_list, code_lut = q22_code_lut(cust.dicts["c_phone"], prefixes)
    return (len(pref_list),
            P.plan_join(orders, "o_custkey", cust, "c_custkey"),
            cust["c_custkey"], cust["c_phone"],
            cust["c_acctbal"], orders["o_custkey"], code_lut)


def cq22(tables: Tables,
         prefixes: Tuple[str, ...] = ("13", "31", "23", "29", "30", "18",
                                      "17")):
    """Well-funded customers with no orders, grouped by phone prefix."""
    pref_list = sorted(set(prefixes))  # q22_code_lut's group order
    packed = np.asarray(_q22_core(*_args_q22(tables, prefixes)))
    return [(pref_list[i], {"n": int(packed[0, i]),
                            "bal": float(packed[1, i])})
            for i in range(len(pref_list)) if packed[0, i]]


COLUMNAR_QUERIES: Dict[str, Callable] = {
    "q01": cq01, "q02": cq02, "q03": cq03, "q04": cq04, "q06": cq06,
    "q12": cq12, "q13": cq13, "q14": cq14, "q17": cq17, "q22": cq22,
}


def tables_from_rows(data: Dict[str, List[dict]]) -> Tables:
    """Columnarize ``workloads.tpch.generate()`` output and collect
    planner statistics at ingest (the reference's StorageCollectStats
    moment)."""
    out = {}
    for name, rows in data.items():
        if rows:
            out[name] = ColumnTable.from_rows(rows)
            analyze_table(out[name])
    return out


# ------------------------------------------------------- fused suite
_SUITE_CORES: Dict[str, Tuple[Callable, Callable]] = {
    "q01": (_q01_core, _args_q01), "q02": (_q02_core, _args_q02),
    "q03": (_q03_core, _args_q03), "q04": (_q04_core, _args_q04),
    "q06": (_q06_core, _args_q06), "q12": (_q12_core, _args_q12),
    "q13": (_q13_core, _args_q13), "q14": (_q14_core, _args_q14),
    "q17": (_q17_core, _args_q17), "q22": (_q22_core, _args_q22),
}

_SLOT = object()  # placeholder for a device array in an args template


def suite_args_split(tables: Tables):
    """Split every query core's arguments into (templates, arrays):
    the single source of truth for which suite arguments are traced
    device arrays (slots) vs compile-time statics — shared by
    ``compile_suite`` and the AOT loader so they cannot diverge."""
    templates: Dict[str, list] = {}
    arrays: Dict[str, list] = {}
    for name, (_core, args_fn) in _SUITE_CORES.items():
        t, arr = [], []
        for a in args_fn(tables):
            if isinstance(a, (jnp.ndarray, jax.Array)):
                t.append(_SLOT)
                arr.append(a)
            else:
                t.append(a)
        templates[name] = t
        arrays[name] = arr
    return templates, arrays


def compile_suite(tables: Tables) -> Callable[[], Dict[str, object]]:
    """Fuse the ENTIRE ten-query suite into one jitted program.

    The reference must execute each query as its own distributed job
    with materialized intermediates; here the per-query cores are
    inlined into a single XLA program, so the whole benchmark suite
    costs ONE controller round-trip + one device schedule. Returns a
    zero-argument callable producing ``{name: raw core output}`` (the
    same arrays each ``cqNN`` wrapper formats); call it repeatedly —
    the compiled program is cached on the callable.
    """
    templates, arrays = suite_args_split(tables)

    @jax.jit
    def mega(arrs: Dict[str, list]):
        out = {}
        for name, t in templates.items():
            it = iter(arrs[name])
            rebuilt = [next(it) if x is _SLOT else x for x in t]
            out[name] = _SUITE_CORES[name][0](*rebuilt)
        return out

    def runner():
        return mega(arrays)

    runner.jitted = mega  # exposed so tests can assert one compilation
    runner.arrays = arrays  # exposed for AOT export (plan/aot.py)
    runner.templates = templates  # the matching statics, same split
    return runner
