"""ColumnTable: a relation as a struct of device arrays.

Design rules (all driven by XLA's static-shape compilation model):

- **Numeric columns** are ``int32`` / ``float32`` device arrays.
- **String columns** are dictionary-encoded at ingest: an ``int32``
  code array plus a host-side ``list[str]`` dictionary. Predicates on
  strings become integer compares on device; the strings themselves
  never leave the host.
- **Dates** are ``int32`` yyyymmdd (order-isomorphic to ISO strings, so
  range predicates are int compares — same trick the reference's
  drivers use with encoded ints, ``src/tpch/source/Query06/``).
- **Filters never shrink arrays.** A filtered table keeps every row and
  carries a boolean ``valid`` mask; aggregations apply the mask. This
  keeps every intermediate shape static so one jit covers all
  selectivities. (The reference's row pipeline has the same structure
  inverted: its FilterExecutor emits a bitmap consumed downstream —
  ``src/lambdas/headers/FilterExecutor.h``.)

Row↔column conversion accepts the row dicts produced by
``workloads.tpch.generate``/``parse_tbl`` so the columnar engine can be
golden-tested against the host row engine on identical data.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

import jax.tree_util

_DATE_RE = re.compile(r"^(\d{4})-(\d{2})-(\d{2})$")


def date_to_int(s: str) -> int:
    """ISO date string → yyyymmdd int32."""
    m = _DATE_RE.match(s)
    if not m:
        raise ValueError(f"not an ISO date: {s!r}")
    y, mo, d = m.groups()
    return int(y) * 10000 + int(mo) * 100 + int(d)


def int_to_date(v: int) -> str:
    v = int(v)
    return f"{v // 10000:04d}-{(v // 100) % 100:02d}-{v % 100:02d}"


def _encode_strings(values: List[str], is_date: bool):
    """Shared string-column encoder: ISO dates → yyyymmdd int32 (no
    dictionary), anything else → dictionary codes. Returns
    ``(codes, dictionary_or_None)``. Single definition so both ingestion
    paths (from_rows / from_columns) stay type-identical on the same
    data."""
    if is_date:
        return jnp.asarray(np.fromiter((date_to_int(v) for v in values),
                                       np.int32, len(values))), None
    uniq = sorted(set(values))
    code = {s: i for i, s in enumerate(uniq)}
    return jnp.asarray(np.fromiter((code[v] for v in values),
                                   np.int32, len(values))), uniq


class _TableAuxKey:
    """Hashable static metadata of a ColumnTable (column names + string
    dictionaries) with the hash precomputed once — jit cache lookups on
    table arguments stay O(1) after the first (identity fast path), not
    O(total dictionary bytes) per call.

    Deliberately carries NOTHING derived from column DATA: jax reuses
    treedefs (and thus aux objects) across equal-schema tables, so any
    per-data payload here would alias between distinct tables — see
    relational/stats.py for why the stats cache is per-instance."""

    __slots__ = ("names", "dicts", "_hash")

    def __init__(self, names, dicts):
        self.names = names
        self.dicts = dicts
        self._hash = hash((names, dicts))

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        if self is other:
            return True
        return (isinstance(other, _TableAuxKey) and self._hash == other._hash
                and self.names == other.names and self.dicts == other.dicts)


@dataclasses.dataclass
class ColumnTable:
    """A relation: named device columns + optional validity mask.

    ``dicts[name]`` present ⇒ ``cols[name]`` holds int32 codes into it.
    ``valid`` of None means "all rows valid" (saves a mask op on the
    common unfiltered scan).
    """

    cols: Dict[str, jnp.ndarray]
    dicts: Dict[str, List[str]] = dataclasses.field(default_factory=dict)
    valid: Optional[jnp.ndarray] = None

    # --- construction -------------------------------------------------
    @staticmethod
    def from_rows(rows: Sequence[Dict[str, Any]],
                  date_cols: Sequence[str] = ()) -> "ColumnTable":
        """Build from row dicts. Column kinds are inferred from the first
        row: str → dictionary-encoded (unless named in ``date_cols`` or
        shaped like an ISO date, then yyyymmdd int32), int → int32,
        float → float32."""
        if not rows:
            raise ValueError("from_rows needs at least one row")
        names = list(rows[0].keys())
        cols: Dict[str, jnp.ndarray] = {}
        dicts: Dict[str, List[str]] = {}
        for name in names:
            v0 = rows[0][name]
            values = [r[name] for r in rows]
            if isinstance(v0, str):
                is_date = name in date_cols or bool(_DATE_RE.match(v0))
                cols[name], uniq = _encode_strings(values, is_date)
                if uniq is not None:
                    dicts[name] = uniq
            elif isinstance(v0, bool):
                cols[name] = jnp.asarray(np.asarray(values, np.bool_))
            elif isinstance(v0, int):
                cols[name] = jnp.asarray(np.asarray(values, np.int32))
            else:
                cols[name] = jnp.asarray(np.asarray(values, np.float32))
        return ColumnTable(cols, dicts)

    @staticmethod
    def from_columns(cols: Dict[str, np.ndarray],
                     dicts: Optional[Dict[str, List[str]]] = None,
                     date_cols: Sequence[str] = ()) -> "ColumnTable":
        """Build from the columnar parser's output
        (``workloads.tpch.parse_tbl_columnar``): numeric numpy arrays
        and object arrays of strings."""
        out: Dict[str, jnp.ndarray] = {}
        dd: Dict[str, List[str]] = dict(dicts or {})
        for name, arr in cols.items():
            a = np.asarray(arr)
            if a.dtype.kind in "OUS":
                vals = [str(x) for x in a.tolist()]
                is_date = name in date_cols or bool(
                    len(vals) and _DATE_RE.match(vals[0]))
                out[name], uniq = _encode_strings(vals, is_date)
                if uniq is not None:
                    dd[name] = uniq
            elif a.dtype.kind == "i":
                out[name] = jnp.asarray(a.astype(np.int32))
            elif a.dtype.kind == "f":
                out[name] = jnp.asarray(a.astype(np.float32))
            else:
                out[name] = jnp.asarray(a)
        return ColumnTable(out, dd)

    # --- shape / access ----------------------------------------------
    @property
    def num_rows(self) -> int:
        return int(next(iter(self.cols.values())).shape[0])

    def __getitem__(self, name: str) -> jnp.ndarray:
        return self.cols[name]

    def mask(self) -> jnp.ndarray:
        """Validity as a bool array (materializes all-true if unset)."""
        if self.valid is not None:
            return self.valid
        n = self.num_rows
        return jnp.ones((n,), jnp.bool_)

    def code(self, name: str, value: str) -> int:
        """Dictionary code of ``value`` in string column ``name``; -1 if
        absent (compares false against every row on device)."""
        try:
            return self.dicts[name].index(value)
        except ValueError:
            return -1

    def codes_where(self, name: str, pred) -> List[int]:
        """All dictionary codes whose string satisfies ``pred`` — for
        LIKE-style predicates evaluated once on the host dictionary
        instead of per row (e.g. Q02 'ends with BRUSHED', Q13 comment
        NOT LIKE)."""
        return [i for i, s in enumerate(self.dicts[name]) if pred(s)]

    def decode(self, name: str, code: int) -> str:
        return self.dicts[name][int(code)]

    def compact(self) -> "ColumnTable":
        """Materialize validity: drop invalid rows (placement padding,
        applied filters) and return a mask-free table. Host-side dynamic
        shape — call OUTSIDE jit; traced code uses the mask algebra
        instead. This is the bridge from a placement-padded stored table
        back to the direct columnar query path, which assumes every row
        is real."""
        if self.valid is None:
            return self
        cached = self.__dict__.get("_compacted")
        if cached is not None:
            return cached
        keep = np.asarray(self.valid)
        if bool(keep.all()):
            out = ColumnTable(self.cols, self.dicts, None)
        else:
            idx = jnp.asarray(np.flatnonzero(keep))
            out = ColumnTable({n: jnp.take(c, idx, axis=0)
                               for n, c in self.cols.items()},
                              self.dicts, None)
        # memoized: repeated direct-path queries over one stored table
        # must not re-gather per call (and downstream per-table caches —
        # column stats, join plans — key on the compacted instance)
        self.__dict__["_compacted"] = out
        return out

    # --- relational verbs (mask algebra) ------------------------------
    def filter(self, mask: jnp.ndarray) -> "ColumnTable":
        """AND a predicate mask into validity. Shapes unchanged."""
        new = mask if self.valid is None else (self.valid & mask)
        return ColumnTable(self.cols, self.dicts, new)

    def select(self, names: Sequence[str]) -> "ColumnTable":
        return ColumnTable({n: self.cols[n] for n in names},
                           {n: d for n, d in self.dicts.items() if n in names},
                           self.valid)

    def with_column(self, name: str, arr: jnp.ndarray,
                    dictionary: Optional[List[str]] = None) -> "ColumnTable":
        cols = dict(self.cols)
        cols[name] = arr
        dicts = dict(self.dicts)
        if dictionary is not None:
            dicts[name] = dictionary
        return ColumnTable(cols, dicts, self.valid)

    # --- persistence (store spill / checkpoint) -----------------------
    def __getstate__(self):
        """Pickle via host numpy (device arrays aren't spill-portable);
        lets a ColumnTable live in a SetStore set like any object and
        survive ``flush``/``load_set``."""
        return {"cols": {n: np.asarray(c) for n, c in self.cols.items()},
                "dicts": self.dicts,
                "valid": None if self.valid is None else np.asarray(self.valid)}

    def __setstate__(self, state):
        self.cols = {n: jnp.asarray(c) for n, c in state["cols"].items()}
        self.dicts = state["dicts"]
        v = state["valid"]
        self.valid = None if v is None else jnp.asarray(v)

    # --- pytree protocol ----------------------------------------------
    # Registered below: a ColumnTable is a jit-traceable value (columns
    # and validity are leaves; names and string dictionaries are static
    # metadata). This is what lets a table stored in a set become a
    # *traced argument* of a compiled query plan — and, when its columns
    # carry a NamedSharding from a set placement, what lets XLA
    # partition the whole query and insert the collectives
    # (netsdb_tpu.parallel.placement).
    def tree_flatten(self):
        names = tuple(sorted(self.cols))
        children = tuple(self.cols[n] for n in names) + (self.valid,)
        # Dictionaries can be huge (e.g. a comment column ≈ one string
        # per row); a query executes on every call but the dict content
        # never changes after construction, so the aux key — tuple copy
        # AND its hash — is built once per table, not per flatten
        # (protects the executor's compiled-plan fast path).
        key = self.__dict__.get("_aux_key")
        if key is None or key.names != names:
            key = _TableAuxKey(
                names, tuple((k, tuple(v))
                             for k, v in sorted(self.dicts.items())))
            self.__dict__["_aux_key"] = key
        return children, key

    @classmethod
    def tree_unflatten(cls, aux, children):
        obj = cls.__new__(cls)
        obj.cols = dict(zip(aux.names, children[:-1]))
        obj.dicts = {k: list(v) for k, v in aux.dicts}
        obj.valid = children[-1]
        obj.__dict__["_aux_key"] = aux
        return obj

    # --- host materialization ----------------------------------------
    def to_rows(self, date_cols: Sequence[str] = ()) -> List[Dict[str, Any]]:
        """Decode to row dicts (drops invalid rows). Host-side; for
        tests and result iteration, not the hot path."""
        host = {n: np.asarray(c) for n, c in self.cols.items()}
        ok = np.asarray(self.mask())
        out = []
        for i in range(len(ok)):
            if not ok[i]:
                continue
            row = {}
            for n, c in host.items():
                v = c[i]
                if n in self.dicts:
                    row[n] = self.dicts[n][int(v)]
                elif n in date_cols:
                    row[n] = int_to_date(int(v))
                elif c.dtype.kind == "f":
                    row[n] = float(v)
                elif c.dtype.kind == "b":
                    row[n] = bool(v)
                else:
                    row[n] = int(v)
            out.append(row)
        return out


jax.tree_util.register_pytree_node(
    ColumnTable,
    ColumnTable.tree_flatten,
    ColumnTable.tree_unflatten,
)
