"""Out-of-core relational execution: TPC-H through the paged store.

The reference's PageScanner streams sets bigger than RAM through every
pipeline — 64 MB pages pinned one at a time, fed to the pipeline
threads, evicted behind them (``src/storage/headers/PageScanner.h``,
``PageCircularBuffer.h``). Round 1 wired that streaming to matmul only;
this module runs the COLUMNAR QUERY ENGINE the same way: fact-table
columns live as row-chunk pages in the native page store (whose arena
cap forces spill-to-disk for cold pages), and a query is one compiled
chunk-step folded over the stream.

The chunk step IS the distributed engine's combiner: a masked partial
aggregate with a fixed-shape output (``sharded.py`` runs the same
kernels over shards in SPACE and merges with psum; here the "shards"
arrive in TIME and merge by accumulation — the same math either way,
so out-of-core answers match in-memory ones to float summation order).

Chunks are padded to the fixed page row count, so every chunk reuses
ONE compiled XLA program (static shapes; the ragged tail rides the
validity mask like everywhere else in this framework).
"""

from __future__ import annotations

import contextlib
import itertools
from typing import Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from netsdb_tpu import obs
from netsdb_tpu.relational.table import ColumnTable, date_to_int, int_to_date
from netsdb_tpu.storage.paged import PagedTensorStore
from netsdb_tpu.utils.locks import RWLock

_INT_KINDS = "ib"


class PagedColumns:
    """A relation's columns paged as row-chunks in a PagedTensorStore.

    Integer and float columns pack into two page matrices with a SHARED
    row blocking, so one stream step yields every column for the same
    row range (the reference's page layout holds whole objects per page
    for the same reason). Dictionaries and host metadata stay resident
    — only bulk column data pages."""

    def __init__(self, store: PagedTensorStore, name: str,
                 int_names: List[str], float_names: List[str],
                 num_rows: int, row_block: int,
                 dicts: Optional[Dict[str, List[str]]] = None,
                 stats: Optional[Dict[str, object]] = None):
        self.store = store
        self.name = name
        self.int_names = int_names
        self.float_names = float_names
        self.num_rows = num_rows
        self.row_block = row_block
        self.dicts = dicts or {}
        # stream-vs-mutation guard: streams (executor folds, snapshots)
        # run OUTSIDE the SetStore lock, so a concurrent append/drop
        # could free or grow pages mid-stream; streams hold read, the
        # mutators hold write (the arena pin, Python-side)
        self.rw = RWLock(name="PagedColumns.rw")
        self.dropped = False  # set by drop(); appends must not
        # resurrect freed arena names (a fresh put under a dead name
        # would leak unreferenced pages)
        # chunks yielded over this relation's lifetime — the per-
        # relation page-load diagnostic the grace-hash tests assert on
        # (one-pass discipline: probe chunks read ONCE, not once per
        # build block)
        self.pages_streamed = 0
        # ingest-time ColumnStats per int column — collected in the one
        # pass that already touches every row, so the planner never has
        # to re-stream the set (the reference's StorageCollectStats
        # moment, ``PangeaStorageServer.h:48``)
        self.stats = stats or {}
        # device-cache binding (storage/devcache.py), set by
        # ``SetStore._bind_cache`` for store-owned relations only —
        # grace-hash spill partitions and bench temporaries stay
        # uncached. ``_mutations`` is this handle's own append/drop
        # counter: it rides every cache key so even direct
        # ``pc.append`` callers (bypassing the store's version bump)
        # can never leave a stale cached run matchable.
        self.devcache = None
        self.cache_scope = None
        self.cache_version_fn = None
        self._mutations = 0

    # ------------------------------------------------------------ ingest
    @staticmethod
    def _pack(cols: Dict[str, np.ndarray], int_names: List[str],
              float_names: List[str]):
        """Columns → (int32 matrix, float32 matrix, row count), the ONE
        packing used by ingest and append (divergent packing would make
        appended pages unreadable against ingested ones)."""
        lengths = {n: len(np.asarray(c)) for n, c in cols.items()}
        if len(set(lengths.values())) > 1:
            raise ValueError(f"ragged columns cannot page together: "
                             f"{lengths}")
        n = next(iter(lengths.values()))
        imat = (np.stack([np.asarray(cols[c]).astype(np.int32)
                          for c in int_names], axis=1)
                if int_names else None)
        fmat = (np.stack([np.asarray(cols[c]).astype(np.float32)
                          for c in float_names], axis=1)
                if float_names else None)
        return imat, fmat, n

    @staticmethod
    def ingest(store: PagedTensorStore, name: str,
               cols: Dict[str, np.ndarray],
               row_block: Optional[int] = None,
               dicts: Optional[Dict[str, List[str]]] = None,
               ) -> "PagedColumns":
        """Page a dict of host columns. ``row_block`` defaults so that
        one int-matrix page is ~the configured page size."""
        int_names = sorted(n for n, c in cols.items()
                           if np.asarray(c).dtype.kind in _INT_KINDS)
        float_names = sorted(n for n, c in cols.items()
                             if n not in int_names)
        imat, fmat, num_rows = PagedColumns._pack(cols, int_names,
                                                  float_names)
        if row_block is None:
            width = max(len(int_names) + len(float_names), 1)
            row_block = max(store.config.page_size_bytes // (4 * width),
                            1024)
        row_block = min(row_block, num_rows)
        from netsdb_tpu.relational.stats import analyze_array

        stats = {}
        if imat is not None:
            stats = {n: analyze_array(imat[:, j])
                     for j, n in enumerate(int_names)}
            store.put(f"{name}.int", imat, row_block=row_block)
        if fmat is not None:
            store.put(f"{name}.float", fmat, row_block=row_block)
        return PagedColumns(store, name, int_names, float_names,
                            num_rows, row_block, dicts, stats)

    @staticmethod
    def from_table(store: PagedTensorStore, name: str, table: ColumnTable,
                   columns: List[str],
                   row_block: Optional[int] = None) -> "PagedColumns":
        cols = {n: np.asarray(table[n]) for n in columns}
        return PagedColumns.ingest(store, name, cols, row_block,
                                   dicts={n: d for n, d in
                                          table.dicts.items()
                                          if n in columns})

    # ------------------------------------------------------------ append
    def append(self, cols: Dict[str, np.ndarray]) -> None:
        """Append a batch of rows as ADDITIONAL pages (the reference's
        addData continuously appending to a set) — no rewrite of
        existing pages. ATOMIC at the relation level: a failure while
        writing either matrix rolls both back to the pre-append page
        count (a half-written batch would otherwise desynchronize the
        co-paged int/float streams and brick the whole set). Stats and
        ``num_rows`` update only after both writes succeed."""
        from netsdb_tpu.relational.stats import ColumnStats, analyze_array

        if set(cols) != set(self.int_names) | set(self.float_names):
            raise ValueError(
                f"append schema mismatch: have "
                f"{sorted(set(self.int_names) | set(self.float_names))}, "
                f"got {sorted(cols)}")
        # _pack re-casts by the STORED classification, so a float batch
        # column landing on an int-classified stored column would
        # silently truncate via astype(int32) — reject it (int→float
        # widens losslessly and stays allowed)
        for n in self.int_names:
            if np.asarray(cols[n]).dtype.kind not in _INT_KINDS:
                raise TypeError(
                    f"append column {n!r} is float-valued but the "
                    f"stored column is int-classified; casting would "
                    f"truncate — convert explicitly first")
        imat, fmat, n_new = self._pack(cols, self.int_names,
                                       self.float_names)
        if n_new == 0:
            return  # all-masked/empty batch: a no-op, not a stats merge
        with self.rw.write():  # drain in-flight streams before growing
            if self.dropped:
                raise KeyError(f"paged relation {self.name!r} was "
                               f"dropped; cannot append")
            undo = []
            for suffix, mat in ((".int", imat), (".float", fmat)):
                if mat is None:
                    continue
                full = self.name + suffix
                undo.append((full, self.store.num_blocks(full),
                             self.num_rows))
                try:
                    self.store.put(full, mat, append=True)
                except Exception:
                    for uname, npages, rows in undo:
                        self.store.truncate_to(uname, npages, rows)
                    raise
            for j, name in enumerate(self.int_names):
                new = analyze_array(imat[:, j])
                old = self.stats.get(name)
                self.stats[name] = (new if old is None else ColumnStats(
                    old.n_rows + new.n_rows, min(old.min_val, new.min_val),
                    max(old.max_val, new.max_val), -1))
            n_before = self.num_rows
            self.num_rows += n_new
            self._mutations += 1  # cached whole RUNS of the old rows
            # are dead (their key carries this counter); cached BLOCKS
            # are range-keyed and survive — only the appended tail is
            # dirty. Invalidating here (not just in SetStore._touch)
            # covers direct pc.append callers that bypass the store.
        if (self.devcache is not None and self.cache_scope is not None
                and getattr(self.devcache, "partial", False)):
            self.devcache.invalidate_range(self.cache_scope, n_before,
                                           self.num_rows)

    def update_column(self, name: str, values) -> None:
        """Overwrite ONE column's values in place (same row count) —
        the update-in-place write. Each page the column lives in is
        rewritten where it sits (``PagedTensorStore.rewrite_block``,
        same shape — no layout change, no page movement), and the
        device cache drops only block entries whose stream PROJECTED
        this column (per-column dirty ranges): a query over the other
        columns keeps serving its cached blocks with zero re-stages.
        Column-projected streams key their blocks by their projection
        (``_partial_plan(columns=...)``); full-table streams carry no
        projection marker and always drop — they contain this column."""
        values = np.asarray(values)
        if name in self.dicts:
            raise ValueError(f"update_column: {name!r} is dict-encoded"
                             f" — update through re-ingest (codes would"
                             f" be meaningless)")
        if name in self.int_names:
            if values.dtype.kind not in _INT_KINDS:
                raise TypeError(
                    f"update_column {name!r}: stored column is "
                    f"int-classified; casting floats would truncate")
            suffix, names = ".int", self.int_names
        elif name in self.float_names:
            suffix, names = ".float", self.float_names
        else:
            raise KeyError(f"no column {name!r} in {self.name!r}")
        if len(values) != self.num_rows:
            raise ValueError(
                f"update_column {name!r}: {len(values)} values for "
                f"{self.num_rows} rows (in-place updates replace the "
                f"whole column)")
        full = self.name + suffix
        j = names.index(name)
        with self.rw.write():  # drain in-flight streams first
            if self.dropped:
                raise KeyError(f"paged relation {self.name!r} was "
                               f"dropped; cannot update")
            for idx, (s0, e0) in enumerate(self.store.block_ranges(full)):
                _start, blk = self.store.read_block(full, idx)
                arr = np.array(blk)  # read_block views are read-only
                arr[:, j] = values[s0:e0]
                self.store.rewrite_block(full, idx, arr)
            if name in self.int_names:
                from netsdb_tpu.relational.stats import analyze_array

                self.stats[name] = analyze_array(values.astype(np.int32))
            self._mutations += 1  # whole-run keys of old content die
        if (self.devcache is not None and self.cache_scope is not None
                and getattr(self.devcache, "partial", False)):
            self.devcache.invalidate_range(self.cache_scope, 0,
                                           self.num_rows,
                                           columns=(name,))

    # ------------------------------------------------------------ stream
    def pad_rows(self) -> int:
        """Row count every streamed chunk pads to: ``row_block``'s
        shape BUCKET when the config enables bucketing (so ragged
        tails and differing ingest sizes reuse one compiled chunk step
        per bucket — ``plan/staging.bucket_rows``), else ``row_block``
        exactly. Padded rows ride the validity mask either way."""
        from netsdb_tpu.plan.staging import pad_rows_target

        return pad_rows_target(
            self.row_block,
            getattr(self.store.config, "shape_bucketing", True),
            density=getattr(self.store.config, "bucket_density", 2))

    def stream(self, prefetch: Optional[int] = None, device: bool = True):
        """Chunk stream of (cols, valid, start_row), every chunk padded
        to :meth:`pad_rows` rows — the PageScanner loop feeding the
        compiled chunk step. Ragged blocks (appended batches' tails)
        are masked, never reshaped; ``start_row`` is the chunk's global
        row offset (exact even for ragged streams).

        ``device=False`` keeps the chunks as NUMPY columns (the serve
        wire streams pages to a client — the device must never see
        them) and returns a plain generator.  ``device=True`` returns a
        :class:`~netsdb_tpu.plan.staging.StagedStream`: the device
        upload runs ``config.stage_depth`` chunks ahead on a background
        thread, so the next chunk lands in HBM while the consumer's
        step computes.  ``prefetch`` (None = the
        ``config.stream_prefetch_pages`` knob) is the HOST read-ahead
        depth underneath.  Either way the relation's read lock is held
        for the stream's lifetime — on the staging thread for the
        device path — so a concurrent append/drop (write lock) cannot
        free or grow pages mid-stream; close() abandoned streams."""
        if not device:
            return self._host_stream(prefetch)
        from netsdb_tpu.plan.staging import stage_stream

        def place(item):
            cols, valid, start = item
            return ({k: jnp.asarray(v) for k, v in cols.items()},
                    jnp.asarray(valid), start)

        return stage_stream(
            self._host_stream(prefetch), place,
            depth=getattr(self.store.config, "stage_depth", 2),
            name=f"cols:{self.name}")

    def _host_stream(self, prefetch: Optional[int] = None,
                     blocks: Optional[List[int]] = None,
                     columns: Optional[List[str]] = None
                     ) -> Iterator[Tuple[Dict[str, np.ndarray],
                                         np.ndarray, int]]:
        """Locked host-side chunk generator (numpy columns). Runs —
        lock acquisition included — on whichever thread iterates it:
        the consumer directly (``device=False``) or the staging thread
        (``device=True``). ``blocks`` restricts to those page indices
        (the stitched gap feed — cached pages never touch the arena);
        ``columns`` projects: a matrix none of whose columns are
        requested is never read at all."""
        with self.rw.read():
            if self.dropped:
                raise KeyError(f"paged relation {self.name!r} was "
                               f"dropped; cannot stream")
            yield from self._stream_unlocked(prefetch, blocks, columns)

    def _stream_unlocked(self, prefetch: Optional[int] = None,
                         blocks: Optional[List[int]] = None,
                         columns: Optional[List[str]] = None
                         ) -> Iterator[Tuple[Dict[str, np.ndarray],
                                             np.ndarray, int]]:
        if columns is not None:
            missing = set(columns) - (set(self.int_names)
                                      | set(self.float_names))
            if missing:
                raise KeyError(f"no columns {sorted(missing)} in "
                               f"{self.name!r}")
        want = (lambda n: columns is None or n in columns)
        streams = []
        if self.int_names and any(want(n) for n in self.int_names):
            streams.append((self.int_names,
                            self.store.stream_blocks(f"{self.name}.int",
                                                     prefetch,
                                                     blocks=blocks)))
        if self.float_names and any(want(n) for n in self.float_names):
            streams.append((self.float_names,
                            self.store.stream_blocks(
                                f"{self.name}.float", prefetch,
                                blocks=blocks)))
        while True:
            chunk: Dict[str, np.ndarray] = {}
            start = n = None
            exhausted, yielded = [], []
            for names, it in streams:
                try:
                    s0, block = next(it)
                except StopIteration:
                    exhausted.append(names)
                    continue
                yielded.append(names)
                if start is None:
                    start, n = s0, block.shape[0]
                elif s0 != start or block.shape[0] != n:
                    raise RuntimeError(
                        "int/float page streams desynchronized "
                        f"({s0},{block.shape[0]}) vs ({start},{n})")
                for j, name in enumerate(names):
                    if want(name):
                        chunk[name] = block[:, j]
            if exhausted:
                # both streams must end on the same round — one ending
                # early would otherwise silently truncate the other's
                # remaining rows out of the query result
                if yielded:
                    raise RuntimeError(
                        "int/float page streams desynchronized: "
                        f"{exhausted} ended while {yielded} still had "
                        f"blocks")
                return
            pad = self.pad_rows() - n
            if pad > 0:
                chunk = {k: np.pad(v, (0, pad)) for k, v in chunk.items()}
            valid = np.arange(n + max(pad, 0)) < n
            self.pages_streamed += 1
            yield chunk, valid, start

    def num_pages(self) -> int:
        """Row-chunk page count (the co-paged int/float streams share
        one blocking, so either matrix's count is THE count)."""
        suffix = ".int" if self.int_names else ".float"
        return self.store.num_blocks(self.name + suffix)

    def _cache_ref(self, kind: str, placement, columns=None):
        """(cache, key) when this relation is store-owned and the
        device cache is on, else (None, None). The key is the
        tentpole's ``(db:set, version, bucket, sharding)`` — plus this
        handle's own mutation counter, the stream kind and any column
        PROJECTION — so a warm stream of the SAME content/shape/
        sharding replays device-resident blocks and any write anywhere
        unkeys every old run."""
        cache = self.devcache
        if (cache is None or not cache.enabled
                or self.cache_scope is None or self.dropped):
            return None, None
        ver = (self.cache_version_fn()
               if self.cache_version_fn is not None else 0)
        key = (self.cache_scope, ver, self._mutations, kind,
               self.pad_rows(),
               placement.label() if placement is not None else None)
        if columns is not None:
            key = key + (("cols",) + tuple(sorted(columns)),)
        return cache, key

    def partial_base_key(self, kind: str, placement, columns=None):
        """The block-entry base key for one stream shape of this
        relation: ``(scope, kind, bucket, sharding)`` — NO write
        version and NO mutation counter (block freshness is
        dirty-range invalidation's job) — plus, for column-PROJECTED
        streams, a trailing ``frozenset`` of the projected columns:
        the marker per-column invalidation matches against (an entry
        whose projection is disjoint from an updated column survives;
        unmarked entries contain every column and always drop). Also
        the key ``parallel/reshard.reshard_set`` moves entries
        between: same shape, different sharding label."""
        base = (self.cache_scope, kind, self.pad_rows(),
                placement.label() if placement is not None else None)
        if columns is not None:
            base = base + (frozenset(columns),)
        return base

    def _partial_plan(self, kind: str, placement, prefetch,
                      columns=None):
        """A :class:`~netsdb_tpu.plan.staging.PartialPlan` for one
        stream of this relation under the block-granular cache, or
        None (cache off / whole-run mode / unbound temporary)."""
        from netsdb_tpu.plan.staging import PartialPlan

        cache = self.devcache
        if (cache is None or not cache.enabled
                or not getattr(cache, "partial", False)
                or self.cache_scope is None or self.dropped):
            return None
        base_key = self.partial_base_key(kind, placement, columns)
        ranges = self.block_ranges()
        if not ranges:
            return None
        return PartialPlan(
            cache, base_key, ranges,
            lambda idxs: self._host_stream(prefetch, blocks=idxs,
                                           columns=columns))

    def block_ranges(self) -> List[Tuple[int, int]]:
        """The relation's [(start_row, end_row)] block layout —
        metadata only (the co-paged int/float matrices share one
        blocking, so either matrix's layout is THE layout)."""
        suffix = ".int" if self.int_names else ".float"
        return self.store.block_ranges(self.name + suffix)

    def drop(self) -> None:
        """Free this relation's pages from the shared arena (both the
        int and float matrices). After this the PagedColumns is dead.
        Waits for in-flight streams (read lock holders) to drain."""
        with self.rw.write():
            self.dropped = True
            self._mutations += 1
            for suffix in (".int", ".float"):
                self.store.drop(self.name + suffix)
        if self.devcache is not None and self.cache_scope is not None:
            self.devcache.invalidate(self.cache_scope)

    def stream_tables(self, prefetch: Optional[int] = None,
                      placement=None,
                      columns: Optional[List[str]] = None):
        """The PageScanner feed for the set/DAG API: a
        :class:`~netsdb_tpu.plan.staging.StagedStream` of chunk
        ColumnTables (validity-masked, plus a ``_rowid`` global-row-
        index column so key-range folds can recover absolute rows).
        The whole device leg — pad, upload, mesh-shard — runs
        ``config.stage_depth`` chunks ahead on the staging thread, so
        the next chunk is HBM-resident while the consumer's fold step
        computes; ``prefetch`` (None = the config knob) is the host
        page read-ahead underneath.

        ``placement`` mesh-shards every chunk's rows before yielding —
        the streamed-pages-onto-mesh-shards path (each device folds its
        shard of every page; XLA inserts the per-chunk collectives the
        reference's workers-stream-local-partitions model implies,
        ``PipelineStage.cc:228-265``). Ingest rounds ``row_block`` to
        the shard granularity (and buckets ≥ 16 are multiples of 8),
        so placed chunks usually shard without a second padding round —
        when a bucket doesn't divide, ``shard_table`` pads the
        remainder (one deterministic final shape per bucket either
        way).

        Store-owned relations consult the cross-query DEVICE CACHE
        first (``storage/devcache.py``). Whole-run mode
        (``device_cache_partial=off``): a warm stream replays the
        placed chunk run already in device memory and a cold stream
        installs the completed run on the way through. Partial mode
        (the default): each cached BLOCK range serves from HBM — zero
        arena reads — stitched in row order with gap ranges streaming
        through the normal pipeline, and every placed gap block
        installs as it goes (early exit keeps the consumed prefix).
        Cached chunks are owned by the cache, never donation targets
        (fold steps donate only their carried accumulator).

        ``columns`` projects the stream to just those columns: a
        packed matrix none of whose columns are requested is never
        read from the arena, and the cached blocks key on the
        projection — a per-column dirty range from ``update_column``
        drops only the streams that contained the touched column."""
        from netsdb_tpu.plan.staging import stage_stream

        cache, cache_key = self._cache_ref("tables", placement, columns)
        base_rowid = np.arange(self.pad_rows(), dtype=np.int32)
        dicts = self.dicts
        if columns is not None:
            dicts = {k: v for k, v in dicts.items() if k in columns}

        def place(item):
            cols, valid, start = item
            cols = dict(cols)
            # the stream's own start is exact even for ragged
            # (appended) block sequences; invalid tail rows get bogus
            # ids, masked like everything else
            cols["_rowid"] = base_rowid[:len(valid)] + start
            if placement is not None:
                from netsdb_tpu.parallel.placement import shard_table

                # shard_table pads to the shard granularity and
                # device_puts every column with the mesh sharding
                return shard_table(ColumnTable(cols, dicts, valid),
                                   placement)
            return ColumnTable({k: jnp.asarray(v) for k, v in cols.items()},
                               dicts, jnp.asarray(valid))

        partial = self._partial_plan("tables", placement, prefetch,
                                     columns)
        if partial is not None:
            return stage_stream(
                None, place,
                depth=getattr(self.store.config, "stage_depth", 2),
                name=f"tables:{self.name}", partial=partial,
                scope=str(self.cache_scope))
        return stage_stream(
            self._host_stream(prefetch, columns=columns), place,
            depth=getattr(self.store.config, "stage_depth", 2),
            name=f"tables:{self.name}",
            cache=cache, cache_key=cache_key,
            cache_validator=(
                None if cache is None else
                lambda: self._cache_ref("tables", placement,
                                        columns)[1] == cache_key))

    def stream_host_tables(self, prefetch: Optional[int] = None
                           ) -> Iterator[ColumnTable]:
        """Yield each chunk as a COMPACT host-side ColumnTable (numpy
        columns, padding stripped, no ``_rowid``) — the serve wire's
        page feed (``FrontendQueryTestServer.cc:785-890`` streams each
        node's local pages to the client page by page): per-frame bytes
        bounded by one page, and the device never sees the data."""
        # closing: an abandoned OUTER iterator (the serve wire loop
        # stops early / errors) must close the inner locked stream NOW,
        # not at GC — GeneratorExit propagates through the with
        with contextlib.closing(
                self.stream(prefetch, device=False)) as chunks:
            for cols, valid, _start in chunks:
                n = int(np.asarray(valid).sum())
                yield ColumnTable({k: v[:n] for k, v in cols.items()},
                                  dict(self.dicts), None)

    def to_host_table(self) -> ColumnTable:
        """Materialize the relation as one HOST-resident ColumnTable
        (numpy columns, nothing touches the device) — the snapshot path
        (``SetStore.flush``): device memory stays bounded no matter how
        large the paged relation is."""
        with obs.span(f"ooc.host_assemble:{self.name}", "storage"):
            return self._to_host_table()

    def _to_host_table(self) -> ColumnTable:
        parts: Dict[str, List[np.ndarray]] = {}
        n_done = 0
        # the consistency check compares against num_rows AS OF the
        # snapshot (read under the same lock the stream holds): a
        # concurrent append landing after the stream drains must not
        # turn a perfectly consistent pre-append snapshot into an error
        with self.rw.read():
            expected = self.num_rows
            for cols, valid, _start in self._stream_unlocked():
                n = int(np.asarray(valid).sum())
                for k, v in cols.items():
                    parts.setdefault(k, []).append(np.asarray(v)[:n])
                n_done += n
        if n_done != expected:
            raise RuntimeError(f"paged set {self.name!r}: streamed "
                               f"{n_done} rows, expected {expected}")
        from netsdb_tpu.relational.stats import inject_stats

        out = ColumnTable({k: np.concatenate(v)
                           for k, v in parts.items()}, self.dicts, None)
        return inject_stats(out, self.stats)

    def to_table(self) -> ColumnTable:
        """Materialize the whole relation as one DEVICE-resident
        ColumnTable — the compatibility escape hatch (``get_table`` on
        a paged set, fold-less query fallback). Defeats paging by
        construction; the streamed path is ``stream_tables``."""
        host = self.to_host_table()
        from netsdb_tpu.relational.stats import inject_stats

        out = ColumnTable({k: jnp.asarray(v) for k, v in host.cols.items()},
                          host.dicts, None)
        return inject_stats(out, self.stats)


# ----------------------------------------------- grace-hash partitioning
_grace_ids = itertools.count()

#: Fibonacci-multiply constant (golden-ratio reciprocal in 64 bits) —
#: the splitmix64 first-stage multiplier
_KEY_MIX_MULT = np.uint64(0x9E3779B97F4A7C15)


def mix_partition_key(kv: np.ndarray) -> np.ndarray:
    """Avalanche a key column before the partition modulus (uint64).

    Bare ``key % nparts`` collapses clustered/strided key sets: keys
    sharing a factor with ``nparts`` (every ``k*nparts``-strided id
    column does) land in a handful of partitions, re-inflating the
    per-partition build table that must be device-resident — the
    grace-hash memory bound degrades toward the full build side. A
    Fibonacci multiply + xor-shift (splitmix-style finalizer) spreads
    any key structure uniformly; applied identically on BOTH the build
    and the probe side (both stream through
    :func:`partition_by_key`), so matching keys still meet in the same
    partition — the reference hash-partitions both sides the same way
    (``PipelineStage.cc`` partition stage)."""
    h = np.asarray(kv).astype(np.int64).view(np.uint64) * _KEY_MIX_MULT
    h ^= h >> np.uint64(29)
    h *= np.uint64(0xBF58476D1CE4E5B9)
    h ^= h >> np.uint64(32)
    return h


def partition_by_key(pc: PagedColumns, key: str, nparts: int,
                     keep_rowid: bool = False,
                     columns: Optional[Tuple[str, ...]] = None
                     ) -> List[Optional[PagedColumns]]:
    """ONE streaming pass over ``pc``, hash-partitioning its valid rows
    by ``mix(key) % nparts`` (:func:`mix_partition_key` — both join
    sides mix identically, so clustered/strided keys keep the
    per-partition memory bound) into ``nparts`` spill relations in the
    SAME arena — the reference's partition stage writing both join
    sides through the partitioned hash-set manager
    (``src/queryExecution/source/PipelineStage.cc:1652-1728``,
    ``HashSetManager.h``). Per-partition output buffers flush to arena
    pages at the relation's row_block (bounded host memory: nparts ×
    row_block rows), so partitions spill like any other paged data.

    ``keep_rowid=True`` stores the original global ``_rowid`` as a
    ``_rowid0`` column (the partition stream renumbers ``_rowid``;
    folds that arbitrate on global row order need the original).
    Negative keys (orphans/invalid) route to partition 0, where the
    kernels' orphan-key rule drops them. Returns None for partitions
    that received no rows."""
    parts: List[Optional[PagedColumns]] = [None] * nparts
    bufs: List[Dict[str, List[np.ndarray]]] = [{} for _ in range(nparts)]
    buf_rows = [0] * nparts
    uid = next(_grace_ids)

    def flush(p: int) -> None:
        if buf_rows[p] == 0:
            return
        cols = {k: np.concatenate(v) for k, v in bufs[p].items()}
        if parts[p] is None:
            parts[p] = PagedColumns.ingest(
                pc.store, f"{pc.name}#gr{uid}p{p}", cols,
                row_block=pc.row_block, dicts=dict(pc.dicts))
        else:
            parts[p].append(cols)
        bufs[p] = {}
        buf_rows[p] = 0

    # pure HOST pass: hashing/routing never touches the device (the
    # chunks would only round-trip H2D→D2H for numpy bucketing)
    with obs.span(f"ooc.partition:{pc.name}", "storage"), \
            contextlib.closing(pc.stream(prefetch=2,
                                         device=False)) as chunks:
        for ccols, valid, start in chunks:
            n = int(np.asarray(valid).sum())
            cols = {k: v[:n] for k, v in ccols.items()
                    if columns is None or k in columns or k == key}
            if keep_rowid:
                cols["_rowid0"] = np.arange(
                    start, start + n, dtype=np.int32)
            kv = cols[key]
            pid = np.where(kv >= 0,
                           (mix_partition_key(kv)
                            % np.uint64(nparts)).astype(np.int64), 0)
            for p in np.unique(pid):
                sel = pid == p
                for name, c in cols.items():
                    bufs[p].setdefault(name, []).append(c[sel])
                buf_rows[p] += int(sel.sum())
                if buf_rows[p] >= pc.row_block:
                    flush(p)
    for p in range(nparts):
        flush(p)
    return parts


# --------------------------------------------------------- fold runner
def run_fold(fold, pc: PagedColumns, *resident, placement=None):
    """Thin standalone driver for a FoldSpec over one paged relation —
    delegates to the SAME loop the plan executor runs for paged
    ScanSets, exposed for direct/bench use without a Client. One jit
    per pass per call; call-site loops should go through the executor,
    whose compiled-step cache amortizes across jobs."""
    from netsdb_tpu.plan.executor import _run_fold_once
    from netsdb_tpu.plan.staging import fold_donate_argnums

    donate_default = fold_donate_argnums(pc.store.config)

    def step_jit(pidx, step, donate=None):
        return jax.jit(step, donate_argnums=(
            donate_default if donate is None else donate))

    return _run_fold_once(fold, pc, resident, placement, step_jit)


# ---------------------------------------------------------------- Q01
def ooc_q01(pc: PagedColumns, delta_date: str = "1998-09-02"):
    """Q01 over a paged lineitem — same result structure as
    ``queries.cq01``. Thin wrapper: the math lives in
    ``relational.folds.fold_q01`` (the SAME fold the set-API DAG
    streams); only the host-side row decoding is local."""
    from netsdb_tpu.relational.folds import fold_q01

    n_ls = len(pc.dicts["l_linestatus"])
    n_groups = len(pc.dicts["l_returnflag"]) * n_ls
    sums, counts = jax.device_get(
        run_fold(fold_q01({}, {}, {}, delta_date=delta_date), pc))
    names = ("sum_qty", "sum_base_price", "sum_disc_price", "sum_charge",
             "sum_disc")
    out = []
    for g in range(n_groups):
        cnt = int(counts[g])
        if cnt == 0:
            continue
        key = (pc.dicts["l_returnflag"][g // n_ls],
               pc.dicts["l_linestatus"][g % n_ls])
        v = {names[i]: float(sums[i, g]) for i in range(5)}
        v["count"] = cnt
        v["avg_qty"] = v["sum_qty"] / cnt
        v["avg_price"] = v["sum_base_price"] / cnt
        v["avg_disc"] = v["sum_disc"] / cnt
        out.append((key, v))
    out.sort(key=lambda kv: kv[0])
    return out


# ---------------------------------------------------------------- Q06
def ooc_q06(pc: PagedColumns, d0: str = "1994-01-01",
            d1: str = "1995-01-01", disc: float = 0.06, qty: int = 24):
    """Q06 over a paged lineitem — same result as ``queries.cq06``.
    Thin wrapper over ``relational.folds.fold_q06``."""
    from netsdb_tpu.relational.folds import fold_q06

    (acc,) = run_fold(fold_q06({}, {}, {}, d0=d0, d1=d1, disc=disc,
                               qty=qty), pc)
    return [("revenue", float(acc))]


# ---------------------------------------------- Q03: out-of-core JOIN
# The reference joins out of core by making the hash table itself a
# partitioned, spillable object: build stages write a PartitionedHashSet
# through HashSetManager, probe stages stream pages against it
# (``src/queryExecution/headers/HashSetManager.h``,
# ``HermesExecutionServer.cc:901``). The columnar equivalent here:
#
# - BUILD: customer ⋈ orders collapses to a dense per-orderkey LUT
#   [qualifies, o_orderdate, o_shippriority], paged into the SAME
#   spillable store as the data (row_block = partition size, so
#   partition p is exactly block p — resident only while probed).
# - PROBE/MERGE: ``ooc_q03`` is now a thin wrapper over the SAME
#   grace-hash machinery the set-API DAG uses for a paged build side
#   (``relational.dag.q03_probe_fold`` — outer loop over build blocks,
#   inner fold over the lineitem stream, per-partition top-k merged).

def build_q03_side(store: PagedTensorStore,
                   orders: Dict[str, np.ndarray],
                   customer: Dict[str, np.ndarray],
                   segment_code: int, date_int: int,
                   key_cap: int, name: str = "q03.build") -> int:
    """Build the resident side of the Q03 join: filter customers by
    segment, join to orders (host-side build, the small tables), and
    page the per-orderkey LUT into ``store`` partitioned by key range.
    Returns the number of partitions."""
    c_key = np.asarray(customer["c_custkey"])
    c_ok = np.asarray(customer["c_mktsegment"]) == segment_code
    cust_lut = np.zeros(int(c_key.max()) + 1, np.bool_)
    cust_lut[c_key] = c_ok

    o_key = np.asarray(orders["o_orderkey"])
    o_cust = np.asarray(orders["o_custkey"])
    o_date = np.asarray(orders["o_orderdate"])
    o_prio = np.asarray(orders["o_shippriority"])
    o_ok = (o_date < date_int) & cust_lut[o_cust]

    n_keys = int(o_key.max()) + 1
    build = np.zeros((n_keys, 3), np.int32)
    build[o_key, 0] = o_ok
    build[o_key, 1] = o_date
    build[o_key, 2] = o_prio
    store.put(name, build, row_block=key_cap)
    return store.num_blocks(name)


def ooc_q03(pc: PagedColumns, store: PagedTensorStore,
            date: str = "1995-03-15", k: int = 10,
            build_name: str = "q03.build") -> List[Dict[str, object]]:
    """Q03 with lineitem streamed from pages and the join LUT loaded one
    partition at a time — same result structure as ``queries.cq03``.
    Peak device state: one partition's build columns + one per-row
    revenue accumulator + one page of probe columns, independent of
    table or key-space size.

    Thin wrapper: each LUT block becomes a build-side ColumnTable
    (non-qualifying keys → -1, dropped by the orphan-key rule) and the
    grace-hash loop runs the SAME fold + merge the set-API DAG uses for
    a paged build side (``relational.dag.q03_probe_fold``). This bench
    driver keeps the LEGACY per-block discipline (full probe re-stream
    per LUT block — its build lives in a raw block store, not a
    relation); the canonical ONE-PASS grace hash is the set-API path
    (``q03_build_sink``/``q03_probe_sink``, both sides
    hash-partitioned, probe pages read once)."""
    from netsdb_tpu.relational.dag import q03_probe_fold, q03_rows
    from netsdb_tpu.relational.planner import JoinPlan

    if "l_orderkey" not in pc.stats:
        raise KeyError(
            "ooc_q03 needs ingest-time stats for 'l_orderkey' (the join "
            "key-space bound); this PagedColumns has none — re-ingest "
            "via PagedColumns.ingest/from_table")
    ks = pc.stats["l_orderkey"].key_space
    fold = q03_probe_fold(date_to_int(date), k, JoinPlan("lut", max(ks, 1)))
    jstep = jax.jit(fold.passes[0][1])
    out = None
    for p in range(store.num_blocks(build_name)):
        start, bmat = store.read_block(build_name, p)
        keys = np.where(bmat[:, 0] > 0,
                        np.arange(bmat.shape[0], dtype=np.int32) + start,
                        -1).astype(np.int32)
        btab = ColumnTable({"o_orderkey": jnp.asarray(keys),
                            "o_orderdate": jnp.asarray(bmat[:, 1])})
        state = fold.passes[0][0](None, pc, btab)
        with contextlib.closing(pc.stream_tables()) as chunks:
            for chunk in chunks:
                state = jstep(state, chunk, btab)
        part = fold.finalize(state, pc, btab)
        out = part if out is None else fold.merge(out, part)
    return q03_rows(out) if out is not None else []


Q01_COLUMNS = ["l_shipdate", "l_returnflag", "l_linestatus",
               "l_quantity", "l_extendedprice", "l_discount", "l_tax"]
Q06_COLUMNS = ["l_shipdate", "l_discount", "l_quantity",
               "l_extendedprice"]
Q03_COLUMNS = ["l_orderkey", "l_shipdate", "l_extendedprice",
               "l_discount"]


def bench_paged_set_api(rows: int = 60_000_000,
                        pool_bytes: int = 1 << 30,
                        page_bytes: int = 1 << 20,
                        seed: int = 0) -> Dict[str, object]:
    """The SET-API paged path at SF10 scale (60M-row lineitem ≈ SF10's
    59.99M) on the real chip — round-5 item 5: the same
    ``suite_sink_for``/grace-hash DAGs the tests verify at KB scale,
    measured at larger-than-pool scale through ``create_set(storage=
    "paged")`` + ``send_table``, never the thin ``ooc_*`` drivers.

    Measures: q01 through ``suite_sink_for`` (fold streamed over the
    arena), q03 through ``q03_build_sink`` (paged build set) +
    ``q03_probe_sink`` (ONE-PASS grace hash, probe-pass ratio
    asserted), with arena spills recorded. On the axon-tunnel dev
    setup the chunk uploads are transfer-bound (~12-18 MB/s);
    attached-HBM numbers are the deployment case (BASELINE.md
    caveat)."""
    import shutil
    import tempfile
    import time

    from netsdb_tpu.client import Client
    from netsdb_tpu.config import Configuration
    from netsdb_tpu.relational import dag as rdag
    from netsdb_tpu.storage.store import SetIdentifier

    rng = np.random.default_rng(seed)
    n_orders = max(rows // 4, 1)
    n_cust = max(n_orders // 10, 1)
    li = {
        "l_orderkey": rng.integers(0, n_orders, rows, dtype=np.int32),
        "l_shipdate": rng.integers(19920101, 19981231, rows,
                                   dtype=np.int32),
        "l_returnflag": rng.integers(0, 3, rows, dtype=np.int32),
        "l_linestatus": rng.integers(0, 2, rows, dtype=np.int32),
        "l_quantity": rng.integers(1, 51, rows,
                                   dtype=np.int32).astype(np.float32),
        "l_extendedprice": rng.uniform(1000, 100000,
                                       rows).astype(np.float32),
        "l_discount": rng.uniform(0, 0.1, rows).astype(np.float32),
        "l_tax": rng.uniform(0, 0.08, rows).astype(np.float32),
    }
    orders = {
        "o_orderkey": np.arange(n_orders, dtype=np.int32),
        "o_custkey": rng.integers(0, n_cust, n_orders, dtype=np.int32),
        "o_orderdate": rng.integers(19920101, 19981231, n_orders,
                                    dtype=np.int32),
        "o_shippriority": np.zeros(n_orders, np.int32),
    }
    cust = {
        "c_custkey": np.arange(n_cust, dtype=np.int32),
        "c_mktsegment": rng.integers(0, 5, n_cust, dtype=np.int32),
    }
    table_bytes = sum(c.nbytes for c in li.values())
    root = tempfile.mkdtemp(prefix="paged_api_bench_")
    out: Dict[str, object] = {
        "rows": rows, "table_bytes": table_bytes,
        "pool_bytes": pool_bytes,
        "pool_fraction": round(pool_bytes / table_bytes, 3)}
    try:
        c = Client(Configuration(root_dir=root,
                                 page_size_bytes=page_bytes,
                                 page_pool_bytes=pool_bytes))
        c.create_database("d")
        for name, cols, dicts in (
                ("lineitem", li, {"l_returnflag": ["A", "N", "R"],
                                  "l_linestatus": ["F", "O"]}),
                ("orders", orders, None),
                ("customer", cust,
                 {"c_mktsegment": ["AUTOMOBILE", "BUILDING",
                                   "FURNITURE", "HOUSEHOLD",
                                   "MACHINERY"]})):
            c.create_set("d", name, type_name="table",
                         storage="paged" if name != "customer"
                         else "memory")
            t0 = time.perf_counter()
            c.send_table("d", name, ColumnTable(cols, dicts or {}))
            out[f"ingest_{name}_s"] = round(time.perf_counter() - t0, 2)
        del li, orders  # free the host copies; the arena owns the data

        t0 = time.perf_counter()
        q01 = rdag.run_query(c, rdag.q01_sink("d"))
        out["q01_s"] = round(time.perf_counter() - t0, 2)
        out["q01_groups"] = int(np.asarray(q01.mask()).sum())

        cinfo = c.analyze_set("d", "customer")
        seg = cinfo["dicts"]["c_mktsegment"].index("BUILDING")
        c.create_set("d", "q03_build", type_name="table",
                     storage="paged")
        t0 = time.perf_counter()
        c.execute_computations(rdag.q03_build_sink(
            "d", n_customers=n_cust, segment_code=seg))
        out["q03_build_s"] = round(time.perf_counter() - t0, 2)
        li_pc = c.store.get_items(SetIdentifier("d", "lineitem"))[0]
        before = li_pc.pages_streamed
        t0 = time.perf_counter()
        q03 = rdag.run_query(c, rdag.q03_probe_sink(
            "d", n_orders=n_orders))
        out["q03_probe_s"] = round(time.perf_counter() - t0, 2)
        out["q03_rows"] = len(rdag.q03_rows(q03))
        out["probe_passes"] = round(
            (li_pc.pages_streamed - before) / max(li_pc.num_pages(), 1),
            2)
        bpc = c.store.get_items(SetIdentifier("d", "q03_build"))[0]
        out["build_pages"] = bpc.num_pages()
        out["store_stats"] = c.store.page_store().stats()
        out["native"] = c.store.page_store().native
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return out


def bench_out_of_core(rows: int = 60_000_000,
                      pool_bytes: int = 1 << 30,
                      row_block: Optional[int] = None,
                      seed: int = 0) -> Dict[str, object]:
    """SF10-scale synthetic lineitem (60M rows ≈ SF10's 59.99M) through
    q01+q06 under a pool cap far smaller than the table — the
    PageScanner larger-than-memory proof, measured. Verifies against an
    in-memory numpy oracle on the same data."""
    import time

    from netsdb_tpu.config import Configuration

    rng = np.random.default_rng(seed)
    cols = {
        "l_shipdate": rng.integers(19920101, 19981231, rows,
                                   dtype=np.int32),
        "l_returnflag": rng.integers(0, 3, rows, dtype=np.int32),
        "l_linestatus": rng.integers(0, 2, rows, dtype=np.int32),
        "l_quantity": rng.integers(1, 51, rows,
                                   dtype=np.int32).astype(np.float32),
        "l_extendedprice": rng.uniform(1000, 100000,
                                       rows).astype(np.float32),
        "l_discount": rng.uniform(0, 0.1, rows).astype(np.float32),
        "l_tax": rng.uniform(0, 0.08, rows).astype(np.float32),
    }
    table_bytes = sum(c.nbytes for c in cols.values())
    import tempfile

    cfg = Configuration(root_dir=tempfile.mkdtemp(prefix="ooc_bench_"))
    store = PagedTensorStore(cfg, pool_bytes=pool_bytes)
    if row_block is None:
        # one page must be far smaller than the pool or ingest cannot
        # even allocate (several pages stay pinned concurrently): cap a
        # page at pool/8, floor at 4k rows
        width = len(cols)
        row_block = max(min(cfg.page_size_bytes // (4 * width),
                            pool_bytes // (8 * 4 * width)), 4096)
    t0 = time.perf_counter()
    pc = PagedColumns.ingest(store, "lineitem", cols, row_block=row_block,
                             dicts={"l_returnflag": ["A", "N", "R"],
                                    "l_linestatus": ["F", "O"]})
    ingest_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    r01 = ooc_q01(pc)
    q01_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    r06 = ooc_q06(pc)
    q06_s = time.perf_counter() - t0

    # spot-verify q06 against a numpy oracle on the same host columns
    a, b = date_to_int("1994-01-01"), date_to_int("1995-01-01")
    m = ((cols["l_shipdate"] >= a) & (cols["l_shipdate"] < b)
         & (cols["l_discount"] >= 0.06 - 0.011)
         & (cols["l_discount"] <= 0.06 + 0.011)
         & (cols["l_quantity"] < 24))
    oracle = float((cols["l_extendedprice"][m]
                    * cols["l_discount"][m]).sum(dtype=np.float64))
    rel_err = abs(r06[0][1] - oracle) / max(abs(oracle), 1e-9)

    out = {"rows": rows, "table_bytes": table_bytes,
           "pool_bytes": pool_bytes,
           "pool_fraction": round(pool_bytes / table_bytes, 3),
           "ingest_s": round(ingest_s, 2),
           "q01_s": round(q01_s, 2), "q06_s": round(q06_s, 2),
           "q01_groups": len(r01), "q06_rel_err": rel_err,
           "store_stats": store.stats(), "native": store.native}
    store.close()
    import shutil

    shutil.rmtree(cfg.root_dir, ignore_errors=True)  # spilled pages
    return out
