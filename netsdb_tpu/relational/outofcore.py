"""Out-of-core relational execution: TPC-H through the paged store.

The reference's PageScanner streams sets bigger than RAM through every
pipeline — 64 MB pages pinned one at a time, fed to the pipeline
threads, evicted behind them (``src/storage/headers/PageScanner.h``,
``PageCircularBuffer.h``). Round 1 wired that streaming to matmul only;
this module runs the COLUMNAR QUERY ENGINE the same way: fact-table
columns live as row-chunk pages in the native page store (whose arena
cap forces spill-to-disk for cold pages), and a query is one compiled
chunk-step folded over the stream.

The chunk step IS the distributed engine's combiner: a masked partial
aggregate with a fixed-shape output (``sharded.py`` runs the same
kernels over shards in SPACE and merges with psum; here the "shards"
arrive in TIME and merge by accumulation — one compiled program either
way, so out-of-core answers are bit-comparable to in-memory ones).

Chunks are padded to the fixed page row count, so every chunk reuses
ONE compiled XLA program (static shapes; the ragged tail rides the
validity mask like everywhere else in this framework).
"""

from __future__ import annotations

import functools
from typing import Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from netsdb_tpu.relational.table import ColumnTable, date_to_int, int_to_date
from netsdb_tpu.storage.paged import PagedTensorStore

_INT_KINDS = "ib"


class PagedColumns:
    """A relation's columns paged as row-chunks in a PagedTensorStore.

    Integer and float columns pack into two page matrices with a SHARED
    row blocking, so one stream step yields every column for the same
    row range (the reference's page layout holds whole objects per page
    for the same reason). Dictionaries and host metadata stay resident
    — only bulk column data pages."""

    def __init__(self, store: PagedTensorStore, name: str,
                 int_names: List[str], float_names: List[str],
                 num_rows: int, row_block: int,
                 dicts: Optional[Dict[str, List[str]]] = None):
        self.store = store
        self.name = name
        self.int_names = int_names
        self.float_names = float_names
        self.num_rows = num_rows
        self.row_block = row_block
        self.dicts = dicts or {}

    # ------------------------------------------------------------ ingest
    @staticmethod
    def ingest(store: PagedTensorStore, name: str,
               cols: Dict[str, np.ndarray],
               row_block: Optional[int] = None,
               dicts: Optional[Dict[str, List[str]]] = None,
               ) -> "PagedColumns":
        """Page a dict of host columns. ``row_block`` defaults so that
        one int-matrix page is ~the configured page size."""
        int_names = sorted(n for n, c in cols.items()
                           if np.asarray(c).dtype.kind in _INT_KINDS)
        float_names = sorted(n for n, c in cols.items()
                             if n not in int_names)
        lengths = {n: len(np.asarray(c)) for n, c in cols.items()}
        if len(set(lengths.values())) > 1:
            raise ValueError(f"ragged columns cannot page together: "
                             f"{lengths}")
        num_rows = next(iter(lengths.values()))
        if row_block is None:
            width = max(len(int_names) + len(float_names), 1)
            row_block = max(store.config.page_size_bytes // (4 * width),
                            1024)
        row_block = min(row_block, num_rows)
        if int_names:
            imat = np.stack([np.asarray(cols[n]).astype(np.int32)
                             for n in int_names], axis=1)
            store.put(f"{name}.int", imat, row_block=row_block)
        if float_names:
            fmat = np.stack([np.asarray(cols[n]).astype(np.float32)
                             for n in float_names], axis=1)
            store.put(f"{name}.float", fmat, row_block=row_block)
        return PagedColumns(store, name, int_names, float_names,
                            num_rows, row_block, dicts)

    @staticmethod
    def from_table(store: PagedTensorStore, name: str, table: ColumnTable,
                   columns: List[str],
                   row_block: Optional[int] = None) -> "PagedColumns":
        cols = {n: np.asarray(table[n]) for n in columns}
        return PagedColumns.ingest(store, name, cols, row_block,
                                   dicts={n: d for n, d in
                                          table.dicts.items()
                                          if n in columns})

    # ------------------------------------------------------------ stream
    def stream(self, prefetch: int = 2
               ) -> Iterator[Tuple[Dict[str, jnp.ndarray], jnp.ndarray]]:
        """Yield (cols, valid) per chunk, every chunk padded to
        ``row_block`` rows — the PageScanner loop feeding the compiled
        chunk step. Ragged tails are masked, never reshaped."""
        streams = []
        if self.int_names:
            streams.append((self.int_names,
                            self.store.stream_blocks(f"{self.name}.int",
                                                     prefetch)))
        if self.float_names:
            streams.append((self.float_names,
                            self.store.stream_blocks(
                                f"{self.name}.float", prefetch)))
        while True:
            chunk: Dict[str, np.ndarray] = {}
            start = n = None
            exhausted, yielded = [], []
            for names, it in streams:
                try:
                    s0, block = next(it)
                except StopIteration:
                    exhausted.append(names)
                    continue
                yielded.append(names)
                if start is None:
                    start, n = s0, block.shape[0]
                elif s0 != start or block.shape[0] != n:
                    raise RuntimeError(
                        "int/float page streams desynchronized "
                        f"({s0},{block.shape[0]}) vs ({start},{n})")
                for j, name in enumerate(names):
                    chunk[name] = block[:, j]
            if exhausted:
                # both streams must end on the same round — one ending
                # early would otherwise silently truncate the other's
                # remaining rows out of the query result
                if yielded:
                    raise RuntimeError(
                        "int/float page streams desynchronized: "
                        f"{exhausted} ended while {yielded} still had "
                        f"blocks")
                return
            pad = self.row_block - n
            if pad:
                chunk = {k: np.pad(v, (0, pad)) for k, v in chunk.items()}
            valid = np.arange(self.row_block) < n
            yield ({k: jnp.asarray(v) for k, v in chunk.items()},
                   jnp.asarray(valid))


# ---------------------------------------------------------------- Q01
@functools.partial(jax.jit, static_argnums=(0, 1))
def _q01_fold(n_groups: int, n_ls: int, sums, counts, valid, ship, rf,
              ls, qty, price, disc, tax, delta):
    """One page of Q01: the same combiner as ``sharded._q01_local``,
    accumulated instead of psum'd."""
    from netsdb_tpu.relational import kernels as K

    mask = valid & (ship <= delta)
    seg = rf * n_ls + ls
    qty = qty.astype(jnp.float32)
    disc_price = price * (1.0 - disc)
    charge = disc_price * (1.0 + tax)
    rows = [K.segment_sum(v, seg, n_groups, mask)
            for v in (qty, price, disc_price, charge, disc)]
    return sums + jnp.stack(rows), counts + K.segment_count(seg, n_groups,
                                                            mask)


def ooc_q01(pc: PagedColumns, delta_date: str = "1998-09-02"):
    """Q01 over a paged lineitem — same result structure as
    ``queries.cq01``. One compiled fold per page; accumulator shape
    (5, groups) + (groups,) regardless of table size."""
    n_ls = len(pc.dicts["l_linestatus"])
    n_groups = len(pc.dicts["l_returnflag"]) * n_ls
    delta = date_to_int(delta_date)
    sums = jnp.zeros((5, n_groups), jnp.float32)
    counts = jnp.zeros((n_groups,), jnp.int32)
    for cols, valid in pc.stream():
        sums, counts = _q01_fold(
            n_groups, n_ls, sums, counts, valid, cols["l_shipdate"],
            cols["l_returnflag"], cols["l_linestatus"],
            cols["l_quantity"], cols["l_extendedprice"],
            cols["l_discount"], cols["l_tax"], delta)
    sums, counts = jax.device_get((sums, counts))
    names = ("sum_qty", "sum_base_price", "sum_disc_price", "sum_charge",
             "sum_disc")
    out = []
    for g in range(n_groups):
        cnt = int(counts[g])
        if cnt == 0:
            continue
        key = (pc.dicts["l_returnflag"][g // n_ls],
               pc.dicts["l_linestatus"][g % n_ls])
        v = {names[i]: float(sums[i, g]) for i in range(5)}
        v["count"] = cnt
        v["avg_qty"] = v["sum_qty"] / cnt
        v["avg_price"] = v["sum_base_price"] / cnt
        v["avg_disc"] = v["sum_disc"] / cnt
        out.append((key, v))
    out.sort(key=lambda kv: kv[0])
    return out


# ---------------------------------------------------------------- Q06
@jax.jit
def _q06_fold(acc, valid, ship, discount, quantity, price, a, b, disc,
              qty):
    mask = (valid & (ship >= a) & (ship < b)
            & (discount >= disc - 0.011) & (discount <= disc + 0.011)
            & (quantity < qty))
    return acc + jnp.sum(jnp.where(mask, price * discount, 0.0))


def ooc_q06(pc: PagedColumns, d0: str = "1994-01-01",
            d1: str = "1995-01-01", disc: float = 0.06, qty: int = 24):
    """Q06 over a paged lineitem — same result as ``queries.cq06``."""
    acc = jnp.zeros((), jnp.float32)
    a, b = date_to_int(d0), date_to_int(d1)
    for cols, valid in pc.stream():
        acc = _q06_fold(acc, valid, cols["l_shipdate"],
                        cols["l_discount"], cols["l_quantity"],
                        cols["l_extendedprice"], a, b, disc, qty)
    return [("revenue", float(acc))]


# ---------------------------------------------- Q03: out-of-core JOIN
# The reference joins out of core by making the hash table itself a
# partitioned, spillable object: build stages write a PartitionedHashSet
# through HashSetManager, probe stages stream pages against it
# (``src/queryExecution/headers/HashSetManager.h``,
# ``HermesExecutionServer.cc:901``). The columnar equivalent here:
#
# - BUILD: customer ⋈ orders collapses to a dense per-orderkey LUT
#   [qualifies, o_orderdate, o_shippriority], paged into the SAME
#   spillable store as the data (row_block = partition size, so
#   partition p is exactly block p — resident only while probed).
# - PROBE: lineitem streams once per key-range partition; rows outside
#   the partition are masked (grace-hash discipline: join state is
#   bounded by the partition size, never by the key space). The probe
#   fold is one compiled program reused across pages AND partitions.
# - MERGE: per-partition top-k candidates merge on the host (tiny).

@functools.partial(jax.jit, static_argnums=(0,))
def _q03_probe_fold(cap: int, acc, start, qual, valid, okey, ship,
                    price, disc, date):
    from netsdb_tpu.relational import kernels as K

    rel = okey - start
    in_part = (rel >= 0) & (rel < cap)
    relc = jnp.clip(rel, 0, cap - 1)
    m = valid & in_part & (ship > date) & (jnp.take(qual, relc) > 0)
    return acc + K.segment_sum(price * (1.0 - disc), relc, cap, m)


def build_q03_side(store: PagedTensorStore,
                   orders: Dict[str, np.ndarray],
                   customer: Dict[str, np.ndarray],
                   segment_code: int, date_int: int,
                   key_cap: int, name: str = "q03.build") -> int:
    """Build the resident side of the Q03 join: filter customers by
    segment, join to orders (host-side build, the small tables), and
    page the per-orderkey LUT into ``store`` partitioned by key range.
    Returns the number of partitions."""
    c_key = np.asarray(customer["c_custkey"])
    c_ok = np.asarray(customer["c_mktsegment"]) == segment_code
    cust_lut = np.zeros(int(c_key.max()) + 1, np.bool_)
    cust_lut[c_key] = c_ok

    o_key = np.asarray(orders["o_orderkey"])
    o_cust = np.asarray(orders["o_custkey"])
    o_date = np.asarray(orders["o_orderdate"])
    o_prio = np.asarray(orders["o_shippriority"])
    o_ok = (o_date < date_int) & cust_lut[o_cust]

    n_keys = int(o_key.max()) + 1
    build = np.zeros((n_keys, 3), np.int32)
    build[o_key, 0] = o_ok
    build[o_key, 1] = o_date
    build[o_key, 2] = o_prio
    store.put(name, build, row_block=key_cap)
    return store.num_blocks(name)


def ooc_q03(pc: PagedColumns, store: PagedTensorStore,
            date: str = "1995-03-15", k: int = 10,
            build_name: str = "q03.build") -> List[Dict[str, object]]:
    """Q03 with lineitem streamed from pages and the join LUT loaded one
    partition at a time — same result structure as ``queries.cq03``.
    Peak device state: one partition's LUT column + one ``(cap,)``
    revenue accumulator + one page of probe columns, independent of
    table or key-space size."""
    date_i = date_to_int(date)
    num_parts = store.num_blocks(build_name)
    cand: List[Dict[str, object]] = []
    for p in range(num_parts):
        start, bmat = store.read_block(build_name, p)
        # static cap = this partition's row count; all full partitions
        # share one compiled fold, the ragged tail compiles once more
        cap = bmat.shape[0]
        qual = jnp.asarray(bmat[:, 0])
        acc = jnp.zeros((cap,), jnp.float32)
        for cols, valid in pc.stream():
            acc = _q03_probe_fold(cap, acc, start, qual, valid,
                                  cols["l_orderkey"], cols["l_shipdate"],
                                  cols["l_extendedprice"],
                                  cols["l_discount"], date_i)
        acc_h = np.asarray(acc)
        top = np.argsort(-acc_h)[:k]
        for i in top:
            if acc_h[i] > 0:
                cand.append({"okey": start + int(i),
                             "odate": int_to_date(int(bmat[i, 1])),
                             "revenue": float(acc_h[i])})
    cand.sort(key=lambda r: (-r["revenue"], r["odate"]))
    return cand[:k]


Q01_COLUMNS = ["l_shipdate", "l_returnflag", "l_linestatus",
               "l_quantity", "l_extendedprice", "l_discount", "l_tax"]
Q06_COLUMNS = ["l_shipdate", "l_discount", "l_quantity",
               "l_extendedprice"]
Q03_COLUMNS = ["l_orderkey", "l_shipdate", "l_extendedprice",
               "l_discount"]


def bench_out_of_core(rows: int = 60_000_000,
                      pool_bytes: int = 1 << 30,
                      row_block: Optional[int] = None,
                      seed: int = 0) -> Dict[str, object]:
    """SF10-scale synthetic lineitem (60M rows ≈ SF10's 59.99M) through
    q01+q06 under a pool cap far smaller than the table — the
    PageScanner larger-than-memory proof, measured. Verifies against an
    in-memory numpy oracle on the same data."""
    import time

    from netsdb_tpu.config import Configuration

    rng = np.random.default_rng(seed)
    cols = {
        "l_shipdate": rng.integers(19920101, 19981231, rows,
                                   dtype=np.int32),
        "l_returnflag": rng.integers(0, 3, rows, dtype=np.int32),
        "l_linestatus": rng.integers(0, 2, rows, dtype=np.int32),
        "l_quantity": rng.integers(1, 51, rows,
                                   dtype=np.int32).astype(np.float32),
        "l_extendedprice": rng.uniform(1000, 100000,
                                       rows).astype(np.float32),
        "l_discount": rng.uniform(0, 0.1, rows).astype(np.float32),
        "l_tax": rng.uniform(0, 0.08, rows).astype(np.float32),
    }
    table_bytes = sum(c.nbytes for c in cols.values())
    import tempfile

    cfg = Configuration(root_dir=tempfile.mkdtemp(prefix="ooc_bench_"))
    store = PagedTensorStore(cfg, pool_bytes=pool_bytes)
    if row_block is None:
        # one page must be far smaller than the pool or ingest cannot
        # even allocate (several pages stay pinned concurrently): cap a
        # page at pool/8, floor at 4k rows
        width = len(cols)
        row_block = max(min(cfg.page_size_bytes // (4 * width),
                            pool_bytes // (8 * 4 * width)), 4096)
    t0 = time.perf_counter()
    pc = PagedColumns.ingest(store, "lineitem", cols, row_block=row_block,
                             dicts={"l_returnflag": ["A", "N", "R"],
                                    "l_linestatus": ["F", "O"]})
    ingest_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    r01 = ooc_q01(pc)
    q01_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    r06 = ooc_q06(pc)
    q06_s = time.perf_counter() - t0

    # spot-verify q06 against a numpy oracle on the same host columns
    a, b = date_to_int("1994-01-01"), date_to_int("1995-01-01")
    m = ((cols["l_shipdate"] >= a) & (cols["l_shipdate"] < b)
         & (cols["l_discount"] >= 0.06 - 0.011)
         & (cols["l_discount"] <= 0.06 + 0.011)
         & (cols["l_quantity"] < 24))
    oracle = float((cols["l_extendedprice"][m]
                    * cols["l_discount"][m]).sum(dtype=np.float64))
    rel_err = abs(r06[0][1] - oracle) / max(abs(oracle), 1e-9)

    out = {"rows": rows, "table_bytes": table_bytes,
           "pool_bytes": pool_bytes,
           "pool_fraction": round(pool_bytes / table_bytes, 3),
           "ingest_s": round(ingest_s, 2),
           "q01_s": round(q01_s, 2), "q06_s": round(q06_s, 2),
           "q01_groups": len(r01), "q06_rel_err": rel_err,
           "store_stats": store.stats(), "native": store.native}
    store.close()
    import shutil

    shutil.rmtree(cfg.root_dir, ignore_errors=True)  # spilled pages
    return out
