"""Columnar queries as Computation DAGs over stored sets.

This is the glue the reference has by construction and round 2 lacked:
its TPC-H drivers build Computation graphs over *stored sets* and the
scheduler runs every stage distributed against local partitions
(``src/tpch/source/Query01/``,
``src/serverFunctionalities/source/QuerySchedulerServer.cc:216-330``).
Here a query is a traced ``Apply`` over a :class:`ColumnTable` scanned
from a set; because the executor passes single-table sets as jit
*arguments* (``plan/executor.py``) and a placement-carrying set holds
mesh-sharded columns (``parallel/placement.py``), the SAME DAG runs
single-device or distributed depending only on how the set was created
— distribution flows through the database API, not through
hand-sharded arrays.

Every traced body ANDs ``table.mask()`` into its predicate so the
invalid rows introduced by placement row-padding (and by upstream
``filter`` verbs) never contribute — correctness is the mask algebra's,
independent of shard count.

Results are themselves relations (small ColumnTables with group-key
code columns + aggregate columns and a ``valid`` mask over non-empty
groups), materialized into the output set like the reference's OUTPUT
sets — so a client scans query results with the same ``get_table`` /
``to_rows`` surface it uses for base tables.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from netsdb_tpu.plan.computations import Apply, ScanSet, WriteSet
from netsdb_tpu.relational.queries import _q01_fold
from netsdb_tpu.relational.table import ColumnTable, date_to_int


def _q03_filter_node(db: str, segment_code: int, d: int, jp_cust,
                     orders_set: str, customer_set: str):
    """The shared customer-qualified, date-qualified orders stage —
    ONE builder for q03_sink's inline build side and q03_build_sink's
    materialized build stage, so the two cannot diverge."""
    from netsdb_tpu.plan.computations import Join
    from netsdb_tpu.relational import kernels as K

    def filter_orders(orders: ColumnTable, cust: ColumnTable) -> ColumnTable:
        cust_ok = (cust["c_mktsegment"] == segment_code) & cust.mask()
        _, chit = K.pk_fk_join(cust["c_custkey"], orders["o_custkey"],
                               cust_ok, plan=jp_cust)
        return orders.filter(chit & (orders["o_orderdate"] < d))

    return Join(ScanSet(db, orders_set), ScanSet(db, customer_set),
                fn=filter_orders,
                label=f"q03filter:{segment_code}:{d}:{jp_cust.key_space}")


def q01_sink(db: str, lineitem_set: str = "lineitem",
             delta_date: str = "1998-09-02",
             output_set: str = "q01_out") -> WriteSet:
    """Pricing-summary DAG: SCAN(lineitem) → APPLY(q01) → OUTPUT.

    The result table has one row per (returnflag, linestatus) group:
    code columns carry the group keys (with the input's dictionaries,
    so ``to_rows`` decodes them), aggregates ride as float columns,
    and ``valid`` masks out empty groups.

    The node carries a :class:`~netsdb_tpu.plan.fold.FoldSpec` and
    derives its whole-table path from it, so the same sink runs
    resident (one jitted body), streamed over a paged lineitem (the
    executor folds the step over the page stream), or streamed-sharded
    when the set is paged AND placed — out-of-core is a property of
    the set, not of the query (ref ``PageScanner.h:25-34``).
    """
    from netsdb_tpu.plan.fold import FoldSpec
    from netsdb_tpu.relational.folds import fold_q01

    delta = date_to_int(delta_date)
    base = fold_q01({}, {}, {}, delta_date=delta_date)

    def fin(state, src) -> ColumnTable:
        sums, counts = state
        n_ls = len(src.dicts["l_linestatus"])
        n_groups = len(src.dicts["l_returnflag"]) * n_ls
        gid = jnp.arange(n_groups, dtype=jnp.int32)
        cnt_f = jnp.maximum(counts, 1).astype(jnp.float32)
        return ColumnTable(
            cols={
                "l_returnflag": gid // n_ls,
                "l_linestatus": gid % n_ls,
                "sum_qty": sums[0], "sum_base_price": sums[1],
                "sum_disc_price": sums[2], "sum_charge": sums[3],
                "sum_disc": sums[4], "count": counts,
                "avg_qty": sums[0] / cnt_f,
                "avg_price": sums[1] / cnt_f,
                "avg_disc": sums[4] / cnt_f,
            },
            dicts={"l_returnflag": src.dicts["l_returnflag"],
                   "l_linestatus": src.dicts["l_linestatus"]},
            valid=counts > 0)

    from netsdb_tpu.plan.fold import tree_add_states

    # state = per-group (sums, counts) — additive over row partitions,
    # so the fold scatters across a sharded worker pool (each shard
    # folds its local pages, the coordinator tree-adds the states and
    # finalizes once; float sums reassociate — see FoldSpec.state_merge)
    return WriteSet(Apply(ScanSet(db, lineitem_set),
                          fold=FoldSpec(base.passes, fin,
                                        state_merge=tree_add_states),
                          label=f"cq01:{delta}"),
                    db, output_set)


def q06_sink(db: str, lineitem_set: str = "lineitem",
             d0: str = "1994-01-01", d1: str = "1995-01-01",
             disc: float = 0.06, qty: int = 24,
             output_set: str = "q06_out") -> WriteSet:
    """Revenue-forecast DAG: one fused filtered reduction; the result
    is a 1-row relation {revenue}."""
    from netsdb_tpu.plan.fold import FoldSpec
    from netsdb_tpu.relational.folds import fold_q06

    a, b = date_to_int(d0), date_to_int(d1)
    base = fold_q06({}, {}, {}, d0=d0, d1=d1, disc=disc, qty=qty)

    def fin(state, src) -> ColumnTable:
        return ColumnTable(cols={"revenue": state[None]})

    from netsdb_tpu.plan.fold import tree_add_states

    return WriteSet(Apply(ScanSet(db, lineitem_set),
                          fold=FoldSpec(base.passes, fin,
                                        state_merge=tree_add_states),
                          label=f"cq06:{a}:{b}:{disc}:{qty}"),
                    db, output_set)


def q03_sink(db: str, n_orders: int, n_customers: int, segment_code: int,
             date: str = "1995-03-15", k: int = 10,
             lineitem_set: str = "lineitem", orders_set: str = "orders",
             customer_set: str = "customer",
             output_set: str = "q03_out") -> WriteSet:
    """Top-unshipped-orders DAG over THREE stored sets:
    SCAN(orders) ⋈ SCAN(customer) → SCAN(lineitem) ⋈ · → OUTPUT.

    The join strategy is the LUT probe (`kernels.pk_fk_join`); with the
    fact set placement-sharded and the dimension sets replicated
    (broadcast join), XLA keeps LUT builds local and inserts one psum
    for the per-order revenue segments — the reference's
    broadcast-join + shuffle-aggregation plan chosen declaratively by
    set placement. Statics (key spaces, segment code) come from the
    caller; use :func:`q03_sink_for` to derive them from stored tables.
    Result: a k-row relation {okey, odate, revenue} masked to real
    hits, ordered by (-revenue, odate).

    The probe side is a :class:`~netsdb_tpu.plan.fold.FoldSpec` whose
    revenue accumulator lives in the BUILD side's *row* space (not the
    key space), with a ``merge`` rule re-top-k'ing partition outputs —
    so when the build side arrives as a paged set the executor runs the
    grace-hash discipline (outer loop over build blocks, inner stream
    over lineitem, state bounded by the block size; ref partitioned
    hash sets, ``src/queryExecution/headers/HashSetManager.h``)."""
    from netsdb_tpu.plan.computations import Join
    from netsdb_tpu.relational.planner import JoinPlan

    d = date_to_int(date)
    jp_cust = JoinPlan("lut", n_customers)
    jp_orders = JoinPlan("lut", n_orders)

    filtered = _q03_filter_node(db, segment_code, d, jp_cust,
                                orders_set, customer_set)
    joined = Join(ScanSet(db, lineitem_set), filtered,
                  fold=q03_probe_fold(d, k, jp_orders),
                  label=f"q03join:{d}:{k}:{n_orders}")
    return WriteSet(joined, db, output_set)


def q03_build_sink(db: str, n_customers: int, segment_code: int,
                   date: str = "1995-03-15",
                   orders_set: str = "orders",
                   customer_set: str = "customer",
                   output_set: str = "q03_build") -> WriteSet:
    """Stage 1 of the out-of-core Q03: materialize the filtered build
    side (customer-qualified, date-qualified orders) into its own
    output set. Created with ``storage="paged"``, that set becomes a
    block-partitioned spillable hash side; stage 2
    (:func:`q03_sink` with ``prebuilt_set=``) then probes it
    grace-hash style — the reference's build-stage/probe-stage split
    (``HermesExecutionServer.cc:901``, partitioned hash sets)."""
    from netsdb_tpu.relational.planner import JoinPlan

    node = _q03_filter_node(db, segment_code, date_to_int(date),
                            JoinPlan("lut", n_customers),
                            orders_set, customer_set)
    return WriteSet(node, db, output_set)


def q03_probe_sink(db: str, n_orders: int, date: str = "1995-03-15",
                   k: int = 10, lineitem_set: str = "lineitem",
                   build_set: str = "q03_build",
                   output_set: str = "q03_out") -> WriteSet:
    """Stage 2 of the out-of-core Q03: probe a PRE-BUILT (possibly
    paged) build set with the lineitem stream. With both sets paged the
    executor runs the full grace-hash discipline — outer loop over the
    build's blocks, inner fold over the probe stream, partition top-ks
    merged (``plan/executor.py::_run_fold``)."""
    from netsdb_tpu.plan.computations import Join
    from netsdb_tpu.relational.planner import JoinPlan

    d = date_to_int(date)
    joined = Join(ScanSet(db, lineitem_set), ScanSet(db, build_set),
                  fold=q03_probe_fold(d, k, JoinPlan("lut", n_orders)),
                  label=f"q03probe:{d}:{k}:{n_orders}")
    return WriteSet(joined, db, output_set)


def q03_probe_fold(d: int, k: int, jp_orders):
    """Lineitem-stream fold against a (possibly block-partitioned)
    orders build side; see :func:`q03_sink`.

    The join plan is re-derived per build block from the block's OWN
    row count (trace-time static): a small dense block keeps the LUT
    gather, a block dwarfed by the key space takes the sort join — so
    per-chunk device state stays bounded by the partition, never by
    the key space (the grace-hash discipline; ``jp_orders`` supplies
    only the key-space bound)."""
    from netsdb_tpu.plan.fold import single_pass
    from netsdb_tpu.relational import kernels as K
    from netsdb_tpu.relational.planner import plan_join_from_stats
    from netsdb_tpu.relational.stats import ColumnStats

    def _block_plan(orders: ColumnTable, n_probe: int):
        ks = jp_orders.key_space
        return plan_join_from_stats(
            ColumnStats(orders.num_rows, 0, ks - 1, -1), n_probe)

    def init(prev, src, orders):
        return jnp.zeros((orders.num_rows,), jnp.float32)

    def step(rev_acc, li: ColumnTable, orders: ColumnTable):
        li, orders = _fold_mask(li), _fold_mask(orders)
        oidx, ohit = K.pk_fk_join(orders["o_orderkey"], li["l_orderkey"],
                                  orders["o_orderkey"] >= 0,
                                  plan=_block_plan(orders, li.num_rows))
        li_ok = ohit & (li["l_shipdate"] > d)
        return rev_acc + K.segment_sum(
            li["l_extendedprice"] * (1.0 - li["l_discount"]), oidx,
            orders.num_rows, li_ok)

    def fin(rev_acc, src, orders: ColumnTable) -> ColumnTable:
        orders = _fold_mask(orders)
        top_idx, top_ok = K.top_k_masked(rev_acc,
                                         min(k, rev_acc.shape[0]),
                                         rev_acc > 0)
        return ColumnTable(
            cols={"okey": jnp.take(orders["o_orderkey"], top_idx),
                  "odate": jnp.take(orders["o_orderdate"], top_idx),
                  "revenue": jnp.take(rev_acc, top_idx)},
            valid=top_ok)

    def merge(a: ColumnTable, b: ColumnTable) -> ColumnTable:
        rev = jnp.concatenate([a["revenue"], b["revenue"]])
        valid = jnp.concatenate([a.mask(), b.mask()])
        idx, ok = K.top_k_masked(rev, min(k, rev.shape[0]),
                                 valid & (rev > 0))
        cat = lambda c: jnp.take(jnp.concatenate([a[c], b[c]]), idx)
        return ColumnTable(cols={"okey": cat("okey"), "odate": cat("odate"),
                                 "revenue": jnp.take(rev, idx)},
                           valid=ok)

    return single_pass(init, step, fin, merge,
                       probe_key="l_orderkey", build_key="o_orderkey",
                       probe_columns=("l_shipdate", "l_extendedprice",
                                      "l_discount"))


def q03_sink_for(client, db: str, segment: str = "BUILDING",
                 date: str = "1995-03-15", k: int = 10) -> WriteSet:
    """Derive q03's static parameters (key spaces, segment code) from
    stored-set statistics (``analyze_set`` summaries, never the tables
    themselves — the planner's StorageCollectStats role), then build
    the sink."""
    orders = client.analyze_set(db, "orders")
    cust = client.analyze_set(db, "customer")
    seg_dict = cust["dicts"]["c_mktsegment"]
    return q03_sink(
        db,
        n_orders=orders["stats"]["o_orderkey"].key_space,
        n_customers=cust["stats"]["c_custkey"].key_space,
        # -1 for an unknown segment → matches nothing → empty result
        # (ColumnTable.code semantics), never a build-time crash
        segment_code=(seg_dict.index(segment) if segment in seg_dict
                      else -1),
        date=date, k=k)


def q03_rows(result: ColumnTable) -> list:
    """Decode a q03 result relation to the row-engine's output shape."""
    import numpy as np

    ok = np.asarray(result.mask())
    okey = np.asarray(result["okey"])
    odate = np.asarray(result["odate"])
    rev = np.asarray(result["revenue"])
    from netsdb_tpu.relational.table import int_to_date

    rows = [{"okey": int(okey[j]), "odate": int_to_date(int(odate[j])),
             "revenue": float(rev[j])}
            for j in range(len(ok)) if ok[j]]
    rows.sort(key=lambda r: (-r["revenue"], r["odate"]))
    return rows


# ------------------------------------------- whole suite via the set API
# Which stored sets each query core scans, in its args order.
_QUERY_TABLES = {
    "q01": ("lineitem",),
    # partsupp LAST: the fact table sits at the fold node's direct
    # input so a paged partsupp streams (suite cores read tables by
    # NAME, so scan order is free)
    "q02": ("part", "supplier", "nation", "region", "partsupp"),
    "q03": ("customer", "orders", "lineitem"),
    "q04": ("orders", "lineitem"),
    "q06": ("lineitem",),
    "q12": ("orders", "lineitem"),
    "q13": ("customer", "orders"),
    "q14": ("lineitem", "part"),
    "q17": ("lineitem", "part"),
    "q22": ("customer", "orders"),
}

# The recommended placements for a distributed TPC-H database: fact
# tables row-sharded, dimension tables replicated (broadcast join) —
# padding-inertness of every core was audited under this convention
# (fact padding rows carry -1 keys after the mask fold below, which the
# orphan-key rule drops everywhere).
FACT_TABLES = ("lineitem", "orders")


def _fold_mask(t: ColumnTable) -> ColumnTable:
    """Fold validity INTO the columns (trace-safe, no compaction):
    invalid rows get -1 in int/code columns — dropped everywhere by the
    kernels' orphan-key/in-range rule — and 0 in measures. The returned
    table carries the original's aux key so warmed planner stats stay
    visible (stats.py)."""
    if t.valid is None:
        return t
    m = t.valid
    cols = {}
    for name, c in t.cols.items():
        if c.dtype.kind == "b":
            cols[name] = jnp.where(m, c, False)  # -1 would cast to True
        elif c.dtype.kind == "i":
            cols[name] = jnp.where(m, c, jnp.asarray(-1, c.dtype))
        else:
            cols[name] = jnp.where(m, c, jnp.asarray(0, c.dtype))
    return ColumnTable(cols, t.dicts, None)


def suite_sink_for(client, db: str, qname: str,
                   output_set: Optional[str] = None, **params) -> WriteSet:
    """ANY of the ten TPC-H query cores as a Computation DAG over
    stored (placement-sharded) sets — the whole columnar suite
    distributed through the database API with zero per-query DAG code.

    Build time: planner statistics are computed host-side from the
    stored tables and CLOSED OVER by the traced body (plain data, so
    the DAG ships to a daemon intact). Trace time: each scanned table's
    validity folds into its columns (`_fold_mask`), the captured stats
    are injected into the traced clones (`stats.inject_stats` — traced
    arrays cannot be analyzed), then the SAME core the single-device
    engine runs (`queries._SUITE_CORES`) executes over the sharded
    columns; XLA inserts the collectives. Output: the core's raw
    arrays, bit-comparable to the single-device core.

    Statistics come from ``client.analyze_set`` — collected where the
    data lives (ingest-time for paged sets, daemon-side for a
    RemoteClient) and shipped as summaries, never as tables (ref
    ``StorageCollectStats``, ``PangeaStorageServer.h:48``).

    When the query's fact set was created with ``storage="paged"``,
    the sink carries the query's streamable fold
    (:mod:`netsdb_tpu.relational.folds`) and the executor runs it
    page-by-page under the arena's pool cap — same DAG, out-of-core
    decided by the set."""
    from netsdb_tpu.plan.computations import Join
    from netsdb_tpu.relational.folds import SUITE_FOLDS
    from netsdb_tpu.relational.queries import _SUITE_CORES
    from netsdb_tpu.relational.stats import inject_stats

    if qname not in _QUERY_TABLES:
        raise KeyError(f"unknown suite query {qname!r}; "
                       f"have {sorted(_QUERY_TABLES)}")
    names = _QUERY_TABLES[qname]
    core, args_fn = _SUITE_CORES[qname]
    info = {n: client.analyze_set(db, n) for n in names}
    captured = {n: dict(info[n]["stats"]) for n in names}
    dicts_map = {n: info[n]["dicts"] for n in names}
    nrows = {n: info[n]["num_rows"] for n in names}
    # the captured stats are DATA-dependent state closed over by the
    # traced body; they must be part of the compiled-plan cache key
    # (via the label) or re-ingesting different data would silently
    # reuse a stale closure (e.g. an old key_space shrinking a LUT join
    # and dropping rows) — same hazard class as the transformer DAG's
    # mesh identity
    import hashlib

    stats_tag = hashlib.blake2s(
        repr(sorted((n, sorted((c, s.n_rows, s.min_val, s.max_val)
                               for c, s in cs.items()))
                    for n, cs in captured.items())).encode()
    ).hexdigest()[:12]

    def run_core(*tabs) -> tuple:
        tables = {n: inject_stats(_fold_mask(t), captured[n])
                  for n, t in zip(names, tabs)}
        out = core(*args_fn(tables, **params))
        return out if isinstance(out, tuple) else (out,)

    # the query's streamable fold, attached when its fact table is a
    # direct input of the final node (always true for the ten cores:
    # the fact is first or last in _QUERY_TABLES) — used by the
    # executor only when that set is actually paged
    fold = None
    fact = None
    if qname in SUITE_FOLDS:
        fact, builder = SUITE_FOLDS[qname]
        fold = builder(captured, dicts_map, nrows, **params)

    # chain the scans into one traced N-ary application via
    # tuple-passing binary Joins (the reference compiles multi-way
    # joins into binary stages the same way)
    node = ScanSet(db, names[0])
    if len(names) == 1:
        node = Apply(node, lambda t: run_core(t),
                     label=f"suite:{qname}:{params}:{stats_tag}",
                     fold=fold)
    else:
        for n in names[1:-1]:
            # passthrough: a PAGED dim rides the gather chain as its
            # stream handle so the fold node can grace-hash it (or
            # host-materialize it itself) — the gather must not force it
            node = Join(node, ScanSet(db, n),
                        fn=lambda a, b: (a + (b,) if isinstance(a, tuple)
                                         else (a, b)),
                        label=f"gather:{n}", passthrough=True)
        # the fold's stream side must be a DIRECT input of this node:
        # the last scan (fold_src=1) or, for 2-table queries, the first
        direct = (fact == names[-1]
                  or (fact == names[0] and len(names) == 2))
        node = Join(node, ScanSet(db, names[-1]),
                    fn=lambda a, b: run_core(*(a + (b,) if isinstance(a, tuple)
                                               else (a, b))),
                    label=f"suite:{qname}:{params}:{stats_tag}",
                    fold=fold if direct else None,
                    fold_src=1 if fact == names[-1] else 0)
    return WriteSet(node, db, output_set or f"{qname}_out")


def run_query(client, sink: WriteSet, job_name: Optional[str] = None):
    """Execute one columnar-DAG sink and return the result ColumnTable
    (also materialized into the sink's output set)."""
    name = job_name or f"dag-{sink.set_name}"
    results = client.execute_computations(sink, job_name=name)
    return next(iter(results.values()))
