"""Columnar queries as Computation DAGs over stored sets.

This is the glue the reference has by construction and round 2 lacked:
its TPC-H drivers build Computation graphs over *stored sets* and the
scheduler runs every stage distributed against local partitions
(``src/tpch/source/Query01/``,
``src/serverFunctionalities/source/QuerySchedulerServer.cc:216-330``).
Here a query is a traced ``Apply`` over a :class:`ColumnTable` scanned
from a set; because the executor passes single-table sets as jit
*arguments* (``plan/executor.py``) and a placement-carrying set holds
mesh-sharded columns (``parallel/placement.py``), the SAME DAG runs
single-device or distributed depending only on how the set was created
— distribution flows through the database API, not through
hand-sharded arrays.

Every traced body ANDs ``table.mask()`` into its predicate so the
invalid rows introduced by placement row-padding (and by upstream
``filter`` verbs) never contribute — correctness is the mask algebra's,
independent of shard count.

Results are themselves relations (small ColumnTables with group-key
code columns + aggregate columns and a ``valid`` mask over non-empty
groups), materialized into the output set like the reference's OUTPUT
sets — so a client scans query results with the same ``get_table`` /
``to_rows`` surface it uses for base tables.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from netsdb_tpu.plan.computations import Apply, ScanSet, WriteSet
from netsdb_tpu.relational.queries import _q01_fold
from netsdb_tpu.relational.table import ColumnTable, date_to_int


def q01_sink(db: str, lineitem_set: str = "lineitem",
             delta_date: str = "1998-09-02",
             output_set: str = "q01_out") -> WriteSet:
    """Pricing-summary DAG: SCAN(lineitem) → APPLY(q01) → OUTPUT.

    The result table has one row per (returnflag, linestatus) group:
    code columns carry the group keys (with the input's dictionaries,
    so ``to_rows`` decodes them), aggregates ride as float columns,
    and ``valid`` masks out empty groups.
    """
    delta = date_to_int(delta_date)

    def q01(t: ColumnTable) -> ColumnTable:
        n_ls = len(t.dicts["l_linestatus"])
        n_groups = len(t.dicts["l_returnflag"]) * n_ls
        mask = (t["l_shipdate"] <= delta) & t.mask()
        sums, counts = _q01_fold(
            n_groups, n_ls, t["l_returnflag"], t["l_linestatus"],
            t["l_quantity"], t["l_extendedprice"], t["l_discount"],
            t["l_tax"], mask)
        gid = jnp.arange(n_groups, dtype=jnp.int32)
        cnt_f = jnp.maximum(counts, 1).astype(jnp.float32)
        return ColumnTable(
            cols={
                "l_returnflag": gid // n_ls,
                "l_linestatus": gid % n_ls,
                "sum_qty": sums[0], "sum_base_price": sums[1],
                "sum_disc_price": sums[2], "sum_charge": sums[3],
                "sum_disc": sums[4], "count": counts,
                "avg_qty": sums[0] / cnt_f,
                "avg_price": sums[1] / cnt_f,
                "avg_disc": sums[4] / cnt_f,
            },
            dicts={"l_returnflag": t.dicts["l_returnflag"],
                   "l_linestatus": t.dicts["l_linestatus"]},
            valid=counts > 0)

    return WriteSet(Apply(ScanSet(db, lineitem_set), q01,
                          label=f"cq01:{delta}"),
                    db, output_set)


def q06_sink(db: str, lineitem_set: str = "lineitem",
             d0: str = "1994-01-01", d1: str = "1995-01-01",
             disc: float = 0.06, qty: int = 24,
             output_set: str = "q06_out") -> WriteSet:
    """Revenue-forecast DAG: one fused filtered reduction; the result
    is a 1-row relation {revenue}."""
    a, b = date_to_int(d0), date_to_int(d1)

    def q06(t: ColumnTable) -> ColumnTable:
        mask = ((t["l_shipdate"] >= a) & (t["l_shipdate"] < b)
                & (t["l_discount"] >= disc - 0.011)
                & (t["l_discount"] <= disc + 0.011)
                & (t["l_quantity"] < qty) & t.mask())
        rev = jnp.sum(jnp.where(mask, t["l_extendedprice"] * t["l_discount"],
                                0.0))
        return ColumnTable(cols={"revenue": rev[None]})

    return WriteSet(Apply(ScanSet(db, lineitem_set), q06,
                          label=f"cq06:{a}:{b}:{disc}:{qty}"),
                    db, output_set)


def run_query(client, sink: WriteSet, job_name: Optional[str] = None):
    """Execute one columnar-DAG sink and return the result ColumnTable
    (also materialized into the sink's output set)."""
    name = job_name or f"dag-{sink.set_name}"
    results = client.execute_computations(sink, job_name=name)
    return next(iter(results.values()))
