"""Columnar queries as Computation DAGs over stored sets.

This is the glue the reference has by construction and round 2 lacked:
its TPC-H drivers build Computation graphs over *stored sets* and the
scheduler runs every stage distributed against local partitions
(``src/tpch/source/Query01/``,
``src/serverFunctionalities/source/QuerySchedulerServer.cc:216-330``).
Here a query is a traced ``Apply`` over a :class:`ColumnTable` scanned
from a set; because the executor passes single-table sets as jit
*arguments* (``plan/executor.py``) and a placement-carrying set holds
mesh-sharded columns (``parallel/placement.py``), the SAME DAG runs
single-device or distributed depending only on how the set was created
— distribution flows through the database API, not through
hand-sharded arrays.

Every traced body ANDs ``table.mask()`` into its predicate so the
invalid rows introduced by placement row-padding (and by upstream
``filter`` verbs) never contribute — correctness is the mask algebra's,
independent of shard count.

Results are themselves relations (small ColumnTables with group-key
code columns + aggregate columns and a ``valid`` mask over non-empty
groups), materialized into the output set like the reference's OUTPUT
sets — so a client scans query results with the same ``get_table`` /
``to_rows`` surface it uses for base tables.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from netsdb_tpu.plan.computations import Apply, ScanSet, WriteSet
from netsdb_tpu.relational.queries import _q01_fold
from netsdb_tpu.relational.table import ColumnTable, date_to_int


def q01_sink(db: str, lineitem_set: str = "lineitem",
             delta_date: str = "1998-09-02",
             output_set: str = "q01_out") -> WriteSet:
    """Pricing-summary DAG: SCAN(lineitem) → APPLY(q01) → OUTPUT.

    The result table has one row per (returnflag, linestatus) group:
    code columns carry the group keys (with the input's dictionaries,
    so ``to_rows`` decodes them), aggregates ride as float columns,
    and ``valid`` masks out empty groups.
    """
    delta = date_to_int(delta_date)

    def q01(t: ColumnTable) -> ColumnTable:
        n_ls = len(t.dicts["l_linestatus"])
        n_groups = len(t.dicts["l_returnflag"]) * n_ls
        mask = (t["l_shipdate"] <= delta) & t.mask()
        sums, counts = _q01_fold(
            n_groups, n_ls, t["l_returnflag"], t["l_linestatus"],
            t["l_quantity"], t["l_extendedprice"], t["l_discount"],
            t["l_tax"], mask)
        gid = jnp.arange(n_groups, dtype=jnp.int32)
        cnt_f = jnp.maximum(counts, 1).astype(jnp.float32)
        return ColumnTable(
            cols={
                "l_returnflag": gid // n_ls,
                "l_linestatus": gid % n_ls,
                "sum_qty": sums[0], "sum_base_price": sums[1],
                "sum_disc_price": sums[2], "sum_charge": sums[3],
                "sum_disc": sums[4], "count": counts,
                "avg_qty": sums[0] / cnt_f,
                "avg_price": sums[1] / cnt_f,
                "avg_disc": sums[4] / cnt_f,
            },
            dicts={"l_returnflag": t.dicts["l_returnflag"],
                   "l_linestatus": t.dicts["l_linestatus"]},
            valid=counts > 0)

    return WriteSet(Apply(ScanSet(db, lineitem_set), q01,
                          label=f"cq01:{delta}"),
                    db, output_set)


def q06_sink(db: str, lineitem_set: str = "lineitem",
             d0: str = "1994-01-01", d1: str = "1995-01-01",
             disc: float = 0.06, qty: int = 24,
             output_set: str = "q06_out") -> WriteSet:
    """Revenue-forecast DAG: one fused filtered reduction; the result
    is a 1-row relation {revenue}."""
    a, b = date_to_int(d0), date_to_int(d1)

    def q06(t: ColumnTable) -> ColumnTable:
        mask = ((t["l_shipdate"] >= a) & (t["l_shipdate"] < b)
                & (t["l_discount"] >= disc - 0.011)
                & (t["l_discount"] <= disc + 0.011)
                & (t["l_quantity"] < qty) & t.mask())
        rev = jnp.sum(jnp.where(mask, t["l_extendedprice"] * t["l_discount"],
                                0.0))
        return ColumnTable(cols={"revenue": rev[None]})

    return WriteSet(Apply(ScanSet(db, lineitem_set), q06,
                          label=f"cq06:{a}:{b}:{disc}:{qty}"),
                    db, output_set)


def q03_sink(db: str, n_orders: int, n_customers: int, segment_code: int,
             date: str = "1995-03-15", k: int = 10,
             lineitem_set: str = "lineitem", orders_set: str = "orders",
             customer_set: str = "customer",
             output_set: str = "q03_out") -> WriteSet:
    """Top-unshipped-orders DAG over THREE stored sets:
    SCAN(orders) ⋈ SCAN(customer) → SCAN(lineitem) ⋈ · → OUTPUT.

    The join strategy is the LUT probe (`kernels.pk_fk_join`); with the
    fact set placement-sharded and the dimension sets replicated
    (broadcast join), XLA keeps LUT builds local and inserts one psum
    for the per-order revenue segments — the reference's
    broadcast-join + shuffle-aggregation plan chosen declaratively by
    set placement. Statics (key spaces, segment code) come from the
    caller; use :func:`q03_sink_for` to derive them from stored tables.
    Result: a k-row relation {okey, odate, revenue} masked to real
    hits, ordered by (-revenue, odate)."""
    from netsdb_tpu.plan.computations import Join
    from netsdb_tpu.relational.planner import JoinPlan

    d = date_to_int(date)
    jp_cust = JoinPlan("lut", n_customers)
    jp_orders = JoinPlan("lut", n_orders)

    def filter_orders(orders: ColumnTable, cust: ColumnTable) -> ColumnTable:
        from netsdb_tpu.relational import kernels as K

        cust_ok = (cust["c_mktsegment"] == segment_code) & cust.mask()
        _, chit = K.pk_fk_join(cust["c_custkey"], orders["o_custkey"],
                               cust_ok, plan=jp_cust)
        return orders.filter(chit & (orders["o_orderdate"] < d))

    def join_lineitem(li: ColumnTable, orders: ColumnTable) -> ColumnTable:
        import jax.numpy as jnp

        from netsdb_tpu.relational import kernels as K

        l_okey = li["l_orderkey"]
        oidx, ohit = K.pk_fk_join(orders["o_orderkey"], l_okey,
                                  orders.mask(), plan=jp_orders)
        li_ok = ohit & (li["l_shipdate"] > d) & li.mask()
        rev = K.segment_sum(li["l_extendedprice"] * (1.0 - li["l_discount"]),
                            l_okey, n_orders, li_ok)
        odate = K.segment_min(jnp.take(orders["o_orderdate"], oidx),
                              l_okey, n_orders, li_ok)
        top_idx, top_ok = K.top_k_masked(rev, k, rev > 0)
        return ColumnTable(
            cols={"okey": top_idx,
                  "odate": jnp.take(odate, top_idx),
                  "revenue": jnp.take(rev, top_idx)},
            valid=top_ok)

    filtered = Join(ScanSet(db, orders_set), ScanSet(db, customer_set),
                    fn=filter_orders,
                    label=f"q03filter:{segment_code}:{d}:{n_customers}")
    joined = Join(ScanSet(db, lineitem_set), filtered, fn=join_lineitem,
                  label=f"q03join:{d}:{k}:{n_orders}")
    return WriteSet(joined, db, output_set)


def q03_sink_for(client, db: str, segment: str = "BUILDING",
                 date: str = "1995-03-15", k: int = 10) -> WriteSet:
    """Derive q03's static parameters (key spaces, segment code) from
    the stored tables — the planner's statistics role — then build the
    sink."""
    import jax.numpy as jnp

    orders = client.get_table(db, "orders")
    cust = client.get_table(db, "customer")
    return q03_sink(
        db,
        n_orders=int(jnp.max(orders["o_orderkey"])) + 1,
        n_customers=int(jnp.max(cust["c_custkey"])) + 1,
        segment_code=cust.code("c_mktsegment", segment),
        date=date, k=k)


def q03_rows(result: ColumnTable) -> list:
    """Decode a q03 result relation to the row-engine's output shape."""
    import numpy as np

    ok = np.asarray(result.mask())
    okey = np.asarray(result["okey"])
    odate = np.asarray(result["odate"])
    rev = np.asarray(result["revenue"])
    from netsdb_tpu.relational.table import int_to_date

    rows = [{"okey": int(okey[j]), "odate": int_to_date(int(odate[j])),
             "revenue": float(rev[j])}
            for j in range(len(ok)) if ok[j]]
    rows.sort(key=lambda r: (-r["revenue"], r["odate"]))
    return rows


# ------------------------------------------- whole suite via the set API
# Which stored sets each query core scans, in its args order.
_QUERY_TABLES = {
    "q01": ("lineitem",),
    "q02": ("part", "partsupp", "supplier", "nation", "region"),
    "q03": ("customer", "orders", "lineitem"),
    "q04": ("orders", "lineitem"),
    "q06": ("lineitem",),
    "q12": ("orders", "lineitem"),
    "q13": ("customer", "orders"),
    "q14": ("lineitem", "part"),
    "q17": ("lineitem", "part"),
    "q22": ("customer", "orders"),
}

# The recommended placements for a distributed TPC-H database: fact
# tables row-sharded, dimension tables replicated (broadcast join) —
# padding-inertness of every core was audited under this convention
# (fact padding rows carry -1 keys after the mask fold below, which the
# orphan-key rule drops everywhere).
FACT_TABLES = ("lineitem", "orders")


def _fold_mask(t: ColumnTable) -> ColumnTable:
    """Fold validity INTO the columns (trace-safe, no compaction):
    invalid rows get -1 in int/code columns — dropped everywhere by the
    kernels' orphan-key/in-range rule — and 0 in measures. The returned
    table carries the original's aux key so warmed planner stats stay
    visible (stats.py)."""
    if t.valid is None:
        return t
    m = t.valid
    cols = {}
    for name, c in t.cols.items():
        if c.dtype.kind == "b":
            cols[name] = jnp.where(m, c, False)  # -1 would cast to True
        elif c.dtype.kind == "i":
            cols[name] = jnp.where(m, c, jnp.asarray(-1, c.dtype))
        else:
            cols[name] = jnp.where(m, c, jnp.asarray(0, c.dtype))
    return ColumnTable(cols, t.dicts, None)


def suite_sink_for(client, db: str, qname: str,
                   output_set: Optional[str] = None, **params) -> WriteSet:
    """ANY of the ten TPC-H query cores as a Computation DAG over
    stored (placement-sharded) sets — the whole columnar suite
    distributed through the database API with zero per-query DAG code.

    Build time: planner statistics are computed host-side from the
    stored tables and CLOSED OVER by the traced body (plain data, so
    the DAG ships to a daemon intact). Trace time: each scanned table's
    validity folds into its columns (`_fold_mask`), the captured stats
    are injected into the traced clones (`stats.inject_stats` — traced
    arrays cannot be analyzed), then the SAME core the single-device
    engine runs (`queries._SUITE_CORES`) executes over the sharded
    columns; XLA inserts the collectives. Output: the core's raw
    arrays, bit-comparable to the single-device core.

    Building from a RemoteClient works but pulls each scanned table
    once to compute its stats — build sinks with an in-process client
    (or cache them) when the tables are large."""
    from netsdb_tpu.plan.computations import Join
    from netsdb_tpu.relational.queries import _SUITE_CORES
    from netsdb_tpu.relational.stats import analyze_table, inject_stats

    if qname not in _QUERY_TABLES:
        raise KeyError(f"unknown suite query {qname!r}; "
                       f"have {sorted(_QUERY_TABLES)}")
    names = _QUERY_TABLES[qname]
    core, args_fn = _SUITE_CORES[qname]
    captured = {n: dict(analyze_table(client.get_table(db, n)))
                for n in names}
    # the captured stats are DATA-dependent state closed over by the
    # traced body; they must be part of the compiled-plan cache key
    # (via the label) or re-ingesting different data would silently
    # reuse a stale closure (e.g. an old key_space shrinking a LUT join
    # and dropping rows) — same hazard class as the transformer DAG's
    # mesh identity
    import hashlib

    stats_tag = hashlib.blake2s(
        repr(sorted((n, sorted((c, s.n_rows, s.min_val, s.max_val)
                               for c, s in cs.items()))
                    for n, cs in captured.items())).encode()
    ).hexdigest()[:12]

    def run_core(*tabs) -> tuple:
        tables = {n: inject_stats(_fold_mask(t), captured[n])
                  for n, t in zip(names, tabs)}
        out = core(*args_fn(tables, **params))
        return out if isinstance(out, tuple) else (out,)

    # chain the scans into one traced N-ary application via
    # tuple-passing binary Joins (the reference compiles multi-way
    # joins into binary stages the same way)
    node = ScanSet(db, names[0])
    if len(names) == 1:
        node = Apply(node, lambda t: run_core(t),
                     label=f"suite:{qname}:{params}:{stats_tag}")
    else:
        for n in names[1:-1]:
            node = Join(node, ScanSet(db, n),
                        fn=lambda a, b: (a + (b,) if isinstance(a, tuple)
                                         else (a, b)),
                        label=f"gather:{n}")
        node = Join(node, ScanSet(db, names[-1]),
                    fn=lambda a, b: run_core(*(a + (b,) if isinstance(a, tuple)
                                               else (a, b))),
                    label=f"suite:{qname}:{params}:{stats_tag}")
    return WriteSet(node, db, output_set or f"{qname}_out")


def run_query(client, sink: WriteSet, job_name: Optional[str] = None):
    """Execute one columnar-DAG sink and return the result ColumnTable
    (also materialized into the sink's output set)."""
    name = job_name or f"dag-{sink.set_name}"
    results = client.execute_computations(sink, job_name=name)
    return next(iter(results.values()))
