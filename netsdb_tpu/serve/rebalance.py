"""Live shard rebalancing — the feedback loop that moves the data.

The serve layer's placement map (``serve/placement.py``) fixes slot
COUNT at create time but not slot OWNERSHIP: ``move_slot`` re-owns one
shard slot under an epoch bump. This module is the loop that decides
WHEN to move (a skew detector on the sched-feedback cadence, fed by
the attribution ledger), WHAT to move (a byte-bounded greedy planner,
hottest member → coldest member), and HOW (the RESHARD sub-protocol:
copy the source partition to the destination, write-seal the source,
drain the tail, verify row counts, commit the epoch, drop the source)
— the reference's self-managed placement decisions (Lachesis picks
page placement from observed workload; netsDB's scheduler re-spreads
JobStages over registered workers) grown into live data movement.

**Zero downtime by construction.** A move never takes the set
offline: the source keeps serving READS until the epoch commits (the
copy + seal only block writes to that one slot, answered with the
typed retryable :class:`ShardUnavailable`), and in-flight frames
routed under the old map get the existing typed
:class:`PlacementStale` refresh-and-retry story. Nothing is ever
applied under a revised membership half-way — the commit point is one
``move_slot`` epoch bump, all-or-nothing per move.

**Exactness.** The copy is count-verified: rows at seal time must
equal rows installed at the destination, or the move aborts (source
unsealed, destination clear on the next prepare) and the round ends.
A dropped source leaves a TOMBSTONE: routed frames still riding the
old epoch get ``PlacementStale`` instead of silently applying into a
cleared set. The seal carries a TTL (:data:`SEAL_TTL_S`) so a leader
death mid-move self-heals — the source resumes serving under the
unchanged persisted map once the seal expires.

Formulas here are PINNED — module constants with the exact weights,
pure functions over snapshots — the same test contract discipline as
``serve/sched/feedback.py``. Tests assert against these names; tuning
means editing the constant, not a magic number in a loop body.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from netsdb_tpu import obs
from netsdb_tpu.serve import placement as _placement
from netsdb_tpu.serve.protocol import (CODEC_PICKLE, MsgType,
                                       ProtocolError)
from netsdb_tpu.utils.locks import TrackedLock


class MoveAborted(RuntimeError):
    """A slot move failed one of its structural checks (source shrank
    mid-copy, destination count mismatch, placement entry vanished)
    and was unwound. Deliberately NOT a transport error: the abort
    path must never confuse a failed verification with a dead peer."""

# --- pinned formula constants (test contract) -------------------------
#: weight of one admitted request against a set (attribution ledger's
#: ``requests`` metric) in the heat formula
REQUEST_WEIGHT = 1.0
#: weight of one executor chunk folded over the set — streamed scans
#: touch many chunks per request, so a chunk counts a quarter request
CHUNK_WEIGHT = 0.25
#: weight of one staged byte: one MiB of ingest ≈ one request of load
BYTE_WEIGHT = 1.0 / (1 << 20)
#: a feedback window whose TOTAL heat delta is below this floor yields
#: no skew verdict (and resets the streak) — idle pools never trigger
MIN_WINDOW_HEAT = 8.0
#: the planner stops once max/mean heat falls to this ratio — moving
#: past "roughly even" just burns bytes chasing noise. Note a pool of
#: N members whose sets were created at N-1 (one fresh, slot-less
#: daemon) reads N/(N-1) even when ownership is as even as it can
#: get, so this must sit BELOW that floor for the pool sizes the
#: serve layer targets (5 members → 1.25)
SETTLE_RATIO = 1.1
#: write-seal TTL on a move's source slot: a leader that dies between
#: seal and commit leaves the source self-unsealing after this many
#: seconds, resuming service under the unchanged persisted map
SEAL_TTL_S = 60.0
#: bounded move log kept for `cli obs --placement` / RESHARD status
MOVE_LOG = 32

#: (attribution metric, weight) pairs the set-heat formula sums
HEAT_METRICS: Tuple[Tuple[str, float], ...] = (
    ("requests", REQUEST_WEIGHT),
    ("executor.chunks", CHUNK_WEIGHT),
    ("staged_bytes", BYTE_WEIGHT),
)


# --- pure formula functions (snapshots in, numbers out) ---------------
def set_heats(attrib_snapshot: Dict[str, Dict[str, Dict[str, float]]]
              ) -> Dict[str, float]:
    """Per-set load from one attribution-ledger snapshot: for every
    ``db:set`` scope, the HEAT_METRICS-weighted sum across all
    clients. The unattributable ``*`` scope is ignored — it cannot be
    placed."""
    out: Dict[str, float] = {}
    for per_scope in (attrib_snapshot or {}).values():
        for scope, metrics in per_scope.items():
            if scope == "*":
                continue
            h = 0.0
            for name, weight in HEAT_METRICS:
                h += weight * float(metrics.get(name, 0) or 0)
            if h:
                out[scope] = out.get(scope, 0.0) + h
    return out


def addr_heats(entries: Dict[Tuple[str, str], Dict[str, Any]],
               heats: Dict[str, float],
               members: List[str]) -> Dict[str, float]:
    """Per-member load: each set's heat splits evenly over its LIVE
    slots (routing is slot-uniform by construction — hash placement
    by design, range placement by the contiguous ingest split), and a
    member's heat is the sum of its owned shares. Every pool member
    appears — a fresh slot-less daemon reads exactly 0.0, which is
    what makes pool growth look like skew."""
    out: Dict[str, float] = {addr: 0.0 for addr in members}
    for (db, set_name), entry in entries.items():
        h = heats.get(f"{db}:{set_name}", 0.0)
        slots = entry.get("slots", ())
        if not h or not slots:
            continue
        share = h / len(slots)
        for sl in slots:
            if sl.get("state") == _placement.LIVE \
                    and sl["addr"] in out:
                out[sl["addr"]] += share
    return out


def skew_ratio(heats: Dict[str, float]) -> float:
    """max/mean member heat — 1.0 is perfectly even; an idle pool
    (mean 0) also reads 1.0 so emptiness never looks like skew."""
    if not heats:
        return 1.0
    vals = list(heats.values())
    mean = sum(vals) / len(vals)
    if mean <= 0.0:
        return 1.0
    return max(vals) / mean


def plan_moves(entries: Dict[Tuple[str, str], Dict[str, Any]],
               heats: Dict[str, float],
               sizes: Dict[Tuple[str, str], int],
               members: List[str],
               max_bytes: int) -> List[Dict[str, Any]]:
    """The byte-bounded greedy planner: while the pool reads skewed
    (above :data:`SETTLE_RATIO`), take one LIVE slot from the hottest
    member and give it to the coldest member that owns NO slot of
    that set (slot-stable routing: a member may own at most one slot
    per set). Candidate slots rank by heat share (ties to the smaller
    partition — cheaper bytes for the same balance). ``sizes`` maps
    ``(addr, "db:set")`` to that member's LOCAL partition bytes.

    ``max_bytes`` bounds the ROUND: planning stops before a move
    would exceed it, except the first move always fits — a single
    oversized slot must stay movable or the pool can never heal.

    A pool with NO heat signal at all (fresh restart, idle ledger)
    plans by slot count instead: every set weighs 1.0, so growth
    still spreads ownership."""
    heats = dict(heats)
    if sum(heats.values()) <= 0.0:
        heats = {f"{db}:{s}": 1.0 for (db, s) in entries}
    member_heat = addr_heats(entries, heats, members)
    owners: Dict[Tuple[str, str], set] = {
        key: {sl["addr"] for sl in entry.get("slots", ())}
        for key, entry in entries.items()}
    moves: List[Dict[str, Any]] = []
    used = 0
    # bounded by the total slot population — each iteration moves one
    for _ in range(sum(len(e.get("slots", ())) for e in entries.values())):
        if skew_ratio(member_heat) <= SETTLE_RATIO:
            break
        hot = max(member_heat, key=member_heat.get)  # type: ignore[arg-type]
        best = None
        for (db, set_name), entry in entries.items():
            slots = entry.get("slots", ())
            share = heats.get(f"{db}:{set_name}", 0.0) / max(len(slots), 1)
            for i, sl in enumerate(slots):
                if sl["addr"] != hot \
                        or sl.get("state") != _placement.LIVE:
                    continue
                nbytes = int(sizes.get((hot, f"{db}:{set_name}"), 0))
                # coldest member not already owning a slot of this set
                dsts = [a for a in members
                        if a != hot and a not in owners[(db, set_name)]]
                if not dsts:
                    continue
                dst = min(dsts, key=lambda a: member_heat[a])
                if member_heat[dst] + share >= member_heat[hot]:
                    continue  # not a strict improvement: the slot
                    # would leave the destination at least as hot as
                    # the source started — churn, not balance
                cand = (share, -nbytes, db, set_name, i, dst, nbytes)
                if best is None or cand > best:
                    best = cand
        if best is None:
            break
        share, _neg, db, set_name, slot, dst, nbytes = best
        if moves and max_bytes > 0 and used + nbytes > max_bytes:
            break
        moves.append({"db": db, "set": set_name, "slot": slot,
                      "src": hot, "dst": dst, "nbytes": nbytes,
                      "heat": share})
        used += nbytes
        member_heat[hot] -= share
        member_heat[dst] += share
        owners[(db, set_name)].discard(hot)
        owners[(db, set_name)].add(dst)
    return moves


class SkewDetector:
    """Sustained-imbalance detector over cumulative attribution
    snapshots: each :meth:`observe` differences the per-set heats
    against the previous call (one feedback WINDOW), rebuilds member
    heats from the window's delta, and counts CONSECUTIVE windows
    whose skew ratio exceeds the threshold. ``windows`` in a row →
    one True verdict (and the streak resets, so a campaign must
    re-earn the next one). Windows below :data:`MIN_WINDOW_HEAT`
    reset the streak — idle pools never rebalance."""

    def __init__(self, ratio: float, windows: int):
        self.ratio = float(ratio)
        self.windows = max(int(windows), 1)
        self.streak = 0
        self._prev: Dict[str, float] = {}

    def observe(self, cum_heats: Dict[str, float],
                entries: Dict[Tuple[str, str], Dict[str, Any]],
                members: List[str]) -> Tuple[float, bool]:
        delta = {s: max(0.0, v - self._prev.get(s, 0.0))
                 for s, v in cum_heats.items()}
        self._prev = dict(cum_heats)
        if sum(delta.values()) < MIN_WINDOW_HEAT:
            self.streak = 0
            return 1.0, False
        ratio = skew_ratio(addr_heats(entries, delta, members))
        if ratio > self.ratio:
            self.streak += 1
        else:
            self.streak = 0
        if self.streak >= self.windows:
            self.streak = 0
            return ratio, True
        return ratio, False


# --- worker-side move legs (the RESHARD op dispatcher) ----------------
def _seal_key(db: str, set_name: str) -> Tuple[str, str]:
    return (str(db), str(set_name))


def sealed(ctl, db: str, set_name: str) -> bool:
    """Is (db, set) write-sealed on this daemon? Expired seals clear
    lazily — a leader death mid-move self-heals after SEAL_TTL_S."""
    key = _seal_key(db, set_name)
    with ctl._shard_mu:
        deadline = ctl._reshard_seals.get(key)
        if deadline is None:
            return False
        if time.monotonic() >= deadline:
            del ctl._reshard_seals[key]
            return False
        return True


def tombstoned(ctl, db: str, set_name: str) -> bool:
    """Was (db, set)'s local copy dropped by a committed move? Routed
    frames still riding the old epoch must answer PlacementStale, not
    silently apply into the cleared set."""
    with ctl._shard_mu:
        return _seal_key(db, set_name) in ctl._reshard_moved


def _local_partition(ctl, db: str, set_name: str):
    """This daemon's local partition as ``("table", ColumnTable)`` /
    ``("items", list)`` plus its row count. Table sets compact to one
    host table (the scatter-leg shape); everything else ships its raw
    item list."""
    from netsdb_tpu.serve import shard as _shard
    from netsdb_tpu.storage.store import SetIdentifier

    t = _shard.local_table(ctl, db, set_name)
    if t is not None:
        return "table", t, int(t.num_rows)
    items = ctl.library.store.get_items(SetIdentifier(db, set_name))
    return "items", list(items), len(items)


def _slice_table(t, offset: int):
    from netsdb_tpu.relational.table import ColumnTable

    return ColumnTable({k: v[offset:] for k, v in t.cols.items()},
                       dict(t.dicts))


def _concat_tables(a, b):
    import jax.numpy as jnp

    from netsdb_tpu.relational.table import ColumnTable

    if sorted(a.cols) != sorted(b.cols) or a.dicts != b.dicts:
        raise MoveAborted(
            "reshard install: tail chunk schema diverged from the "
            "initial copy — the move must abort, not merge")
    cols = {k: jnp.asarray(np.concatenate([np.asarray(a.cols[k]),
                                           np.asarray(b.cols[k])]))
            for k in a.cols}
    return ColumnTable(cols, dict(a.dicts))


def handle_reshard(ctl, p: Dict[str, Any]) -> Dict[str, Any]:
    """One worker-side RESHARD op against this daemon's local state.
    Runs in-process when the leader itself is a move endpoint, over
    the wire (CODEC_PICKLE replies — partitions ride the frame)
    otherwise. Ops:

    * ``prepare`` — create db + a clean local slot set (clearing any
      stale partial copy a previous aborted move left) and lift any
      tombstone: this daemon is about to become an owner again.
    * ``pull`` — the local partition from ``offset`` (0 = everything;
      the tail drain passes the initial copy's row count).
    * ``install`` — write one pulled chunk (``append`` merges the
      sealed tail after the initial copy).
    * ``seal`` / ``unseal`` — write-seal the slot behind a TTL;
      routed writes answer typed retryable while sealed, reads keep
      serving (the old owner serves until the epoch commits).
    * ``count`` — local rows + bytes (the commit verification read).
    * ``drop`` — the post-commit cleanup: clear the local copy, drop
      the shard registration, tombstone the scope.
    * ``warm`` — best-effort destination pre-warm (never
      correctness-bearing)."""
    from netsdb_tpu.storage.store import SetIdentifier

    op = p.get("op")
    db, set_name = p.get("db"), p.get("set")
    if not db or not set_name:
        raise ProtocolError("RESHARD frame needs db + set")
    ident = SetIdentifier(db, set_name)
    key = _seal_key(db, set_name)
    if op == "prepare":
        meta = p.get("meta") or {}
        ctl.library.create_database(db)
        if not ctl.library.set_exists(db, set_name):
            ctl.library.create_set(
                db, set_name,
                type_name=meta.get("type_name", "tensor"),
                persistence=meta.get("persistence", "transient"),
                eviction=meta.get("eviction", "lru"),
                storage=meta.get("storage", "memory"))
        ctl.library.clear_set(db, set_name)
        with ctl._shard_mu:
            ctl._reshard_moved.discard(key)
            ctl._reshard_seals.pop(key, None)
        return {}
    if op == "pull":
        offset = int(p.get("offset", 0))
        kind, payload, rows = _local_partition(ctl, db, set_name)
        if kind == "table":
            chunk = None if offset >= rows \
                else (payload if offset == 0
                      else _slice_table(payload, offset))
            return {"rows": rows, "kind": kind, "table": chunk}
        return {"rows": rows, "kind": kind,
                "items": payload[offset:]}
    if op == "install":
        append = bool(p.get("append"))
        if p.get("kind") == "table":
            chunk = p.get("table")
            if not append:
                ctl.library.store.clear_set(ident)
                if chunk is not None:
                    ctl.library.store.add_data(ident, [chunk])
            elif chunk is not None:
                _k, existing, _n = _local_partition(ctl, db, set_name)
                if _k == "table" and existing is not None:
                    merged = _concat_tables(existing, chunk)
                else:
                    merged = chunk
                ctl.library.store.clear_set(ident)
                ctl.library.store.add_data(ident, [merged])
        else:
            items = p.get("items") or []
            if not append:
                ctl.library.store.clear_set(ident)
            if items:
                ctl.library.store.add_data(ident, items)
        _k, _payload, rows = _local_partition(ctl, db, set_name)
        return {"rows": rows}
    if op == "seal":
        ttl = float(p.get("ttl_s", SEAL_TTL_S))
        with ctl._shard_mu:
            ctl._reshard_seals[key] = time.monotonic() + ttl
        _k, _payload, rows = _local_partition(ctl, db, set_name)
        return {"rows": rows}
    if op == "unseal":
        with ctl._shard_mu:
            ctl._reshard_seals.pop(key, None)
        return {}
    if op == "count":
        _k, _payload, rows = _local_partition(ctl, db, set_name)
        stats = ctl.library.store.set_stats(ident)
        return {"rows": rows, "nbytes": int(stats.get("nbytes", 0))}
    if op == "drop":
        with ctl._shard_mu:
            ctl._shard_sets.pop(key, None)
            ctl._reshard_seals.pop(key, None)
            ctl._reshard_moved.add(key)
        ctl.library.clear_set(db, set_name)
        return {}
    if op == "warm":
        # best-effort: page-touch the freshly installed partition so
        # the first post-move query doesn't pay the assembly (paged
        # relations re-stage off the arena; resident tables compact).
        # Never correctness-bearing — any failure is the cold path.
        try:
            _k, _payload, rows = _local_partition(ctl, db, set_name)
            return {"warmed": rows > 0, "rows": rows}
        except Exception as e:  # noqa: BLE001 — warm is advisory
            return {"warmed": False, "error": f"{type(e).__name__}: {e}"}
    raise ProtocolError(f"unknown RESHARD op {op!r}")


class _PeerDown(Exception):
    """A move leg died on a TRANSPORT failure (peer unreachable) —
    carries the peer so the abort path can degrade exactly it."""

    def __init__(self, addr: str, cause: BaseException):
        super().__init__(f"{addr}: {type(cause).__name__}: {cause}")
        self.addr = addr


class Rebalancer:
    """Leader-side campaign driver: the skew detector on the
    sched-feedback cadence, the byte-bounded planner, and the
    per-move RESHARD executor. One instance per controller;
    :meth:`check` is safe to call from the feedback thread, the pool
    health loop, an admin frame, and tests concurrently — a single
    campaign runs at a time, every extra caller no-ops.

    ``_mu`` is a LEAF lock (tracked rank ``serve.Rebalancer._mu``):
    it guards only detector state, the running flag, and the move
    log. All placement reads, ledger snapshots, and every network
    leg run strictly outside it — the shard-section discipline."""

    def __init__(self, ctl):
        self.ctl = ctl
        cfg = ctl.config
        self._mu = TrackedLock("serve.Rebalancer._mu")
        self._detector = SkewDetector(
            getattr(cfg, "rebalance_skew_ratio", 2.0),
            getattr(cfg, "rebalance_windows", 3))
        self._force = False
        self._running = False
        self._last_ratio = 1.0
        self._log: List[Dict[str, Any]] = []

    # --- triggers -----------------------------------------------------
    def pool_changed(self) -> None:
        """Pool growth/shrink (a daemon registered, an eviction):
        bypass the sustained-window requirement — the next check
        plans immediately."""
        with self._mu:
            self._force = True

    # --- introspection ------------------------------------------------
    def status(self) -> Dict[str, Any]:
        epoch = self.ctl.placement.to_wire()["epoch"]
        with self._mu:
            return {"enabled": bool(getattr(self.ctl.config,
                                            "rebalance", False)),
                    "running": self._running,
                    "last_ratio": round(self._last_ratio, 4),
                    "streak": self._detector.streak,
                    "epoch": epoch,
                    "moves": list(self._log)}

    def placement_view(self) -> Dict[str, Any]:
        """The ``cli obs --placement`` data source: the full per-slot
        ownership table joined with local partition bytes (one
        best-effort COLLECT_STATS fan-out) and ledger heat shares,
        plus the rebalancer's status and last-move log — ONE
        server-side extractor so the pretty and ``--json`` renderings
        cannot drift."""
        ctl = self.ctl
        members = [ctl.advertise_addr] + [
            a for a in ctl._worker_addrs
            if not ctl.shards.is_degraded(a)]
        entries: Dict[Tuple[str, str], Dict[str, Any]] = {}
        for db, s in ctl.placement.sets():
            e = ctl.placement.entry(db, s)
            if e is not None:
                entries[(db, s)] = e
        heats = set_heats(obs.attrib.LEDGER.snapshot())
        sizes = self._gather_sizes(entries)
        sets_out = []
        for (db, s), e in sorted(entries.items()):
            scope = f"{db}:{s}"
            slots = e.get("slots", ())
            live = sum(1 for sl in slots
                       if sl.get("state") == _placement.LIVE)
            share = (heats.get(scope, 0.0) / live) if live else 0.0
            sets_out.append({
                "db": db, "set": s, "mode": e.get("mode"),
                "epoch": e.get("epoch"),
                "heat": round(heats.get(scope, 0.0), 4),
                "slots": [{
                    "slot": i, "addr": sl["addr"],
                    "state": sl.get("state"),
                    "nbytes": sizes.get((sl["addr"], scope), 0),
                    "heat": round(
                        share if sl.get("state") == _placement.LIVE
                        else 0.0, 4),
                } for i, sl in enumerate(slots)],
            })
        member_heat = addr_heats(entries, heats, members)
        return {"status": self.status(),
                "members": [{
                    "addr": a,
                    "heat": round(member_heat.get(a, 0.0), 4),
                    "nbytes": sum(n for (ad, _sc), n in sizes.items()
                                  if ad == a),
                    "slots": sum(
                        1 for e in entries.values()
                        for sl in e.get("slots", ())
                        if sl["addr"] == a
                        and sl.get("state") == _placement.LIVE),
                } for a in members],
                "skew_ratio": round(skew_ratio(member_heat), 4),
                "sets": sets_out}

    # --- the cadence entry point --------------------------------------
    def check(self, force: bool = False) -> Optional[List[Dict[str, Any]]]:
        """One skew-detector pass; plans + runs a bounded move round
        when the imbalance is sustained (or a pool change forced it).
        Returns the round's move results (None = no round ran)."""
        ctl = self.ctl
        if not getattr(ctl.config, "rebalance", False):
            return None
        if not ctl._worker_addrs:
            return None
        if ctl._ha is not None and ctl._ha.role != "leader":
            return None  # only the leader moves data
        obs.REGISTRY.counter("rebalance.skew_checks").inc()
        members = [ctl.advertise_addr] + [
            a for a in ctl._worker_addrs
            if not ctl.shards.is_degraded(a)]
        entries: Dict[Tuple[str, str], Dict[str, Any]] = {}
        for db, s in ctl.placement.sets():
            e = ctl.placement.entry(db, s)
            if e is not None:
                entries[(db, s)] = e
        heats = set_heats(obs.attrib.LEDGER.snapshot())
        obs.REGISTRY.gauge("placement.epoch").set(
            ctl.placement.to_wire()["epoch"])
        with self._mu:
            ratio, sustained = self._detector.observe(
                heats, entries, members)
            self._last_ratio = ratio
            go = (sustained or self._force or force) \
                and not self._running and bool(entries) \
                and len(members) > 1
            if go:
                self._running = True
                self._force = False
        if not go:
            return None
        try:
            plan = plan_moves(
                self._movable(entries), heats,
                self._gather_sizes(entries), members,
                int(getattr(ctl.config,
                            "rebalance_max_bytes_per_round", 0)))
            if not plan:
                return []
            return self.run_moves(plan)
        finally:
            with self._mu:
                self._running = False

    def _movable(self, entries):
        """Planner input: paged sets stay put (their partitions live
        in the arena — moving them re-hosts resident, a follow-on)."""
        out = {}
        for (db, s), entry in entries.items():
            if self.ctl.library.store.storage_of(
                    _ident(db, s)) == "paged":
                continue
            out[(db, s)] = entry
        return out

    def _gather_sizes(self, entries) -> Dict[Tuple[str, str], int]:
        """Per-(member, scope) local partition bytes: the leader's own
        store plus one best-effort COLLECT_STATS fan-out (a silent
        worker just contributes zero — the planner still balances by
        heat, the byte bound degrades to move-count)."""
        ctl = self.ctl
        sizes: Dict[Tuple[str, str], int] = {}
        for scope, stats in (ctl.library.collect_stats() or {}).items():
            sizes[(ctl.advertise_addr, scope)] = \
                int(stats.get("nbytes", 0) or 0)
        replies = ctl.shards.fanout(MsgType.COLLECT_STATS,
                                    {"local_only": True})
        for addr, reply in (replies or {}).items():
            if not isinstance(reply, dict) or "error" in reply:
                continue
            for scope, stats in (reply.get("sets") or {}).items():
                sizes[(addr, scope)] = int(stats.get("nbytes", 0) or 0)
        return sizes

    # --- the move executor --------------------------------------------
    def _op(self, addr: str, payload: Dict[str, Any]) -> Dict[str, Any]:
        from netsdb_tpu.serve.errors import (
            ConnectionLostError,
            DeadlineExceededError,
            RemoteTimeoutError,
        )

        if addr == self.ctl.advertise_addr:
            return handle_reshard(self.ctl, payload)
        try:
            return self.ctl.shards.peer_request(
                addr, MsgType.RESHARD, payload, CODEC_PICKLE)
        except (OSError, ProtocolError, ConnectionLostError,
                RemoteTimeoutError, DeadlineExceededError) as e:
            # the peer-request layer wraps transport death in its
            # typed retryable family — for a MOVE leg that still
            # means "peer down": abort and degrade, don't guess
            raise _PeerDown(addr, e) from e

    def run_moves(self, plan: List[Dict[str, Any]]
                  ) -> List[Dict[str, Any]]:
        """Execute one planned round, move by move. The round stops
        at the first failed move (membership just changed under the
        plan — the next cadence replans against reality)."""
        results = []
        for mv in plan:
            try:
                self._move(mv["db"], mv["set"], int(mv["slot"]),
                           mv["src"], mv["dst"],
                           nbytes=int(mv.get("nbytes", 0)))
                results.append({**mv, "ok": True})
            except Exception as e:  # noqa: BLE001 — aborted typed below
                results.append({**mv, "ok": False,
                                "error": f"{type(e).__name__}: {e}"})
                break
        return results

    def _move(self, db: str, set_name: str, slot: int,
              src: str, dst: str, nbytes: int = 0) -> None:
        """One all-or-nothing slot move under the commit ordering:

        pull(src) → prepare(dst) → install → SEAL(src) → pull tail →
        install tail → count-verify(dst) → ``move_slot`` epoch bump →
        persist + replicate → register dst (SHARD_RESYNC) →
        push epochs → drop(src).

        The epoch bump is the commit point. Failures BEFORE it unwind
        to "nothing happened" (source unsealed, destination garbage
        cleared by its next prepare); a destination that dies between
        the bump and its registration REVERTS the bump (another epoch
        bump back to the source — the source still holds everything).
        A transport-dead peer is degraded (slots to handoff, epoch
        bump) — exactly the eviction story a failed heartbeat gives."""
        ctl = self.ctl
        cs = ctl.library.catalog.get_set(db, set_name) or {}
        meta = {"type_name": cs.get("type", "tensor"),
                "persistence": cs.get("persistence", "transient"),
                "storage": (cs.get("meta") or {}).get("storage",
                                                      "memory")}
        sealed_src = False
        try:
            pull0 = self._op(src, {"op": "pull", "db": db,
                                   "set": set_name, "offset": 0})
            n0 = int(pull0["rows"])
            self._op(dst, {"op": "prepare", "db": db, "set": set_name,
                           "meta": meta})
            self._ship(dst, db, set_name, pull0, append=False)
            sealed_src = True
            n1 = int(self._op(src, {"op": "seal", "db": db,
                                    "set": set_name,
                                    "ttl_s": SEAL_TTL_S})["rows"])
            if n1 < n0:
                raise MoveAborted(
                    f"reshard source {db}:{set_name}[{slot}] shrank "
                    f"mid-copy ({n0} → {n1} rows); aborting the move")
            if n1 > n0:
                tail = self._op(src, {"op": "pull", "db": db,
                                      "set": set_name, "offset": n0})
                self._ship(dst, db, set_name, tail, append=True)
            got = int(self._op(dst, {"op": "count", "db": db,
                                     "set": set_name})["rows"])
            if got != n1:
                raise MoveAborted(
                    f"reshard copy of {db}:{set_name}[{slot}] "
                    f"verified {got} rows at {dst}, source sealed "
                    f"{n1}; aborting the move")
        except Exception as e:
            self._abort(db, set_name, src, dst, e,
                        unseal_src=sealed_src)
            raise
        # --- commit ---------------------------------------------------
        entry = ctl.placement.move_slot(db, set_name, slot, dst)
        if entry is None:
            self._abort(db, set_name, src, dst,
                        MoveAborted("placement entry vanished"),
                        unseal_src=True)
            raise MoveAborted(
                f"reshard commit of {db}:{set_name}[{slot}] found no "
                f"placement entry; move aborted")
        ctl._replicate_placement()  # persist BEFORE the dst resync —
        # a leader restart must reload the post-move map, never a map
        # whose registered owners it cannot reconstruct
        if dst != ctl.advertise_addr:
            try:
                ctl.shards.peer_request(
                    dst, MsgType.SHARD_RESYNC,
                    {"sets": [{"db": db, "set": set_name,
                               "slot": slot,
                               "epoch": entry["epoch"]}]})
            except Exception as e:  # noqa: BLE001 — revert the bump
                # the destination died AFTER the bump: the source
                # still holds every row, so re-own it (another bump)
                # rather than strand the slot on a corpse
                ctl.placement.move_slot(db, set_name, slot, src)
                ctl._replicate_placement()
                self._abort(db, set_name, src, dst, e,
                            unseal_src=True)
                raise
        ctl._push_epochs()
        try:
            self._op(src, {"op": "drop", "db": db, "set": set_name})
        except Exception as e:  # noqa: BLE001 — committed; src is the
            # only loose end and it just proved unreachable: degrade
            # it so its stale copy can never serve
            ctl._evict_shard(src, f"reshard drop failed: "
                                  f"{type(e).__name__}: {e}")
        obs.REGISTRY.counter("rebalance.moves").inc()
        if nbytes:
            obs.REGISTRY.counter("rebalance.bytes_moved").inc(nbytes)
        obs.REGISTRY.gauge("placement.epoch").set(entry["epoch"])
        with self._mu:
            self._log.append({"db": db, "set": set_name, "slot": slot,
                              "src": src, "dst": dst,
                              "nbytes": nbytes,
                              "epoch": entry["epoch"]})
            del self._log[:-MOVE_LOG]
        try:
            self._op(dst, {"op": "warm", "db": db, "set": set_name})
        except Exception as e:  # noqa: BLE001 — warm is advisory
            del e
            pass

    def _ship(self, dst: str, db: str, set_name: str,
              pulled: Dict[str, Any], append: bool) -> None:
        payload = {"op": "install", "db": db, "set": set_name,
                   "kind": pulled.get("kind"), "append": append}
        if pulled.get("kind") == "table":
            if append and pulled.get("table") is None:
                return  # empty tail — nothing to merge
            payload["table"] = pulled.get("table")
        else:
            payload["items"] = pulled.get("items") or []
        self._op(dst, payload)

    def _abort(self, db: str, set_name: str, src: str, dst: str,
               cause: BaseException, unseal_src: bool) -> None:
        """Unwind one failed move: tick the abort counter, lift the
        source seal (best-effort — the TTL covers an unreachable
        source), and degrade a transport-dead peer so the pool's
        epoch rolls forward to handoff exactly like a failed
        heartbeat."""
        obs.REGISTRY.counter("rebalance.aborts").inc()
        if unseal_src:
            try:
                self._op(src, {"op": "unseal", "db": db,
                               "set": set_name})
            except Exception as e:  # noqa: BLE001 — TTL covers it
                del e
                pass
        if isinstance(cause, _PeerDown):
            self.ctl._evict_shard(
                cause.addr, f"reshard move failed: {cause}")
        with self._mu:
            self._log.append({"db": db, "set": set_name, "src": src,
                              "dst": dst, "aborted": True,
                              "error": f"{type(cause).__name__}: "
                                       f"{cause}"})
            del self._log[:-MOVE_LOG]

    # --- the learning-loop arm ----------------------------------------
    def advise(self, measure) -> Dict[str, Any]:
        """The placement-advisor protocol (learning/advisor.py's
        rebalance arm): measure baseline routed throughput, apply the
        current move plan, re-measure, COMMIT when the plan helped
        (ticking ``rebalance.advisor_commits``) or REVERT every move
        (the inverse plan) when it did not. ``measure()`` returns a
        higher-is-better number."""
        before = float(measure())
        self.pool_changed()
        results = self.check() or []
        applied = [r for r in results if r.get("ok")]
        if not applied:
            return {"decision": "no-plan", "before": before,
                    "after": before, "moves": results}
        after = float(measure())
        if after >= before:
            obs.REGISTRY.counter("rebalance.advisor_commits").inc(
                len(applied))
            return {"decision": "commit", "before": before,
                    "after": after, "moves": applied}
        inverse = [{"db": r["db"], "set": r["set"], "slot": r["slot"],
                    "src": r["dst"], "dst": r["src"],
                    "nbytes": r.get("nbytes", 0)}
                   for r in reversed(applied)]
        self.run_moves(inverse)
        return {"decision": "revert", "before": before,
                "after": after, "moves": applied}


def _ident(db: str, set_name: str):
    from netsdb_tpu.storage.store import SetIdentifier

    return SetIdentifier(db, set_name)
