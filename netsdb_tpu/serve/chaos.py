"""Deterministic fault injection for the serve control plane.

A :class:`ChaosInjector` is an explicit object handed to a
:class:`~netsdb_tpu.serve.client.RemoteClient` (request/reply frames),
a :class:`~netsdb_tpu.serve.server.ServeController` (request recv +
reply send), or a controller's ``follower_chaos`` (leader→follower
mirror frames). Production paths never construct one, and the hook in
``protocol.send_frame``/``recv_frame_raw`` is a single ``is None``
check — zero cost when chaos is off.

Two modes, freely combined:

* **scripted** (:meth:`arm`): a FIFO of exact actions consumed by the
  next matching frames — the deterministic mode the chaos tests use to
  place one fault at one protocol step.
* **probabilistic**: seeded per-frame rates (``drop``/``delay``/
  ``corrupt``/``truncate``), bounded by ``max_faults`` so a retrying
  client always converges. Same seed → same fault sequence.

Actions (``where="send"`` unless noted):

* ``drop`` — the frame is never written (or read, ``where="recv"``);
  the socket is torn down so the peer observes a reset instead of
  hanging, and :class:`ConnectionResetError` is raised locally.
* ``delay`` — sleep ``delay_s`` before the frame proceeds (drives the
  timeout paths).
* ``corrupt`` — every body byte is XOR-flipped; the header (and its
  length field) stays valid, so the peer reads a well-framed body that
  fails to decode — the CorruptFrame path.
* ``corrupt_seg`` — flips one byte in the middle of the largest
  OUT-OF-BAND tensor segment (wire format v3): the msgpack body still
  decodes, but the segment no longer matches its checksum in the
  segment table — the corruption lands where msgpack's own framing
  cannot see it. Falls back to ``corrupt`` on frames without segments.
* ``truncate`` — the frame is cut mid-flight, then the socket is torn
  down: the peer's ``_recv_exact`` sees EOF mid-frame. On a codec-2
  frame the cut lands INSIDE the first tensor segment (header, segment
  table and body all arrive intact first).
* ``kill`` — alias of ``drop``; reads better in follower-kill tests.

Every injected fault is recorded in :attr:`faults` for assertions.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, List, Optional, Tuple

from netsdb_tpu.utils.locks import TrackedLock

_ACTIONS = ("drop", "delay", "corrupt", "corrupt_seg", "truncate", "kill")


class ChaosInjector:
    def __init__(self, seed: int = 0, drop: float = 0.0, delay: float = 0.0,
                 corrupt: float = 0.0, truncate: float = 0.0,
                 delay_s: float = 0.05,
                 max_faults: Optional[int] = None):
        self._rng = random.Random(seed)
        self._rates = (("drop", drop), ("delay", delay),
                       ("corrupt", corrupt), ("truncate", truncate))
        self.delay_s = delay_s
        self.max_faults = max_faults
        self._mu = TrackedLock("ChaosInjector._mu")
        # scripted queue: (action, where, types-or-None, delay_s)
        self._script: List[Tuple[str, str, Optional[frozenset], float]] = []
        self.faults: List[Tuple[str, str, Any]] = []  # (action, where, typ)

    # --- configuration -------------------------------------------------
    def arm(self, *actions: str, where: str = "send", types=None,
            delay_s: Optional[float] = None) -> "ChaosInjector":
        """Queue deterministic actions for the next frames passing the
        ``where`` hook (optionally only frames whose type is in
        ``types``). Scripted actions fire regardless of ``max_faults``."""
        for a in actions:
            if a not in _ACTIONS:
                raise ValueError(f"unknown chaos action {a!r}")
            with self._mu:
                self._script.append(
                    (a, where, frozenset(int(t) for t in types) if types
                     else None, self.delay_s if delay_s is None else delay_s))
        return self

    # --- decision ------------------------------------------------------
    def _next(self, where: str, msg_type: Optional[int]):
        with self._mu:
            for i, (action, w, types, dly) in enumerate(self._script):
                if w != where:
                    continue
                if types is not None and (msg_type is None
                                          or int(msg_type) not in types):
                    continue
                del self._script[i]
                self.faults.append((action, where, msg_type))
                return action, dly
            if self.max_faults is not None \
                    and len(self.faults) >= self.max_faults:
                return None, 0.0
            roll = self._rng.random()
            acc = 0.0
            for action, rate in self._rates:
                acc += rate
                if roll < acc:
                    self.faults.append((action, where, msg_type))
                    return action, self.delay_s
        return None, 0.0

    # --- hooks (called from protocol.py) -------------------------------
    @staticmethod
    def _teardown(sock) -> None:
        import socket as _socket

        try:
            sock.shutdown(_socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass

    def on_send(self, sock, msg_type: int, header: bytes, body: bytes,
                segtable: bytes = b"", segments=()) -> Tuple:
        """Possibly fault the outgoing frame; returns the (header,
        segtable, body, segments) to actually write. ``segments`` are
        the out-of-band tensor buffers of a codec-2 frame (empty
        otherwise); the segment TABLE — lengths + checksums — is never
        rewritten, so a mutated segment arrives detectably stale.
        ``drop``/``truncate`` tear the socket down and raise
        ConnectionResetError so the caller's failure path runs exactly
        as it would on a real reset."""
        segments = list(segments)
        action, dly = self._next("send", msg_type)
        if action is None:
            return header, segtable, body, segments
        if action == "delay":
            time.sleep(dly)
            return header, segtable, body, segments
        if action == "corrupt_seg" and segments:
            i = max(range(len(segments)), key=lambda k: segments[k].nbytes)
            mutated = bytearray(segments[i])
            mutated[len(mutated) // 2] ^= 0xA5
            segments[i] = memoryview(mutated)
            return header, segtable, body, segments
        if action in ("corrupt", "corrupt_seg"):
            return header, segtable, bytes(b ^ 0xA5 for b in body), segments
        if action == "truncate":
            try:
                sock.sendall(header)
                sock.sendall(segtable)
                if segments:
                    # the cut lands INSIDE a tensor segment: body and
                    # segment table arrive whole, the raw buffer doesn't
                    sock.sendall(body)
                    first = segments[0]
                    sock.sendall(first[: max(1, first.nbytes // 2)])
                else:
                    sock.sendall(body[: max(1, len(body) // 2)])
            except OSError:
                pass
            self._teardown(sock)
            raise ConnectionResetError(
                f"chaos: frame type {msg_type} truncated (injected)")
        # drop / kill
        self._teardown(sock)
        raise ConnectionResetError(
            f"chaos: frame type {msg_type} dropped (injected)")

    def on_recv(self, sock) -> None:
        """Possibly fault before reading the next frame (the incoming
        direction — frame types are unknown until read, so recv scripts
        match any type)."""
        action, dly = self._next("recv", None)
        if action is None:
            return
        if action == "delay":
            time.sleep(dly)
            return
        self._teardown(sock)
        raise ConnectionResetError("chaos: inbound frame dropped (injected)")
