"""Stateful interactive serving: the session subsystem.

A *session* is a named, TTL'd decode loop over one registered model
(``models/decode.py``): ``SESSION_OPEN`` binds ``sid → (model, owner,
ttl)``, each ``GENERATE`` advances the session's recurrent state by
one step, ``SESSION_CLOSE`` drops it. Three stores cooperate, fastest
first:

* **Device cache** (``storage/devcache.py`` session entries) — the hot
  copy: one MUTABLE entry per ``(session, model, layer)``, updated in
  place every step. The methods mutating it are called ONLY from this
  module (the ``session-state-mutation`` lint rule).
* **Host arena** (:class:`SessionArena`) — where evicted/expired
  layers land via the devcache spill callback, and where a session
  revives from after pressure, TTL expiry, or owner failover. A warm
  decode step never touches it (``arena.reads`` is the structural
  gate's counter).
* **The replicated session table** (:class:`SessionTable`) — sid →
  metadata. Not replicated by itself: the MIRRORED ``SESSION_OPEN`` /
  ``GENERATE`` / ``SESSION_CLOSE`` frames replay at every follower,
  which re-derives the same table (and the same devcache/arena state,
  since decode is deterministic) — the HA-log-shipping discipline the
  data plane already uses, reused verbatim for sessions.

Every layer value is stored STEP-TAGGED (``{"step": n, "v": array}``)
in both the devcache and the arena. The newest copy of each layer is
always in exactly one of the two (resident beats arena; the arena
keeps the highest-step spill), so a revive assembled layer-by-layer
is consistent by construction — and a torn assembly (which would mean
a bookkeeping bug, not a race) raises instead of silently decoding
from mixed steps.

Ownership and stickiness: the pool leader places each session
deterministically (itself, or one live worker by sid hash), pushing
``SESSION_OPEN op=adopt`` — with the model's dense weights on the
first session per (owner, model) — to a worker owner. A frame landing
on a non-owner answers the typed retryable ``SessionMoved`` carrying
the owner's address; the client re-points and retries under the SAME
idempotency token, so a step is never double-applied to one state
copy, and a re-applied step after failover recomputes bit-identically
from the last durable state."""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from netsdb_tpu import obs
from netsdb_tpu.models import decode as _decode
from netsdb_tpu.serve.errors import ServeFault, SessionMoved, SessionUnknown
from netsdb_tpu.serve.protocol import MsgType, CODEC_PICKLE
from netsdb_tpu.serve.sched.sessions import DecodeBatcher
from netsdb_tpu.utils.locks import TrackedLock


def _host(value: Any) -> np.ndarray:
    """A host-side copy of one layer value (device array or ndarray).
    The spill callback runs under the devcache lock; this is the one
    transfer it performs."""
    return np.array(np.asarray(value))


class SessionTable:
    """sid → session metadata. Every daemon re-derives its own copy
    from the mirrored frame stream (module docstring); the wire dump
    only rides follower resync snapshots."""

    def __init__(self):
        self._mu = TrackedLock("SessionTable._mu")
        self._rows: Dict[str, Dict[str, Any]] = {}

    def open(self, sid: str, db: str, kind: str, owner: str,
             ttl_s: float, home: Optional[str] = None) -> Dict[str, Any]:
        with self._mu:
            row = self._rows.get(sid)
            if row is None:
                row = {"sid": sid, "db": db, "kind": kind,
                       "owner": owner, "home": home, "ttl_s": float(ttl_s),
                       "steps": 0}
                self._rows[sid] = row
            return dict(row)

    def get(self, sid: str) -> Optional[Dict[str, Any]]:
        with self._mu:
            row = self._rows.get(sid)
            return dict(row) if row else None

    def steps(self, sid: str) -> int:
        with self._mu:
            row = self._rows.get(sid)
            return int(row["steps"]) if row else 0

    def bump(self, sid: str) -> int:
        with self._mu:
            row = self._rows[sid]
            row["steps"] += 1
            return int(row["steps"])

    def set_steps(self, sid: str, steps: int) -> None:
        with self._mu:
            row = self._rows.get(sid)
            if row is not None and int(steps) > int(row["steps"]):
                row["steps"] = int(steps)

    def set_owner(self, sid: str, owner: str,
                  home: Optional[str] = None) -> None:
        with self._mu:
            row = self._rows.get(sid)
            if row is not None:
                row["owner"] = owner
                if home is not None:
                    row["home"] = home

    def close(self, sid: str) -> bool:
        with self._mu:
            return self._rows.pop(sid, None) is not None

    def count(self) -> int:
        with self._mu:
            return len(self._rows)

    def sessions(self) -> List[Dict[str, Any]]:
        with self._mu:
            return [dict(r) for r in self._rows.values()]

    def to_wire(self) -> List[Dict[str, Any]]:
        return self.sessions()

    def load_wire(self, rows: List[Dict[str, Any]]) -> None:
        with self._mu:
            for r in rows or []:
                self._rows[str(r["sid"])] = dict(r)


class SessionArena:
    """Host-side spill store for evicted/expired session state. A
    LEAF: its lock nests under the devcache lock (the spill callback)
    and under nothing else, and it never calls out. ``reads`` counts
    revive lookups that RETURNED state — the warm-decode structural
    gate asserts it stays flat across hot steps."""

    def __init__(self):
        self._mu = TrackedLock("SessionArena._mu")
        # (sid, db) → {"layers": {layer: {"step", "v"(host)}},
        #              "steps": int, "dirty": bool}
        self._slots: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self.reads = 0
        self.writes = 0

    def merge_layer(self, sid: str, db: str, layer: str, step: int,
                    value: np.ndarray, steps_hint: int = 0) -> None:
        key = (sid, db)
        with self._mu:
            slot = self._slots.setdefault(
                key, {"layers": {}, "steps": 0, "dirty": False})
            cur = slot["layers"].get(layer)
            if cur is None or int(step) >= int(cur["step"]):
                slot["layers"][layer] = {"step": int(step), "v": value}
            slot["steps"] = max(int(slot["steps"]), int(step),
                                int(steps_hint))
            slot["dirty"] = True
            self.writes += 1

    def merge_state(self, sid: str, db: str,
                    layers: Dict[str, Dict[str, Any]], steps: int,
                    dirty: bool = False) -> None:
        """A whole-state merge (the op=spill push path) — per-layer
        highest-step-wins, same rule as :meth:`merge_layer`."""
        with self._mu:
            slot = self._slots.setdefault(
                (sid, db), {"layers": {}, "steps": 0, "dirty": False})
            for layer, rec in (layers or {}).items():
                cur = slot["layers"].get(layer)
                if cur is None or int(rec["step"]) >= int(cur["step"]):
                    slot["layers"][layer] = {"step": int(rec["step"]),
                                             "v": rec["v"]}
            slot["steps"] = max(int(slot["steps"]), int(steps))
            if dirty:
                slot["dirty"] = True
            self.writes += 1

    def get_layer(self, sid: str, db: str,
                  layer: str) -> Optional[Dict[str, Any]]:
        with self._mu:
            slot = self._slots.get((sid, db))
            rec = slot["layers"].get(layer) if slot else None
            if rec is not None:
                self.reads += 1
                return dict(rec)
            return None

    def snapshot_slot(self, sid: str, db: str) -> Optional[Dict[str, Any]]:
        with self._mu:
            slot = self._slots.get((sid, db))
            if slot is None:
                return None
            return {"layers": {k: dict(v)
                               for k, v in slot["layers"].items()},
                    "steps": int(slot["steps"])}

    def steps(self, sid: str, db: str) -> int:
        with self._mu:
            slot = self._slots.get((sid, db))
            return int(slot["steps"]) if slot else 0

    def drop(self, sid: str) -> int:
        with self._mu:
            keys = [k for k in self._slots if k[0] == sid]
            for k in keys:
                del self._slots[k]
            return len(keys)

    def take_dirty(self) -> List[Tuple[str, str]]:
        """Pop the dirty markers (the housekeeping push drain)."""
        with self._mu:
            out = [k for k, s in self._slots.items() if s["dirty"]]
            for k in out:
                self._slots[k]["dirty"] = False
            return out

    def mark_dirty(self, sid: str, db: str) -> None:
        with self._mu:
            slot = self._slots.get((sid, db))
            if slot is not None:
                slot["dirty"] = True

    def stats(self) -> Dict[str, int]:
        with self._mu:
            return {"entries": len(self._slots),
                    "reads": self.reads, "writes": self.writes,
                    "bytes": sum(rec["v"].nbytes
                                 for s in self._slots.values()
                                 for rec in s["layers"].values())}


class SessionManager:
    """One per daemon: owns the decode runtime, the table/arena pair,
    the per-model batch coalescer, and the housekeeping thread (TTL
    sweep + spill push to the session's home leader)."""

    def __init__(self, ctl):
        self._ctl = ctl
        cfg = ctl.config
        self.ttl_s = float(getattr(cfg, "session_ttl_s", 600.0))
        self.state_cap = int(getattr(cfg, "session_state_bytes",
                                     16 << 20))
        self.runtime = _decode.DecodeRuntime(
            ctl.library,
            model_dedup=bool(getattr(cfg, "model_dedup", False)))
        self.table = SessionTable()
        self.arena = SessionArena()
        self.batcher = DecodeBatcher(
            self._run_batch,
            max_batch=int(getattr(cfg, "decode_batch_max", 8)))
        # models whose dense weights already shipped to an owner —
        # later sessions of the same (owner, model) adopt weight-less.
        # Guarded by _shipped_mu (handler threads race on it) and
        # invalidated by forget_owner() when the pool health loop
        # degrades/readmits a member: a restarted worker lost its
        # resident models, so a weight-less adopt there would fail
        # register_model and silently degrade placement to
        # leader-local ownership.
        self._shipped: set = set()
        self._shipped_mu = TrackedLock("SessionManager._shipped_mu")
        # per-session last-applied idempotency record
        # {token, steps, y}: the daemon-local idempotency cache only
        # dedupes retries that land on the SAME daemon — this record
        # travels WITH the state (spill push, move, handoff, adopt),
        # so a retry under the same token landing at the session's
        # NEW owner replays the recorded reply instead of advancing
        # the state a second time (the handle's no-double-apply
        # contract across relocations).
        self._applied: Dict[str, Dict[str, Any]] = {}
        self._applied_mu = TrackedLock("SessionManager._applied_mu")
        self._hk_thread: Optional[threading.Thread] = None
        self._hk_stop = threading.Event()
        self._hk_mu = TrackedLock("SessionManager._hk_mu")
        # per-session exclusion between a decode step's load→step→save
        # and a handoff/move/close packing or dropping that state. The
        # server's mirrored-frame ordering locks only exist on daemons
        # WITH followers — a plain pool worker needs this or a live
        # move can tear an in-flight step. A batch takes its sids in
        # sorted order; every other holder takes exactly one, so the
        # two can never deadlock.
        self._sid_locks: Dict[str, TrackedLock] = {}
        self._sid_locks_mu = TrackedLock("SessionManager._sid_locks_mu")
        # diagnostics breadcrumbs (racy-by-design single slots: the
        # LAST best-effort fault, surfaced via stats(); the counters
        # next to each write are the authoritative tally)
        self._last_spill_fault: Optional[str] = None
        self._last_place_fault: Optional[str] = None
        ctl.library.store.device_cache().set_session_spill(self._on_spill)

    # --- roles ---------------------------------------------------------
    def _me(self) -> str:
        return self._ctl.advertise_addr

    def _authoritative(self, row: Dict[str, Any]) -> bool:
        """Is this daemon the session's authority (may adopt, place,
        and answer SessionMoved)? With HA armed, the current LEADER
        is; unarmed, the session's home daemon is (a pool worker's
        rows carry the leader as home, so the worker only ever
        applies what it owns or bounces)."""
        ha = self._ctl._ha
        if ha is not None:
            from netsdb_tpu.serve import ha as _ha

            return ha.role == _ha.LEADER
        home = row.get("home")
        return home is None or home == self._me()

    def _replica(self) -> bool:
        ha = self._ctl._ha
        if ha is None:
            return False
        from netsdb_tpu.serve import ha as _ha

        return ha.role != _ha.LEADER

    def _live_workers(self) -> List[str]:
        ctl = self._ctl
        return [a for a in ctl._worker_addrs
                if not ctl.shards.is_degraded(a)]

    def _pick_owner(self, sid: str) -> str:
        """Deterministic placement from replicated inputs only: sid
        hash over the sorted live workers, or self when the pool is
        plain. A follower replaying the open (usually with no worker
        list) picks ITSELF — exactly the owner it must be if it is
        ever promoted, so failover needs no table rewrite."""
        if self._replica():
            return self._me()
        live = sorted(self._live_workers())
        if not live:
            return self._me()
        h = int(hashlib.sha1(sid.encode()).hexdigest(), 16)
        return live[h % len(live)]

    # --- devcache/arena state movement --------------------------------
    # (the ONLY call sites of the devcache session_* mutators — the
    # session-state-mutation lint rule pins this)
    def _cache(self):
        return self._ctl.library.store.device_cache()

    def _on_spill(self, sid: str, model: str, layer: str,
                  value: Any) -> None:
        """Devcache eviction/expiry escape hatch — LEAF (runs under
        the cache lock): host-copy the layer into the arena, tagged
        with its own step."""
        try:
            rec = value if isinstance(value, dict) else {
                "step": self.table.steps(sid), "v": value}
            self.arena.merge_layer(
                sid, model, layer, int(rec.get("step", 0)),
                _host(rec["v"]), steps_hint=self.table.steps(sid))
        except Exception as e:  # noqa: BLE001 — spill must never
            # take the cache down with it; the arena just misses
            # this copy (counted, last fault kept for stats())
            self._last_spill_fault = repr(e)
            obs.REGISTRY.counter("session.spill_errors").inc()

    def _install_state(self, sid: str, db: str, ttl_s: float,
                       state: Dict[str, Any], step: int) -> None:
        for layer, v in state.items():
            self._cache().session_put(sid, db, layer,
                                      {"step": int(step), "v": v},
                                      ttl_s)

    def _load_state(self, sid: str, db: str,
                    ttl_s: float) -> Tuple[Dict[str, Any], int]:
        """Assemble the session's CURRENT state layer by layer:
        newest copy wins — the resident devcache entry, unless the
        arena's spill for that layer is NEWER (then the arena copy
        revives and re-installs). A resident copy can legitimately be
        stale: a mirror follower replays ``op=open`` owning the
        session itself and installs init state at step 0, while a
        worker-owned session's durability arrives only via mirrored
        ``op=spill`` merges into the arena — after promotion the
        step-0 resident layers would otherwise assemble consistently
        and silently rewind the session. All layers must land on one
        step — a mixed assembly is a torn state and raises rather
        than decoding garbage."""
        layers = self.runtime.state_layers(db)
        out: Dict[str, Any] = {}
        steps_seen = set()
        # the arena's high-water step, read WITHOUT a read tick: on a
        # warm step every resident layer is at least this new, so the
        # zero-warm-arena-reads gate still holds
        arena_steps = self.arena.steps(sid, db)
        for layer in layers:
            rec = self._cache().session_get(sid, db, layer)
            if rec is not None and int(rec["step"]) < arena_steps:
                newer = self.arena.get_layer(sid, db, layer)
                if newer is not None \
                        and int(newer["step"]) > int(rec["step"]):
                    rec = newer
                    self._cache().session_put(sid, db, layer,
                                              dict(rec), ttl_s)
            if rec is None:
                rec = self.arena.get_layer(sid, db, layer)
                if rec is not None:
                    self._cache().session_put(sid, db, layer,
                                              dict(rec), ttl_s)
            if rec is None:
                if self.table.steps(sid) == 0 and arena_steps == 0:
                    rec = {"step": 0,
                           "v": self.runtime.init_state(db)[layer]}
                    self._cache().session_put(sid, db, layer,
                                              dict(rec), ttl_s)
                else:
                    raise SessionUnknown(
                        f"session {sid!r} state layer {layer!r} lost "
                        f"(not resident, no arena spill)")
            out[layer] = rec["v"]
            steps_seen.add(int(rec["step"]))
        if len(steps_seen) > 1:
            raise ServeFault(
                f"session {sid!r} state torn across steps "
                f"{sorted(steps_seen)}")
        step = steps_seen.pop() if steps_seen else 0
        self.table.set_steps(sid, step)
        return out, step

    def _save_state(self, sid: str, db: str, ttl_s: float,
                    state: Dict[str, Any], step: int) -> None:
        for layer, v in state.items():
            rec = {"step": int(step), "v": v}
            if self._cache().session_update(sid, db, layer, rec):
                continue
            if not self._cache().session_put(sid, db, layer, rec,
                                             ttl_s):
                # budget-rejected (the layer alone exceeds the whole
                # cache budget, so eviction can't make room): the
                # advanced state must still land somewhere durable —
                # straight into the arena, same as any spill, so the
                # next step revives it instead of raising
                # SessionUnknown over silently-dropped state
                self.arena.merge_layer(sid, db, layer, int(step),
                                       _host(v), steps_hint=int(step))
                obs.REGISTRY.counter("session.budget_spills").inc()

    def _pack(self, sid: str, db: str) -> Dict[str, Any]:
        """The session's full host-side state (devcache first, arena
        fallback per layer) — the op=spill/handoff payload. The
        last-applied idempotency record rides along so the dedup
        guarantee survives the relocation."""
        layers: Dict[str, Dict[str, Any]] = {}
        for layer in self.runtime.state_layers(db):
            rec = self._cache().session_get(sid, db, layer,
                                            touch=False)
            if rec is None:
                rec = self.arena.get_layer(sid, db, layer)
            if rec is not None:
                layers[layer] = {"step": int(rec["step"]),
                                 "v": _host(rec["v"])}
        out = {"layers": layers,
               "steps": max([self.table.steps(sid),
                             self.arena.steps(sid, db)]
                            + [r["step"] for r in layers.values()]
                            or [0])}
        applied = self._applied_record(sid)
        if applied is not None:
            out["applied"] = applied
        return out

    # --- the per-session applied-token record -------------------------
    def _applied_record(self, sid: str) -> Optional[Dict[str, Any]]:
        """Host-copied wire form of the session's last-applied step
        record, or None."""
        with self._applied_mu:
            last = self._applied.get(sid)
            if last is None:
                return None
            return {"token": last["token"],
                    "steps": int(last["steps"]),
                    "y": _host(last["y"])}

    def _note_applied(self, sid: str,
                      rec: Optional[Dict[str, Any]]) -> None:
        """Adopt a shipped applied-token record (move/handoff/spill
        push) — newest step wins, so a stale straggler push can never
        roll the dedup horizon backwards."""
        if not rec or not rec.get("token"):
            return
        with self._applied_mu:
            cur = self._applied.get(sid)
            if cur is None \
                    or int(rec.get("steps", 0)) >= int(cur["steps"]):
                self._applied[sid] = {"token": rec["token"],
                                      "steps": int(rec.get("steps", 0)),
                                      "y": rec["y"]}

    # --- the batched decode step --------------------------------------
    def _sid_lock(self, sid: str) -> TrackedLock:
        with self._sid_locks_mu:
            return self._sid_locks.setdefault(
                sid, TrackedLock("SessionManager._sid_locks[]"))

    def _run_batch(self, db: str,
                   reqs: List[Dict[str, Any]]) -> List[Any]:
        locks = [self._sid_lock(s)
                 for s in sorted({str(r["sid"]) for r in reqs})]
        for lk in locks:
            lk.acquire()
        try:
            return self._run_batch_locked(db, reqs)
        finally:
            for lk in reversed(locks):
                lk.release()

    def _run_batch_locked(self, db: str,
                          reqs: List[Dict[str, Any]]) -> List[Any]:
        with obs.span("session.batch", "serve"):
            results: List[Any] = [None] * len(reqs)
            live: List[int] = []
            states, steps, ttls = [], [], []
            me = self._me()
            for i, r in enumerate(reqs):
                sid = r["sid"]
                row = self.table.get(sid)
                if row is None:
                    results[i] = SessionUnknown(
                        f"unknown session {sid!r}")
                    continue
                if row["owner"] != me:
                    # a handoff/move won the sid lock while this step
                    # sat in the coalesce queue: bounce ONLY this
                    # request typed-retryable, keep the rest batched
                    results[i] = SessionMoved(
                        f"session {sid!r} moved to {row['owner']}",
                        owner_addr=row["owner"])
                    continue
                tok = r.get("tok")
                if tok:
                    with self._applied_mu:
                        last = self._applied.get(sid)
                    if last is not None and last["token"] == tok:
                        # retry of an applied-but-unanswered step whose
                        # record travelled here with the state (the
                        # daemon-local idempotency cache can't have
                        # seen this token): replay the recorded reply,
                        # never advance the state twice under one token
                        results[i] = {"y": last["y"],
                                      "steps": int(last["steps"])}
                        continue
                ttl = float(row["ttl_s"])
                try:
                    st, step = self._load_state(sid, db, ttl)
                except ServeFault as e:
                    results[i] = e
                    continue
                live.append(i)
                states.append(st)
                steps.append(step)
                ttls.append(ttl)
            if live:
                xs = [np.asarray(reqs[i]["x"], np.float32)
                      for i in live]
                with obs.span("session.device", "serve"):
                    new, outs = self.runtime.step_batch(db, states, xs)
                for j, i in enumerate(live):
                    sid = reqs[i]["sid"]
                    step = steps[j] + 1
                    self._save_state(sid, db, ttls[j], new[j], step)
                    self.table.set_steps(sid, step)
                    results[i] = {"y": outs[j], "steps": step}
                    tok = reqs[i].get("tok")
                    if tok:
                        with self._applied_mu:
                            self._applied[sid] = {"token": tok,
                                                  "steps": step,
                                                  "y": outs[j]}
                obs.REGISTRY.counter("session.decode_steps").inc(
                    len(live))
                obs.REGISTRY.counter("session.batch_occupancy").inc(
                    len(live))
            return results

    # --- frame handlers (called from ServeController) ------------------
    def handle_open(self, p: Dict[str, Any]):
        op = p.get("op", "open")
        if op == "open":
            return self._op_open(p)
        if op == "adopt":
            return self._op_adopt(p)
        if op == "spill":
            return self._op_spill(p)
        if op == "lookup":
            return self._op_lookup(p)
        if op == "move":
            return self._op_move(p)
        if op == "handoff":
            return self._op_handoff(p)
        raise ServeFault(f"unknown SESSION_OPEN op {op!r}")

    def _op_open(self, p):
        sid = str(p["sid"])
        db = str(p["db"])
        kind = str(p.get("kind", "lstm"))
        ttl_s = float(p.get("ttl_s") or self.ttl_s)
        heads = p.get("heads")
        spec = self.runtime.register_model(
            db, kind, client=p.get("client"), heads=heads)
        nbytes = self.runtime.state_nbytes(db)
        if nbytes > self.state_cap:
            raise ServeFault(
                f"session state ({nbytes}B) exceeds "
                f"session_state_bytes ({self.state_cap}B)")
        existing = self.table.get(sid)
        if existing is not None:  # idempotent re-open
            return MsgType.OK, {"sid": sid, "owner": existing["owner"],
                                "spec": spec, "state_nbytes": nbytes,
                                "steps": existing["steps"]}
        owner = self._pick_owner(sid)
        if owner != self._me() and not self._replica():
            try:
                self._push_adopt(owner, sid, db, kind, spec, ttl_s)
            except Exception as e:  # noqa: BLE001 — placement is
                # best-effort; a dead worker falls back to local
                # ownership (the client never sees the bounce)
                self._last_place_fault = repr(e)
                owner = self._me()
        self.table.open(sid, db, kind, owner, ttl_s, home=self._me())
        if owner == self._me():
            self._install_state(sid, db, ttl_s,
                                self.runtime.init_state(db), 0)
        obs.REGISTRY.counter("session.opened").inc()
        self._ensure_housekeeping(ttl_s)
        return MsgType.OK, {"sid": sid, "owner": owner, "spec": spec,
                            "state_nbytes": nbytes, "steps": 0}

    def _push_adopt(self, owner: str, sid: str, db: str, kind: str,
                    spec: Dict[str, Any], ttl_s: float,
                    state: Optional[Dict[str, Any]] = None,
                    steps: int = 0) -> None:
        payload = {"op": "adopt", "sid": sid, "db": db, "kind": kind,
                   "heads": spec.get("heads"), "ttl_s": ttl_s,
                   "home": self._me(), "steps": int(steps)}
        if state is not None:
            payload["state"] = state
        with self._shipped_mu:
            shipped = (owner, db) in self._shipped
        if not shipped:
            # two concurrent opens may both ship — benign: the ingest
            # is idempotent; what must never happen is a weight-LESS
            # adopt at an owner that doesn't hold the model
            payload["weights"] = self._export_weights(db, kind)
            payload["block"] = [32, 32]
        self._ctl.shards.peer_request(owner, MsgType.SESSION_OPEN,
                                      payload, codec=CODEC_PICKLE)
        with self._shipped_mu:
            self._shipped.add((owner, db))

    def forget_owner(self, addr: str) -> None:
        """Invalidate the weights-already-shipped record for one pool
        member (called by the pool's degrade/readmit bookkeeping): a
        dead or restarted worker no longer holds the model, so the
        next session placed there must ship weights again."""
        with self._shipped_mu:
            self._shipped = {e for e in self._shipped if e[0] != addr}

    def _export_weights(self, db: str, kind: str) -> Dict[str, np.ndarray]:
        names = (_decode.LSTM_WEIGHTS if kind == "lstm"
                 else _decode.TRANSFORMER_WEIGHTS)
        out = {}
        for n in names:
            t = self._ctl.library.get_tensor(db, n)
            out[n] = np.array(t.data[:t.meta.shape[0],
                                     :t.meta.shape[1]])
        return out

    def _op_adopt(self, p):
        sid = str(p["sid"])
        db = str(p["db"])
        kind = str(p.get("kind", "lstm"))
        ttl_s = float(p.get("ttl_s") or self.ttl_s)
        if p.get("weights"):
            self._install_model_local(db, kind, p["weights"],
                                      tuple(p.get("block") or (32, 32)))
        self.runtime.register_model(db, kind, heads=p.get("heads"))
        self.table.open(sid, db, kind, self._me(), ttl_s,
                        home=p.get("home"))
        self.table.set_owner(sid, self._me(), home=p.get("home"))
        steps = int(p.get("steps", 0))
        state = p.get("state")
        if state:
            self.arena.merge_state(sid, db, state["layers"],
                                   state.get("steps", steps))
            self.table.set_steps(sid, int(state.get("steps", steps)))
            self._note_applied(sid, state.get("applied"))
        elif steps == 0:
            self._install_state(sid, db, ttl_s,
                                self.runtime.init_state(db), 0)
        self._ensure_housekeeping(ttl_s)
        return MsgType.OK, {"sid": sid, "owner": self._me(),
                            "steps": self.table.steps(sid)}

    def _install_model_local(self, db: str, kind: str,
                             weights: Dict[str, np.ndarray],
                             block: Tuple[int, int]) -> None:
        """Ingest shipped dense weights through this daemon's OWN
        library (create_set + send_matrix), so the worker's
        register_model walks the same store path — fingerprints, and
        the dedup pooling wiring, trigger here exactly as at the
        leader."""
        lib = self._ctl.library
        try:
            lib.create_database(db)
        except Exception as e:  # noqa: BLE001 — exists
            del e
        for name, w in weights.items():
            w = np.asarray(w, np.float32)
            if w.ndim == 1:
                w = w.reshape(-1, 1)
            shape = (block[0], 1) if w.shape[1] == 1 else tuple(block)
            try:
                lib.create_set(db, name, type_name="matrix")
            except Exception as e:  # noqa: BLE001 — exists
                del e
            lib.send_matrix(db, name, w, block_shape=shape)

    def _op_spill(self, p):
        sid = str(p["sid"])
        db = str(p["db"])
        state = p.get("state") or {}
        self.arena.merge_state(sid, db, state.get("layers", {}),
                               int(state.get("steps", 0)))
        self.table.set_steps(sid, int(state.get("steps", 0)))
        self._note_applied(sid, state.get("applied"))
        return MsgType.OK, {"sid": sid,
                            "steps": self.arena.steps(sid, db)}

    def _op_lookup(self, p):
        sid = str(p["sid"])
        row = self.table.get(sid)
        if row is None:
            raise SessionUnknown(f"unknown session {sid!r}")
        owner = row["owner"]
        if owner != self._me() and self._authoritative(row) \
                and owner not in self._live_workers():
            # heal: the recorded owner is gone — adopt here, revive
            # lands lazily from the arena on the next decode step
            self.table.set_owner(sid, self._me(), home=self._me())
            owner = self._me()
        elif self._replica():
            self.table.set_owner(sid, self._me())
            owner = self._me()
        return MsgType.OK, {"sid": sid, "owner": owner,
                            "steps": self.table.steps(sid)}

    def _op_move(self, p):
        """Relocate a LIVE session (the rebalance hook): pack the
        state wherever it currently is, adopt it at the target, and
        re-point the table. In-flight client steps bounce with the
        typed retryable ``SessionMoved`` and land at the target."""
        sid = str(p["sid"])
        to = str(p["to"])
        row = self.table.get(sid)
        if row is None:
            raise SessionUnknown(f"unknown session {sid!r}")
        if self._replica():  # replay: converge to self, no RPC
            self.table.set_owner(sid, self._me())
            return MsgType.OK, {"sid": sid, "owner": self._me()}
        db, kind = row["db"], row["kind"]
        if row["owner"] == self._me():
            with self._sid_lock(sid):
                state = self._pack(sid, db)
                # keep a local arena copy until the adopt lands: a
                # failed push must not leave the packed dict as the
                # state's only holder (ownership stays here on
                # failure, and the next step revives from this copy)
                self.arena.merge_state(sid, db, state["layers"],
                                       state["steps"])
                self._cache().session_drop(sid)
        else:
            rep = self._ctl.shards.peer_request(
                row["owner"], MsgType.SESSION_OPEN,
                {"op": "handoff", "sid": sid}, codec=CODEC_PICKLE)
            state = rep.get("state") or {"layers": {}, "steps": 0}
        if to == self._me():
            self.arena.merge_state(sid, db, state["layers"],
                                   state["steps"])
            self._note_applied(sid, state.get("applied"))
            self.table.set_owner(sid, self._me(), home=self._me())
        else:
            self._push_adopt(to, sid, db, kind,
                             self.runtime.spec(db) or {}, row["ttl_s"],
                             state=state, steps=state["steps"])
            self.table.set_owner(sid, to)
            self.arena.drop(sid)  # the adopt landed; the safety copy
            # (and any older spill) must not linger here
        self.table.set_steps(sid, int(state["steps"]))
        return MsgType.OK, {"sid": sid, "owner": to,
                            "steps": int(state["steps"])}

    def _op_handoff(self, p):
        """Old-owner half of a move: pack, then drop the local copy
        and re-point at home so late frames bounce typed."""
        sid = str(p["sid"])
        row = self.table.get(sid)
        if row is None:
            raise SessionUnknown(f"unknown session {sid!r}")
        with self._sid_lock(sid):
            state = self._pack(sid, row["db"])
            self._cache().session_drop(sid)
            self.arena.drop(sid)
            home = row.get("home") or self._me()
            self.table.set_owner(sid, home)
        with self._applied_mu:
            self._applied.pop(sid, None)  # shipped inside ``state``
        return MsgType.OK, {"sid": sid, "state": state}, CODEC_PICKLE

    def handle_generate(self, p: Dict[str, Any]):
        sid = str(p.get("sid") or p.get("set"))
        row = self.table.get(sid)
        if row is None:
            raise SessionUnknown(f"unknown session {sid!r}")
        owner = row["owner"]
        if owner != self._me():
            if self._replica():
                # mirror replay: the leader applied this — apply the
                # same deterministic step so the replica's state stays
                # warm, and converge ownership to self (the owner this
                # daemon must be the moment it is promoted)
                self.table.set_owner(sid, self._me())
            elif self._authoritative(row) \
                    and owner not in self._live_workers():
                # lazy adoption: the recorded owner died — this
                # daemon takes over, reviving from the arena spill
                self.table.set_owner(sid, self._me(), home=self._me())
            else:
                raise SessionMoved(
                    f"session {sid!r} is owned by {owner}",
                    owner_addr=owner)
        db = row["db"]
        # the in-flight frame's idempotency token (contextvar installed
        # by the dispatcher; local import — server imports this module)
        from netsdb_tpu.serve.server import _idem_token_var

        with obs.span("session.coalesce", "serve"):
            out = self.batcher.submit(
                db, sid, {"sid": sid, "x": p["x"],
                          "tok": _idem_token_var.get()})
        return MsgType.OK, {"sid": sid, "y": out["y"],
                            "steps": out["steps"],
                            "owner": self._me()}, CODEC_PICKLE

    def handle_close(self, p: Dict[str, Any]):
        sid = str(p.get("sid") or p.get("set"))
        row = self.table.get(sid)
        if row is None:
            return MsgType.OK, {"sid": sid, "closed": False}
        if row["owner"] != self._me() and not self._replica() \
                and row["owner"] in self._live_workers():
            try:
                self._ctl.shards.peer_request(
                    row["owner"], MsgType.SESSION_CLOSE, {"sid": sid})
            except Exception as e:  # noqa: BLE001 — the owner's
                del e  # TTL sweep collects what this forward missed
        with self._sid_lock(sid):
            dropped = self._cache().session_drop(sid)
            self.arena.drop(sid)
            closed = self.table.close(sid)
        with self._applied_mu:
            self._applied.pop(sid, None)
        # the per-sid lock is deliberately NOT popped: a thread that
        # already fetched the old lock object but not yet acquired it
        # would otherwise share the "exclusive" section with a holder
        # of a fresh object after a same-sid reopen. The map grows by
        # one small object per sid ever opened — the price of the
        # exclusion staying airtight.
        if closed:
            obs.REGISTRY.counter("session.closed").inc()
        return MsgType.OK, {"sid": sid, "closed": closed,
                            "dropped_entries": dropped}

    # --- housekeeping --------------------------------------------------
    def _ensure_housekeeping(self, ttl_s: float) -> None:
        with self._hk_mu:
            if self._hk_thread is not None \
                    and self._hk_thread.is_alive():
                return
            self._hk_stop.clear()
            t = threading.Thread(
                target=self._housekeeping, args=(ttl_s,),
                daemon=True, name="netsdb-session-housekeeping")
            t.start()
            self._hk_thread = t

    def _housekeeping(self, ttl_s: float) -> None:
        interval = max(0.05, min(0.25, float(ttl_s) / 4.0))
        while not self._hk_stop.wait(interval):
            try:
                self._cache().session_sweep()
            except Exception as e:  # noqa: BLE001 — next tick retries
                del e
            self._drain_spill_pushes()

    def _drain_spill_pushes(self) -> None:
        """Ship dirty arena slots of sessions whose home is another
        daemon (a worker's durability push): the home leader merges
        them — and MIRRORS the merge — so a worker death never loses
        more than the not-yet-pushed tail."""
        me = self._me()
        for sid, db in self.arena.take_dirty():
            row = self.table.get(sid)
            home = (row or {}).get("home")
            if not home or home == me:
                continue
            slot = self.arena.snapshot_slot(sid, db)
            if slot is None:
                continue
            applied = self._applied_record(sid)
            if applied is not None:
                slot["applied"] = applied
            try:
                self._ctl.shards.peer_request(
                    home, MsgType.SESSION_OPEN,
                    {"op": "spill", "sid": sid, "db": db,
                     "state": slot},
                    codec=CODEC_PICKLE)
            except Exception as e:  # noqa: BLE001 — re-mark; the
                # next housekeeping tick retries the push
                self._last_spill_fault = repr(e)
                self.arena.mark_dirty(sid, db)
                obs.REGISTRY.counter("session.spill_push_errors").inc()

    def stop(self) -> None:
        self._hk_stop.set()
        t = self._hk_thread
        if t is not None:
            t.join(timeout=2.0)

    # --- introspection -------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        out = {"open": self.table.count(),
               "sessions": [{k: r[k] for k in
                             ("sid", "db", "owner", "steps")}
                            for r in self.table.sessions()],
               "batcher": self.batcher.snapshot(),
               "arena": self.arena.stats(),
               "decode": _decode.decode_stats(),
               "resident_bytes":
                   self._cache().session_resident_bytes()}
        rep = self.runtime.residency_report()
        if rep.get("models"):
            out["residency"] = rep
        return out
