"""True multi-host HA: leader election under monotonic terms.

netsDB's master/worker split has a single point of failure — the
master owns the catalog, and since the scale-out PRs our leader
additionally owns the epoch-versioned placement map and the degraded
-slot handoff buffer. This module is the failover half of closing
that: an ordered **succession list** of daemons (``peers`` — index 0
is the initial leader) where each follower probes every peer AHEAD of
it and promotes itself only after ALL of them have stayed unreachable
for a full election window. Succession order makes the election
deterministic without a quorum protocol: follower *i* can only
promote when followers *0..i-1* are dead too, so two candidates never
promote for the same failure (the double-failover chaos test drives
exactly this ladder).

Terms are the fencing mechanism. Every promotion bumps a monotonic
**term number** (persisted — a restarted daemon cannot come back
believing an old term) and every mirrored frame and handoff drain the
leader emits carries it (``protocol.HA_TERM_KEY``; routed frames
additionally carry their placement epoch, hence the ``(term, epoch)``
pair in the PR story). A deposed leader's straggler write therefore
arrives at the new leader with a stale term and is REJECTED — typed
:class:`~netsdb_tpu.serve.errors.NotLeader` naming both terms, counted
``ha.stragglers_rejected`` — never double-applied; the deposed leader
steps down when it sees the rejection, and the client's retry lands on
the new leader under the same idempotency token.

The controller side of promotion (placement restore + rebind, epoch
push, follower adoption, handoff drain) lives in
``ServeController._promote_self`` — this module only decides WHEN and
keeps the term/role/leader-address record consistent.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional

from netsdb_tpu import obs
from netsdb_tpu.serve.errors import NotLeader
from netsdb_tpu.utils.locks import TrackedLock
from netsdb_tpu.utils.timing import deadline_after, seconds_left

LEADER = "leader"
FOLLOWER = "follower"


class HAState:
    """One daemon's HA record: (term, role, leader address) plus the
    leader's replicated placement map, guarded by a leaf-rank lock.
    The term persists to ``<state_dir>/ha_term.json`` on every change
    so a RESTARTED daemon resumes at (at least) the term it last knew
    — a deposed leader that crashed and came back cannot mint writes
    under its old term."""

    def __init__(self, self_addr: str, peers: List[str],
                 state_dir: Optional[str] = None):
        if self_addr not in peers:
            raise ValueError(
                f"HA succession list {peers!r} does not contain this "
                f"daemon's advertise address {self_addr!r}")
        self._mu = TrackedLock("serve.HAState._mu")
        self.self_addr = self_addr
        self.peers = list(peers)
        self._path = (os.path.join(state_dir, "ha_term.json")
                      if state_dir else None)
        self._term = 1
        self._role = LEADER if peers[0] == self_addr else FOLLOWER
        self._leader_addr: Optional[str] = peers[0]
        #: the leader's replicated placement map (wire form), shipped
        #: on every epoch bump (HA_STATE) — what a freshly promoted
        #: leader restores so routed ingest works immediately
        self._placement_wire: Optional[Dict[str, Any]] = None
        self._load()

    # --- persistence (term only — roles re-derive, maps re-replicate)
    def _load(self) -> None:
        if not self._path or not os.path.exists(self._path):
            return
        try:
            with open(self._path, "r", encoding="utf-8") as f:
                rec = json.load(f)
            self._term = max(self._term, int(rec.get("term", 1)))
        except (OSError, ValueError, TypeError, KeyError):
            return  # unreadable record: keep the derived defaults

    def _save_locked(self) -> None:
        """Caller holds ``_mu``. Best-effort atomic write — a failed
        persist degrades restart fencing, never the live protocol."""
        if not self._path:
            return
        try:
            parent = os.path.dirname(self._path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            tmp = self._path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"term": self._term}, f)
            os.replace(tmp, self._path)
        except OSError:
            return

    # --- reads --------------------------------------------------------
    @property
    def term(self) -> int:
        with self._mu:
            return self._term

    @property
    def role(self) -> str:
        with self._mu:
            return self._role

    @property
    def leader_addr(self) -> Optional[str]:
        with self._mu:
            return self._leader_addr

    def earlier_peers(self) -> List[str]:
        """Peers AHEAD of this daemon in succession order — the set
        that must ALL be dead before this daemon may promote."""
        return self.peers[:self.peers.index(self.self_addr)]

    def later_peers(self) -> List[str]:
        """Peers BEHIND this daemon — the mirror set it adopts as its
        followers when promoted."""
        return self.peers[self.peers.index(self.self_addr) + 1:]

    def snapshot(self) -> Dict[str, Any]:
        """The PING/COLLECT_STATS section."""
        with self._mu:
            return {"term": self._term, "role": self._role,
                    "leader": self._leader_addr}

    def placement_wire(self) -> Optional[Dict[str, Any]]:
        with self._mu:
            return self._placement_wire

    def store_placement(self, wire: Dict[str, Any]) -> None:
        with self._mu:
            self._placement_wire = wire

    # --- term protocol ------------------------------------------------
    def observe_term(self, term: int) -> None:
        """Validate one inbound leader-originated frame's term. A
        HIGHER term is adopted (a new leader exists; this daemon —
        whatever it thought it was — is now that leader's follower); a
        STALE term, or any leader-to-leader write at this daemon's own
        term, is the deposed-straggler rejection: typed retryable
        :class:`NotLeader` naming both terms, never applied."""
        term = int(term)
        with self._mu:
            if term > self._term:
                self._term = term
                self._role = FOLLOWER
                self._leader_addr = None  # learned via HA_STATE/probe
                self._save_locked()
                obs.REGISTRY.counter("ha.terms").inc()
                return
            if term == self._term and self._role != LEADER:
                return  # the current leader's normal mirror stream
            current, leader = self._term, self._leader_addr
        obs.REGISTRY.counter("ha.stragglers_rejected").inc()
        raise NotLeader(
            f"stale-term write rejected: frame carries term {term}, "
            f"this daemon is at term {current} — the sender was "
            f"deposed; its straggler frames are fenced, not applied",
            leader_addr=leader, term=current)

    def check_client_write(self) -> None:
        """Client-originated mutations are leader-only: a follower (or
        deposed leader) answers the typed retryable :class:`NotLeader`
        carrying the leader it knows about, so the client re-points
        instead of split-braining the stores."""
        with self._mu:
            if self._role == LEADER:
                return
            current, leader = self._term, self._leader_addr
        raise NotLeader(
            f"this daemon is a follower at term {current}; mutations "
            f"go to the leader" + (f" at {leader}" if leader else
                                   " (election in progress)"),
            leader_addr=leader, term=current)

    def adopt_leader(self, addr: Optional[str], term: int) -> None:
        """A probe (or HA_STATE frame) found a live peer claiming
        leadership at ``term``: record it. Stale claims — a deposed
        leader still announcing its old term — are rejected typed, the
        same fencing as :meth:`observe_term`."""
        term = int(term)
        with self._mu:
            if term > self._term:
                self._term = term
                self._role = (LEADER if addr == self.self_addr
                              else FOLLOWER)
                self._leader_addr = addr
                self._save_locked()
                obs.REGISTRY.counter("ha.terms").inc()
                return
            if term == self._term:
                if self._role == LEADER and addr != self.self_addr:
                    current, leader = self._term, self._leader_addr
                else:
                    self._leader_addr = addr
                    return
            else:
                current, leader = self._term, self._leader_addr
        obs.REGISTRY.counter("ha.stragglers_rejected").inc()
        raise NotLeader(
            f"stale leadership claim rejected: {addr} announced term "
            f"{term}, this daemon is at term {current}",
            leader_addr=leader, term=current)

    def promote(self) -> int:
        """This daemon becomes leader under a NEW term (monotonic bump,
        persisted before the role flips live). Returns the new term."""
        with self._mu:
            self._term += 1
            self._role = LEADER
            self._leader_addr = self.self_addr
            self._save_locked()
            term = self._term
        obs.REGISTRY.counter("ha.terms").inc()
        obs.REGISTRY.counter("ha.promotions").inc()
        return term

    def step_down(self, term: Optional[int] = None,
                  leader_addr: Optional[str] = None) -> None:
        """A mirror ack (or HA_STATE) proved a newer leader exists —
        this daemon is deposed. Adopts the higher term when given."""
        with self._mu:
            bumped = term is not None and int(term) > self._term
            if bumped:
                self._term = int(term)
            self._role = FOLLOWER
            if leader_addr:
                self._leader_addr = leader_addr
            elif bumped:
                self._leader_addr = None
            self._save_locked()
        if bumped:
            obs.REGISTRY.counter("ha.terms").inc()


class HAMonitor:
    """The follower-side probe thread: every ``probe_interval_s`` it
    walks this daemon's EARLIER succession peers in order over
    dedicated short-timeout connections. The first live one resets the
    election window (and, if it claims leadership, is adopted as the
    leader); a full ``election_timeout_s`` with every earlier peer
    unreachable triggers promotion (``ctl._promote_self``). Leaders
    idle — the loop is a no-op while this daemon holds the role, and
    re-arms if it is ever deposed."""

    def __init__(self, ctl, ha: HAState, election_timeout_s: float,
                 probe_interval_s: Optional[float] = None):
        self.ctl = ctl
        self.ha = ha
        self.election_timeout_s = float(election_timeout_s)
        self.probe_interval_s = (float(probe_interval_s)
                                 if probe_interval_s is not None
                                 else max(self.election_timeout_s / 5.0,
                                          0.02))
        #: most recent promotion failure (observability; the loop
        #: re-arms a full window and tries again)
        self.last_error: Optional[str] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None or not self.ha.earlier_peers():
            return  # the initial leader has nobody to probe
        t = threading.Thread(target=self._loop, daemon=True,
                             name="netsdb-serve-ha-monitor")
        t.start()
        self._thread = t

    def _probe(self, probes: Dict[str, Any], addr: str) \
            -> Optional[Dict[str, Any]]:
        """One liveness probe; returns the PING reply or None (the
        cached connection is dropped so the next round re-dials)."""
        from netsdb_tpu.serve.client import RemoteClient, RetryPolicy

        try:
            probe = probes.get(addr)
            if probe is None:
                probe = RemoteClient(
                    addr, token=self.ctl.token,
                    timeout=self.ctl.heartbeat_timeout_s,
                    retry=RetryPolicy(max_attempts=1))
                probes[addr] = probe
            return probe.ping()
        except Exception as e:  # noqa: BLE001 — dead peer IS the signal
            del e
            probe = probes.pop(addr, None)
            if probe is not None:
                probe.close()
            return None

    def _loop(self) -> None:
        probes: Dict[str, Any] = {}
        deadline = deadline_after(self.election_timeout_s)
        while not self.ctl._stop.wait(self.probe_interval_s):
            if self.ha.role == LEADER:
                deadline = deadline_after(self.election_timeout_s)
                continue
            alive_reply = None
            for addr in self.ha.earlier_peers():
                reply = self._probe(probes, addr)
                if reply is not None:
                    alive_reply = (addr, reply)
                    break  # ANY live earlier peer blocks promotion
            if alive_reply is not None:
                deadline = deadline_after(self.election_timeout_s)
                addr, reply = alive_reply
                info = reply.get("ha") if isinstance(reply, dict) \
                    else None
                if isinstance(info, dict) and info.get("role") == LEADER:
                    try:
                        self.ha.adopt_leader(addr,
                                             int(info.get("term") or 0))
                    except NotLeader as e:
                        # a deposed earlier peer still claiming its old
                        # term: fenced, and it does NOT reset our view
                        self.last_error = str(e)
                continue
            if seconds_left(deadline) > 0:
                continue
            # every earlier candidate stayed dead for a full window
            try:
                self.ctl._promote_self()
            except Exception as e:  # noqa: BLE001 — re-armed, retried
                self.last_error = f"{type(e).__name__}: {e}"
            deadline = deadline_after(self.election_timeout_s)
        for probe in probes.values():
            probe.close()
