"""Thin RPC client — the PDBClient facade over the wire.

Mirrors :class:`netsdb_tpu.client.Client` method-for-method but sends
typed frames to a resident :class:`~netsdb_tpu.serve.server.ServeController`
instead of owning a store, the way ``PDBClient`` aggregates catalog/
dispatcher/storage/query clients all speaking ``simpleRequest`` RPCs to
the master (``src/mainClient/headers/PDBClient.h:28-295``).

Deliberately JAX-free: a client process never initializes a device
backend (the daemon owns the TPU). Tensors come back as numpy-backed
:class:`RemoteTensor` values whose ``to_dense()`` matches
``BlockedTensor.to_dense()``, so model drivers (``FFModel`` etc.) run
unchanged against either client.
"""

from __future__ import annotations

import socket
import threading
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from netsdb_tpu.serve.protocol import (
    CODEC_MSGPACK,
    CODEC_PICKLE,
    MsgType,
    ProtocolError,
    recv_frame,
    send_frame,
    tensor_to_wire,
)


class RemoteError(RuntimeError):
    """A server-side handler raised; carries the remote traceback."""

    def __init__(self, kind: str, message: str, remote_traceback: str = ""):
        super().__init__(f"{kind}: {message}")
        self.kind = kind
        self.remote_traceback = remote_traceback


class RemoteTableInfo:
    """Summary of a daemon-side table ingest (``send_table`` reply)."""

    def __init__(self, num_rows: int, columns: list):
        self.num_rows = num_rows
        self.columns = columns

    def __repr__(self):
        return f"RemoteTableInfo(rows={self.num_rows}, cols={self.columns})"


class RemoteTensor:
    """Dense result fetched from the daemon — quacks like BlockedTensor
    for the read side (``to_dense``/``shape``/``dtype``)."""

    def __init__(self, dense: np.ndarray, block_shape=None):
        self._dense = dense
        self.block_shape = tuple(block_shape) if block_shape else None

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self._dense.shape)

    @property
    def dtype(self):
        return self._dense.dtype

    def to_dense(self) -> np.ndarray:
        return self._dense

    def __repr__(self) -> str:
        return f"RemoteTensor(shape={self.shape}, dtype={self.dtype})"


class RemoteIdent(Tuple[str, str]):
    """(db, set) result key, printable like SetIdentifier."""

    def __new__(cls, db: str, set_: str):
        return super().__new__(cls, (db, set_))

    @property
    def db(self) -> str:
        return self[0]

    @property
    def set(self) -> str:
        return self[1]

    def __str__(self) -> str:
        return f"{self[0]}:{self[1]}"


class RemoteClient:
    """``Client(address="host:port")`` returns one of these."""

    def __init__(self, address: str, token: Optional[str] = None,
                 timeout: Optional[float] = None):
        host, _, port = address.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self.token = token
        self._lock = threading.Lock()  # one in-flight request per conn
        self._sock: Optional[socket.socket] = None
        self._timeout = timeout
        # thread id that currently drives a streaming reply (scan_stream
        # / chunked pulls) — a nested request from that thread must NOT
        # wait on the lock (self-deadlock) nor write to the streaming
        # socket (frame corruption); it gets a one-shot side connection
        self._stream_owner: Optional[int] = None
        self._connect()

    # --- transport ----------------------------------------------------
    def _dial(self) -> socket.socket:
        """Open + handshake one connection (the single copy of the
        dial sequence — main connection, one-shot side requests and
        nested streams all come through here)."""
        s = socket.create_connection((self.host, self.port),
                                     timeout=self._timeout)
        try:
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            send_frame(s, MsgType.HELLO, {"token": self.token})
            typ, reply = recv_frame(s, allow_pickle=False)
            if typ == MsgType.ERR:
                raise RemoteError(reply.get("error", "Error"),
                                  reply.get("message", "handshake refused"))
        except BaseException:
            s.close()
            raise
        return s

    def _connect(self) -> None:
        self._sock = self._dial()

    def _oneshot_request(self, msg_type: MsgType, payload: Any,
                         codec: int) -> Any:
        """Issue one request over a throwaway connection — used when the
        caller's thread is mid-stream on the main connection (e.g.
        ``for item in c.scan_stream(...): c.send_data(...)``), which
        must neither block on the held lock nor interleave frames."""
        s = self._dial()
        try:
            send_frame(s, msg_type, payload, codec)
            typ, reply = recv_frame(s, allow_pickle=True)
        finally:
            s.close()
        if typ == MsgType.ERR:
            raise RemoteError(reply.get("error", "Error"),
                              reply.get("message", ""),
                              reply.get("traceback", ""))
        return reply

    def _request(self, msg_type: MsgType, payload: Any,
                 codec: int = CODEC_MSGPACK) -> Any:
        if self._stream_owner == threading.get_ident():
            return self._oneshot_request(msg_type, payload, codec)
        with self._lock:
            if self._sock is None:
                self._connect()
            try:
                send_frame(self._sock, msg_type, payload, codec)
                # replies may carry host objects (SCAN_SET) → pickle
                # allowed on this side: the client already trusts the
                # server it chose to connect to
                typ, reply = recv_frame(self._sock, allow_pickle=True)
            except Exception:
                # a mid-request failure (timeout, reset) leaves the
                # stream desynced — a later request would read THIS
                # request's late reply as its own. Drop the connection;
                # the next request reconnects fresh.
                try:
                    self._sock.close()
                finally:
                    self._sock = None
                raise
        if typ == MsgType.ERR:
            raise RemoteError(reply.get("error", "Error"),
                              reply.get("message", ""),
                              reply.get("traceback", ""))
        return reply

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # --- session ------------------------------------------------------
    def ping(self) -> Dict[str, Any]:
        return self._request(MsgType.PING, {})

    def shutdown_server(self) -> None:
        with self._lock:
            if self._sock is None:
                self._connect()
            try:
                send_frame(self._sock, MsgType.SHUTDOWN, {})
                recv_frame(self._sock, allow_pickle=False)
            finally:
                self._sock.close()
                self._sock = None

    # --- DDL (same facade as Client) ----------------------------------
    def create_database(self, db: str) -> None:
        self._request(MsgType.CREATE_DATABASE, {"db": db})

    def create_set(self, db: str, set_name: str, type_name: str = "tensor",
                   persistence: str = "transient", eviction: str = "lru",
                   partition_lambda: Optional[str] = None,
                   placement=None, storage: str = "memory"):
        """``placement`` may be a Placement (serialized via ``to_meta``)
        or its meta dict; the daemon applies it to all ingest into the
        set (distribution declared at createSet, as in the reference's
        PartitionPolicy). ``storage="paged"`` backs the set with the
        daemon's page arena (out-of-core as a set property)."""
        if placement is not None and hasattr(placement, "to_meta"):
            placement = placement.to_meta()
        self._request(MsgType.CREATE_SET, {
            "db": db, "set": set_name, "type_name": type_name,
            "persistence": persistence, "eviction": eviction,
            "partition_lambda": partition_lambda,
            "placement": placement, "storage": storage})
        return RemoteIdent(db, set_name)

    def remove_set(self, db: str, set_name: str) -> None:
        self._request(MsgType.REMOVE_SET, {"db": db, "set": set_name})

    def clear_set(self, db: str, set_name: str) -> None:
        self._request(MsgType.CLEAR_SET, {"db": db, "set": set_name})

    def set_exists(self, db: str, set_name: str) -> bool:
        return self._request(MsgType.SET_EXISTS,
                             {"db": db, "set": set_name})["exists"]

    def list_sets(self) -> List[Tuple[str, str]]:
        return [tuple(s) for s in
                self._request(MsgType.LIST_SETS, {})["sets"]]

    def register_type(self, type_name: str, entry_point: str,
                      source: Optional[str] = None,
                      ship_module: bool = False) -> None:
        """``source``/``ship_module`` ship the UDF module's code to the
        daemon (the reference's .so replication on registerType) so
        EXECUTE_PLAN can bind types the server never installed. Shipped
        source is code the daemon executes — same trust boundary as the
        pickle codec (serve/protocol.py security note)."""
        if ship_module and source is None:
            from netsdb_tpu.catalog.catalog import read_module_source

            source = read_module_source(entry_point)
        self._request(MsgType.REGISTER_TYPE,
                      {"type_name": type_name, "entry_point": entry_point,
                       "source": source})

    # --- data path ----------------------------------------------------
    def send_data(self, db: str, set_name: str, items: Sequence[Any]) -> None:
        self._request(MsgType.SEND_DATA,
                      {"db": db, "set": set_name, "items": list(items)},
                      codec=CODEC_PICKLE)

    def send_table(self, db: str, set_name: str, rows_or_table,
                   date_cols: Sequence[str] = (),
                   append: bool = False) -> "RemoteTableInfo":
        """Ship rows (or a pre-built ColumnTable) for daemon-side
        columnar ingest — dictionary encoding + the set's placement
        happen server-side, where the devices are. Returns a
        :class:`RemoteTableInfo` quacking like the ingested table's
        summary (``num_rows``/``columns``), mirroring the in-process
        facade without pulling the whole table back."""
        from netsdb_tpu.relational.table import ColumnTable

        items = (rows_or_table if isinstance(rows_or_table, ColumnTable)
                 else list(rows_or_table))
        reply = self._request(
            MsgType.SEND_DATA,
            {"db": db, "set": set_name, "items": items,
             "as_table": True, "date_cols": list(date_cols),
             "append": append},
            codec=CODEC_PICKLE)
        return RemoteTableInfo(reply["count"], list(reply["columns"]))

    def analyze_set(self, db: str, set_name: str) -> Dict[str, Any]:
        """Planner statistics computed DAEMON-side; only the summaries
        cross the wire (ref StorageCollectStats,
        ``PangeaStorageServer.h:48``). This is what lets
        ``relational.dag.suite_sink_for`` build all ten suite sinks
        over a daemon without pulling a single table."""
        from netsdb_tpu.relational.stats import ColumnStats

        reply = self._request(MsgType.ANALYZE_SET,
                              {"db": db, "set": set_name})
        return {"num_rows": reply["num_rows"],
                "dicts": {k: list(v) for k, v in reply["dicts"].items()},
                "stats": {k: ColumnStats(*v)
                          for k, v in reply["stats"].items()}}

    def get_table(self, db: str, set_name: str):
        """Fetch a table set as a host-side ColumnTable (pickled via its
        numpy ``__getstate__``)."""
        items = list(self.get_set_iterator(db, set_name))
        from netsdb_tpu.relational.table import ColumnTable

        tables = [i for i in items if isinstance(i, ColumnTable)]
        if len(tables) != 1:
            raise ValueError(
                f"set {db}:{set_name} holds {len(tables)} tables; expected 1")
        return tables[0]

    def send_matrix(self, db: str, set_name: str, dense, block_shape=None,
                    dtype=None) -> RemoteTensor:
        dense = np.asarray(dense, dtype=dtype)
        reply = self._request(MsgType.SEND_MATRIX, {
            "db": db, "set": set_name,
            "tensor": tensor_to_wire(dense, block_shape)})
        return RemoteTensor(dense, reply.get("block_shape"))

    def get_tensor(self, db: str, set_name: str) -> RemoteTensor:
        reply = self._request(MsgType.GET_TENSOR, {"db": db, "set": set_name})
        return RemoteTensor(reply["data"], reply.get("block_shape"))

    def paged_matmul(self, db: str, set_name: str, rhs) -> np.ndarray:
        """``stored @ rhs`` computed daemon-side with the stored matrix
        streamed from the arena (paged TENSOR sets never materialize;
        their GET_TENSOR raises by design)."""
        reply = self._request(MsgType.PAGED_MATMUL,
                              {"db": db, "set": set_name,
                               "rhs": np.asarray(rhs)})
        return np.asarray(reply["data"])

    def get_tensor_chunked(self, db: str, set_name: str,
                           chunk_bytes: int = 8 << 20) -> RemoteTensor:
        """Pull a tensor as a chunked stream: client holds the result
        array plus ONE chunk (vs. array + full frame for GET_TENSOR) —
        the page-streamed model transfer path for big weight sets."""
        meta = None
        buf = None
        off = 0
        for frame in self._stream(MsgType.GET_TENSOR_CHUNKED,
                                  {"db": db, "set": set_name,
                                   "chunk_bytes": int(chunk_bytes)}):
            if meta is None:
                meta = frame["meta"]
                buf = bytearray(meta["nbytes"])
            else:
                b = frame["b"]
                buf[off:off + len(b)] = b
                off += len(b)
        if meta is None:
            raise ProtocolError("empty chunked-tensor stream")
        dense = np.frombuffer(bytes(buf), dtype=np.dtype(meta["dtype"])
                              ).reshape(meta["shape"])
        return RemoteTensor(dense, meta.get("block_shape"))

    def get_set_iterator(self, db: str, set_name: str) -> Iterator[Any]:
        reply = self._request(MsgType.SCAN_SET, {"db": db, "set": set_name})
        return iter(reply["items"])

    def scan_stream(self, db: str, set_name: str,
                    max_frame_bytes: int = 4 << 20) -> Iterator[Any]:
        """Stream a set's items with bounded buffering on both ends:
        the server packs ≤ ``max_frame_bytes`` of pickled items per
        frame; this generator holds one frame at a time. The streamed
        ``getSetIterator`` (ref FrontendQueryTestServer.cc:785-890).

        The connection is held for the duration of the iteration (one
        in-flight request per connection, as in the reference's
        PDBCommunicator); abandoning the iterator early closes the
        socket so the next request reconnects cleanly."""
        import pickle

        for frame in self._stream(MsgType.SCAN_SET_STREAM,
                                  {"db": db, "set": set_name,
                                   "max_frame_bytes": int(max_frame_bytes)}):
            yield from pickle.loads(frame["batch"])

    def get_table_streamed(self, db: str, set_name: str,
                           max_frame_bytes: int = 4 << 20):
        """Assemble a table set from the STREAMED scan: for paged sets
        the daemon ships one host-side chunk table per frame straight
        off its arena stream (it never materializes the relation,
        device- or wire-side); this client holds the growing columns
        plus ONE chunk. The page-streamed remote read for exactly the
        sets ``get_table``'s single-frame reply is too big for."""
        from netsdb_tpu.relational.table import ColumnTable

        parts: dict = {}
        dicts: dict = {}
        got = False
        for item in self.scan_stream(db, set_name, max_frame_bytes):
            if not isinstance(item, ColumnTable):
                raise TypeError(
                    f"set {db}:{set_name} holds "
                    f"{type(item).__name__} items, not tables")
            got = True
            dicts.update(item.dicts)
            cols = item.compact().cols if item.valid is not None \
                else item.cols
            for k, v in cols.items():
                parts.setdefault(k, []).append(np.asarray(v))
        if not got:
            raise ValueError(f"set {db}:{set_name} is empty")
        return ColumnTable({k: np.concatenate(v)
                            for k, v in parts.items()}, dicts, None)

    @staticmethod
    def _stream_frames(sock: socket.socket, msg_type: MsgType,
                       payload: Any) -> Iterator[Any]:
        """Frame loop of one streaming request over ``sock``: yield each
        STREAM_ITEM payload until STREAM_END; ERR raises (the stream
        ends, the connection stays frame-synchronized)."""
        send_frame(sock, msg_type, payload)
        while True:
            typ, reply = recv_frame(sock, allow_pickle=True)
            if typ == MsgType.STREAM_END:
                return
            if typ == MsgType.ERR:
                raise RemoteError(reply.get("error", "Error"),
                                  reply.get("message", ""),
                                  reply.get("traceback", ""))
            yield reply

    def _stream(self, msg_type: MsgType, payload: Any) -> Iterator[Any]:
        """Issue a streaming request; yield each STREAM_ITEM payload
        until STREAM_END. ERR aborts with RemoteError. If the consumer
        abandons the generator mid-stream, the socket is dropped (a
        half-read stream cannot be resynchronized). A stream opened
        from a thread ALREADY mid-stream (nested iteration) runs over
        its own dedicated connection — like nested plain requests
        (`_oneshot_request`), it must neither wait on the held lock nor
        interleave frames on the streaming socket."""
        if self._stream_owner == threading.get_ident():
            s = self._dial()
            try:
                yield from self._stream_frames(s, msg_type, payload)
            finally:
                s.close()
            return
        self._lock.acquire()
        self._stream_owner = threading.get_ident()
        done = False
        try:
            if self._sock is None:
                self._connect()
            yield from self._stream_frames(self._sock, msg_type, payload)
            done = True
        except RemoteError:
            done = True  # ERR terminates the stream; conn is sync'd
            raise
        except (ConnectionError, OSError):
            done = False
            raise
        finally:
            self._stream_owner = None
            if not done and self._sock is not None:
                try:
                    self._sock.close()
                finally:
                    self._sock = None
            self._lock.release()

    def dedup_resident(self, sets: Sequence[Tuple[str, str]],
                       bands: int = 16, seed: int = 0) -> Dict[str, Any]:
        """Daemon-side block-level model dedup: shared blocks across the
        given weight sets materialize once in HBM (see
        ``Client.dedup_resident``). Returns the pooling report."""
        return self._request(MsgType.DEDUP_RESIDENT,
                             {"sets": [list(s) for s in sets],
                              "bands": bands, "seed": seed})

    def add_shared_mapping(self, private_db: str, private_set: str,
                           shared_db: str, shared_set: str,
                           mapping: Optional[Dict] = None) -> None:
        self._request(MsgType.ADD_SHARED_MAPPING, {
            "private_db": private_db, "private_set": private_set,
            "shared_db": shared_db, "shared_set": shared_set,
            "mapping": mapping})

    def flush_data(self) -> None:
        self._request(MsgType.FLUSH_DATA, {})

    def load_set(self, db: str, set_name: str) -> None:
        self._request(MsgType.LOAD_SET, {"db": db, "set": set_name})

    # --- query execution ----------------------------------------------
    def execute_computations(self, *sinks, job_name: str = "remote-job",
                             materialize: bool = True,
                             fetch_results: bool = True):
        """Ship the Computation DAG (cloudpickle — the analogue of
        shipping serialized Computations + registered UDF code) and run
        it on the daemon. Returns {ident: value} like the library
        client; ``fetch_results=False`` skips pulling result payloads
        (they stay resident server-side, the common serving pattern)."""
        reply = self._request(
            MsgType.EXECUTE_COMPUTATIONS,
            {"sinks": list(sinks), "job_name": job_name,
             "materialize": materialize},
            codec=CODEC_PICKLE)
        return self._collect_results(reply["results"], fetch_results)

    def execute_plan(self, plan_text: str, registry: Dict[str, Any],
                     job_name: str = "remote-plan", materialize: bool = True,
                     fetch_results: bool = True):
        """Pickle-free execution: ship plan text + label→entry-point
        registry; the daemon rebinds labels to registered types
        (``ParsedPlan.to_computations``). The TCAP path."""
        reply = self._request(
            MsgType.EXECUTE_PLAN,
            {"plan": plan_text, "registry": registry, "job_name": job_name,
             "materialize": materialize})
        return self._collect_results(reply["results"], fetch_results)

    def _collect_results(self, summaries: Dict[str, Any],
                         fetch: bool) -> Dict[RemoteIdent, Any]:
        out: Dict[RemoteIdent, Any] = {}
        for key, summary in summaries.items():
            db, _, set_name = key.partition(":")
            ident = RemoteIdent(db, set_name)
            if not fetch:
                out[ident] = summary
            elif summary.get("kind") == "tensor":
                out[ident] = self.get_tensor(db, set_name)
            else:
                items = list(self.get_set_iterator(db, set_name))
                out[ident] = dict(items) if summary.get("kind") == "map" \
                    else items
        return out

    def list_jobs(self) -> List[Dict[str, Any]]:
        return self._request(MsgType.LIST_JOBS, {})["jobs"]

    # --- stats --------------------------------------------------------
    def collect_stats(self) -> Dict[str, Any]:
        return self._request(MsgType.COLLECT_STATS, {})
