"""Thin RPC client — the PDBClient facade over the wire.

Mirrors :class:`netsdb_tpu.client.Client` method-for-method but sends
typed frames to a resident :class:`~netsdb_tpu.serve.server.ServeController`
instead of owning a store, the way ``PDBClient`` aggregates catalog/
dispatcher/storage/query clients all speaking ``simpleRequest`` RPCs to
the master (``src/mainClient/headers/PDBClient.h:28-295``).

Deliberately JAX-free: a client process never initializes a device
backend (the daemon owns the TPU). Tensors come back as numpy-backed
:class:`RemoteTensor` values whose ``to_dense()`` matches
``BlockedTensor.to_dense()``, so model drivers (``FFModel`` etc.) run
unchanged against either client.
"""

from __future__ import annotations

import contextlib
import dataclasses
import queue as _queue
import random
import socket
import threading
import time
import uuid
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from netsdb_tpu import obs
from netsdb_tpu.serve.errors import (  # noqa: F401 — re-exported API
    AdmissionFullError,
    AuthError,
    CoalesceAbortedError,
    ConnectionLostError,
    CorruptFrameError,
    DeadlineExceededError,
    FollowerDegradedError,
    LaneSaturatedError,
    NotLeaderError,
    PlacementStaleError,
    ProtocolVersionError,
    RemoteError,
    RemoteTimeoutError,
    RetryableRemoteError,
    SessionMovedError,
    SessionUnknownError,
    ShardUnavailableError,
    classify_remote,
)
from netsdb_tpu.serve.protocol import (
    CLIENT_ID_KEY,
    CODEC_MSGPACK,
    CODEC_PICKLE,
    IDEMPOTENCY_KEY,
    LANE_KEY,
    MUTATING_TYPES,
    PLACEMENT_EPOCH_KEY,
    PROTO_VERSION,
    QUERY_ID_KEY,
    SESSION_KEY,
    SHARD_SLOT_KEY,
    MsgType,
    ProtocolError,
    recv_frame,
    send_frame,
    tensor_to_wire,
)
from netsdb_tpu.utils.locks import TrackedLock
from netsdb_tpu.utils.timing import deadline_after, seconds_left

#: frame types that open a client-side query trace (and mint the query
#: id the daemon's trace joins on) — the query-shaped requests whose
#: time decomposition GET_TRACE answers; decode steps trace too, so a
#: slow GENERATE decomposes into coalesce-wait / state-load / device
TRACED_TYPES = frozenset({MsgType.EXECUTE_COMPUTATIONS,
                          MsgType.EXECUTE_PLAN,
                          MsgType.GENERATE})


@dataclasses.dataclass
class RetryPolicy:
    """Exponential backoff with jitter for retryable failures.

    ``deadline_s`` bounds one LOGICAL request across all its attempts
    (a per-request deadline, measured on the monotonic clock); when the
    next backoff would cross it, :class:`DeadlineExceededError` is
    raised instead of sleeping. ``max_attempts=1`` disables retries
    (the follower mirror links use this: a mirror failure must surface
    immediately so the leader can evict + resync, not be papered over)."""

    max_attempts: int = 4
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.5
    deadline_s: Optional[float] = None

    def backoff_s(self, attempt: int, rng: random.Random) -> float:
        d = min(self.base_delay_s * self.multiplier ** (attempt - 1),
                self.max_delay_s)
        return d * (1.0 - self.jitter * rng.random())


class RemoteTableInfo:
    """Summary of a daemon-side table ingest (``send_table`` reply)."""

    def __init__(self, num_rows: int, columns: list):
        self.num_rows = num_rows
        self.columns = columns

    def __repr__(self):
        return f"RemoteTableInfo(rows={self.num_rows}, cols={self.columns})"


class RemoteTensor:
    """Dense result fetched from the daemon — quacks like BlockedTensor
    for the read side (``to_dense``/``shape``/``dtype``)."""

    def __init__(self, dense: np.ndarray, block_shape=None):
        self._dense = dense
        self.block_shape = tuple(block_shape) if block_shape else None

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self._dense.shape)

    @property
    def dtype(self):
        return self._dense.dtype

    def to_dense(self) -> np.ndarray:
        return self._dense

    def __repr__(self) -> str:
        return f"RemoteTensor(shape={self.shape}, dtype={self.dtype})"


class RemoteIdent(Tuple[str, str]):
    """(db, set) result key, printable like SetIdentifier."""

    def __new__(cls, db: str, set_: str):
        return super().__new__(cls, (db, set_))

    @property
    def db(self) -> str:
        return self[0]

    @property
    def set(self) -> str:
        return self[1]

    def __str__(self) -> str:
        return f"{self[0]}:{self[1]}"


class RemoteClient:
    """``Client(address="host:port")`` returns one of these."""

    def __init__(self, address: str, token: Optional[str] = None,
                 timeout: Optional[float] = None,
                 retry: Optional[RetryPolicy] = None,
                 chaos=None, seed: Optional[int] = None,
                 connect_timeout: Optional[float] = None,
                 replicas: Optional[Sequence[str]] = None,
                 hedge_delay_s: Optional[float] = None,
                 ingest_window: int = 4,
                 ingest_chunk_bytes: int = 8 << 20,
                 client_id: Optional[str] = None,
                 lane: Optional[str] = None,
                 trace_sample: Optional[int] = None,
                 ship_traces: bool = True,
                 failover: Optional[Sequence[str]] = None):
        """``timeout``: socket-level timeout applied to every blocking
        recv after the handshake (None = block; a hung server then
        surfaces as :class:`RemoteTimeoutError` instead of a wedged
        caller). ``connect_timeout`` bounds the dial + handshake
        separately — a caller that must tolerate slow REPLIES (long
        jobs) can still refuse to hang on a peer that accepts the TCP
        connection and then goes silent (defaults to ``timeout``).
        ``retry``: :class:`RetryPolicy` for retryable failures; the
        default retries 4 attempts with jittered exponential backoff.
        ``chaos``: a :class:`~netsdb_tpu.serve.chaos.ChaosInjector`
        faulting this client's request/reply frames (tests only).
        ``seed`` seeds the backoff jitter for reproducible schedules.

        ``replicas``: addresses of other daemons holding the same data
        (mirrored followers). When set, idempotent READS hedge: if the
        primary's reply hasn't landed after the observed-p99 latency
        (or ``hedge_delay_s`` when given), the same request is issued
        to a replica over a one-shot connection and the first success
        wins — tail latency becomes the replicas' min, not the
        primary's max. Mutations never hedge (ordering runs through the
        leader).

        ``ingest_window``/``ingest_chunk_bytes``: the bulk-ingest
        pipeline knobs — ``send_data``/``send_table`` stream large
        payloads as ~``ingest_chunk_bytes`` chunks with up to
        ``ingest_window`` chunks in flight before waiting on acks
        (depth-W pipelining, not stop-and-wait).

        ``client_id``: the identity (tenant/service string) attached to
        every frame (``protocol.CLIENT_ID_KEY``); the daemon aggregates
        staged bytes, device-cache traffic and executor chunk counts
        per (client, db:set) — visible in COLLECT_STATS'
        ``attribution`` section. None = unattributed ("anon" daemon
        bucket).

        ``lane``: optional scheduler lane hint
        (``protocol.LANE_KEY``) attached to every frame — the daemon
        admits this client's jobs through that priority lane of its
        query scheduler (``serve/sched/``). Absent, jobs ride the
        client-identity lane. Lane *weights* are server configuration
        (``config.sched_lanes``) — naming a lane grants no priority
        the operator didn't configure.

        ``trace_sample``: mint a query id (and therefore pay
        end-to-end tracing) for 1 in N query-shaped requests —
        ``obs.sample_qid``. None takes ``DEFAULT_CONFIG.
        obs_trace_sample``; 1 traces everything. ``ship_traces``: after
        a traced request completes, ship the client's span profile to
        the daemon (PUT_TRACE, on a background shipper thread over its
        own connection — never the request critical path) so GET_TRACE
        returns one merged client→leader→follower decomposition;
        best-effort — a lost ship costs the client section, never the
        request. :meth:`flush_traces` drains the queue.

        ``failover``: candidate leader addresses (the HA succession
        list). Two rediscovery paths use it: a typed ``NotLeader``
        refusal that NAMES the current leader re-points there
        immediately; a connection loss (or a NotLeader with no known
        leader — mid-election) rotates through the candidates across
        the normal retry/backoff schedule, which doubles as the
        bounded election-window wait. Empty = PR 9 behavior (retries
        stay pinned to one address)."""
        host, _, port = address.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self.token = token
        # one in-flight request per conn; tracked rank (the witness
        # coverage the PR 8 carry-over asked for)
        self._lock = TrackedLock("RemoteClient._lock")
        self._sock: Optional[socket.socket] = None
        self._timeout = timeout
        self._connect_timeout = (connect_timeout if connect_timeout
                                 is not None else timeout)
        self._retry = retry or RetryPolicy()
        self._chaos = chaos
        self._rng = random.Random(seed)
        #: attempts consumed by the most recent logical request (1 = no
        #: retry) and total retries over this client's lifetime —
        #: observability for tests and callers tuning policies
        self.last_attempts = 0
        self.total_retries = 0
        # hedged-read state: replica ring + observed read latencies.
        # The adaptive p99 hedge trigger and the metrics registry read
        # the SAME numbers: latencies land in this client's bounded
        # histogram (obs.Histogram — what hedge_delay_s quantiles over)
        # and every observation is mirrored into the shared registry
        # histogram "serve.client.read_latency_s" that COLLECT_STATS
        # ships, so introspection and stats can never disagree.
        self._replicas = list(replicas or [])
        self._hedge_delay_s = hedge_delay_s
        self._read_hist = obs.Histogram(max_samples=256)
        self._hedge_rr = 0
        self.hedges_issued = 0
        self.hedges_won = 0
        self.ingest_window = max(1, int(ingest_window))
        self.ingest_chunk_bytes = max(64 << 10, int(ingest_chunk_bytes))
        self.client_id = client_id
        self.lane = lane
        if trace_sample is None:
            from netsdb_tpu.config import DEFAULT_CONFIG

            trace_sample = getattr(DEFAULT_CONFIG, "obs_trace_sample", 1)
        self._trace_sample = max(1, int(trace_sample))
        # own sampler phase: the process-default sampler would
        # phase-lock under interleaved clients (obs.QidSampler
        # docstring) — per-client state keeps trace_sample=N meaning
        # exactly 1-in-N of THIS client's requests
        self._qid_sampler = obs.QidSampler()
        self.ship_traces = bool(ship_traces)
        # background PUT_TRACE shipper (lazy): completed client traces
        # queue here and ship over a dedicated connection OFF the
        # request critical path
        self._ship_mu = TrackedLock("RemoteClient._ship_mu")
        self._ship_q: Optional["_queue.Queue"] = None
        self._ship_thread: Optional[threading.Thread] = None
        # thread id that currently drives a streaming reply (scan_stream
        # / chunked pulls) — a nested request from that thread must NOT
        # wait on the lock (self-deadlock) nor write to the streaming
        # socket (frame corruption); it gets a one-shot side connection
        self._stream_owner: Optional[int] = None
        # placement-aware routing state: the daemon's sharded-set map
        # (shipped in the handshake ONLY when sharded sets exist —
        # un-sharded clients never pay a frame), per-shard connection
        # cache, and the stale-map refresh guard. A PlacementStale
        # rejection refreshes the cache between retry attempts.
        self._placement_mu = TrackedLock("RemoteClient._placement_mu")
        self._placement_wire: Optional[Dict[str, Any]] = None
        self._shard_clients: Dict[str, "RemoteClient"] = {}
        # serializes the PLACEMENT fetch: concurrent refreshers wait
        # for the in-flight result; owner thread id breaks re-entry
        self._placement_fetch_mu = TrackedLock(
            "RemoteClient._placement_fetch_mu")
        self._refreshing_placement: Optional[int] = None
        # HA failover: candidate leaders + rotation cursor (guarded by
        # _lock with the rest of the connection state)
        self._failover = [a for a in (failover or [])]
        self._failover_idx = 0
        #: times this client re-pointed at a different daemon
        #: (observability for the failover tests)
        self.failovers = 0
        self._connect()

    # --- transport ----------------------------------------------------
    def _dial(self, budget_s: Optional[float] = None,
              address: Optional[str] = None) -> socket.socket:
        """Open + handshake one connection (the single copy of the
        dial sequence — main connection, one-shot side requests,
        nested streams and replica hedges all come through here).
        ``budget_s`` caps the connect + handshake below the configured
        connect timeout — the per-request deadline must bound a hung
        DIAL too (a blackholed host, or a peer that accepts TCP and
        never answers HELLO), not just a hung reply. The HELLO carries
        :data:`~netsdb_tpu.serve.protocol.PROTO_VERSION`; a
        wire-format mismatch in either direction is the typed fatal
        :class:`ProtocolVersionError` — mixed-version peers never get
        past the handshake."""
        host, port = self.host, self.port
        if address is not None:
            h, _, p = address.rpartition(":")
            host, port = (h or "127.0.0.1"), int(p)
        ct = self._connect_timeout
        if budget_s is not None:
            ct = budget_s if ct is None else min(ct, budget_s)
        s = socket.create_connection((host, port), timeout=ct)
        try:
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            send_frame(s, MsgType.HELLO, {"token": self.token,
                                          "proto": PROTO_VERSION})
            typ, reply = recv_frame(s, allow_pickle=False)
            if typ == MsgType.ERR:
                # handshake refusals are fatal by construction
                # (auth / wire-format mismatch)
                raise classify_remote(reply)
            if reply.get("version") != PROTO_VERSION:
                raise ProtocolVersionError(
                    "ProtocolVersionError",
                    f"daemon at {host}:{port} speaks wire format "
                    f"v{reply.get('version')}; this client is "
                    f"v{PROTO_VERSION} — mixed versions are refused")
            if isinstance(reply.get("placement"), dict):
                # v3 handshake placement shipping: cache the sharded-
                # set map so ingest routes to owning shards without an
                # extra fetch
                with self._placement_mu:
                    self._placement_wire = reply["placement"]
            s.settimeout(self._timeout)  # steady-state I/O bound
        except BaseException:
            s.close()
            raise
        return s

    def _connect(self, budget_s: Optional[float] = None) -> None:
        self._sock = self._dial(budget_s)

    def _oneshot_request(self, msg_type: MsgType, payload: Any,
                         codec: int,
                         io_timeout: Optional[float] = None,
                         address: Optional[str] = None) -> Any:
        """Issue one request over a throwaway connection — used when the
        caller's thread is mid-stream on the main connection (e.g.
        ``for item in c.scan_stream(...): c.send_data(...)``), which
        must neither block on the held lock nor interleave frames, and
        by hedged reads dialing a replica (``address``)."""
        s = self._dial(io_timeout, address=address)
        try:
            if io_timeout is not None:
                s.settimeout(io_timeout)
            send_frame(s, msg_type, payload, codec, chaos=self._chaos)
            typ, reply = self._recv_reply(s)
        finally:
            s.close()
        if typ == MsgType.ERR:
            raise classify_remote(reply)
        return reply

    @staticmethod
    def _recv_reply(sock) -> Tuple[Any, Any]:
        """Reply recv with decode failures typed: a body that fails to
        decode (bit flips on the wire) is the retryable CorruptFrame
        family, not an anonymous pickle/msgpack exception. Replies may
        carry host objects (SCAN_SET) → pickle allowed on this side:
        the client already trusts the server it chose to connect to."""
        try:
            return recv_frame(sock, allow_pickle=True)
        except (ConnectionError, OSError):
            raise
        except Exception as e:
            raise CorruptFrameError(
                type(e).__name__, f"reply body failed to decode: {e}") from e

    def _request_once(self, msg_type: MsgType, payload: Any, codec: int,
                      io_timeout: Optional[float] = None) -> Any:
        """One attempt on the persistent connection. Any mid-request
        failure leaves the frame stream desynced — a later request
        would read THIS request's late reply as its own — so the socket
        is closed and the next attempt re-dials lazily. ``io_timeout``
        tightens this attempt's socket timeout (the per-request
        deadline must bound a HUNG attempt, not just the gaps between
        attempts); the steady-state timeout is restored on success."""
        with self._lock:
            if self._sock is None:
                self._connect(io_timeout)
            try:
                if io_timeout is not None:
                    self._sock.settimeout(io_timeout)
                with obs.span("client.send", "client"):
                    send_frame(self._sock, msg_type, payload, codec,
                               chaos=self._chaos)
                with obs.span("client.wait", "client"):
                    # lint: disable=lock-blocking-call -- the conn lock exists to serialize one in-flight request per connection; holding it across the reply IS the protocol, and the wait is bounded by the socket timeout set at dial
                    typ, reply = self._recv_reply(self._sock)
                if io_timeout is not None:
                    self._sock.settimeout(self._timeout)
            except Exception:
                self._drop_connection()
                raise
        if typ == MsgType.ERR:
            raise classify_remote(reply)
        return reply

    def _retry_driver(self, attempt_fn,
                      deadline_s: Optional[float] = None) -> Any:
        """The ONE retry engine (plain requests, hedged reads and bulk
        conversations all run through here): call ``attempt_fn(
        io_timeout)`` under the client's :class:`RetryPolicy` and the
        per-request deadline, retrying typed-retryable failures with
        jittered exponential backoff. ``io_timeout`` caps the attempt's
        socket timeout at the remaining budget — the deadline bounds a
        HUNG attempt too, not just the backoff gaps. Every raised error
        is typed (:class:`RemoteError` family) — callers never see a
        bare socket exception."""
        policy = self._retry
        budget_s = deadline_s if deadline_s is not None else policy.deadline_s
        deadline = deadline_after(budget_s) if budget_s is not None else None
        attempt = 1
        while True:
            self.last_attempts = attempt
            io_timeout = None  # None = keep the steady-state timeout
            if deadline is not None:
                left = seconds_left(deadline)
                if left <= 0:
                    raise DeadlineExceededError(
                        "DeadlineExceeded",
                        f"request deadline of {budget_s}s already spent "
                        f"before attempt {attempt}")
                io_timeout = left if self._timeout is None \
                    else min(self._timeout, left)
            try:
                return attempt_fn(io_timeout)
            except RemoteError as e:
                if not e.retryable:
                    raise
                failure: RemoteError = e
            except (socket.timeout, TimeoutError) as e:
                failure = RemoteTimeoutError(type(e).__name__,
                                             str(e) or "socket timeout")
            except (ConnectionError, OSError) as e:
                # includes ProtocolError (desync/truncation) and refused
                # re-dials — the connection is already dropped, the next
                # attempt re-dials fresh
                failure = ConnectionLostError(type(e).__name__, str(e))
            if attempt >= policy.max_attempts:
                raise failure
            if isinstance(failure, NotLeaderError):
                addr = getattr(failure, "leader_addr", None)
                if addr:
                    # the refusal NAMES the leader: re-point and retry
                    # immediately — deterministic redirect, not
                    # congestion, so backoff would only add latency
                    self._switch_address(addr)
                    attempt += 1
                    self.total_retries += 1
                    obs.REGISTRY.counter("serve.client.retries").inc()
                    continue
                # mid-election (no leader known yet): fall through to
                # the normal backoff — it doubles as the bounded
                # election-window wait — rotating candidates meanwhile
                self._rotate_failover()
            elif isinstance(failure, (ConnectionLostError,
                                      RemoteTimeoutError)) \
                    and self._failover:
                # the daemon died outright (no typed refusal to carry
                # a leader address): walk the succession list — one of
                # the candidates is (or is about to become) the leader
                self._rotate_failover()
            if isinstance(failure, PlacementStaleError):
                # the frame rode an out-of-date placement map: refresh
                # the cache and retry IMMEDIATELY — the rejection is
                # deterministic (not congestion), so exponential
                # backoff would only delay the re-route
                self._refresh_placement()
                attempt += 1
                self.total_retries += 1
                obs.REGISTRY.counter("serve.client.retries").inc()
                continue
            delay = policy.backoff_s(attempt, self._rng)
            hint = getattr(failure, "retry_after_s", None)
            if hint is not None and hint > 0:
                # the server computed this from its lane's observed
                # queue-wait histogram (serve/sched/) — honor it when
                # it says to wait LONGER than the exponential policy
                # would. The policy stays the floor: a near-zero
                # historical median during a fresh saturation spike
                # must not collapse backoff into a retry storm. Small
                # multiplicative jitter keeps a rejected herd from
                # re-synchronizing on the exact same instant.
                delay = max(delay, float(hint)
                            * (1.0 + 0.25 * self._rng.random()))
            if deadline is not None and delay > seconds_left(deadline):
                raise DeadlineExceededError(
                    "DeadlineExceeded",
                    f"request deadline of {budget_s}s exhausted after "
                    f"{attempt} attempt(s); last failure: {failure}",
                ) from failure
            time.sleep(delay)
            attempt += 1
            self.total_retries += 1
            obs.REGISTRY.counter("serve.client.retries").inc()

    def _request(self, msg_type: MsgType, payload: Any,
                 codec: int = CODEC_MSGPACK,
                 deadline_s: Optional[float] = None) -> Any:
        """One logical request: attach an idempotency token to mutating
        frames and this client's identity to every frame, mint a
        SAMPLED query id for query-shaped frames (the trace the
        daemon's spans join on — 1 in ``trace_sample``), then retry
        under :meth:`_retry_driver`. A traced request ships its client
        span profile to the daemon afterwards (PUT_TRACE,
        best-effort)."""
        if isinstance(payload, dict):
            extra = {}
            if msg_type in MUTATING_TYPES \
                    and IDEMPOTENCY_KEY not in payload:
                # one token per LOGICAL request: every retry resends the
                # same token, so the server can dedupe a mutation whose
                # first reply was lost mid-wire
                extra[IDEMPOTENCY_KEY] = uuid.uuid4().hex
            if self.client_id is not None \
                    and CLIENT_ID_KEY not in payload:
                extra[CLIENT_ID_KEY] = str(self.client_id)
            if self.lane is not None and LANE_KEY not in payload:
                extra[LANE_KEY] = str(self.lane)
            if extra:
                payload = dict(payload)
                payload.update(extra)
        qid = None
        if msg_type in TRACED_TYPES and isinstance(payload, dict) \
                and QUERY_ID_KEY not in payload and obs.enabled():
            # one id per LOGICAL query (retries reuse it), minted 1-in-N
            # (config.obs_trace_sample via the constructor) so high-QPS
            # traffic traces at bounded cost; a payload already carrying
            # a qid is a forwarded frame (the leader's mirror path) —
            # its originating client owns the trace
            qid = self._qid_sampler.sample(self._trace_sample)
            if qid is not None:
                payload = dict(payload)
                payload[QUERY_ID_KEY] = qid
        oneshot = self._stream_owner == threading.get_ident()

        def attempt(io_timeout):
            if oneshot:
                return self._oneshot_request(msg_type, payload, codec,
                                             io_timeout=io_timeout)
            if self._replicas and msg_type not in MUTATING_TYPES \
                    and msg_type != MsgType.SHUTDOWN:
                return self._request_hedged(msg_type, payload, codec,
                                            io_timeout=io_timeout)
            return self._request_once(msg_type, payload, codec,
                                      io_timeout=io_timeout)

        if qid is None:
            return self._retry_driver(attempt, deadline_s)
        with obs.trace(qid, origin="client") as tr:
            out = self._retry_driver(attempt, deadline_s)
        if tr is not None and self.ship_traces:
            # the trace closed on context exit (total_s final): ship
            # the client half so the daemon's GET_TRACE returns one
            # merged end-to-end profile
            self._ship_trace(qid, tr)
        return out

    def _ship_trace(self, qid: str, tr) -> None:
        """Queue a completed client trace for the background shipper —
        NEVER on the caller's critical path: at ``trace_sample=1`` a
        synchronous PUT_TRACE would add a full extra RPC to every
        request (doubling client-observed latency for small warm
        queries). Best-effort end to end: a full queue drops the
        profile (``trace_ship_dropped``), ship failures are counted,
        neither ever surfaces to the request that produced the trace.
        :meth:`flush_traces` waits for the queue to drain (tests,
        orderly shutdown)."""
        with self._ship_mu:
            if self._ship_q is None:
                self._ship_q = _queue.Queue(maxsize=64)
                self._ship_thread = threading.Thread(
                    target=self._ship_loop, args=(self._ship_q,),
                    daemon=True, name="netsdb-trace-ship")
                self._ship_thread.start()
            q = self._ship_q
        try:
            q.put_nowait({"qid": qid, "profile": tr.profile()})
        except _queue.Full:
            obs.REGISTRY.counter("serve.client.trace_ship_dropped").inc()

    def _ship_loop(self, q: "_queue.Queue") -> None:
        """Shipper thread body: drain queued profiles over its own
        dedicated connection (the main connection and its lock stay
        untouched — a ship can never interleave with a stream or block
        a request). The socket persists across ships and re-dials
        after any failure. ``q`` is bound at spawn — ``close()`` nulls
        the instance attribute, and this loop must keep draining to
        its sentinel regardless."""
        sock = None
        try:
            while True:
                item = q.get()
                try:
                    if item is None:
                        return  # close() sentinel
                    try:
                        if sock is None:
                            sock = self._dial()
                        send_frame(sock, MsgType.PUT_TRACE, item,
                                   CODEC_MSGPACK, chaos=self._chaos)
                        typ, reply = self._recv_reply(sock)
                        if typ == MsgType.ERR:
                            raise classify_remote(reply)
                        obs.REGISTRY.counter(
                            "serve.client.traces_shipped").inc()
                    except Exception as e:  # noqa: BLE001 — counted
                        obs.REGISTRY.counter(
                            "serve.client.trace_ship_failures").inc()
                        del e
                        if sock is not None:
                            try:
                                sock.close()
                            except OSError:
                                pass
                            sock = None
                finally:
                    q.task_done()
        finally:
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass

    def flush_traces(self, timeout_s: float = 5.0) -> bool:
        """Wait until every queued client trace has shipped (or
        failed), up to ``timeout_s``; True when the queue drained. The
        request path never waits — this is for tests and orderly
        shutdown."""
        q = self._ship_q
        if q is None:
            return True
        deadline = time.monotonic() + timeout_s
        with q.all_tasks_done:
            while q.unfinished_tasks:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                q.all_tasks_done.wait(left)
        return True

    # --- windowed bulk ingest (BULK_BEGIN/CHUNK/COMMIT) ---------------
    def _bulk_once(self, sock: socket.socket, begin: dict,
                   chunk_fn) -> Any:
        """One attempt of a streamed-ingest conversation on ``sock``:
        BEGIN, then chunks pipelined ``ingest_window`` deep (each chunk
        is acked by the server after it DECODES — outside any set lock
        — so acks overlap the client's next sends instead of
        stop-and-wait), then COMMIT, whose reply is the target op's
        reply. A BEGIN answered without ``go`` is the server replaying
        a completed execution from the idempotency cache — the retry
        path after a lost final ack — and ends the conversation
        immediately."""
        send_frame(sock, MsgType.BULK_BEGIN, begin, chaos=self._chaos)
        typ, reply = self._recv_reply(sock)
        if typ == MsgType.ERR:
            raise classify_remote(reply)
        if not (isinstance(reply, dict) and reply.get("go")):
            return reply  # deduplicated replay of the completed reply
        seq = 0
        unacked = 0
        for chunk in chunk_fn():
            chunk["seq"] = seq
            send_frame(sock, MsgType.BULK_CHUNK, chunk, chaos=self._chaos)
            seq += 1
            unacked += 1
            while unacked >= self.ingest_window:
                typ, ack = self._recv_reply(sock)
                if typ == MsgType.ERR:
                    raise classify_remote(ack)
                unacked -= 1
        while unacked:
            typ, ack = self._recv_reply(sock)
            if typ == MsgType.ERR:
                raise classify_remote(ack)
            unacked -= 1
        send_frame(sock, MsgType.BULK_COMMIT, {"chunks": seq},
                   chaos=self._chaos)
        typ, reply = self._recv_reply(sock)
        if typ == MsgType.ERR:
            raise classify_remote(reply)
        return reply

    def _bulk_request(self, op: MsgType, meta: dict, chunk_fn,
                      deadline_s: Optional[float] = None,
                      token: Optional[str] = None) -> Any:
        """One LOGICAL bulk ingest: stream ``chunk_fn()``'s chunks under
        the windowed-ack protocol, retrying the whole conversation on
        retryable failures under the client's :class:`RetryPolicy`.
        ``chunk_fn`` must return a fresh chunk iterator per call (each
        retry re-streams). The single idempotency token spans every
        attempt: nothing applies server-side until COMMIT, and a retry
        after a lost COMMIT reply replays the cached result instead of
        double-applying. From a thread that is mid-stream on the main
        connection the whole conversation rides a one-shot side
        connection (same rule as nested plain requests). ``token``
        overrides the minted idempotency token — routed shard ingest
        passes its slot-stable token so retries across placement
        refreshes stay at-most-once."""
        token = token or uuid.uuid4().hex
        begin = {"op": int(op), "meta": meta, IDEMPOTENCY_KEY: token}
        if self.client_id is not None:
            begin[CLIENT_ID_KEY] = str(self.client_id)

        def attempt(io_timeout):
            if self._stream_owner == threading.get_ident():
                s = self._dial(io_timeout)
                try:
                    if io_timeout is not None:
                        s.settimeout(io_timeout)
                    return self._bulk_once(s, begin, chunk_fn)
                finally:
                    s.close()
            with self._lock:
                if self._sock is None:
                    self._connect(io_timeout)
                try:
                    if io_timeout is not None:
                        self._sock.settimeout(io_timeout)
                    out = self._bulk_once(self._sock, begin, chunk_fn)
                    if io_timeout is not None:
                        self._sock.settimeout(self._timeout)
                    return out
                except Exception:
                    # ANY mid-conversation failure desyncs the
                    # chunk stream — drop and re-dial on retry
                    self._drop_connection()
                    raise

        return self._retry_driver(attempt, deadline_s)

    # --- hedged reads -------------------------------------------------
    def _observe_read_latency(self, dt: float) -> None:
        """One read's latency, recorded ONCE into both views: this
        client's bounded histogram (what :meth:`hedge_delay_s`
        quantiles over) and the process-shared registry histogram
        (what COLLECT_STATS ships) — same observations, same numbers."""
        self._read_hist.observe(dt)
        obs.REGISTRY.histogram("serve.client.read_latency_s").observe(dt)

    def hedge_delay_s(self) -> float:
        """Current hedge trigger: the explicit knob when set, else the
        observed p99 of this client's recent read latencies (adaptive —
        a hedge should fire only when THIS request is already in the
        tail; quantiled over the shared latency histogram), else a
        50 ms cold-start default."""
        if self._hedge_delay_s is not None:
            return self._hedge_delay_s
        if self._read_hist.sample_count >= 8:
            p99 = self._read_hist.quantile(0.99)
            if p99 is not None:
                return p99
        return 0.05

    def read_latency_stats(self) -> Dict[str, Any]:
        """Summary of this client's observed read latencies — the same
        histogram the hedge trigger quantiles over."""
        return self._read_hist.summary()

    def _request_hedged(self, msg_type: MsgType, payload: Any, codec: int,
                        io_timeout: Optional[float] = None) -> Any:
        """One attempt of an idempotent read with tail-latency hedging:
        the primary runs on the persistent connection; if its reply
        hasn't landed within :meth:`hedge_delay_s`, the SAME request is
        issued to the next replica over a one-shot connection and the
        first success wins. When the hedge wins, the primary's socket
        is force-closed so its worker thread (and the connection lock)
        are released promptly instead of waiting out a slow reply.
        Reads are idempotent by taxonomy, so duplicated execution is
        harmless; failures surface exactly like an unhedged attempt
        (the retry loop above classifies them).

        Cost note: the primary runs on a short-lived thread so the
        caller can time it — ~tens of µs per read, small against a
        loopback RPC and irrelevant against the tail latencies hedging
        exists to cut. Clients that never want that overhead simply
        don't pass ``replicas``."""
        t0 = time.perf_counter()
        results: "_queue.Queue" = _queue.Queue()

        def attempt(tag, fn):
            try:
                results.put((tag, None, fn()))
            except BaseException as e:  # noqa: BLE001 — re-raised below
                results.put((tag, e, None))

        threading.Thread(
            target=attempt, daemon=True,
            args=("primary", lambda: self._request_once(
                msg_type, payload, codec, io_timeout=io_timeout)),
        ).start()
        try:
            tag, err, val = results.get(timeout=self.hedge_delay_s())
        except _queue.Empty:
            self.hedges_issued += 1
            obs.REGISTRY.counter("serve.client.hedges_issued").inc()
            addr = self._replicas[self._hedge_rr % len(self._replicas)]
            self._hedge_rr += 1
            threading.Thread(
                target=attempt, daemon=True,
                args=("hedge", lambda: self._oneshot_request(
                    msg_type, payload, codec, io_timeout=io_timeout,
                    address=addr)),
            ).start()
            tag, err, val = results.get()
            if err is not None:
                # first responder failed — wait for the straggler
                tag2, err2, val2 = results.get()
                if err2 is None:
                    tag, err, val = tag2, None, val2
                elif tag == "hedge":
                    tag, err = "primary", err2  # prefer the primary's error
        if err is not None:
            raise err
        if tag == "hedge":
            self.hedges_won += 1
            obs.REGISTRY.counter("serve.client.hedges_won").inc()
            # release the primary (it holds _lock until its recv ends)
            self._force_close()
            # if the primary ALREADY finished and released the lock,
            # nobody else will reap the now-closed socket — a later
            # request would find it non-None, fail, and burn a retry
            # attempt. Non-blocking: when the primary still holds the
            # lock, its own failure path drops the connection.
            if self._lock.acquire(blocking=False):
                try:
                    self._drop_connection()
                finally:
                    self._lock.release()
        self._observe_read_latency(time.perf_counter() - t0)
        return val

    def _drop_connection(self) -> None:
        """Tear down the persistent socket (idempotent, never raises);
        the next request re-dials lazily. Callers must hold ``_lock``
        or be the only thread touching the client."""
        s, self._sock = self._sock, None
        if s is not None:
            try:
                s.close()
            except OSError:
                pass

    def _switch_address(self, address: str) -> None:
        """Re-point this client at a different daemon (HA failover:
        a NotLeader refusal named the real leader, or the candidate
        rotation picked the next succession peer). The persistent
        connection drops; the next attempt re-dials the new address.
        The placement cache is KEPT — epochs validate it, and the
        promotion's rebind bumped exactly the epochs that moved, so a
        genuinely stale map costs one typed PlacementStale, not a
        mandatory refetch on every failover."""
        host, _, port = address.rpartition(":")
        with self._lock:
            if (host or "127.0.0.1") == self.host \
                    and int(port) == self.port:
                return
            self.host = host or "127.0.0.1"
            self.port = int(port)
            self._drop_connection()
        self.failovers += 1

    def _rotate_failover(self) -> None:
        """Advance to the next failover candidate (skipping the
        current address). No-op without a candidate list."""
        if not self._failover:
            return
        n = len(self._failover)
        for _ in range(n):
            cand = self._failover[self._failover_idx % n]
            self._failover_idx += 1
            h, _, p = cand.rpartition(":")
            if (h or "127.0.0.1") != self.host or int(p) != self.port:
                self._switch_address(cand)
                return

    def _force_close(self) -> None:
        """Unstick an in-flight request from ANOTHER thread: shut the
        socket down without taking ``_lock`` (the stuck thread holds
        it), making its blocking recv fail immediately. Used by the
        leader's follower eviction so a hung mirror can never wedge the
        sender thread."""
        s = self._sock
        if s is not None:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass

    def close(self) -> None:
        with self._ship_mu:
            q, t = self._ship_q, self._ship_thread
            self._ship_q = None
            self._ship_thread = None
        if q is not None:
            # give in-flight ships a bounded grace, then stop the
            # shipper (daemon thread — an unreachable server can't
            # wedge close)
            try:
                q.put_nowait(None)
            except _queue.Full:
                pass
            if t is not None:
                t.join(timeout=2.0)
        with self._placement_mu:
            shard_clients = list(self._shard_clients.values())
            self._shard_clients.clear()
        for sc in shard_clients:
            sc.close()
        with self._lock:
            self._drop_connection()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # --- session ------------------------------------------------------
    def ping(self) -> Dict[str, Any]:
        return self._request(MsgType.PING, {})

    def shutdown_server(self) -> None:
        with self._lock:
            if self._sock is None:
                self._connect()
            try:
                send_frame(self._sock, MsgType.SHUTDOWN, {})
                # lint: disable=lock-blocking-call -- shutdown ack wait on the serialized connection; bounded by the socket timeout, and the daemon dying mid-wait is the success path
                recv_frame(self._sock, allow_pickle=False)
            except (ConnectionError, OSError):
                pass  # the daemon may die before acking — that's success
            finally:
                self._drop_connection()

    # --- DDL (same facade as Client) ----------------------------------
    def create_database(self, db: str) -> None:
        self._request(MsgType.CREATE_DATABASE, {"db": db})

    def create_set(self, db: str, set_name: str, type_name: str = "tensor",
                   persistence: str = "transient", eviction: str = "lru",
                   partition_lambda: Optional[str] = None,
                   placement=None, storage: str = "memory"):
        """``placement`` may be a Placement (serialized via ``to_meta``)
        or its meta dict; the daemon applies it to all ingest into the
        set (distribution declared at createSet, as in the reference's
        PartitionPolicy). ``storage="paged"`` backs the set with the
        daemon's page arena (out-of-core as a set property)."""
        if placement is not None and hasattr(placement, "to_meta"):
            placement = placement.to_meta()
        reply = self._request(MsgType.CREATE_SET, {
            "db": db, "set": set_name, "type_name": type_name,
            "persistence": persistence, "eviction": eviction,
            "partition_lambda": partition_lambda,
            "placement": placement, "storage": storage})
        entry = reply.get("placement") if isinstance(reply, dict) \
            else None
        if isinstance(entry, dict):
            # a SHARDED create returns its placement entry — cache it
            # now so the very first ingest routes instead of paying a
            # stale-map rejection round-trip
            with self._placement_mu:
                wire = self._placement_wire or {"epoch": 0, "sets": {}}
                wire.setdefault("sets", {})[f"{db}:{set_name}"] = entry
                wire["epoch"] = max(int(wire.get("epoch") or 0),
                                    int(entry.get("epoch") or 0))
                self._placement_wire = wire
        return RemoteIdent(db, set_name)

    def remove_set(self, db: str, set_name: str) -> None:
        self._request(MsgType.REMOVE_SET, {"db": db, "set": set_name})

    def clear_set(self, db: str, set_name: str) -> None:
        self._request(MsgType.CLEAR_SET, {"db": db, "set": set_name})

    def set_exists(self, db: str, set_name: str) -> bool:
        return self._request(MsgType.SET_EXISTS,
                             {"db": db, "set": set_name})["exists"]

    def list_sets(self) -> List[Tuple[str, str]]:
        return [tuple(s) for s in
                self._request(MsgType.LIST_SETS, {})["sets"]]

    def register_type(self, type_name: str, entry_point: str,
                      source: Optional[str] = None,
                      ship_module: bool = False) -> None:
        """``source``/``ship_module`` ship the UDF module's code to the
        daemon (the reference's .so replication on registerType) so
        EXECUTE_PLAN can bind types the server never installed. Shipped
        source is code the daemon executes — same trust boundary as the
        pickle codec (serve/protocol.py security note)."""
        if ship_module and source is None:
            from netsdb_tpu.catalog.catalog import read_module_source

            source = read_module_source(entry_point)
        self._request(MsgType.REGISTER_TYPE,
                      {"type_name": type_name, "entry_point": entry_point,
                       "source": source})

    # --- placement-aware routing (sharded worker pools) ---------------
    def _refresh_placement(self) -> None:
        """Re-fetch the daemon's placement map (best-effort: a refresh
        failure leaves the old cache — the next routed attempt then
        rejects typed again and retries). Concurrent callers WAIT for
        the in-flight fetch and use its result (returning immediately
        would hand them the known-stale map for another doomed
        round); same-thread re-entry (the PLACEMENT request's own
        retry path) is a no-op."""
        me = threading.get_ident()
        if self._refreshing_placement == me:
            return
        if not self._placement_fetch_mu.acquire(blocking=False):
            # another thread is fetching: park until ITS result lands
            self._placement_fetch_mu.acquire()
            self._placement_fetch_mu.release()
            return
        self._refreshing_placement = me
        try:
            wire = self._request(MsgType.PLACEMENT, {})
            with self._placement_mu:
                self._placement_wire = wire
            obs.REGISTRY.counter(
                "serve.client.placement_refreshes").inc()
        except Exception as e:  # noqa: BLE001 — best-effort by contract
            del e
        finally:
            self._refreshing_placement = None
            self._placement_fetch_mu.release()

    def placement_map(self) -> Optional[Dict[str, Any]]:
        """The cached placement map (tests/tooling probe)."""
        with self._placement_mu:
            return self._placement_wire

    def _placement_entry(self, db: str, set_name: str,
                         refresh: bool = False) -> Optional[Dict]:
        """One set's shard entry from the CACHED map — no wire traffic
        unless ``refresh`` (the default path stays frame-identical for
        clients of un-sharded daemons, whose cache is None)."""
        from netsdb_tpu.serve.placement import PlacementMap

        if refresh:
            self._refresh_placement()
        with self._placement_mu:
            wire = self._placement_wire
        if not wire:
            return None
        return PlacementMap.entry_from_wire(wire, db, set_name)

    def _shard_client(self, addr: str) -> "RemoteClient":
        """Cached direct connection to one shard daemon. Single
        attempt per request — the ROUTED retry loop owns retries (it
        must refresh the map between attempts, which a nested
        exponential retry would just delay)."""
        with self._placement_mu:
            sc = self._shard_clients.get(addr)
        if sc is not None:
            return sc
        sc = RemoteClient(addr, token=self.token, timeout=self._timeout,
                          retry=RetryPolicy(max_attempts=1),
                          connect_timeout=self._connect_timeout,
                          ingest_window=self.ingest_window,
                          ingest_chunk_bytes=self.ingest_chunk_bytes,
                          client_id=self.client_id, lane=self.lane,
                          ship_traces=False)
        with self._placement_mu:
            other = self._shard_clients.setdefault(addr, sc)
        if other is not sc:
            sc.close()
        return other

    def _drop_shard_client(self, addr: str) -> None:
        with self._placement_mu:
            sc = self._shard_clients.pop(addr, None)
        if sc is not None:
            sc.close()

    def _send_partition(self, addr: str, db: str, set_name: str,
                        part, as_table: bool, date_cols, epoch: int,
                        slot: int, token: str,
                        chunk_bytes: int) -> Any:
        """One slot's partition to its owning daemon (or the leader,
        for a handoff slot): big payloads stream under the windowed-ack
        pipeline with the placement epoch in the BEGIN meta, small ones
        ride one frame. ``token`` is the slot's STABLE idempotency
        token — every retry of this logical ingest re-sends it, so a
        partition whose first apply succeeded (reply lost) deduplicates
        instead of double-appending."""
        from netsdb_tpu.relational.table import ColumnTable

        sc = self._shard_client(addr)
        if isinstance(part, ColumnTable):
            nbytes = sum(np.asarray(v).nbytes
                         for v in part.cols.values())
            if nbytes >= chunk_bytes:
                return sc._bulk_request(
                    MsgType.SEND_DATA,
                    {"db": db, "set": set_name, "mode": "table",
                     "date_cols": list(date_cols), "append": True,
                     "dicts": {k: list(v)
                               for k, v in part.dicts.items()},
                     "nrows": part.num_rows,
                     "pepoch": int(epoch), "slot": int(slot)},
                    sc._table_chunks(part, chunk_bytes), token=token)
            payload: Dict[str, Any] = {
                "db": db, "set": set_name, "items": part,
                "as_table": True, "date_cols": list(date_cols),
                "append": True}
        elif as_table:
            if len(part) >= self.PIPELINE_MIN_ITEMS:
                return sc._bulk_request(
                    MsgType.SEND_DATA,
                    {"db": db, "set": set_name, "mode": "items",
                     "as_table": True, "date_cols": list(date_cols),
                     "append": True,
                     "pepoch": int(epoch), "slot": int(slot)},
                    sc._item_chunks(list(part), chunk_bytes),
                    token=token)
            payload = {"db": db, "set": set_name, "items": list(part),
                       "as_table": True, "date_cols": list(date_cols),
                       "append": True}
        else:
            if len(part) >= self.PIPELINE_MIN_ITEMS:
                return sc._bulk_request(
                    MsgType.SEND_DATA,
                    {"db": db, "set": set_name, "mode": "items",
                     "pepoch": int(epoch), "slot": int(slot)},
                    sc._item_chunks(list(part), chunk_bytes),
                    token=token)
            payload = {"db": db, "set": set_name, "items": list(part)}
        payload[PLACEMENT_EPOCH_KEY] = int(epoch)
        payload[SHARD_SLOT_KEY] = int(slot)
        payload[IDEMPOTENCY_KEY] = token
        return sc._request(MsgType.SEND_DATA, payload,
                           codec=CODEC_PICKLE)

    def _routed_ingest(self, db: str, set_name: str,
                       parts: Dict[int, Any], as_table: bool,
                       date_cols, chunk_bytes: int) -> Dict[int, Any]:
        """One logical ingest fanned out to the owning shards in
        parallel — aggregate bandwidth scales with pool size. Failed
        slots retry under the client's RetryPolicy with the placement
        map REFRESHED between rounds (an evicted slot's partition then
        re-routes to the leader's handoff buffer under the new epoch);
        per-slot idempotency tokens make every retry at-most-once."""
        tokens = {slot: uuid.uuid4().hex for slot in parts}
        remaining = dict(parts)
        replies: Dict[int, Any] = {}
        policy = self._retry
        attempt = 1
        obs.REGISTRY.counter("serve.client.routed_ingests").inc()
        while True:
            entry = self._placement_entry(db, set_name,
                                          refresh=attempt > 1)
            if entry is None:
                raise PlacementStaleError(
                    "PlacementStale",
                    f"{db}:{set_name} vanished from the placement map")
            errors: Dict[int, BaseException] = {}
            lock = threading.Lock()

            def send_slot(slot, part, entry=entry, errors=errors,
                          lock=lock):
                sl = entry["slots"][slot]
                addr = (f"{self.host}:{self.port}"
                        if sl["state"] != "live" else sl["addr"])
                try:
                    reply = self._send_partition(
                        addr, db, set_name, part, as_table, date_cols,
                        entry["epoch"], slot, tokens[slot],
                        chunk_bytes)
                    with lock:
                        replies[slot] = reply
                except Exception as e:  # noqa: BLE001 — EVERY failure
                    # must land in `errors`: a slot in neither dict
                    # would be dropped from `remaining` and its
                    # partition silently lost while the ingest
                    # reports success
                    self._drop_shard_client(addr)
                    with lock:
                        errors[slot] = e
            threads = []
            for slot, part in remaining.items():
                t = threading.Thread(target=send_slot,
                                     args=(slot, part), daemon=True)
                t.start()
                threads.append(t)
            for t in threads:
                t.join()
            remaining = {slot: part for slot, part in remaining.items()
                         if slot in errors}
            if not remaining:
                return replies
            # a deterministic (non-retryable) slot failure wins
            # immediately — retrying the whole round against it would
            # burn the backoff schedule on a hopeless slot and could
            # surface a different slot's transient error instead
            fatal = next((e for e in errors.values()
                          if isinstance(e, RemoteError)
                          and not e.retryable), None)
            if fatal is not None:
                raise fatal
            if attempt >= policy.max_attempts:
                raise next(iter(errors.values()))
            if not all(isinstance(e, PlacementStaleError)
                       for e in errors.values()):
                # transient transport faults back off; pure stale-map
                # rejections are deterministic — the refresh at the
                # top of the next round resolves them instantly
                time.sleep(policy.backoff_s(attempt, self._rng))
            attempt += 1
            self.total_retries += 1
            obs.REGISTRY.counter("serve.client.retries").inc()

    # --- data path ----------------------------------------------------

    #: below this many items, ``send_data`` keeps the single-frame path
    #: (a BEGIN/COMMIT conversation is pure overhead for tiny batches)
    PIPELINE_MIN_ITEMS = 64

    def _item_chunks(self, items: list, chunk_bytes: int):
        """Adaptive item batching — ``scan_stream``'s frame sizing
        applied to the SEND direction: the first chunk holds one item
        (never pack an unmeasured batch), then the batch size tracks
        observed bytes-per-item with growth capped at 4×/chunk. Each
        blob rides as a uint8 view so the pickled bytes go out-of-band
        (no msgpack body copy)."""
        import pickle

        def chunks():
            i = 0
            target = 1
            while i < len(items):
                batch = items[i:i + target]
                blob = pickle.dumps(batch, protocol=pickle.HIGHEST_PROTOCOL)
                yield {"n": len(batch), "blob": np.frombuffer(blob, np.uint8)}
                per_item = max(len(blob) // len(batch), 1)
                target = max(1, min(chunk_bytes // per_item, 4 * target))
                i += len(batch)

        return chunks

    def send_data(self, db: str, set_name: str, items: Sequence[Any],
                  pipeline: Optional[bool] = None,
                  chunk_bytes: Optional[int] = None) -> None:
        """Object ingest. Large batches stream as bounded chunks under
        the depth-W windowed-ack pipeline (``pipeline=None`` decides by
        item count; force ``True``/``False`` to pin a path — the bench
        pins both to record the streamed-vs-monolithic win).

        A set the cached placement map shows as PARTITIONED routes
        instead: items split across the owning shards (hash or range,
        per the set's placement) and every partition ships directly to
        its shard in parallel — aggregate ingest bandwidth scales with
        the pool. A stale map rejects typed and the retry re-routes."""
        from netsdb_tpu.serve import placement as _pl

        items = list(items)
        entry = self._placement_entry(db, set_name)
        if entry is not None:
            cb = int(chunk_bytes or self.ingest_chunk_bytes)
            parts = dict(_pl.split_items(items, entry))
            self._routed_ingest(db, set_name, parts, as_table=False,
                                date_cols=(), chunk_bytes=cb)
            return
        use = (pipeline if pipeline is not None
               else len(items) >= self.PIPELINE_MIN_ITEMS)
        if not use:
            try:
                self._request(MsgType.SEND_DATA,
                              {"db": db, "set": set_name,
                               "items": items},
                              codec=CODEC_PICKLE)
            except PlacementStaleError:
                # the set sharded after this client's map snapshot:
                # refresh and route (the one-hop upgrade path)
                if self._placement_entry(db, set_name,
                                         refresh=True) is None:
                    raise
                self.send_data(db, set_name, items, pipeline=pipeline,
                               chunk_bytes=chunk_bytes)
            return
        cb = int(chunk_bytes or self.ingest_chunk_bytes)
        try:
            self._bulk_request(
                MsgType.SEND_DATA,
                {"db": db, "set": set_name, "mode": "items"},
                self._item_chunks(items, cb))
        except PlacementStaleError:
            if self._placement_entry(db, set_name, refresh=True) is None:
                raise
            self.send_data(db, set_name, items, pipeline=pipeline,
                           chunk_bytes=chunk_bytes)

    def _table_chunks(self, table, chunk_bytes: int):
        """Row-range slices of a ColumnTable's columns: numpy views
        (zero copy) that ride as out-of-band segments — the zero-copy
        bulk-table path. The dictionaries travel once in the BEGIN
        meta; every chunk shares them."""
        cols = {k: np.ascontiguousarray(np.asarray(v))
                for k, v in table.cols.items()}
        nrows = table.num_rows
        row_bytes = max(1, sum(c.dtype.itemsize for c in cols.values()))
        per_chunk = max(1, chunk_bytes // row_bytes)

        def chunks():
            for start in range(0, max(nrows, 1), per_chunk):
                stop = min(nrows, start + per_chunk)
                yield {"rows": [start, stop],
                       "cols": {k: v[start:stop] for k, v in cols.items()}}

        return chunks

    def send_table(self, db: str, set_name: str, rows_or_table,
                   date_cols: Sequence[str] = (),
                   append: bool = False,
                   pipeline: Optional[bool] = None,
                   chunk_bytes: Optional[int] = None) -> "RemoteTableInfo":
        """Ship rows (or a pre-built ColumnTable) for daemon-side
        columnar ingest — dictionary encoding + the set's placement
        happen server-side, where the devices are. Returns a
        :class:`RemoteTableInfo` quacking like the ingested table's
        summary (``num_rows``/``columns``), mirroring the in-process
        facade without pulling the whole table back.

        Bulk payloads stream: a ColumnTable goes out as row-range
        column slices riding out-of-band segments (zero host-side
        copies of the column bytes); a rows list goes out as adaptive
        pickled batches. Both run ``ingest_window`` chunks deep under
        the windowed-ack pipeline. ``pipeline=None`` decides by size;
        pin ``True``/``False`` to force a path.

        A PARTITIONED set (cached placement map) routes instead: the
        rows split across the owning shards and every partition
        streams directly to its shard in parallel. ``append=False``
        first clears the set pool-wide (the leader fans the clear
        out), then appends each shard's partition."""
        from netsdb_tpu.relational.table import ColumnTable

        cb = int(chunk_bytes or self.ingest_chunk_bytes)
        entry = self._placement_entry(db, set_name)
        if entry is not None:
            return self._send_table_routed(db, set_name, rows_or_table,
                                           date_cols, append, cb)
        try:
            return self._send_table_plain(db, set_name, rows_or_table,
                                          date_cols, append, pipeline,
                                          cb)
        except PlacementStaleError:
            # the set sharded after this client's map snapshot
            if self._placement_entry(db, set_name, refresh=True) is None:
                raise
            return self.send_table(db, set_name, rows_or_table,
                                   date_cols=date_cols, append=append,
                                   pipeline=pipeline,
                                   chunk_bytes=chunk_bytes)

    def _send_table_routed(self, db: str, set_name: str, rows_or_table,
                           date_cols, append: bool,
                           chunk_bytes: int) -> "RemoteTableInfo":
        from netsdb_tpu.relational.table import ColumnTable
        from netsdb_tpu.serve import placement as _pl

        entry = self._placement_entry(db, set_name)
        if not append:
            # replace = pool-wide clear (leader fans out), then append
            # partitions; the slot idempotency tokens keep the append
            # half at-most-once across retries
            self.clear_set(db, set_name)
        if isinstance(rows_or_table, ColumnTable):
            table = rows_or_table
            parts = dict(_pl.split_table(table, entry))
            replies = self._routed_ingest(db, set_name, parts,
                                          as_table=True,
                                          date_cols=date_cols,
                                          chunk_bytes=chunk_bytes)
            cols = sorted(table.cols)
            total = int(table.compact().num_rows
                        if table.valid is not None else table.num_rows)
        else:
            items = list(rows_or_table)
            parts = dict(_pl.split_items(items, entry))
            replies = self._routed_ingest(db, set_name, parts,
                                          as_table=True,
                                          date_cols=date_cols,
                                          chunk_bytes=chunk_bytes)
            cols = sorted({c for r in replies.values()
                           if isinstance(r, dict)
                           for c in (r.get("columns") or ())})
            total = len(items)
        return RemoteTableInfo(total, cols)

    def _send_table_plain(self, db, set_name, rows_or_table, date_cols,
                          append, pipeline, cb) -> "RemoteTableInfo":
        from netsdb_tpu.relational.table import ColumnTable

        if isinstance(rows_or_table, ColumnTable):
            table = rows_or_table
            if table.valid is not None:
                table = table.compact()
            nbytes = sum(np.asarray(v).nbytes for v in table.cols.values())
            use = pipeline if pipeline is not None else nbytes >= cb
            if use:
                reply = self._bulk_request(
                    MsgType.SEND_DATA,
                    {"db": db, "set": set_name, "mode": "table",
                     "date_cols": list(date_cols), "append": append,
                     "dicts": {k: list(v) for k, v in table.dicts.items()},
                     "nrows": table.num_rows},
                    self._table_chunks(table, cb))
                return RemoteTableInfo(reply["count"],
                                       list(reply["columns"]))
            items = table
        else:
            items = list(rows_or_table)
            use = (pipeline if pipeline is not None
                   else len(items) >= self.PIPELINE_MIN_ITEMS)
            if use:
                reply = self._bulk_request(
                    MsgType.SEND_DATA,
                    {"db": db, "set": set_name, "mode": "items",
                     "as_table": True, "date_cols": list(date_cols),
                     "append": append},
                    self._item_chunks(items, cb))
                return RemoteTableInfo(reply["count"],
                                       list(reply["columns"]))
        reply = self._request(
            MsgType.SEND_DATA,
            {"db": db, "set": set_name, "items": items,
             "as_table": True, "date_cols": list(date_cols),
             "append": append},
            codec=CODEC_PICKLE)
        return RemoteTableInfo(reply["count"], list(reply["columns"]))

    def analyze_set(self, db: str, set_name: str) -> Dict[str, Any]:
        """Planner statistics computed DAEMON-side; only the summaries
        cross the wire (ref StorageCollectStats,
        ``PangeaStorageServer.h:48``). This is what lets
        ``relational.dag.suite_sink_for`` build all ten suite sinks
        over a daemon without pulling a single table."""
        from netsdb_tpu.relational.stats import ColumnStats

        reply = self._request(MsgType.ANALYZE_SET,
                              {"db": db, "set": set_name})
        return {"num_rows": reply["num_rows"],
                "dicts": {k: list(v) for k, v in reply["dicts"].items()},
                "stats": {k: ColumnStats(*v)
                          for k, v in reply["stats"].items()}}

    def get_table(self, db: str, set_name: str):
        """Fetch a table set as a host-side ColumnTable (pickled via its
        numpy ``__getstate__``)."""
        items = list(self.get_set_iterator(db, set_name))
        from netsdb_tpu.relational.table import ColumnTable

        tables = [i for i in items if isinstance(i, ColumnTable)]
        if len(tables) != 1:
            raise ValueError(
                f"set {db}:{set_name} holds {len(tables)} tables; expected 1")
        return tables[0]

    def send_matrix(self, db: str, set_name: str, dense, block_shape=None,
                    dtype=None) -> RemoteTensor:
        dense = np.asarray(dense, dtype=dtype)
        entry = self._placement_entry(db, set_name)
        if entry is not None:
            return self._send_matrix_routed(db, set_name, dense,
                                            block_shape, entry)
        reply = self._request(MsgType.SEND_MATRIX, {
            "db": db, "set": set_name,
            "tensor": tensor_to_wire(dense, block_shape)})
        return RemoteTensor(dense, reply.get("block_shape"))

    def _send_matrix_routed(self, db: str, set_name: str, dense,
                            block_shape, entry) -> RemoteTensor:
        """Batch-partitioned tensor ingest — the model-serving scoring
        frame: rows split by the placement's contiguous range slices,
        slice *i* to slot *i*, so slot order IS batch order and the
        tensor-chain scatter-gather concat reassembles the exact input
        order byte-for-byte. Slices go out in parallel (aggregate
        ingest bandwidth scales with the pool, like routed tables); a
        degraded slot's typed refusal surfaces to the caller — scoring
        batches are transient, so there is no handoff buffering to
        fall back on."""
        from netsdb_tpu.serve import placement as _pl

        if entry.get("mode") != "range":
            raise ValueError(
                f"tensor set {db}:{set_name} is partitioned "
                f"{entry.get('mode')!r}; matrices shard by contiguous "
                f"row ranges only — create with placement=\"range\"")
        slots = entry["slots"]
        slices = _pl.range_slices(int(dense.shape[0]), len(slots))
        errors: Dict[int, BaseException] = {}
        lock = threading.Lock()

        def send_slot(i: int, lo: int, hi: int) -> None:
            sl = slots[i]
            addr = (f"{self.host}:{self.port}"
                    if sl["state"] != "live" else sl["addr"])
            try:
                sc = self._shard_client(addr)
                sc._request(MsgType.SEND_MATRIX, {
                    "db": db, "set": set_name,
                    "tensor": tensor_to_wire(
                        np.ascontiguousarray(dense[lo:hi]), block_shape),
                    PLACEMENT_EPOCH_KEY: int(entry["epoch"]),
                    SHARD_SLOT_KEY: i})
            except Exception as e:  # noqa: BLE001 — surfaced below
                self._drop_shard_client(addr)
                with lock:
                    errors[i] = e

        threads = []
        for i, (lo, hi) in enumerate(slices):
            t = threading.Thread(target=send_slot, args=(i, lo, hi),
                                 daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
        if errors:
            raise errors[min(errors)]
        obs.REGISTRY.counter("serve.client.routed_ingests").inc()
        return RemoteTensor(dense,
                            list(block_shape) if block_shape else None)

    def get_tensor(self, db: str, set_name: str) -> RemoteTensor:
        reply = self._request(MsgType.GET_TENSOR, {"db": db, "set": set_name})
        return RemoteTensor(reply["data"], reply.get("block_shape"))

    def paged_matmul(self, db: str, set_name: str, rhs) -> np.ndarray:
        """``stored @ rhs`` computed daemon-side with the stored matrix
        streamed from the arena (paged TENSOR sets never materialize;
        their GET_TENSOR raises by design)."""
        reply = self._request(MsgType.PAGED_MATMUL,
                              {"db": db, "set": set_name,
                               "rhs": np.asarray(rhs)})
        return np.asarray(reply["data"])

    def get_tensor_chunked(self, db: str, set_name: str,
                           chunk_bytes: int = 8 << 20) -> RemoteTensor:
        """Pull a tensor as a chunked stream: client holds the result
        array plus ONE chunk (vs. array + full frame for GET_TENSOR) —
        the page-streamed model transfer path for big weight sets."""
        meta = None
        buf = None
        off = 0
        for frame in self._stream(MsgType.GET_TENSOR_CHUNKED,
                                  {"db": db, "set": set_name,
                                   "chunk_bytes": int(chunk_bytes)}):
            if meta is None:
                meta = frame["meta"]
                buf = bytearray(meta["nbytes"])
            else:
                b = frame["b"]  # uint8 ndarray (out-of-band) or bytes
                n = b.nbytes if isinstance(b, np.ndarray) else len(b)
                buf[off:off + n] = b if not isinstance(b, np.ndarray) \
                    else memoryview(b)
                off += n
        if meta is None:
            raise ProtocolError("empty chunked-tensor stream")
        # frombuffer over the assembled bytearray: writable, no copy
        dense = np.frombuffer(buf, dtype=np.dtype(meta["dtype"])
                              ).reshape(meta["shape"])
        return RemoteTensor(dense, meta.get("block_shape"))

    def get_set_iterator(self, db: str, set_name: str) -> Iterator[Any]:
        reply = self._request(MsgType.SCAN_SET, {"db": db, "set": set_name})
        return iter(reply["items"])

    def scan_stream(self, db: str, set_name: str,
                    max_frame_bytes: int = 4 << 20) -> Iterator[Any]:
        """Stream a set's items with bounded buffering on both ends:
        the server packs ≤ ``max_frame_bytes`` of pickled items per
        frame; this generator holds one frame at a time. The streamed
        ``getSetIterator`` (ref FrontendQueryTestServer.cc:785-890).

        The connection is held for the duration of the iteration (one
        in-flight request per connection, as in the reference's
        PDBCommunicator); abandoning the iterator early closes the
        socket so the next request reconnects cleanly."""
        import pickle

        for frame in self._stream(MsgType.SCAN_SET_STREAM,
                                  {"db": db, "set": set_name,
                                   "max_frame_bytes": int(max_frame_bytes)}):
            yield from pickle.loads(frame["batch"])

    def get_table_streamed(self, db: str, set_name: str,
                           max_frame_bytes: int = 4 << 20):
        """Assemble a table set from the STREAMED scan: for paged sets
        the daemon ships one host-side chunk table per frame straight
        off its arena stream (it never materializes the relation,
        device- or wire-side); this client holds the growing columns
        plus ONE chunk. The page-streamed remote read for exactly the
        sets ``get_table``'s single-frame reply is too big for."""
        from netsdb_tpu.relational.table import ColumnTable

        parts: dict = {}
        dicts: dict = {}
        got = False
        # closing: the TypeError below abandons the stream mid-scan —
        # the generator (and its socket) must close NOW, not at GC
        with contextlib.closing(
                self.scan_stream(db, set_name, max_frame_bytes)) as items:
            for item in items:
                if not isinstance(item, ColumnTable):
                    raise TypeError(
                        f"set {db}:{set_name} holds "
                        f"{type(item).__name__} items, not tables")
                got = True
                dicts.update(item.dicts)
                cols = item.compact().cols if item.valid is not None \
                    else item.cols
                for k, v in cols.items():
                    parts.setdefault(k, []).append(np.asarray(v))
        if not got:
            raise ValueError(f"set {db}:{set_name} is empty")
        return ColumnTable({k: np.concatenate(v)
                            for k, v in parts.items()}, dicts, None)

    @staticmethod
    def _stream_frames(sock: socket.socket, msg_type: MsgType,
                       payload: Any) -> Iterator[Any]:
        """Frame loop of one streaming request over ``sock``: yield each
        STREAM_ITEM payload until STREAM_END; ERR raises (the stream
        ends, the connection stays frame-synchronized)."""
        send_frame(sock, msg_type, payload)
        while True:
            typ, reply = recv_frame(sock, allow_pickle=True)
            if typ == MsgType.STREAM_END:
                return
            if typ == MsgType.ERR:
                raise classify_remote(reply)
            yield reply

    def _stream_hedged(self, msg_type: MsgType,
                       payload: Any) -> Iterator[Any]:
        """Streaming read with FIRST-ITEM hedging — ``_request_hedged``
        extended to streams: the primary opens the stream on a
        dedicated connection; if its first frame hasn't landed within
        :meth:`hedge_delay_s`, the SAME request goes to the next
        replica, and whichever connection delivers a first frame first
        WINS — the loser's socket is closed immediately (cancelled),
        so at most one duplicated first frame ever crosses the wire,
        not a duplicated full scan. After the first item the winner's
        stream is consumed inline (a half-read stream cannot switch
        connections mid-flight), so hedging bounds time-to-first-item
        — the metric that dominates interactive scans — while the
        stream body rides ordinary TCP backpressure. Reads only, like
        every hedge (mutations never stream)."""
        first_q: "_queue.Queue" = _queue.Queue()
        socks: Dict[str, socket.socket] = {}
        cancelled: set = set()
        state_lock = threading.Lock()

        def opener(tag: str, address: Optional[str]) -> None:
            s = None
            try:
                s = self._dial(address=address)
                with state_lock:
                    if tag in cancelled:
                        s.close()
                        return
                    socks[tag] = s
                send_frame(s, msg_type, payload, chaos=self._chaos)
                typ, reply = self._recv_reply(s)
                first_q.put((tag, typ, reply, None))
            except BaseException as e:  # noqa: BLE001 — surfaced below
                # a failed leg closes its own socket (the cancel sweep
                # only covers the LOSING healthy leg)
                with state_lock:
                    socks.pop(tag, None)
                if s is not None:
                    s.close()
                first_q.put((tag, None, None, e))

        threading.Thread(target=opener, daemon=True,
                         args=("primary", None)).start()
        t0 = time.perf_counter()
        try:
            winner = first_q.get(timeout=self.hedge_delay_s())
            legs = 1 if winner[0] == "primary" else 2
        except _queue.Empty:
            self.hedges_issued += 1
            obs.REGISTRY.counter("serve.client.hedges_issued").inc()
            addr = self._replicas[self._hedge_rr % len(self._replicas)]
            self._hedge_rr += 1
            threading.Thread(target=opener, daemon=True,
                             args=("hedge", addr)).start()
            legs = 2
            winner = first_q.get()
            if winner[3] is not None:
                # first responder failed — wait for the straggler; on a
                # double failure prefer the primary's error
                other = first_q.get()
                legs = 0  # both legs reported; nothing left to cancel
                if other[3] is None:
                    winner = other
                elif winner[0] == "hedge":
                    winner = other
        tag, typ, frame, err = winner
        if legs:
            # cancel the loser: close its socket (unblocks a parked
            # recv) or poison its tag so a not-yet-dialed leg closes
            # itself on arrival
            with state_lock:
                for other_tag in ("primary", "hedge"):
                    if other_tag == tag:
                        continue
                    cancelled.add(other_tag)
                    s = socks.pop(other_tag, None)
                    if s is not None:
                        try:
                            s.shutdown(socket.SHUT_RDWR)
                        except OSError:
                            pass
                        s.close()
        if err is not None:
            raise err
        if tag == "hedge":
            self.hedges_won += 1
            obs.REGISTRY.counter("serve.client.hedges_won").inc()
        self._observe_read_latency(time.perf_counter() - t0)
        with state_lock:
            sock = socks.pop(tag)
        try:
            while True:
                if typ == MsgType.STREAM_END:
                    return
                if typ == MsgType.ERR:
                    raise classify_remote(frame)
                yield frame
                typ, frame = self._recv_reply(sock)
        finally:
            # dedicated connection: never resynchronized, always closed
            sock.close()

    def _stream(self, msg_type: MsgType, payload: Any) -> Iterator[Any]:
        """Issue a streaming request; yield each STREAM_ITEM payload
        until STREAM_END. ERR aborts with RemoteError. If the consumer
        abandons the generator mid-stream, the socket is dropped (a
        half-read stream cannot be resynchronized). A stream opened
        from a thread ALREADY mid-stream (nested iteration) runs over
        its own dedicated connection — like nested plain requests
        (`_oneshot_request`), it must neither wait on the held lock nor
        interleave frames on the streaming socket. With ``replicas``
        configured, streams hedge their FIRST item over dedicated
        connections (:meth:`_stream_hedged`) — the persistent
        connection and its lock stay untouched, so nested requests
        from the consuming thread need no special-casing.

        Streams bypass :meth:`_request`, so the client identity is
        attached HERE — the heaviest read path must attribute like any
        other frame (scan batches book under this tenant's
        ``requests``/scan work, not ``anon``)."""
        if self.client_id is not None and isinstance(payload, dict) \
                and CLIENT_ID_KEY not in payload:
            payload = dict(payload)
            payload[CLIENT_ID_KEY] = str(self.client_id)
        if self._replicas and self._stream_owner != threading.get_ident():
            yield from self._stream_hedged(msg_type, payload)
            return
        if self._stream_owner == threading.get_ident():
            s = self._dial()
            try:
                yield from self._stream_frames(s, msg_type, payload)
            finally:
                s.close()
            return
        self._lock.acquire()
        self._stream_owner = threading.get_ident()
        done = False
        try:
            if self._sock is None:
                self._connect()
            # lint: disable=lock-blocking-call -- a streaming reply owns the connection for its lifetime by design; nested requests from the stream-owner thread take a one-shot side connection instead of this lock
            yield from self._stream_frames(self._sock, msg_type, payload)
            done = True
        except RemoteError:
            done = True  # ERR terminates the stream; conn is sync'd
            raise
        except (ConnectionError, OSError):
            done = False
            raise
        finally:
            self._stream_owner = None
            if not done:
                self._drop_connection()
            self._lock.release()

    def resync_follower(self, snapshot_blob, step: int,
                        chunk_bytes: int = 8 << 20) -> Dict[str, Any]:
        """Stream a leader store snapshot (``checkpoint.dumps_store``
        bytes) to this daemon in bounded frames under the windowed-ack
        pipeline — follower resync with NO shared-filesystem
        assumption (the snapshot never touches the follower's disk).
        Chunks are memoryview slices of the blob riding out-of-band
        (zero copies leader-side)."""
        mv = memoryview(snapshot_blob)

        def chunks():
            for off in range(0, max(mv.nbytes, 1), chunk_bytes):
                yield {"blob": np.frombuffer(mv[off:off + chunk_bytes],
                                             np.uint8)}

        return self._bulk_request(
            MsgType.RESYNC_FOLLOWER,
            {"step": int(step), "nbytes": mv.nbytes}, chunks)

    def dedup_resident(self, sets: Sequence[Tuple[str, str]],
                       bands: int = 16, seed: int = 0) -> Dict[str, Any]:
        """Daemon-side block-level model dedup: shared blocks across the
        given weight sets materialize once in HBM (see
        ``Client.dedup_resident``). Returns the pooling report."""
        return self._request(MsgType.DEDUP_RESIDENT,
                             {"sets": [list(s) for s in sets],
                              "bands": bands, "seed": seed})

    def add_shared_mapping(self, private_db: str, private_set: str,
                           shared_db: str, shared_set: str,
                           mapping: Optional[Dict] = None) -> None:
        self._request(MsgType.ADD_SHARED_MAPPING, {
            "private_db": private_db, "private_set": private_set,
            "shared_db": shared_db, "shared_set": shared_set,
            "mapping": mapping})

    def flush_data(self) -> None:
        self._request(MsgType.FLUSH_DATA, {})

    def load_set(self, db: str, set_name: str) -> None:
        self._request(MsgType.LOAD_SET, {"db": db, "set": set_name})

    # --- stateful serving (serve/sessions.py) -------------------------
    @property
    def current_address(self) -> str:
        return f"{self.host}:{self.port}"

    def open_session(self, db: str, kind: str = "lstm",
                     ttl_s: Optional[float] = None,
                     heads: Optional[int] = None,
                     session_id: Optional[str] = None) -> "SessionHandle":
        """Open one interactive decode session over model ``db``.
        The session id is CLIENT-minted: the mirrored open replays at
        every follower with the same sid (handler-side minting would
        not reach them — mirror forwards copy the payload before the
        handler runs). Returns a :class:`SessionHandle` whose
        ``generate`` calls route sticky to the owning daemon."""
        sid = str(session_id or uuid.uuid4().hex)
        payload: Dict[str, Any] = {"op": "open", "sid": sid, "db": db,
                                   "kind": kind, SESSION_KEY: sid}
        if ttl_s is not None:
            payload["ttl_s"] = float(ttl_s)
        if heads is not None:
            payload["heads"] = int(heads)
        rep = self._request(MsgType.SESSION_OPEN, payload)
        return SessionHandle(self, sid, db, kind,
                             owner=rep.get("owner"),
                             spec=rep.get("spec"),
                             steps=int(rep.get("steps", 0)))

    # --- query execution ----------------------------------------------
    def execute_computations(self, *sinks, job_name: str = "remote-job",
                             materialize: bool = True,
                             fetch_results: bool = True,
                             explain: bool = False):
        """Ship the Computation DAG (cloudpickle — the analogue of
        shipping serialized Computations + registered UDF code) and run
        it on the daemon. Returns {ident: value} like the library
        client; ``fetch_results=False`` skips pulling result payloads
        (they stay resident server-side, the common serving pattern).

        ``explain=True`` is EXPLAIN ANALYZE: the daemon records every
        plan node's wall/device time, rows, chunk and cache/compile
        counters and round-trips the annotated tree — the return
        becomes ``(results, operators_tree)``. Render it with
        ``obs.operators.render_tree`` (what ``cli obs --explain``
        does)."""
        reply = self._request(
            MsgType.EXECUTE_COMPUTATIONS,
            {"sinks": list(sinks), "job_name": job_name,
             "materialize": materialize, "explain": bool(explain)},
            codec=CODEC_PICKLE)
        results = self._collect_results(reply["results"], fetch_results)
        if explain:
            tree = reply.get("operators")
            if reply.get("shard_operators") and isinstance(tree, dict):
                # scatter queries: the per-shard region forest rides
                # the coordinator tree (render with
                # obs.operators.render_shard_forest)
                tree = dict(tree,
                            shard_operators=reply["shard_operators"])
            return results, tree
        return results

    def execute_plan(self, plan_text: str, registry: Dict[str, Any],
                     job_name: str = "remote-plan", materialize: bool = True,
                     fetch_results: bool = True, explain: bool = False):
        """Pickle-free execution: ship plan text + label→entry-point
        registry; the daemon rebinds labels to registered types
        (``ParsedPlan.to_computations``). The TCAP path.
        ``explain=True`` returns ``(results, operators_tree)`` — see
        :meth:`execute_computations`."""
        reply = self._request(
            MsgType.EXECUTE_PLAN,
            {"plan": plan_text, "registry": registry, "job_name": job_name,
             "materialize": materialize, "explain": bool(explain)})
        results = self._collect_results(reply["results"], fetch_results)
        if explain:
            return results, reply.get("operators")
        return results

    def _collect_results(self, summaries: Dict[str, Any],
                         fetch: bool) -> Dict[RemoteIdent, Any]:
        out: Dict[RemoteIdent, Any] = {}
        for key, summary in summaries.items():
            db, _, set_name = key.partition(":")
            ident = RemoteIdent(db, set_name)
            if not fetch:
                out[ident] = summary
            elif summary.get("kind") == "tensor":
                out[ident] = self.get_tensor(db, set_name)
            else:
                items = list(self.get_set_iterator(db, set_name))
                out[ident] = dict(items) if summary.get("kind") == "map" \
                    else items
        return out

    def list_jobs(self) -> List[Dict[str, Any]]:
        return self._request(MsgType.LIST_JOBS, {})["jobs"]

    # --- stats --------------------------------------------------------
    def collect_stats(self) -> Dict[str, Any]:
        return self._request(MsgType.COLLECT_STATS, {})

    def get_trace(self, last: Optional[int] = None,
                  qid: Optional[str] = None,
                  slow: bool = False) -> Dict[str, Any]:
        """Completed query trace profiles from the daemon's ring
        (newest last). ``qid`` filters to one query; ``last`` bounds
        the count. On a leader, profiles carry ``followers`` sections
        merged by query id (one logical query decomposed across every
        daemon that ran it) and — for queries whose client shipped its
        spans via PUT_TRACE — a ``client`` section with the send/wait
        decomposition. ``slow=True`` reads the persisted slow-query
        ring (``<root>/slowlog/``) instead: the outliers that survived
        ring rotation and daemon restarts."""
        return self._request(MsgType.GET_TRACE,
                             {"last": last, "qid": qid,
                              "slow": bool(slow)})

    def health(self) -> Dict[str, Any]:
        """The daemon's SLO/health readout (obs/slo.py): evaluated
        objectives with multi-window burn rates, recent
        breach/recovery events and the slowlog summary; leaders merge
        follower sections (best-effort — a slow follower reports an
        error entry, never gets evicted by a health read)."""
        return self._request(MsgType.HEALTH, {})

    def get_metrics(self, format: Optional[str] = None,
                    window_s: Optional[float] = None) -> Dict[str, Any]:
        """Continuous telemetry (obs/history.py). Default: the
        registry snapshot + history summary + derived rates over
        ``window_s`` (QPS, staged MB/s, hit-rate trend — what ``cli
        obs --top`` refreshes from). ``format="openmetrics"``: the
        Prometheus text exposition instead (reply ``{"text": ...}``),
        with leader-merged follower samples."""
        payload: Dict[str, Any] = {}
        if format:
            payload["format"] = format
        if window_s is not None:
            payload["window_s"] = float(window_s)
        return self._request(MsgType.GET_METRICS, payload)

    def placement_view(self) -> Dict[str, Any]:
        """The leader's live placement table (serve/rebalance.py):
        per-slot owner/state/bytes/heat for every sharded set, the
        per-member heat/byte totals, the current skew ratio, and the
        rebalancer's status + last-move log — what ``cli obs
        --placement`` renders."""
        return self._request(MsgType.RESHARD, {"op": "view"},
                             codec=CODEC_PICKLE)

    def rebalance_status(self) -> Dict[str, Any]:
        """The rebalancer's own state (enabled/running/last skew
        ratio/streak/epoch + move log), without the per-slot join."""
        return self._request(MsgType.RESHARD, {"op": "status"},
                             codec=CODEC_PICKLE)

    def add_worker(self, addr: str,
                   campaign: bool = True) -> Dict[str, Any]:
        """Register one new pool worker on a live leader (the
        scale-out path the rebalancer treats as a forced trigger).
        ``campaign=False`` registers without moving anything."""
        return self._request(
            MsgType.RESHARD,
            {"op": "add_worker", "addr": str(addr),
             "campaign": bool(campaign)}, codec=CODEC_PICKLE)


class SessionHandle:
    """Client-side handle for one interactive decode session.

    Stickiness: ``generate`` targets the session's OWNER directly —
    the main client when the leader owns it, a cached single-attempt
    shard connection when a pool worker does. Every hop the session
    takes shows up as a typed retryable signal, and the handle owns
    the re-pointing loop (the shard clients are deliberately
    max_attempts=1, so no nested retry fights it):

    * ``SessionMoved`` — the refusal NAMES the new owner: re-point and
      retry immediately.
    * ``NotLeader`` — the leader moved: follow the named leader (or
      the main client's failover rotation) and re-LOOKUP the owner.
    * connection loss / timeout / other retryables — the owner (or
      mid-election leader) died: re-LOOKUP through the main client,
      whose own retry driver rides the succession list, then retry
      here with jittered backoff.

    Each logical step mints ONE idempotency token and resends it
    across every re-route, so an applied-but-unanswered step dedupes
    at whichever daemon applied it instead of double-advancing the
    state, and a step re-applied by a NEW owner after failover
    recomputes bit-identically from the last durable state."""

    def __init__(self, client: RemoteClient, sid: str, db: str,
                 kind: str, owner: Optional[str] = None,
                 spec: Optional[Dict[str, Any]] = None, steps: int = 0):
        self._client = client
        self.sid = sid
        self.db = db
        self.kind = kind
        self.owner = owner or client.current_address
        self.spec = spec or {}
        self.steps = int(steps)
        self.moves = 0  # typed re-points this handle performed
        self._rng = random.Random(sid)
        self._closed = False

    def _target(self) -> RemoteClient:
        if self.owner == self._client.current_address:
            return self._client
        return self._client._shard_client(self.owner)

    def _lookup(self) -> str:
        """Ask the (current) leader who owns the session — riding the
        main client's NotLeader/failover handling, and healing a
        dead-owner record leader-side."""
        rep = self._client._request(
            MsgType.SESSION_OPEN,
            {"op": "lookup", "sid": self.sid, "db": self.db})
        owner = rep.get("owner") or self._client.current_address
        if owner != self.owner:
            self.moves += 1
        self.owner = owner
        return owner

    def generate(self, x, deadline_s: float = 30.0) -> np.ndarray:
        """One decode step: returns the model's output row for this
        session. Retries typed-retryable failures (owner moves,
        failovers, deaths) under ``deadline_s`` with ONE idempotency
        token for the whole logical step."""
        if self._closed:
            raise RuntimeError(f"session {self.sid!r} is closed")
        payload = {"db": self.db, "set": self.sid, "sid": self.sid,
                   "x": np.asarray(x, np.float32),
                   SESSION_KEY: self.sid,
                   IDEMPOTENCY_KEY: uuid.uuid4().hex}
        deadline = deadline_after(deadline_s)
        attempt = 0
        while True:
            attempt += 1
            try:
                rep = self._target()._request(
                    MsgType.GENERATE, dict(payload),
                    codec=CODEC_PICKLE)
                new_owner = rep.get("owner")
                if new_owner and new_owner != self.owner:
                    self.moves += 1
                    self.owner = new_owner
                self.steps = int(rep.get("steps", self.steps + 1))
                return np.asarray(rep["y"])
            except SessionMovedError as e:
                self.moves += 1
                self.owner = getattr(e, "owner_addr", None) or \
                    self._safe_lookup(deadline, e)
            except NotLeaderError as e:
                addr = getattr(e, "leader_addr", None)
                if addr:
                    self._client._switch_address(addr)
                else:
                    self._client._rotate_failover()
                self._safe_lookup(deadline, e)
            except SessionUnknownError:
                raise
            except (RetryableRemoteError, ConnectionLostError,
                    RemoteTimeoutError, ConnectionError, OSError,
                    DeadlineExceededError) as e:
                # owner died or is mid-election: bounded backoff, then
                # re-discover through the main client's failover path
                if seconds_left(deadline) <= 0:
                    raise
                time.sleep(min(0.5, 0.05 * attempt
                               * (1.0 + self._rng.random())))
                self._safe_lookup(deadline, e)
            if seconds_left(deadline) <= 0:
                raise DeadlineExceededError(
                    "DeadlineExceeded",
                    f"generate deadline of {deadline_s}s exhausted "
                    f"after {attempt} attempt(s)")

    def _safe_lookup(self, deadline, cause) -> str:
        """Owner re-discovery that tolerates the election window: a
        failed lookup keeps the current owner and lets the outer loop
        back off and try again (bounded by the step's deadline)."""
        try:
            return self._lookup()
        except (RemoteError, ConnectionError, OSError):
            if seconds_left(deadline) <= 0:
                raise cause
            return self.owner

    def close(self, deadline_s: float = 10.0) -> bool:
        """Close the session everywhere (idempotent; the daemon's TTL
        sweep collects anything a lost close leaves behind)."""
        if self._closed:
            return False
        self._closed = True
        try:
            rep = self._client._request(
                MsgType.SESSION_CLOSE,
                {"sid": self.sid, "db": self.db, "set": self.sid},
                deadline_s=deadline_s)
            return bool(rep.get("closed"))
        except (RemoteError, ConnectionError, OSError):
            return False

    def __enter__(self) -> "SessionHandle":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __repr__(self) -> str:
        return (f"<SessionHandle {self.sid[:8]} db={self.db!r} "
                f"owner={self.owner} steps={self.steps}>")
