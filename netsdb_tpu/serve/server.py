"""Resident controller daemon — PDBServer + master functionalities.

One process plays the reference's master *and* worker roles: it owns the
TPU (single-controller JAX), the SetStore with device-resident weight
tensors, the catalog, and the compiled-plan cache — all of which stay
live across client sessions, the way netsDB's master runs forever with
model weight sets loaded while many clients run queries
(``src/mainServer/source/MasterMain.cc:64-96``,
``src/queries/headers/QueryClient.h:160-224``).

Structure mirrors ``PDBServer``: a listener thread accepts connections
and hands each to a worker thread; a handler map keyed by frame type
dispatches messages (``src/pdbServer/headers/PDBServer.h:39-152``, where
handlers are registered per object TYPEID). Query jobs additionally pass
through a bounded admission semaphore — the job-queue role of
``QuerySchedulerServer`` — so N clients can run concurrently without
overcommitting the controller.
"""

from __future__ import annotations

import importlib
import importlib.util
import inspect
import itertools
import socket
import threading
import time
import traceback
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from netsdb_tpu.client import Client
from netsdb_tpu.config import Configuration, DEFAULT_CONFIG
from netsdb_tpu.serve.protocol import (
    CODEC_MSGPACK,
    MsgType,
    ProtocolError,
    decode_body,
    recv_frame,
    recv_frame_raw,
    send_frame,
    tensor_from_wire,
)
from netsdb_tpu.storage.store import SetIdentifier


def resolve_entry_point(entry: str, source: Optional[str] = None) -> Any:
    """'pkg.mod:attr' → live object — the analogue of the reference
    loading a registered UDF .so and fixing up its vtable
    (``src/objectModel/headers/VTableMap.h:36-80``).

    ``source``: shipped module text from the catalog. If the module is
    not importable here, it is exec'd into a fresh module under the
    shipped name (the daemon-side ``dlopen`` of a replicated .so,
    ``PDBCatalog.h:45-50``). TRUST BOUNDARY: executing shipped source
    is code execution by design, exactly like the pickle codec
    (serve/protocol.py security note) and the reference's .so shipping
    — the serve layer is a trusted-cluster control plane behind the
    HELLO token."""
    mod_name, _, attr = entry.partition(":")
    try:
        obj = importlib.import_module(mod_name)
    except ModuleNotFoundError:
        if source is None:
            raise
        import sys

        spec = importlib.util.spec_from_loader(mod_name, loader=None)
        mod = importlib.util.module_from_spec(spec)
        exec(compile(source, f"<registered:{mod_name}>", "exec"),
             mod.__dict__)
        sys.modules[mod_name] = mod  # later imports see the shipped code
        obj = mod
    for part in attr.split(".") if attr else []:
        obj = getattr(obj, part)
    return obj


class _RWOrder:
    """Tiny readers-writer lock for mirrored-frame ordering: SET-scoped
    frames hold it shared (plus their per-set lock), global frames
    (jobs, flush, DDL without a set target) hold it exclusively — so
    frames on DIFFERENT sets run concurrently while anything that can
    observe multiple sets serializes against all of them."""

    def __init__(self):
        self._mu = threading.Lock()
        self._readers = 0
        self._no_readers = threading.Condition(self._mu)
        self._writer = threading.Lock()

    def acquire_read(self):
        self._writer.acquire()  # barrier: writers exclude new readers
        with self._mu:
            self._readers += 1
        self._writer.release()

    def release_read(self):
        with self._mu:
            self._readers -= 1
            if self._readers == 0:
                self._no_readers.notify_all()

    def acquire_write(self):
        self._writer.acquire()
        with self._mu:
            while self._readers:
                self._no_readers.wait()

    def release_write(self):
        self._writer.release()


class _FollowerLink:
    """One follower daemon's ordered frame pipe: a FIFO queue drained by
    a dedicated sender thread, so the follower receives mirrored frames
    in exactly the enqueue order while the enqueuer (and the master's
    handler) runs on. ``submit`` returns a record whose ``done`` event
    fires when the follower acked (or errored)."""

    def __init__(self, client):
        import queue

        self.client = client
        self.q: "queue.Queue" = queue.Queue()
        # submit/close are atomic under this lock, so every real item
        # precedes the close sentinel in the queue — nothing can be
        # enqueued behind it and wait forever on its "done" event
        self._lk = threading.Lock()
        self._closed = False
        self.thread = threading.Thread(target=self._drain, daemon=True)
        self.thread.start()

    def submit(self, typ, payload, codec) -> Dict[str, Any]:
        rec: Dict[str, Any] = {"done": threading.Event()}
        with self._lk:
            if self._closed:
                rec["error"] = (f"{self.client.host}:{self.client.port}: "
                                f"follower link closed (daemon shutdown)")
                rec["done"].set()
                return rec
            self.q.put((typ, payload, codec, rec))
        return rec

    def close(self) -> None:
        with self._lk:
            if self._closed:
                return
            self._closed = True
            self.q.put(None)

    def _drain(self) -> None:
        while True:
            item = self.q.get()
            if item is None:
                return
            typ, payload, codec, rec = item
            try:
                rec["reply"] = self.client._request(typ, payload, codec)
            except Exception as e:  # noqa: BLE001 — surfaced by caller
                rec["error"] = (f"{self.client.host}:{self.client.port}: "
                                f"{type(e).__name__}: {e}")
            finally:
                rec["done"].set()


class ServeController:
    """The daemon. ``start()`` runs the listener on a background thread
    (tests); ``serve_forever()`` blocks (the CLI ``serve`` command)."""

    #: frame types every worker must replay for SPMD consistency — the
    #: reference's DDL fan-out + job broadcast (DistributedStorageManager
    #: / HermesExecutionServer.cc:1225-1274). Reads stay master-local.
    MIRRORED = frozenset({
        MsgType.CREATE_DATABASE, MsgType.CREATE_SET, MsgType.REMOVE_SET,
        MsgType.CLEAR_SET, MsgType.REGISTER_TYPE, MsgType.SEND_DATA,
        MsgType.SEND_MATRIX, MsgType.ADD_SHARED_MAPPING,
        MsgType.FLUSH_DATA, MsgType.LOAD_SET,
        MsgType.EXECUTE_COMPUTATIONS, MsgType.EXECUTE_PLAN,
        MsgType.DEDUP_RESIDENT,
    })

    def __init__(self, config: Configuration = DEFAULT_CONFIG,
                 host: str = "127.0.0.1", port: int = 8108,
                 token: Optional[str] = None,
                 max_jobs: Optional[int] = None,
                 allow_pickle: bool = True,
                 followers: Optional[list] = None):
        """``followers``: addresses of worker daemons (one per other
        jax.distributed process). Every state-mutating/job frame this
        master handles is forwarded to them CONCURRENTLY with local
        execution — all processes then run the same SPMD program in the
        same order, which is what XLA's multi-controller collectives
        require (compilation is a rendezvous; sequential forwarding
        would deadlock it). The reference's master→worker job flow."""
        self.config = config
        self.host = host
        self.port = port
        self.token = token
        self.allow_pickle = allow_pickle
        # followers dial LAZILY (with retry) on the first mirrored
        # frame: a master may legitimately start before its workers
        # bind, and eager dialing would kill it with a raw
        # ConnectionRefusedError at startup
        self._follower_addrs: list = list(followers or [])
        self._followers: list = []
        self._links: list = []  # per-follower ordered sender queues
        self.library = Client(config)  # the resident state
        # ORDERING MODEL for mirrored frames (the SPMD argument):
        # - _mirror_lock is held only long enough to ENQUEUE a frame
        #   onto every follower's FIFO sender queue; the enqueue always
        #   happens while the frame's ORDERING lock (below) is held, so
        #   for any two frames that conflict, the master's local
        #   execution order equals every follower's receipt order —
        #   stores cannot silently diverge.
        # - jax.process_count() > 1 (true SPMD over the followers):
        #   EVERY mirrored frame serializes under _collective_lock
        #   across enqueue + local handler. Multi-controller XLA
        #   requires all processes to launch collective programs in one
        #   order, and any mutation can change what a later jitted job
        #   observes, so the only sound order is a total one — the same
        #   per-worker-connection serialization the reference's job
        #   flow has (PDBServer.h:39-152: concurrent handlers, but one
        #   socket per worker orders that worker's stream).
        # - process_count() == 1 (replicated-daemon topology, no
        #   cross-process collectives): SET-scoped frames serialize
        #   per (db,set) and hold _order shared; multi-set frames
        #   (jobs, flush) hold _order exclusively. Frames on different
        #   sets — the common ingest pattern — run concurrently, which
        #   is the round-4 concurrency win; reads never block on any
        #   of this.
        self._mirror_lock = threading.Lock()
        self._collective_lock = threading.Lock()
        self._order = _RWOrder()
        self._set_locks: Dict[Tuple[str, str], threading.Lock] = {}
        self._set_locks_mu = threading.Lock()
        self._jobs_sem = threading.Semaphore(max_jobs or config.num_threads)
        self._job_seq = itertools.count(1)
        self._jobs: Dict[int, Dict[str, Any]] = {}
        self._jobs_lock = threading.Lock()
        self._started = time.time()
        self._stop = threading.Event()
        self._listener: Optional[socket.socket] = None
        self._threads: list = []
        # handler map keyed by frame type — PDBServer::registerHandler
        self.handlers: Dict[MsgType, Callable[[Any], Tuple[MsgType, Any]]] = {
            MsgType.PING: self._on_ping,
            MsgType.CREATE_DATABASE: self._on_create_database,
            MsgType.CREATE_SET: self._on_create_set,
            MsgType.REMOVE_SET: self._on_remove_set,
            MsgType.CLEAR_SET: self._on_clear_set,
            MsgType.SET_EXISTS: self._on_set_exists,
            MsgType.LIST_SETS: self._on_list_sets,
            MsgType.REGISTER_TYPE: self._on_register_type,
            MsgType.SEND_DATA: self._on_send_data,
            MsgType.SEND_MATRIX: self._on_send_matrix,
            MsgType.GET_TENSOR: self._on_get_tensor,
            MsgType.SCAN_SET: self._on_scan_set,
            MsgType.SCAN_SET_STREAM: self._on_scan_set_stream,
            MsgType.GET_TENSOR_CHUNKED: self._on_get_tensor_chunked,
            MsgType.ADD_SHARED_MAPPING: self._on_add_shared_mapping,
            MsgType.DEDUP_RESIDENT: self._on_dedup_resident,
            MsgType.FLUSH_DATA: self._on_flush_data,
            MsgType.LOAD_SET: self._on_load_set,
            MsgType.EXECUTE_COMPUTATIONS: self._on_execute_computations,
            MsgType.EXECUTE_PLAN: self._on_execute_plan,
            MsgType.LIST_JOBS: self._on_list_jobs,
            MsgType.COLLECT_STATS: self._on_collect_stats,
            MsgType.ANALYZE_SET: self._on_analyze_set,
            MsgType.LOCAL_SHARDS: self._on_local_shards,
            MsgType.PAGED_MATMUL: self._on_paged_matmul,
        }

    # --- lifecycle ----------------------------------------------------
    def start(self) -> int:
        """Bind + start the listener thread; returns the bound port
        (``port=0`` picks an ephemeral one)."""
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, self.port))
        self._listener.listen(128)
        self.port = self._listener.getsockname()[1]
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="netsdb-serve-accept")
        t.start()
        self._threads.append(t)
        return self.port

    def serve_forever(self) -> None:
        if self._listener is None:
            self.start()
        try:
            while not self._stop.wait(0.5):
                pass
        except KeyboardInterrupt:
            pass
        finally:
            self.shutdown()

    def shutdown(self) -> None:
        self._stop.set()
        for link in self._links:
            link.close()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None

    # --- connection handling ------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, addr = self._listener.accept()
            except OSError:
                return  # listener closed
            t = threading.Thread(target=self._serve_connection,
                                 args=(conn, addr), daemon=True)
            t.start()

    def _serve_connection(self, conn: socket.socket, addr) -> None:
        with conn:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            try:
                typ, hello = recv_frame(conn, allow_pickle=False)
                if typ != MsgType.HELLO:
                    raise ProtocolError("expected HELLO")
                if self.token and hello.get("token") != self.token:
                    send_frame(conn, MsgType.ERR,
                               {"error": "AuthError", "message": "bad token"})
                    return
                send_frame(conn, MsgType.OK, {"server": "netsdb_tpu",
                                              "version": 2})
            except (ProtocolError, ConnectionError, OSError):
                return
            while not self._stop.is_set():
                try:
                    typ, codec_in, raw = recv_frame_raw(conn)
                except (ProtocolError, ConnectionError, OSError):
                    return
                try:
                    payload = decode_body(raw, codec_in, self.allow_pickle)
                except Exception as e:  # refused codec / corrupt body
                    try:
                        send_frame(conn, MsgType.ERR, {
                            "error": type(e).__name__, "message": str(e)})
                        continue
                    except OSError:
                        return
                if typ == MsgType.SHUTDOWN:
                    send_frame(conn, MsgType.OK, {})
                    self.shutdown()
                    return
                handler = self.handlers.get(typ)
                try:
                    if handler is None:
                        raise ProtocolError(f"no handler for {typ!r}")
                    if self._follower_addrs and typ in self.MIRRORED:
                        out = self._run_mirrored(typ, payload, codec_in,
                                                 handler)
                    else:
                        out = handler(payload)
                    if inspect.isgenerator(out):
                        # streaming handler: each yielded (type, payload
                        # [, codec]) goes out as its own frame; TCP
                        # backpressure bounds server buffering to ONE
                        # frame (the reference's page-by-page result
                        # streaming, FrontendQueryTestServer.cc:785-890).
                        # The contract: ends with STREAM_END, or ERR on
                        # a mid-stream failure — either way the
                        # connection stays frame-synchronized.
                        for frame in out:
                            if len(frame) == 3:
                                f_type, f_payload, f_codec = frame
                            else:
                                (f_type, f_payload), f_codec = frame, CODEC_MSGPACK
                            send_frame(conn, f_type, f_payload, f_codec)
                        continue
                    if len(out) == 3:  # handler picked the reply codec
                        reply_type, reply, codec = out
                    else:
                        reply_type, reply = out
                        codec = CODEC_MSGPACK
                    send_frame(conn, reply_type, reply, codec)
                except BrokenPipeError:
                    return
                except Exception as e:  # handler errors go back as ERR
                    try:
                        send_frame(conn, MsgType.ERR, {
                            "error": type(e).__name__,
                            "message": str(e),
                            "traceback": traceback.format_exc(limit=20),
                        })
                    except OSError:
                        return

    # --- multi-host mirroring (master → workers) ----------------------
    def _ensure_followers(self, timeout_s: float = 30.0) -> None:
        """Dial any not-yet-connected follower, retrying while it comes
        up (bring-up order between master and workers is free). Each
        follower gets a :class:`_FollowerLink` — a FIFO sender thread
        whose queue order IS the follower's frame order."""
        if len(self._followers) == len(self._follower_addrs):
            return
        from netsdb_tpu.serve.client import RemoteClient

        for addr in self._follower_addrs[len(self._followers):]:
            deadline = time.time() + timeout_s
            while True:
                try:
                    fc = RemoteClient(addr, token=self.token)
                    self._followers.append(fc)
                    self._links.append(_FollowerLink(fc))
                    break
                except OSError as e:
                    if time.time() >= deadline:
                        raise ConnectionError(
                            f"follower daemon {addr} unreachable after "
                            f"{timeout_s:.0f}s: {e}") from e
                    time.sleep(0.3)

    #: mirrored frames scoped to ONE (db, set) target — these serialize
    #: per set (and hold the RW order shared) in replicated-daemon
    #: topologies; everything else mirrored is multi-set and holds the
    #: RW order exclusively (ordering model in ``__init__``)
    SET_SCOPED_FRAMES = frozenset({
        MsgType.CREATE_SET, MsgType.REMOVE_SET, MsgType.CLEAR_SET,
        MsgType.SEND_DATA, MsgType.SEND_MATRIX, MsgType.LOAD_SET,
    })

    def _set_lock(self, db: str, set_name: str) -> threading.Lock:
        with self._set_locks_mu:
            return self._set_locks.setdefault((db, set_name),
                                              threading.Lock())

    def _run_mirrored(self, typ, payload, codec, handler):
        """Execute one mutating/job frame on EVERY process, holding the
        frame's ORDERING lock across both the follower enqueue and the
        local handler (see the ordering model in ``__init__`` — the
        lock choice is what keeps master execution order equal to
        follower receipt order for conflicting frames). Forwarding
        itself still overlaps local execution (the processes rendezvous
        inside XLA). A follower failure after local success is raised
        as a split-brain error: the cluster's stores have diverged and
        the operator must recover (the reference aborts the job the
        same way on worker failure)."""
        import jax

        if jax.process_count() > 1:
            # true SPMD: one total order for everything mirrored
            with self._collective_lock:
                return self._mirror_once(typ, payload, codec, handler)
        if typ in self.SET_SCOPED_FRAMES and "db" in payload \
                and "set" in payload:
            self._order.acquire_read()
            try:
                with self._set_lock(payload["db"], payload["set"]):
                    return self._mirror_once(typ, payload, codec, handler)
            finally:
                self._order.release_read()
        self._order.acquire_write()
        try:
            return self._mirror_once(typ, payload, codec, handler)
        finally:
            self._order.release_write()

    def _mirror_once(self, typ, payload, codec, handler):
        with self._mirror_lock:  # short: dial + ordered enqueue only
            self._ensure_followers()
            pending = [link.submit(typ, payload, codec)
                       for link in self._links]
        try:
            out = handler(payload)
        finally:
            for p in pending:
                p["done"].wait()
        errors = [p["error"] for p in pending if p.get("error")]
        if errors:
            raise RuntimeError(
                "follower(s) failed; stores may have diverged: "
                + "; ".join(errors))
        return out

    # --- job bookkeeping ----------------------------------------------
    def _run_job(self, job_name: str, fn: Callable[[], Any]) -> Any:
        job_id = next(self._job_seq)
        rec = {"id": job_id, "name": job_name, "status": "queued",
               "submitted": time.time(), "elapsed": None}
        with self._jobs_lock:
            self._jobs[job_id] = rec
            # bounded history so a long-lived daemon cannot grow this
            while len(self._jobs) > 1024:
                self._jobs.pop(next(iter(self._jobs)))
        with self._jobs_sem:
            rec["status"] = "running"
            t0 = time.perf_counter()
            try:
                out = fn()
                rec["status"] = "done"
                return out
            except Exception:
                rec["status"] = "failed"
                raise
            finally:
                rec["elapsed"] = time.perf_counter() - t0

    # --- handlers -----------------------------------------------------
    def _on_ping(self, p) -> Tuple[MsgType, Any]:
        with self._jobs_lock:
            done = sum(1 for j in self._jobs.values() if j["status"] == "done")
        return MsgType.OK, {"uptime": time.time() - self._started,
                            "jobs_done": done,
                            "sets": len(self.library.store.list_sets())}

    def _on_create_database(self, p):
        self.library.create_database(p["db"])
        return MsgType.OK, {}

    def _on_create_set(self, p):
        self.library.create_set(
            p["db"], p["set"], type_name=p.get("type_name", "tensor"),
            persistence=p.get("persistence", "transient"),
            eviction=p.get("eviction", "lru"),
            partition_lambda=p.get("partition_lambda"),
            placement=p.get("placement"),  # Placement.to_meta dict
            storage=p.get("storage", "memory"))
        return MsgType.OK, {}

    def _on_remove_set(self, p):
        self.library.remove_set(p["db"], p["set"])
        return MsgType.OK, {}

    def _on_clear_set(self, p):
        self.library.clear_set(p["db"], p["set"])
        return MsgType.OK, {}

    def _on_set_exists(self, p):
        return MsgType.OK, {"exists": self.library.set_exists(p["db"], p["set"])}

    def _on_list_sets(self, p):
        return MsgType.OK, {"sets": [list(i) for i in self.library.store.list_sets()]}

    def _on_register_type(self, p):
        self.library.register_type(p["type_name"], p["entry_point"],
                                   source=p.get("source"))
        return MsgType.OK, {}

    def _resolve_registered(self, name_or_entry: str) -> Any:
        """Resolve a registry value: a registered type name goes through
        the catalog (picking up shipped source for modules the daemon
        doesn't have installed); anything else is a raw entry point."""
        entry = self.library.catalog.get_type(name_or_entry)
        if entry is not None:
            return resolve_entry_point(
                entry, self.library.catalog.get_type_source(name_or_entry))
        return resolve_entry_point(name_or_entry)

    def _on_send_data(self, p):
        # objects arrive via the pickle codec (whole payload is a dict)
        if p.get("as_table"):
            # rows → one dictionary-encoded ColumnTable, sharded by the
            # set's placement (dispatcher page-building + partitioning);
            # append=True adds the batch instead of replacing
            t = self.library.send_table(p["db"], p["set"], p["items"],
                                        date_cols=p.get("date_cols", ()),
                                        append=bool(p.get("append")))
            return MsgType.OK, {"count": t.num_rows,
                                "columns": sorted(t.cols)}
        self.library.send_data(p["db"], p["set"], p["items"])
        return MsgType.OK, {"count": len(p["items"])}

    def _on_send_matrix(self, p):
        dense, block_shape = tensor_from_wire(p["tensor"])
        t = self.library.send_matrix(p["db"], p["set"], dense, block_shape)
        if t is None:
            # storage="paged" set: the matrix went into the arena, not
            # HBM — reply from the ingested array (there is no blocked
            # tensor to describe)
            return MsgType.OK, {"shape": list(dense.shape),
                                "dtype": str(np.asarray(dense).dtype),
                                "block_shape": None}
        return MsgType.OK, {"shape": list(t.shape), "dtype": str(t.dtype),
                            "block_shape": list(t.meta.block_shape)}

    def _on_paged_matmul(self, p):
        """stored @ rhs with the stored matrix streamed from the arena
        page by page — the daemon-side consumption path for paged
        TENSOR sets (whose GET_TENSOR deliberately raises)."""
        out = self.library.paged_matmul(p["db"], p["set"],
                                        np.asarray(p["rhs"]))
        return MsgType.OK, {"data": np.asarray(out)}

    def _on_get_tensor(self, p):
        t = self.library.get_tensor(p["db"], p["set"])
        # mesh-spanning placed tensors assemble via follower shards
        t = self._fetch_global(p["db"], p["set"], t)
        dense = np.asarray(t.to_dense())
        return MsgType.OK, {"data": dense,
                            "block_shape": list(t.meta.block_shape)}

    # --- multi-host reads of placed sets -----------------------------
    # A mesh-spanning jax.Array cannot be np.asarray'd on one process.
    # Reads therefore assemble the GLOBAL value host-side: the master
    # fills from its own addressable shards and asks each follower
    # daemon for its local shards over the serve protocol (LOCAL_SHARDS
    # frames) — the reference streaming each node's local pages to the
    # frontend (FrontendQueryTestServer.cc:785-890). Reads never enter
    # the SPMD program: no collectives, no frame-ordering constraints.

    @staticmethod
    def _item_leaves(item) -> Optional[Dict[str, Any]]:
        """Named jax.Array leaves of a stored item (None = host object)."""
        import jax

        from netsdb_tpu.core.blocked import BlockedTensor
        from netsdb_tpu.relational.table import ColumnTable

        if isinstance(item, ColumnTable):
            leaves = dict(item.cols)
            if item.valid is not None:
                leaves["__valid__"] = item.valid
            return leaves
        if isinstance(item, BlockedTensor):
            return {"data": item.data}
        if isinstance(item, jax.Array):
            return {"value": item}
        return None

    @staticmethod
    def _rebuild_item(item, arrays: Dict[str, np.ndarray]):
        from netsdb_tpu.core.blocked import BlockedTensor
        from netsdb_tpu.relational.table import ColumnTable

        if isinstance(item, ColumnTable):
            valid = arrays.pop("__valid__", None)
            return ColumnTable(arrays, dict(item.dicts), valid)
        if isinstance(item, BlockedTensor):
            return BlockedTensor(arrays["data"], item.meta)
        return arrays["value"]

    @staticmethod
    def _shard_ranges(shard, shape):
        return [[s.start or 0, s.stop if s.stop is not None else dim]
                for s, dim in zip(shard.index, shape)]

    def _on_local_shards(self, p):
        """Follower side: this process's addressable shards of one
        stored item's arrays, as (index ranges, raw buffer) pairs."""
        item = self._single_item(p["db"], p["set"])
        leaves = self._item_leaves(item)
        if leaves is None:
            return MsgType.OK, {"leaves": None}
        out = {}
        for name, arr in leaves.items():
            out[name] = [
                {"idx": self._shard_ranges(s, arr.shape),
                 "data": np.asarray(s.data)}
                for s in arr.addressable_shards]
        return MsgType.OK, {"leaves": out,
                            "shapes": {n: list(a.shape)
                                       for n, a in leaves.items()}}

    def _single_item(self, db: str, set_name: str):
        items = self.library.store.get_items(SetIdentifier(db, set_name))
        if len(items) != 1:
            raise ValueError(f"set {db}:{set_name} holds {len(items)} "
                             f"items; shard assembly expects 1")
        return items[0]

    def _fetch_global(self, db: str, set_name: str, item):
        """Item with every mesh-spanning array replaced by its full
        host value (local shards + follower LOCAL_SHARDS)."""
        import jax

        leaves = self._item_leaves(item)
        if leaves is None or all(
                (not isinstance(a, jax.Array)) or a.is_fully_addressable
                for a in leaves.values()):
            return item
        if self._single_item(db, set_name) is not item:
            raise ValueError(
                f"set {db}:{set_name}: shard assembly of mesh-spanning "
                f"arrays supports single-item sets only")
        from netsdb_tpu.serve.protocol import CODEC_MSGPACK

        # the WHOLE assembly — master-local shard copy AND follower
        # fetches — runs under the collective lock, which every
        # spanning mutation (EXECUTE_*/SEND_* in multi-process mode)
        # also holds: without it, a concurrent replacement could tear
        # the result into pre-mutation master halves + post-mutation
        # follower halves
        with self._collective_lock:
            # re-read under the lock: the set may have been replaced
            # while we waited
            item = self._single_item(db, set_name)
            leaves = self._item_leaves(item)
            out: Dict[str, np.ndarray] = {}
            covered: Dict[str, np.ndarray] = {}
            for name, arr in leaves.items():
                buf = np.empty(arr.shape, arr.dtype)
                cov = np.zeros(arr.shape, np.bool_)
                for s in arr.addressable_shards:
                    idx = tuple(slice(a, b) for a, b
                                in self._shard_ranges(s, arr.shape))
                    buf[idx] = np.asarray(s.data)
                    cov[idx] = True
                out[name] = buf
                covered[name] = cov
            with self._mirror_lock:
                self._ensure_followers()
                recs = [link.submit(MsgType.LOCAL_SHARDS,
                                    {"db": db, "set": set_name},
                                    CODEC_MSGPACK)
                        for link in self._links]
            for rec in recs:
                rec["done"].wait()
                if rec.get("error"):
                    raise RuntimeError(f"follower shard fetch failed: "
                                       f"{rec['error']}")
                for name, shards in (rec["reply"]["leaves"] or {}).items():
                    for sh in shards:
                        idx = tuple(slice(a, b) for a, b in sh["idx"])
                        out[name][idx] = sh["data"]
                        covered[name][idx] = True
            missing = [n for n, c in covered.items() if not c.all()]
            if missing:
                # e.g. a client reading through a WORKER daemon (no
                # follower links): returning np.empty garbage would be
                # silent corruption — reads of spanning sets must go to
                # the daemon that knows every holder
                raise RuntimeError(
                    f"set {db}:{set_name}: cannot assemble mesh-spanning "
                    f"columns {missing} — this daemon's local + follower "
                    f"shards do not cover the arrays (read through the "
                    f"master daemon)")
        return self._rebuild_item(item, out)

    def _scan_items(self, db: str, set_name: str):
        """Set scan for the wire: a paged set's PagedColumns handle is
        process-local (it wraps the native arena), so it ships as its
        HOST-assembled table (numpy columns — the device never sees a
        set that was paged because it does not fit; the STREAMED scan
        ships it page by page instead), and mesh-spanning placed items
        assemble their global value first (``_fetch_global``) — clients
        wanting summaries only should use ANALYZE_SET instead."""
        from netsdb_tpu.relational.outofcore import PagedColumns
        from netsdb_tpu.storage.paged import PagedObjects
        from netsdb_tpu.storage.store import _PagedMatrix

        for item in self.library.get_set_iterator(db, set_name):
            if isinstance(item, PagedColumns):
                yield item.to_host_table()
            elif isinstance(item, PagedObjects):
                # record pages stream as records (the handle is
                # process-local; in the STREAMED scan these pack into
                # adaptive bounded frames like any object items)
                yield from item
            elif isinstance(item, _PagedMatrix):
                # the handle is process-local (it wraps the native
                # arena + a lock); the matrix itself deliberately never
                # materializes — consume it with PAGED_MATMUL
                raise ValueError(
                    f"set {db}:{set_name} holds a PAGED matrix — it "
                    f"streams (PAGED_MATMUL) and cannot be scanned "
                    f"over the wire")
            else:
                yield self._fetch_global(db, set_name, item)

    def _on_scan_set(self, p):
        from netsdb_tpu.serve.protocol import CODEC_PICKLE

        items = list(self._scan_items(p["db"], p["set"]))
        # host objects are arbitrary Python → pickle codec on the reply
        return MsgType.OK, {"items": items}, CODEC_PICKLE

    @staticmethod
    def _stream_paged(pc):
        """One host-side compact chunk table per frame, straight off
        the arena stream — the paged relation never materializes on
        the device or as one wire blob."""
        import contextlib
        import pickle

        def gen():
            seq = 0
            with contextlib.closing(
                    pc.stream_host_tables(prefetch=2)) as chunks:
                for tbl in chunks:
                    blob = pickle.dumps([tbl],
                                        protocol=pickle.HIGHEST_PROTOCOL)
                    yield MsgType.STREAM_ITEM, {"seq": seq,
                                                "batch": blob,
                                                "paged_chunk": True}
                    seq += 1
            yield MsgType.STREAM_END, {"frames": seq, "items": seq}

        return gen()

    def _on_scan_set_stream(self, p):
        """Streamed scan: items go out in frames of ~``max_frame_bytes``
        of pickled payload each — the server never materializes the
        whole set's wire form, and TCP backpressure holds buffering to
        one frame (ref FrontendQueryTestServer.cc:785-890 paging results
        to the client page by page).

        Each frame is ONE pickled list of items (per-item pickling
        measured 11× slower at 50k small rows). The items-per-frame
        count adapts to the observed bytes-per-item of the previous
        frame (growth capped at 4×/frame), so a frame overshoots the
        budget only while item sizes are growing and re-converges on
        the next frame — bounded memory, amortized serialization.

        A PAGED set streams its pages directly: one host-side compact
        chunk table per frame straight off the arena stream — the
        relation never materializes on the device OR as one wire blob
        (the reference streaming each node's local pages to the client
        page by page, ``FrontendQueryTestServer.cc:785-890``)."""
        import pickle

        from netsdb_tpu.relational.outofcore import PagedColumns

        budget = int(p.get("max_frame_bytes") or (4 << 20))
        # cheap storage peek — listing a big (possibly spilled)
        # non-paged set's items here would double-iterate it
        pc = None
        store = getattr(self.library, "store", None)
        if store is not None:
            from netsdb_tpu.storage.store import SetIdentifier

            ident = SetIdentifier(p["db"], p["set"])
            if store.storage_of(ident) == "paged":
                items = store.get_items(ident)
                if len(items) == 1 and isinstance(items[0],
                                                  PagedColumns):
                    pc = items[0]
        if pc is not None:
            return self._stream_paged(pc)

        def stream():
            seq = 0
            total = 0
            # target starts at 1: the FIRST frame must not pack an
            # unmeasured batch (32 × 20 MB items would be a ~640 MB
            # frame — the exact both-ends spike streaming exists to
            # remove); the 4×/frame growth reaches steady state in a
            # handful of frames
            target = 1
            batch: list = []
            for item in self._scan_items(p["db"], p["set"]):
                batch.append(item)
                if len(batch) < target:
                    continue
                blob = pickle.dumps(batch,
                                    protocol=pickle.HIGHEST_PROTOCOL)
                yield MsgType.STREAM_ITEM, {"seq": seq, "batch": blob}
                seq += 1
                total += len(batch)
                per_item = max(len(blob) // len(batch), 1)
                target = max(1, min(budget // per_item, 4 * target))
                batch = []
            if batch:
                yield MsgType.STREAM_ITEM, {
                    "seq": seq,
                    "batch": pickle.dumps(batch,
                                          protocol=pickle.HIGHEST_PROTOCOL)}
                seq += 1
                total += len(batch)
            yield MsgType.STREAM_END, {"frames": seq, "items": total}

        return stream()

    def _on_get_tensor_chunked(self, p):
        """Chunked tensor pull: one meta frame, then the dense buffer in
        ``chunk_bytes`` slices, then STREAM_END. Bounds the *transfer*
        buffering to one chunk on each side (vs. a single frame holding
        the full payload twice); the dense host materialization itself
        is one copy, as in `_on_get_tensor`."""
        t = self.library.get_tensor(p["db"], p["set"])
        t = self._fetch_global(p["db"], p["set"], t)
        dense = np.ascontiguousarray(np.asarray(t.to_dense()))
        chunk = int(p.get("chunk_bytes") or (8 << 20))
        view = memoryview(dense).cast("B")
        nbytes = view.nbytes

        def stream():
            yield MsgType.STREAM_ITEM, {
                "seq": 0, "meta": {
                    "shape": list(dense.shape), "dtype": dense.dtype.str,
                    "block_shape": list(t.meta.block_shape),
                    "nbytes": nbytes,
                    "nchunks": max(1, -(-nbytes // chunk))}}
            seq = 1
            for off in range(0, max(nbytes, 1), chunk):
                yield MsgType.STREAM_ITEM, {
                    "seq": seq, "b": bytes(view[off:off + chunk])}
                seq += 1
            yield MsgType.STREAM_END, {"frames": seq}

        return stream()

    def _on_dedup_resident(self, p):
        """Pool shared blocks across resident model weight sets so
        fine-tuned variants share HBM (``Client.dedup_resident``) — the
        serve-time dedup flow (``SharedTensorBlockSet.h:25``)."""
        report = self.library.dedup_resident(
            [tuple(s) for s in p["sets"]], bands=int(p.get("bands", 16)),
            seed=int(p.get("seed", 0)))
        return MsgType.OK, report

    def _on_add_shared_mapping(self, p):
        self.library.add_shared_mapping(
            p["private_db"], p["private_set"], p["shared_db"], p["shared_set"],
            p.get("mapping"))
        return MsgType.OK, {}

    def _on_flush_data(self, p):
        self.library.flush_data()
        return MsgType.OK, {}

    def _on_load_set(self, p):
        self.library.store.load_set(SetIdentifier(p["db"], p["set"]))
        return MsgType.OK, {}

    @staticmethod
    def _sync_results(results: Dict[SetIdentifier, Any]) -> None:
        """Barrier on tensor results: the OK reply must mean the value
        exists, not that XLA enqueued it. A scalar reduce+pull is the
        only sync that holds over the controller↔device tunnel
        (block_until_ready returns early there)."""
        import jax.numpy as jnp

        from netsdb_tpu.core.blocked import BlockedTensor
        from netsdb_tpu.relational.table import ColumnTable

        for val in results.values():
            if isinstance(val, BlockedTensor):
                float(jnp.sum(val.data))
            elif isinstance(val, ColumnTable):
                float(jnp.sum(next(iter(val.cols.values()))
                              .astype(jnp.float32)))

    def _result_summaries(self, results: Dict[SetIdentifier, Any]) -> dict:
        from netsdb_tpu.core.blocked import BlockedTensor
        from netsdb_tpu.relational.table import ColumnTable

        out = {}
        for ident, val in results.items():
            if isinstance(val, BlockedTensor):
                out[str(ident)] = {"kind": "tensor", "shape": list(val.shape),
                                   "dtype": str(val.dtype)}
            elif isinstance(val, ColumnTable):
                out[str(ident)] = {"kind": "table", "rows": val.num_rows,
                                   "columns": sorted(val.cols)}
            elif isinstance(val, dict):
                out[str(ident)] = {"kind": "map", "count": len(val)}
            else:
                out[str(ident)] = {"kind": "objects",
                                   "count": len(list(val))}
        return out

    def _on_execute_computations(self, p):
        """Body (pickle codec): {sinks: [WriteSet...], job_name}. The
        DAG's callables were cloudpickled by the client — the analogue of
        ``executeComputations`` shipping serialized Computation objects
        whose code the worker loads from registered .so files."""
        sinks = p["sinks"]
        job_name = p.get("job_name", "remote-job")

        def run():
            results = self.library.execute_computations(
                *sinks, job_name=job_name,
                materialize=p.get("materialize", True))
            if p.get("sync", True):
                self._sync_results(results)
            return results

        results = self._run_job(job_name, run)
        return MsgType.OK, {"results": self._result_summaries(results)}

    def _on_execute_plan(self, p):
        """Body (msgpack): {plan: text, registry: {label: entry_point or
        {kwargs..., fn: entry_point}}, job_name}. Pickle-free remote
        execution: labels rebind to *registered* entry points, the
        TCAP-text path (``ComputePlan.cc:20-56`` reparsing TCAP at the
        worker and binding against registered types)."""
        from netsdb_tpu.plan.parser import parse_plan

        registry: Dict[str, Any] = {}
        for label, spec in (p.get("registry") or {}).items():
            if isinstance(spec, str):
                registry[label] = self._resolve_registered(spec)
            elif isinstance(spec, dict):
                kw = dict(spec)
                for k, v in list(kw.items()):
                    if isinstance(v, str) and ":" in v:
                        kw[k] = self._resolve_registered(v)
                registry[label] = kw
            else:
                raise ProtocolError(
                    f"registry entry for {label!r} must be an entry-point "
                    f"string or kwargs dict")
        sinks = parse_plan(p["plan"]).to_computations(registry)
        job_name = p.get("job_name", "remote-plan")

        def run():
            results = self.library.execute_computations(
                *sinks, job_name=job_name,
                materialize=p.get("materialize", True))
            if p.get("sync", True):
                self._sync_results(results)
            return results

        results = self._run_job(job_name, run)
        return MsgType.OK, {"results": self._result_summaries(results)}

    def _on_list_jobs(self, p):
        with self._jobs_lock:
            return MsgType.OK, {"jobs": [dict(j) for j in self._jobs.values()]}

    def _on_collect_stats(self, p):
        return MsgType.OK, {"sets": self.library.collect_stats(),
                            "cache": self.library.store.stats.as_dict()}

    def _on_analyze_set(self, p):
        """Planner statistics computed where the data lives — the
        summaries ship, the table stays (ref StorageCollectStats,
        ``PangeaStorageServer.h:48``). ColumnStats flatten to 4-int
        rows; dictionaries are lists of strings (msgpack-safe). A
        mesh-spanning placed table assembles its global columns first
        (stats need every host's rows)."""
        from netsdb_tpu.client import table_info
        from netsdb_tpu.relational.table import ColumnTable

        items = self.library.store.get_items(
            SetIdentifier(p["db"], p["set"]))
        if len(items) == 1 and isinstance(items[0], ColumnTable):
            info = table_info(
                self._fetch_global(p["db"], p["set"], items[0]))
        else:
            info = self.library.analyze_set(p["db"], p["set"])
        return MsgType.OK, {
            "num_rows": int(info["num_rows"]),
            "dicts": {k: list(v) for k, v in info["dicts"].items()},
            "stats": {k: [s.n_rows, s.min_val, s.max_val, s.n_distinct]
                      for k, s in info["stats"].items()}}


def run_daemon(config: Configuration, host: str = "127.0.0.1",
               port: int = 8108, token: Optional[str] = None,
               max_jobs: Optional[int] = None,
               followers: Optional[list] = None) -> int:
    """Start a daemon and block until shutdown — shared by the CLI
    ``serve`` subcommand and :func:`main`. ``followers``: worker-daemon
    addresses for multi-host fan-out (one per other jax.distributed
    process; call ``parallel.distributed.initialize_cluster`` first)."""
    ctl = ServeController(config, host=host, port=port, token=token,
                          max_jobs=max_jobs, followers=followers)
    bound = ctl.start()
    print(f"netsdb_tpu serving on {host}:{bound}", flush=True)
    ctl.serve_forever()
    return 0


def main(argv=None) -> int:
    """``python -m netsdb_tpu.serve.server`` — standalone daemon entry
    (the CLI's ``serve`` subcommand wraps this)."""
    import argparse

    ap = argparse.ArgumentParser(prog="netsdb-tpu-serve")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8108)
    ap.add_argument("--root", default=None, help="database root dir")
    ap.add_argument("--token", default=None, help="shared auth token")
    ap.add_argument("--max-jobs", type=int, default=None)
    ap.add_argument("--followers", default=None,
                    help="comma-separated worker daemon addresses for "
                         "multi-host fan-out (jax.distributed must be "
                         "initialized in every process)")
    args = ap.parse_args(argv)
    config = Configuration(root_dir=args.root) if args.root else DEFAULT_CONFIG
    followers = ([a.strip() for a in args.followers.split(",") if a.strip()]
                 if args.followers else None)
    return run_daemon(config, host=args.host, port=args.port,
                      token=args.token, max_jobs=args.max_jobs,
                      followers=followers)


if __name__ == "__main__":
    raise SystemExit(main())
