"""Resident controller daemon — PDBServer + master functionalities.

One process plays the reference's master *and* worker roles: it owns the
TPU (single-controller JAX), the SetStore with device-resident weight
tensors, the catalog, and the compiled-plan cache — all of which stay
live across client sessions, the way netsDB's master runs forever with
model weight sets loaded while many clients run queries
(``src/mainServer/source/MasterMain.cc:64-96``,
``src/queries/headers/QueryClient.h:160-224``).

Structure mirrors ``PDBServer``: a listener thread accepts connections
and hands each to a worker thread; a handler map keyed by frame type
dispatches messages (``src/pdbServer/headers/PDBServer.h:39-152``, where
handlers are registered per object TYPEID). Query jobs additionally pass
through a bounded admission semaphore — the job-queue role of
``QuerySchedulerServer`` — so N clients can run concurrently without
overcommitting the controller.
"""

from __future__ import annotations

import contextlib
import contextvars
import importlib
import importlib.util
import inspect
import itertools
import os
import socket
import threading
import time
import traceback
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from netsdb_tpu import obs
from netsdb_tpu.client import Client
from netsdb_tpu.config import Configuration, DEFAULT_CONFIG
from netsdb_tpu.serve import sched as _sched
from netsdb_tpu.serve import placement as _placement
from netsdb_tpu.serve import rebalance as _rebalance
from netsdb_tpu.serve import shard as _shard
from netsdb_tpu.serve import ha as _ha
from netsdb_tpu.serve import sessions as _sessions
from netsdb_tpu.serve.sched.sessions import DECODE_LANE
from netsdb_tpu.serve.errors import (
    BACKPRESSURE_FIELDS,
    AdmissionFull,
    CorruptFrame,
    FollowerDegraded,
    LaneSaturated,
    NotLeader,
    NotLeaderError,
    PlacementStale,
    RequestInFlight,
    ShardUnavailable,
)
from netsdb_tpu.serve.protocol import (
    CLIENT_ID_KEY,
    CODEC_MSGPACK,
    CODEC_PICKLE,
    HA_TERM_KEY,
    IDEMPOTENCY_KEY,
    LANE_KEY,
    MAX_FRAME_BYTES,
    PLACEMENT_EPOCH_KEY,
    PROTO_VERSION,
    QUERY_ID_KEY,
    SESSION_KEY,
    SHARD_SLOT_KEY,
    MsgType,
    ProtocolError,
    decode_body,
    recv_frame,
    recv_frame_raw,
    send_frame,
    tensor_from_wire,
)
from netsdb_tpu.storage.mutlog import MutationLog
from netsdb_tpu.storage.store import SetIdentifier
from netsdb_tpu.utils.locks import TrackedLock
from netsdb_tpu.utils.timing import deadline_after, seconds_left, wall_now

#: introspection/meta frame types — excluded from the serve.requests/
#: serve.requests_ok counters and the serve.request_s histogram the
#: SLO engine evaluates (monitoring must not move the SLOs it reads)
OBS_FRAMES = frozenset({MsgType.PING, MsgType.COLLECT_STATS,
                        MsgType.GET_TRACE, MsgType.PUT_TRACE,
                        MsgType.HEALTH, MsgType.GET_METRICS})

#: the in-flight frame's idempotency token, installed for the
#: handler's dynamic extent. The handoff path needs it: a batch
#: buffered for a degraded shard must drain under the CLIENT's token,
#: so a retry re-routed through the leader after the shard already
#: applied the original (reply lost, then eviction) deduplicates at
#: the shard instead of double-appending.
_idem_token_var: "contextvars.ContextVar[Optional[str]]" = \
    contextvars.ContextVar("netsdb_idem_token", default=None)


def resolve_entry_point(entry: str, source: Optional[str] = None) -> Any:
    """'pkg.mod:attr' → live object — the analogue of the reference
    loading a registered UDF .so and fixing up its vtable
    (``src/objectModel/headers/VTableMap.h:36-80``).

    ``source``: shipped module text from the catalog. If the module is
    not importable here, it is exec'd into a fresh module under the
    shipped name (the daemon-side ``dlopen`` of a replicated .so,
    ``PDBCatalog.h:45-50``). TRUST BOUNDARY: executing shipped source
    is code execution by design, exactly like the pickle codec
    (serve/protocol.py security note) and the reference's .so shipping
    — the serve layer is a trusted-cluster control plane behind the
    HELLO token."""
    mod_name, _, attr = entry.partition(":")
    try:
        obj = importlib.import_module(mod_name)
    except ModuleNotFoundError:
        if source is None:
            raise
        import sys

        spec = importlib.util.spec_from_loader(mod_name, loader=None)
        mod = importlib.util.module_from_spec(spec)
        exec(compile(source, f"<registered:{mod_name}>", "exec"),
             mod.__dict__)
        sys.modules[mod_name] = mod  # later imports see the shipped code
        obj = mod
    for part in attr.split(".") if attr else []:
        obj = getattr(obj, part)
    return obj


class _RWOrder:
    """Tiny readers-writer lock for mirrored-frame ordering: SET-scoped
    frames hold it shared (plus their per-set lock), global frames
    (jobs, flush, DDL without a set target) hold it exclusively — so
    frames on DIFFERENT sets run concurrently while anything that can
    observe multiple sets serializes against all of them."""

    def __init__(self):
        self._mu = threading.Lock()
        self._readers = 0
        self._no_readers = threading.Condition(self._mu)
        self._writer = threading.Lock()

    def acquire_read(self):
        self._writer.acquire()  # barrier: writers exclude new readers
        with self._mu:
            self._readers += 1
        self._writer.release()

    def release_read(self):
        with self._mu:
            self._readers -= 1
            if self._readers == 0:
                self._no_readers.notify_all()

    def acquire_write(self):
        self._writer.acquire()
        with self._mu:
            while self._readers:
                self._no_readers.wait()

    def release_write(self):
        self._writer.release()


class _FollowerLink:
    """One follower daemon's ordered frame pipe: a FIFO queue drained by
    a dedicated sender thread, so the follower receives mirrored frames
    in exactly the enqueue order while the enqueuer (and the master's
    handler) runs on. ``submit`` returns a record whose ``done`` event
    fires when the follower acked (or errored)."""

    def __init__(self, addr: str, client):
        import queue

        self.addr = addr
        self.client = client
        self.q: "queue.Queue" = queue.Queue()
        # submit/close are atomic under this lock, so every real item
        # precedes the close sentinel in the queue — nothing can be
        # enqueued behind it and wait forever on its "done" event
        self._lk = TrackedLock("_FollowerLink._lk")
        self._closed = False
        #: mutation-log END offset of the last frame this follower
        #: ACKED — the log-replay resync's resume position. Written
        #: only by the drain thread (FIFO: monotone by construction),
        #: read by the evictor after close(); None until the first
        #: logged frame acks (or when the mutation log is off).
        self.acked_offset: Optional[int] = None
        self.thread = threading.Thread(target=self._drain, daemon=True)
        self.thread.start()

    def submit(self, typ, payload, codec,
               offset: Optional[int] = None) -> Dict[str, Any]:
        """Enqueue one frame; ``offset`` is its mutation-log END
        offset (None when the frame was not logged — stats fan-outs,
        HA_STATE announcements, or the log is off)."""
        rec: Dict[str, Any] = {"done": threading.Event(),
                               "mutlog_off": offset}
        with self._lk:
            if self._closed:
                rec["error"] = (f"{self.addr}: follower link closed "
                                f"(evicted or daemon shutdown)")
                rec["done"].set()
                return rec
            self.q.put((typ, payload, codec, rec))
        return rec

    def close(self, abort: bool = False) -> None:
        """Stop the drain thread. ``abort=True`` additionally tears the
        client socket down from this thread, so a drain blocked in a
        recv on a hung follower fails immediately instead of holding
        mirror records (and their waiters) forever — the eviction
        path."""
        with self._lk:
            if not self._closed:
                self._closed = True
                self.q.put(None)
        if abort:
            self.client._force_close()

    def _drain(self) -> None:
        while True:
            item = self.q.get()
            if item is None:
                return
            typ, payload, codec, rec = item
            if self._closed:
                # evicted mid-queue: items behind the failed one must
                # fail fast, NOT re-dial the dead follower (the client
                # would reconnect with no timeout and could hang this
                # thread forever, un-abortable — the link is done).
                # Each such frame never reached the follower — counted
                # so operators see the divergence depth before the
                # resync closes it (COLLECT_STATS mirror section).
                obs.REGISTRY.counter("serve.mirror_dropped").inc()
                rec["error"] = (f"{self.addr}: follower link closed "
                                f"(evicted) — frame not forwarded")
                rec["done"].set()
                continue
            try:
                rec["reply"] = self.client._request(typ, payload, codec)
                if rec.get("mutlog_off") is not None:
                    self.acked_offset = rec["mutlog_off"]
            except Exception as e:  # noqa: BLE001 — surfaced by caller
                rec["error"] = (f"{self.addr}: {type(e).__name__}: {e}")
                rec["exc"] = e  # typed inspection (NotLeader fencing)
            finally:
                rec["done"].set()


class _IdempotencyCache:
    """Completed-reply cache keyed by client idempotency token — the
    server half of the at-most-once contract for mutating frames. A
    retry whose original is still executing parks on its event instead
    of re-running the handler (double-apply is the failure mode this
    whole class exists to prevent); a retry of a completed request gets
    the cached reply frame verbatim.

    ``persist_path`` (a sqlite file next to the catalog sqlite) makes
    completed tokens survive a daemon RESTART: without it the cache is
    in-memory only, so a client retrying a mutation across a restart
    would re-execute it (the double-apply the ROADMAP open item names).
    Replies persist pickled (the trusted-control-plane boundary, same
    as the checkpoint snapshots); unpicklable replies simply stay
    memory-only — the restart window then degrades to re-execution for
    that one request, never a crash. Rows are pruned to ``capacity``
    on the snapshot-prune path (:meth:`prune`)."""

    def __init__(self, capacity: int = 4096,
                 persist_path: Optional[str] = None):
        self._mu = TrackedLock("_IdempotencyCache._mu")
        self._done: "OrderedDict[str, Tuple]" = OrderedDict()
        self._inflight: Dict[str, threading.Event] = {}
        self._capacity = capacity
        self._db = None
        #: tokens answered from the persisted table (observability for
        #: the restart tests; memory hits don't count)
        self.persist_hits = 0
        self._since_prune = 0
        if persist_path:
            import sqlite3

            parent = os.path.dirname(persist_path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            # one connection, shared across handler threads under _mu.
            # WAL + synchronous=NORMAL: the per-mutation commit must
            # not fsync on the request path (durable across clean
            # restarts, which is the contract — a power loss losing the
            # last tokens degrades to re-execution, same as no cache)
            self._db = sqlite3.connect(persist_path,
                                       check_same_thread=False)
            try:
                self._db.execute("PRAGMA journal_mode=WAL")
                self._db.execute("PRAGMA synchronous=NORMAL")
            except sqlite3.Error:
                pass  # fall back to default journaling
            self._db.execute("CREATE TABLE IF NOT EXISTS idem "
                             "(token TEXT PRIMARY KEY, reply BLOB)")
            self._db.commit()

    def _load_persisted(self, token: str) -> Optional[Tuple]:
        """Caller holds ``_mu``. None on any persistence trouble — the
        worst case is re-execution, never a wedged request."""
        import pickle
        import sqlite3

        if self._db is None:
            return None
        try:
            row = self._db.execute(
                "SELECT reply FROM idem WHERE token = ?",
                (token,)).fetchone()
            if row is None:
                return None
            result = pickle.loads(row[0])
        except (sqlite3.Error, pickle.UnpicklingError, ValueError,
                EOFError, AttributeError, ImportError):
            return None
        self.persist_hits += 1
        self._done[token] = result
        return result

    def _persist(self, token: str, result: Tuple) -> None:
        """Caller holds ``_mu``. Best-effort: replies that cannot
        pickle (live buffers) or a busy sqlite stay memory-only."""
        import pickle
        import sqlite3

        if self._db is None:
            return
        try:
            blob = pickle.dumps(result, protocol=pickle.HIGHEST_PROTOCOL)
            self._db.execute(
                "INSERT OR REPLACE INTO idem (token, reply) VALUES (?, ?)",
                (token, blob))
            self._db.commit()
        except (sqlite3.Error, pickle.PicklingError, TypeError,
                ValueError):
            return

    def claim(self, token: str, wait_s: float) -> Optional[Tuple]:
        """Returns the cached (reply_type, reply, codec) when ``token``
        already completed; None when the caller now OWNS execution (it
        must call :meth:`finish` or :meth:`abort`). Raises
        :class:`RequestInFlight` when the original execution is still
        running after ``wait_s`` — the client backs off and retries."""
        deadline = deadline_after(wait_s)
        while True:
            with self._mu:
                if token in self._done:
                    self._done.move_to_end(token)
                    obs.REGISTRY.counter("serve.idem.memory_hits").inc()
                    return self._done[token]
                cached = self._load_persisted(token)
                if cached is not None:
                    obs.REGISTRY.counter("serve.idem.persist_hits").inc()
                    return cached
                ev = self._inflight.get(token)
                if ev is None:
                    self._inflight[token] = threading.Event()
                    return None
            left = seconds_left(deadline)
            if left <= 0 or not ev.wait(left):
                raise RequestInFlight(
                    f"duplicate request {token[:8]}… still executing "
                    f"after {wait_s}s")
            # original finished (or aborted) — loop to re-check

    def finish(self, token: str, result: Tuple) -> None:
        with self._mu:
            self._done[token] = result
            self._persist(token, result)
            self._since_prune += 1
            # a daemon with no followers never hits the snapshot-prune
            # path, so the table must self-bound too (cheap: one DELETE
            # per _capacity/4 inserts)
            prune_now = self._since_prune >= max(self._capacity // 4, 64)
            if prune_now:
                self._since_prune = 0
            while len(self._done) > self._capacity:
                self._done.popitem(last=False)
            ev = self._inflight.pop(token, None)
        if ev is not None:
            ev.set()
        if prune_now:
            self.prune()

    def abort(self, token: str) -> None:
        """The execution failed without a durable effect worth caching
        (transient fault) — release waiters so a retry re-executes."""
        with self._mu:
            ev = self._inflight.pop(token, None)
        if ev is not None:
            ev.set()

    def alias(self, token: str, target: str) -> bool:
        """Finish ``token`` with ``target``'s cached reply — the
        follower half of the TOKEN_ALIAS frame: a coalesce WAITER's
        token maps onto its leader's mirrored execution, so the
        waiter's post-failover retry dedupes here instead of
        re-executing. False when ``target`` is unknown (the alias
        outran or outlived the mirrored execution's cached reply —
        the retry then degrades to re-execution, never divergence)."""
        with self._mu:
            result = self._done.get(target)
            if result is not None:
                self._done.move_to_end(target)
            else:
                result = self._load_persisted(target)
            if result is None:
                return False
            self._done[token] = result
            self._persist(token, result)
            while len(self._done) > self._capacity:
                self._done.popitem(last=False)
            ev = self._inflight.pop(token, None)
        if ev is not None:
            ev.set()
        return True

    def prune(self) -> None:
        """Drop the oldest persisted tokens beyond ``capacity`` — runs
        on the existing snapshot-prune path (a flapping follower must
        not fill the leader's disk with either snapshots or tokens)."""
        import sqlite3

        with self._mu:
            if self._db is None:
                return
            try:
                self._db.execute(
                    "DELETE FROM idem WHERE rowid NOT IN (SELECT rowid "
                    "FROM idem ORDER BY rowid DESC LIMIT ?)",
                    (self._capacity,))
                self._db.commit()
            except sqlite3.Error:
                return

    def close(self) -> None:
        import sqlite3

        with self._mu:
            db, self._db = self._db, None
            if db is not None:
                try:
                    db.close()
                except sqlite3.Error:
                    pass


def _blob_view(b) -> memoryview:
    """Chunk blob → memoryview. Out-of-band blobs arrive as writable
    uint8 arrays, small inline ones as bytes; both are buffers."""
    return memoryview(b)


class _BulkAssembler:
    """Server half of one streamed-ingest conversation: ``add`` decodes
    a chunk as it lands (OUTSIDE any set lock — the windowed pipeline
    overlaps this work with the client's next sends), ``finish`` builds
    the payload the target op's handler applies under its normal
    ordering locks at COMMIT."""

    def __init__(self, meta: dict):
        self.meta = meta
        self.chunks = 0

    def add(self, payload: dict) -> None:
        raise NotImplementedError

    def finish(self) -> Tuple[dict, int]:
        raise NotImplementedError


class _ItemsAssembler(_BulkAssembler):
    """Pickled item batches (object rows / as_table row dicts)."""

    def __init__(self, meta: dict, allow_pickle: bool):
        super().__init__(meta)
        if not allow_pickle:
            raise ProtocolError(
                "bulk item ingest refused: chunks carry pickle and this "
                "daemon has allow_pickle off")
        self.items: list = []

    def add(self, payload: dict) -> None:
        import pickle

        self.items.extend(pickle.loads(_blob_view(payload["blob"])))
        self.chunks += 1

    def finish(self) -> Tuple[dict, int]:
        out = {"db": self.meta["db"], "set": self.meta["set"],
               "items": self.items}
        if self.meta.get("as_table"):
            out.update(as_table=True,
                       date_cols=list(self.meta.get("date_cols") or ()),
                       append=bool(self.meta.get("append")))
        return out, CODEC_PICKLE


class _TableAssembler(_BulkAssembler):
    """Row-range column slices of one ColumnTable: the full columns are
    preallocated from the BEGIN meta (``nrows``) on the first chunk and
    each chunk lands at its row offset INSIDE ``add`` — the assembly
    copy overlaps the client's in-flight sends instead of serializing
    at COMMIT. ``finish`` only rebuilds the table around the filled
    arrays (with the dictionaries that traveled once in BEGIN) after
    checking row coverage."""

    def __init__(self, meta: dict):
        super().__init__(meta)
        self.nrows = int(meta.get("nrows") or 0)
        self.cols: Optional[Dict[str, np.ndarray]] = None
        self.filled = 0

    def add(self, payload: dict) -> None:
        start, stop = (int(v) for v in payload["rows"])
        if self.cols is None:
            self.cols = {
                name: np.empty((self.nrows,) + np.asarray(arr).shape[1:],
                               np.asarray(arr).dtype)
                for name, arr in payload["cols"].items()}
        for name, arr in payload["cols"].items():
            self.cols[name][start:stop] = np.asarray(arr)
        self.filled += stop - start
        self.chunks += 1

    def finish(self) -> Tuple[dict, int]:
        from netsdb_tpu.relational.table import ColumnTable

        if self.filled != self.nrows or self.cols is None:
            raise CorruptFrame(
                f"bulk table stream covered {self.filled} of "
                f"{self.nrows} rows")
        table = ColumnTable(
            self.cols,
            {k: list(v) for k, v in (self.meta.get("dicts") or {}).items()},
            None)
        return {"db": self.meta["db"], "set": self.meta["set"],
                "items": table, "as_table": True,
                "date_cols": list(self.meta.get("date_cols") or ()),
                "append": bool(self.meta.get("append"))}, CODEC_PICKLE


class _BlobAssembler(_BulkAssembler):
    """Opaque byte stream (the wire-streamed RESYNC_FOLLOWER snapshot):
    chunks land in a preallocated buffer at their running offset."""

    def __init__(self, meta: dict):
        super().__init__(meta)
        self.buf = bytearray(int(meta.get("nbytes") or 0))
        self.off = 0

    def add(self, payload: dict) -> None:
        mv = _blob_view(payload["blob"])
        end = self.off + mv.nbytes
        if end > len(self.buf):
            # more bytes than BEGIN declared: a torn/duplicated stream
            # (or a lying peer) — refuse instead of growing unbounded
            raise CorruptFrame(
                f"bulk blob stream overflowed its declared "
                f"{len(self.buf)} bytes at offset {self.off}")
        self.buf[self.off:end] = mv
        self.off = end
        self.chunks += 1

    def finish(self) -> Tuple[dict, int]:
        out = dict(self.meta)
        out.pop("nbytes", None)
        out["snapshot_blob"] = memoryview(self.buf)[:self.off]  # no copy
        return out, CODEC_PICKLE


class ServeController:
    """The daemon. ``start()`` runs the listener on a background thread
    (tests); ``serve_forever()`` blocks (the CLI ``serve`` command)."""

    #: frame types every worker must replay for SPMD consistency — the
    #: reference's DDL fan-out + job broadcast (DistributedStorageManager
    #: / HermesExecutionServer.cc:1225-1274). Reads stay master-local.
    MIRRORED = frozenset({
        MsgType.CREATE_DATABASE, MsgType.CREATE_SET, MsgType.REMOVE_SET,
        MsgType.CLEAR_SET, MsgType.REGISTER_TYPE, MsgType.SEND_DATA,
        MsgType.SEND_MATRIX, MsgType.ADD_SHARED_MAPPING,
        MsgType.FLUSH_DATA, MsgType.LOAD_SET,
        MsgType.EXECUTE_COMPUTATIONS, MsgType.EXECUTE_PLAN,
        MsgType.DEDUP_RESIDENT,
        # the session lane: replaying opens/steps/closes at every
        # follower is what replicates the session table AND (decode
        # being deterministic) the per-session state itself — the
        # leader-kill chaos test's resume-with-no-token-reuse story
        MsgType.SESSION_OPEN, MsgType.GENERATE, MsgType.SESSION_CLOSE,
    })

    def __init__(self, config: Configuration = DEFAULT_CONFIG,
                 host: str = "127.0.0.1", port: int = 8108,
                 token: Optional[str] = None,
                 max_jobs: Optional[int] = None,
                 allow_pickle: bool = True,
                 followers: Optional[list] = None,
                 admission_timeout_s: float = 120.0,
                 frame_timeout_s: float = 30.0,
                 handshake_timeout_s: float = 10.0,
                 heartbeat_interval_s: float = 2.0,
                 heartbeat_timeout_s: float = 5.0,
                 heartbeat_misses: int = 3,
                 mirror_ack_timeout_s: Optional[float] = 300.0,
                 resync_grace_s: float = 30.0,
                 resync_timeout_s: float = 120.0,
                 workers: Optional[list] = None,
                 ha_peers: Optional[list] = None,
                 chaos=None, follower_chaos=None):
        """``followers``: addresses of worker daemons (one per other
        jax.distributed process). Every state-mutating/job frame this
        master handles is forwarded to them CONCURRENTLY with local
        execution — all processes then run the same SPMD program in the
        same order, which is what XLA's multi-controller collectives
        require (compilation is a rendezvous; sequential forwarding
        would deadlock it). The reference's master→worker job flow.

        ``workers``: addresses of SHARD daemons forming this leader's
        partitioned worker pool (the horizontal scale-out topology —
        distinct from ``followers``, which mirror for redundancy; the
        two pools are orthogonal and a sharded set's pages are never
        mirrored beyond the leader's own slot). Sets created with
        ``placement="hash"``/``"range"`` partition their pages across
        ``[this daemon] + workers``; ingest routes to owning shards,
        queries scatter-gather (``serve/shard.py``), and the leader
        owns the versioned placement map shipped in the handshake.
        Plain ``placement=None`` sets are untouched — the
        single-daemon and mirror paths stay byte-for-byte identical.

        Fault-tolerance knobs (defaults are production-shaped; the
        chaos tests shrink them):

        * ``admission_timeout_s`` — how long a job waits for an
          admission slot before the typed retryable ``AdmissionFull``.
        * ``frame_timeout_s`` — mid-frame recv bound per worker thread
          (a peer silent mid-frame can never wedge a handler), and the
          bound a duplicate idempotent request waits for its original.
        * ``heartbeat_*`` — leader→follower liveness probing over a
          dedicated connection; ``heartbeat_misses`` consecutive
          failures evict the follower into the degraded state.
        * ``mirror_ack_timeout_s`` — bound on waiting for a follower's
          mirror ack before evicting it (None = wait forever).
        * ``resync_grace_s`` — how long a mutating frame waits for an
          in-progress follower resync before the typed retryable
          ``FollowerDegraded``.
        * ``chaos``/``follower_chaos`` — explicit
          :class:`~netsdb_tpu.serve.chaos.ChaosInjector` objects for
          the client-facing and the leader→follower frame paths
          (tests only; production pays one ``is None`` check).

        ``ha_peers``: the ordered succession list arming automatic
        failover (``serve/ha.py``) — index 0 is the initial leader,
        every daemon in the pool passes the SAME list. Armed at the
        end of :meth:`start` (equivalently: call :meth:`arm_ha` after
        start). Orthogonal to ``followers``/``workers``: HA decides
        WHO leads; the mirror stream is still what carries the data."""
        self.config = config
        self.host = host
        self.port = port
        self.token = token
        self.allow_pickle = allow_pickle
        self.admission_timeout_s = admission_timeout_s
        self.frame_timeout_s = frame_timeout_s
        self.handshake_timeout_s = handshake_timeout_s
        self.heartbeat_interval_s = heartbeat_interval_s
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.heartbeat_misses = heartbeat_misses
        self.mirror_ack_timeout_s = mirror_ack_timeout_s
        self.resync_grace_s = resync_grace_s
        self.resync_timeout_s = resync_timeout_s
        self._chaos = chaos
        self._follower_chaos = follower_chaos
        # followers dial LAZILY (with retry) on the first mirrored
        # frame: a master may legitimately start before its workers
        # bind, and eager dialing would kill it with a raw
        # ConnectionRefusedError at startup
        self._follower_addrs: list = list(followers or [])
        # addr → _FollowerLink (active, mirrored-to) and addr → reason
        # (degraded: evicted, awaiting reattach+resync). Both guarded
        # by _followers_mu; a follower address is always in exactly one
        # of {undialed, active, degraded}.
        self._links: Dict[str, _FollowerLink] = {}
        self._degraded: Dict[str, str] = {}
        self._followers_mu = TrackedLock("ServeController._followers_mu")
        # --- sharded worker pool (horizontal scale-out) ---------------
        # the leader's authoritative set→shard map (empty on plain
        # daemons — every placement probe then answers None and the
        # un-sharded paths run unchanged)
        self._worker_addrs: list = list(workers or [])
        self.placement = _placement.PlacementMap()
        # worker-side registrations: (db, set) → {"epoch", "slot"} for
        # sets this daemon holds ONE slot of (written by CREATE_SET's
        # __shard__ marker and SHARD_RESYNC, read on every routed frame)
        self._shard_sets: Dict[Tuple[str, str], Dict[str, int]] = {}
        self._shard_mu = TrackedLock("ServeController._shard_mu")
        # live-rebalance move state (serve/rebalance.py), guarded by
        # _shard_mu with the registrations they fence: write-seals
        # ((db, set) → monotonic expiry — sealed slots answer routed
        # writes typed retryable while their copy drains) and move
        # tombstones (scopes whose local copy a committed move
        # dropped — stale-epoch frames must reject, never apply into
        # the cleared set)
        self._reshard_seals: Dict[Tuple[str, str], float] = {}
        self._reshard_moved: set = set()
        # --- HA runtime (serve/ha.py) ---------------------------------
        # armed by arm_ha() / the ha_peers ctor list; None keeps every
        # single-daemon and plain-mirror path byte-identical
        self._ha: Optional[_ha.HAState] = None
        self._ha_monitor: Optional[_ha.HAMonitor] = None
        self._ha_peers: list = list(ha_peers or [])
        # per-follower mutation-log resume offsets: the END offset of
        # the last frame each (possibly former) follower is known to
        # have applied — written at eviction (link.acked_offset) and
        # after every resync; guarded by _followers_mu
        self._follower_offsets: Dict[str, int] = {}
        # durable mutation log (config.ha_mutlog): the mirror path
        # appends every forwarded frame, so a readmitted follower
        # resyncs by log REPLAY from its last applied offset instead
        # of a whole-store snapshot; `spill` is the handoff buffer's
        # disk shadow — buffered routed ingest survives leader restart
        self.mutlog: Optional[MutationLog] = None
        spill: Optional[MutationLog] = None
        if getattr(config, "ha_mutlog", False):
            self.mutlog = MutationLog(os.path.join(
                config.root_dir, "mutlog", "mirror.log"))
            spill = MutationLog(os.path.join(
                config.root_dir, "mutlog", "handoff.log"))
        # pool connections + handoff buffers + the scatter coordinator
        self.shards = _shard.ShardPool(
            self, handoff_max_bytes=getattr(config,
                                            "shard_handoff_bytes",
                                            256 << 20),
            spill=spill)
        # inbound distributed-shuffle buckets (shard side)
        self._shuffle = _shard.ShuffleInbox()
        # the self-rebalancing loop's leader-side driver: skew
        # detector on the sched-feedback cadence + the byte-bounded
        # move planner/executor (no-op until config.rebalance)
        self.rebalancer = _rebalance.Rebalancer(self)
        #: this daemon's pool identity — rewritten by start() once the
        #: real port is bound (port=0 tests)
        self.advertise_addr = f"{host}:{port}"
        # the runtime lock-order witness (utils/locks.py): config-
        # gated so a production daemon can run lockdep-style checks
        if getattr(config, "lock_witness", False):
            from netsdb_tpu.utils.locks import enable_witness

            enable_witness()
        # set while a follower resync holds the mutation path; mutating
        # frames wait for it (bounded by resync_grace_s) then fail typed
        self._resync_idle = threading.Event()
        self._resync_idle.set()
        self._resync_seq = itertools.count(1)
        #: how the last RESYNC_FOLLOWER restored ("wire" | "path") —
        #: observability for the no-shared-fs acceptance test
        self.last_resync_mode: Optional[str] = None
        # completed-token cache persists NEXT TO the catalog sqlite so
        # a daemon restart cannot double-apply a mutation retried
        # across it (ROADMAP: idempotency across daemon restarts)
        self._idem = _IdempotencyCache(persist_path=os.path.join(
            os.path.dirname(config.catalog_path), "idempotency.sqlite"))
        # query-scoped observability: this daemon's completed trace
        # profiles (GET_TRACE source) — per-controller, NOT the
        # process-default ring, so leader/follower pairs in one test
        # process keep distinct profiles
        self._obs_enabled = bool(getattr(config, "obs_enabled", True))
        self.trace_ring = obs.TraceRing(
            getattr(config, "obs_trace_ring", 64) or 64)
        # the ACTIVE observability layer (this PR): SLO/health engine
        # over the registry (HEALTH frame), the bounded on-disk
        # slow-query ring, and the opt-in per-qid device profiler
        from netsdb_tpu.obs.slo import SLOEngine
        from netsdb_tpu.obs.slowlog import SlowQueryLog

        self.slo = SLOEngine()
        # continuous telemetry: the bounded registry-snapshot ring the
        # GET_METRICS deltas and `cli obs --top` refresh from; the
        # thread starts with the listener and is JOINED at shutdown
        from netsdb_tpu.obs.history import TelemetryHistory

        self.history = TelemetryHistory(
            capacity=getattr(config, "obs_history_len", 120) or 0,
            interval_s=getattr(config, "obs_history_interval_s", 5.0)
            or 0.0)
        self.slowlog = SlowQueryLog(
            config.root_dir,
            capacity=getattr(config, "obs_slowlog_entries", 64) or 64,
            threshold_s=getattr(config, "obs_slow_query_s", None))
        self._device_profile_dir = getattr(
            config, "obs_device_profile_dir", None)
        # one jax.profiler session at a time: concurrent traced queries
        # SKIP (non-blocking acquire), never queue behind the profiler
        self._profiler_mu = TrackedLock("ServeController._profiler_mu")
        self.library = Client(config)  # the resident state
        # the stateful-serving subsystem (serve/sessions.py): session
        # table + host arena + per-model decode batcher, TTL'd mutable
        # state in the devcache above. Constructed unconditionally —
        # a daemon with no sessions pays one idle object
        self.sessions = _sessions.SessionManager(self)
        # ORDERING MODEL for mirrored frames (the SPMD argument):
        # - _mirror_lock is held only long enough to ENQUEUE a frame
        #   onto every follower's FIFO sender queue; the enqueue always
        #   happens while the frame's ORDERING lock (below) is held, so
        #   for any two frames that conflict, the master's local
        #   execution order equals every follower's receipt order —
        #   stores cannot silently diverge.
        # - jax.process_count() > 1 (true SPMD over the followers):
        #   EVERY mirrored frame serializes under _collective_lock
        #   across enqueue + local handler. Multi-controller XLA
        #   requires all processes to launch collective programs in one
        #   order, and any mutation can change what a later jitted job
        #   observes, so the only sound order is a total one — the same
        #   per-worker-connection serialization the reference's job
        #   flow has (PDBServer.h:39-152: concurrent handlers, but one
        #   socket per worker orders that worker's stream).
        # - process_count() == 1 (replicated-daemon topology, no
        #   cross-process collectives): SET-scoped frames serialize
        #   per (db,set) and hold _order shared; multi-set frames
        #   (jobs, flush) hold _order exclusively. Frames on different
        #   sets — the common ingest pattern — run concurrently, which
        #   is the round-4 concurrency win; reads never block on any
        #   of this.
        self._mirror_lock = TrackedLock("ServeController._mirror_lock")
        self._collective_lock = TrackedLock(
            "ServeController._collective_lock")
        self._order = _RWOrder()
        # per-set locks share ONE witness rank: lock LEVELS order, not
        # instances (two different sets' locks never nest)
        self._set_locks: Dict[Tuple[str, str], TrackedLock] = {}
        self._set_locks_mu = TrackedLock("ServeController._set_locks_mu")
        # the query scheduler (serve/sched/): policy-driven admission
        # replacing the old bare bounded semaphore — per-client lanes
        # with quotas/aging, identical-EXECUTE coalescing, and
        # cache-aware hot-set affinity driven by the devcache probe
        self.sched = _sched.QueryScheduler(
            slots=max_jobs or config.num_threads,
            lanes=getattr(config, "sched_lanes", None),
            quota=getattr(config, "sched_lane_quota", 0),
            aging_every=getattr(config, "sched_aging_every", 8),
            coalesce=getattr(config, "sched_coalesce", True),
            affinity=getattr(config, "sched_affinity", True),
            affinity_wait_s=getattr(config, "sched_affinity_wait_s",
                                    30.0),
            # a coalesced waiter waits out the same bound a mirror ack
            # gets: EXECUTEs may legitimately run for minutes, but a
            # hung leader must never wedge waiter handler threads
            coalesce_wait_s=mirror_ack_timeout_s or 300.0,
            coalesce_done_ttl_s=getattr(
                config, "sched_coalesce_done_ttl_s", 0.0),
            coalesce_done_max=getattr(
                config, "sched_coalesce_done_max", 32),
            cache_probe=self._devcache_warm,
            feedback=getattr(config, "sched_feedback", False),
            feedback_every=getattr(config, "sched_feedback_every", 64),
            # SLO burn-rate load shedding (opt-in): the scheduler
            # halves the heaviest non-reserved lane's quota while any
            # objective breaches on all windows (obs/slo.py's
            # multi-window agreement), restoring on recovery
            slo_source=(self.slo.breached_objectives
                        if getattr(config, "sched_slo_shed", False)
                        else None),
            # pin-budget auto-sizing: when the static knob is unset,
            # the feedback cadence re-derives the devcache hot-prefix
            # pin budget from the attribution ledger's hot-set table
            # (serve/sched/feedback.pin_budget — pinned formula)
            pin_auto=(self._refresh_pin_auto
                      if (getattr(config, "device_cache_pin_auto",
                                  False)
                          and not getattr(config,
                                          "device_cache_pin_bytes", 0))
                      else None),
            # live shard rebalancing: one skew-detector pass per
            # feedback window (serve/rebalance.py) — the loop that
            # turns sustained per-slot imbalance into bounded,
            # epoch-bumped slot moves
            rebalance_cb=(self.rebalancer.check
                          if getattr(config, "rebalance", False)
                          else None))
        self._job_seq = itertools.count(1)
        self._jobs: Dict[int, Dict[str, Any]] = {}
        self._jobs_lock = TrackedLock("ServeController._jobs_lock")
        self._started = time.monotonic()  # uptime only — never wall
        self._stop = threading.Event()
        self._listener: Optional[socket.socket] = None
        # live accepted sockets — shutdown() half-closes them so a
        # "killed" daemon stops serving established connections too
        # (idle handler threads block in recv and never see _stop;
        # without this a dead worker could still ACK decode steps into
        # state nobody will ever push home)
        self._conns: set = set()
        self._conns_mu = TrackedLock("ServeController._conns_mu")
        self._threads: list = []
        # health/pool loop handles — promotion must be able to start
        # them on a daemon that booted with neither role
        self._health_thread: Optional[threading.Thread] = None
        self._pool_thread: Optional[threading.Thread] = None
        # handler map keyed by frame type — PDBServer::registerHandler
        self.handlers: Dict[MsgType, Callable[[Any], Tuple[MsgType, Any]]] = {
            MsgType.PING: self._on_ping,
            MsgType.CREATE_DATABASE: self._on_create_database,
            MsgType.CREATE_SET: self._on_create_set,
            MsgType.REMOVE_SET: self._on_remove_set,
            MsgType.CLEAR_SET: self._on_clear_set,
            MsgType.SET_EXISTS: self._on_set_exists,
            MsgType.LIST_SETS: self._on_list_sets,
            MsgType.REGISTER_TYPE: self._on_register_type,
            MsgType.SEND_DATA: self._on_send_data,
            MsgType.SEND_MATRIX: self._on_send_matrix,
            MsgType.GET_TENSOR: self._on_get_tensor,
            MsgType.SCAN_SET: self._on_scan_set,
            MsgType.SCAN_SET_STREAM: self._on_scan_set_stream,
            MsgType.GET_TENSOR_CHUNKED: self._on_get_tensor_chunked,
            MsgType.ADD_SHARED_MAPPING: self._on_add_shared_mapping,
            MsgType.DEDUP_RESIDENT: self._on_dedup_resident,
            MsgType.FLUSH_DATA: self._on_flush_data,
            MsgType.LOAD_SET: self._on_load_set,
            MsgType.EXECUTE_COMPUTATIONS: self._on_execute_computations,
            MsgType.EXECUTE_PLAN: self._on_execute_plan,
            MsgType.LIST_JOBS: self._on_list_jobs,
            MsgType.COLLECT_STATS: self._on_collect_stats,
            MsgType.GET_TRACE: self._on_get_trace,
            MsgType.PUT_TRACE: self._on_put_trace,
            MsgType.HEALTH: self._on_health,
            MsgType.GET_METRICS: self._on_get_metrics,
            MsgType.ANALYZE_SET: self._on_analyze_set,
            MsgType.LOCAL_SHARDS: self._on_local_shards,
            MsgType.PAGED_MATMUL: self._on_paged_matmul,
            MsgType.RESYNC_FOLLOWER: self._on_resync_follower,
            MsgType.PLACEMENT: self._on_placement,
            MsgType.SUBPLAN: self._on_subplan,
            MsgType.SHUFFLE_PUT: self._on_shuffle_put,
            MsgType.SHARD_RESYNC: self._on_shard_resync,
            MsgType.HA_STATE: self._on_ha_state,
            MsgType.TOKEN_ALIAS: self._on_token_alias,
            MsgType.RESHARD: self._on_reshard,
            MsgType.SESSION_OPEN: self._on_session_open,
            MsgType.GENERATE: self._on_generate,
            MsgType.SESSION_CLOSE: self._on_session_close,
        }

    # --- lifecycle ----------------------------------------------------
    def start(self) -> int:
        """Bind + start the listener thread; returns the bound port
        (``port=0`` picks an ephemeral one)."""
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, self.port))
        self._listener.listen(128)
        self.port = self._listener.getsockname()[1]
        self.advertise_addr = f"{self.host}:{self.port}"
        if self.mutlog is not None:
            # durable HA restart: reload the persisted placement map +
            # spilled handoff buffer BEFORE serving any frame, so a
            # restarted leader routes (and drains) exactly what it
            # owned when it died
            self._restore_ha_runtime()
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="netsdb-serve-accept")
        t.start()
        self._threads.append(t)
        if (getattr(self.config, "obs_history_len", 120) or 0) >= 2:
            self.history.start()
        self._start_pool_threads()
        if self._ha_peers:
            self.arm_ha(self._ha_peers)
        return self.port

    def _start_pool_threads(self) -> None:
        """(Re)start the follower-health and shard-pool-health loops
        for whichever roles this daemon currently has. Idempotent —
        called at start() and again by :meth:`_promote_self`, which
        GRANTS roles to a daemon that booted with neither."""
        if self._follower_addrs and (self._health_thread is None
                                     or not self._health_thread.is_alive()):
            h = threading.Thread(target=self._health_loop, daemon=True,
                                 name="netsdb-serve-health")
            h.start()
            self._health_thread = h
            self._threads.append(h)
        if self._worker_addrs and (self._pool_thread is None
                                   or not self._pool_thread.is_alive()):
            s = threading.Thread(target=self._pool_health_loop,
                                 daemon=True,
                                 name="netsdb-serve-pool-health")
            s.start()
            self._pool_thread = s
            self._threads.append(s)

    # --- HA: arming, promotion, durable restart -----------------------
    def arm_ha(self, peers: list,
               election_timeout_s: Optional[float] = None,
               probe_interval_s: Optional[float] = None):
        """Arm automatic failover over the ordered succession list
        ``peers`` (index 0 = initial leader; this daemon's
        ``advertise_addr`` must appear in it). Call after
        :meth:`start` so the advertised address carries the real
        bound port. Returns the live :class:`~netsdb_tpu.serve.ha.HAState`."""
        if election_timeout_s is None:
            election_timeout_s = getattr(
                self.config, "ha_election_timeout_s", 5.0)
        self._ha = _ha.HAState(
            self.advertise_addr, list(peers),
            state_dir=os.path.join(self.config.root_dir, "ha"))
        self._ha_monitor = _ha.HAMonitor(
            self, self._ha, election_timeout_s,
            probe_interval_s=probe_interval_s)
        self._ha_monitor.start()
        return self._ha

    def _promote_self(self) -> None:
        """Follower → leader, called by the HA monitor once every
        earlier succession peer stayed dead through the election
        window. Mints the new term (fencing every straggler from the
        deposed leader), adopts the replicated placement map with the
        dead leader's slots rebound to THIS daemon, adopts the LATER
        succession peers as mirror followers, and replicates the new
        epoch so routed clients re-point after exactly one typed
        ``PlacementStale``."""
        ha = self._ha
        if ha is None or ha.role == _ha.LEADER:
            return
        old_leader = ha.leader_addr
        term = ha.promote()
        wire = ha.placement_wire()
        if wire and (wire.get("sets") or {}):
            self.placement.restore(wire)
        if old_leader and old_leader != self.advertise_addr:
            self.placement.rebind_addr(old_leader, self.advertise_addr)
        later = list(ha.later_peers())
        with self._followers_mu:
            self._follower_addrs = list(later)
        # shard daemons named by the map (minus self and the corpse)
        # become this leader's pool; their health loop starts below
        pool = set()
        for ident in self.placement.sets():
            entry = self.placement.entry(*ident)
            for slot in (entry or {}).get("slots", ()):
                pool.add(slot["addr"])
        pool.discard(self.advertise_addr)
        if old_leader:
            pool.discard(old_leader)
        for addr in sorted(pool):
            if addr not in self._worker_addrs:
                self._worker_addrs.append(addr)
        self._start_pool_threads()
        if self._worker_addrs:
            # prune: the adopted map is authoritative — a slot move
            # the deposed leader committed but never dropped finishes
            # here (stale source registrations retire tombstoned)
            self._push_epochs(prune=True)
        try:
            # eagerly dial the adopted followers (bounded — a dead
            # later peer degrades and reattaches on the normal path)
            self._ensure_followers(
                timeout_s=min(self.heartbeat_timeout_s, 5.0))
        except FollowerDegraded as e:
            del e  # degraded peers reattach via the health loop
        self._replicate_placement()
        from netsdb_tpu.utils.profiling import get_logger

        get_logger("netsdb_tpu.serve").warning(
            "promoted %s to leader (term %d, deposed %s)",
            self.advertise_addr, term, old_leader)

    def _restore_ha_runtime(self) -> None:
        """Durable-restart half of ``ha_mutlog``: reload the persisted
        placement map (rebinding this daemon's possibly-changed
        advertise address) and the spilled handoff buffer, then mark
        the still-absent shard owners degraded so the pool health loop
        readmits them and DRAINS the restored buffer."""
        stored = self._load_placement()
        if stored:
            wire = stored.get("wire") or {}
            if wire.get("sets"):
                self.placement.restore(wire)
                old_addr = stored.get("advertise_addr")
                if old_addr and old_addr != self.advertise_addr:
                    self.placement.rebind_addr(old_addr,
                                               self.advertise_addr)
                # the reconcile push: workers re-register under the
                # persisted (post-move) epochs, and registrations the
                # map no longer grants are pruned — a restart
                # mid-rebalance resumes from the committed map, with
                # any undropped source copy retired here
                self._push_epochs(prune=True)
        pending = self.shards.load_spill()
        if pending:
            owners = set()
            for ident in self.placement.sets():
                entry = self.placement.entry(*ident)
                for slot in (entry or {}).get("slots", ()):
                    if slot.get("state") == _placement.HANDOFF \
                            and slot["addr"] != self.advertise_addr:
                        owners.add(slot["addr"])
            for addr in sorted(owners):
                self.shards.note_degraded(
                    addr, "handoff pending across leader restart")

    def _placement_path(self) -> str:
        return os.path.join(self.config.root_dir, "ha",
                            "placement.json")

    def _save_placement(self) -> None:
        """Best-effort durable copy of the placement map (only when
        the mutation log is on — the durability opt-in). Atomic
        tmp+replace; a failed save degrades to snapshot-era behavior,
        never a crash on the ingest path."""
        if self.mutlog is None:
            return
        import json

        path = self._placement_path()
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"advertise_addr": self.advertise_addr,
                           "wire": self.placement.to_wire()}, f)
            os.replace(tmp, path)
        except OSError as e:
            del e  # best-effort: an unsaved map degrades the NEXT
            pass   # restart to snapshot-era recovery, never this frame

    def _load_placement(self) -> Optional[Dict[str, Any]]:
        import json

        try:
            with open(self._placement_path(), "r", encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _ha_state_payload(self) -> Dict[str, Any]:
        snap = self._ha.snapshot()
        return {"term": snap["term"], "leader": snap["leader"],
                "placement": self.placement.to_wire()}

    def _replicate_placement(self) -> None:
        """Ship the current (term, leader, placement) to every active
        follower — called on every epoch bump so a promoted leader
        serves routed ingest from the instant it wins, without a
        discovery scan. Fire-and-forget through the FIFO links: the
        map rides the same ordered stream as the data it describes."""
        self._save_placement()
        if self._ha is None or self._ha.role != _ha.LEADER:
            return
        payload = self._ha_state_payload()
        with self._followers_mu:
            links = list(self._links.values())
        for link in links:
            link.submit(MsgType.HA_STATE, dict(payload), CODEC_MSGPACK)

    def _send_token_alias(self, alias: str, target: str) -> None:
        """Ship one waiter-token → leader-token alias to every active
        follower (satellite of the coalesce/failover contract). Sent
        AFTER the leader's mirrored execution acked, through the same
        FIFO links — so the target token's reply is already cached on
        the follower when the alias lands. Bounded wait; a miss
        degrades that follower to re-execution on retry, never
        divergence."""
        payload: Dict[str, Any] = {"alias": alias, "target": target}
        if self._ha is not None:
            payload[HA_TERM_KEY] = self._ha.term
        if self.mutlog is not None:
            self.mutlog.append({"op": "alias", "alias": alias,
                                "target": target})
        with self._followers_mu:
            pending = [link.submit(MsgType.TOKEN_ALIAS, dict(payload),
                                   CODEC_MSGPACK)
                       for link in self._links.values()]
        deadline = deadline_after(self.heartbeat_timeout_s)
        for rec in pending:
            rec["done"].wait(max(seconds_left(deadline), 0.0))

    def serve_forever(self) -> None:
        if self._listener is None:
            self.start()
        try:
            while not self._stop.wait(0.5):
                pass
        except KeyboardInterrupt:
            pass
        finally:
            self.shutdown()

    def shutdown(self) -> None:
        self._stop.set()
        # the session housekeeping thread is JOINED (same discipline
        # as the history thread below)
        self.sessions.stop()
        # the telemetry snapshot thread is JOINED, not abandoned — no
        # history thread may outlive its daemon (the leak-registry
        # discipline every obs thread follows)
        self.history.stop()
        # drop this scheduler's registry collector (only if it is
        # still the registered one — a newer controller in the same
        # process may have replaced it)
        obs.REGISTRY.unregister_collector("sched", self.sched.snapshot)
        with self._followers_mu:
            links = list(self._links.values())
        for link in links:
            link.close()
        self.shards.close()
        self._idem.close()
        if self.mutlog is not None:
            self.mutlog.close()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None
        with self._conns_mu:
            conns = list(self._conns)
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

    # --- connection handling ------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, addr = self._listener.accept()
            except OSError:
                return  # listener closed
            t = threading.Thread(target=self._serve_connection,
                                 args=(conn, addr), daemon=True)
            t.start()

    def _serve_connection(self, conn: socket.socket, addr) -> None:
        with self._conns_mu:
            self._conns.add(conn)
        try:
            self._serve_connection_inner(conn, addr)
        finally:
            with self._conns_mu:
                self._conns.discard(conn)

    def _serve_connection_inner(self, conn: socket.socket,
                                addr) -> None:
        with conn:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            try:
                # the handshake must complete promptly; after it, the
                # connection may idle between frames, but once a frame
                # STARTS the peer must finish it within frame_timeout_s
                # (recv_frame_raw's mid-frame bound) — a hung peer can
                # never wedge this worker thread
                conn.settimeout(self.handshake_timeout_s)
                typ, hello = recv_frame(conn, allow_pickle=False)
                if typ != MsgType.HELLO:
                    raise ProtocolError("expected HELLO")
                if hello.get("proto") != PROTO_VERSION:
                    # mixed wire formats are refused OUTRIGHT: a v2 peer
                    # would misparse a v3 segment table as body bytes
                    send_frame(conn, MsgType.ERR, {
                        "error": "ProtocolVersionError",
                        "message": f"this daemon speaks wire format "
                                   f"v{PROTO_VERSION}; peer sent "
                                   f"proto={hello.get('proto')!r}",
                        "retryable": False})
                    return
                if self.token and hello.get("token") != self.token:
                    send_frame(conn, MsgType.ERR,
                               {"error": "AuthError", "message": "bad token"})
                    return
                ok_reply = {"server": "netsdb_tpu",
                            "version": PROTO_VERSION}
                if len(self.placement):
                    # v3 handshake placement shipping: ONLY when sharded
                    # sets exist, so the plain handshake (and every
                    # existing test's frame trace) stays byte-identical
                    ok_reply["placement"] = self.placement.to_wire()
                send_frame(conn, MsgType.OK, ok_reply)
                conn.settimeout(None)
            except (ProtocolError, ConnectionError, OSError):
                return
            while not self._stop.is_set():
                try:
                    typ, codec_in, raw, segs = recv_frame_raw(
                        conn, chaos=self._chaos,
                        mid_frame_timeout=self.frame_timeout_s)
                except (ProtocolError, ConnectionError, OSError):
                    return
                t_dec = time.perf_counter()
                try:
                    payload = decode_body(raw, codec_in, self.allow_pickle,
                                          segments=segs)
                except ProtocolError as e:
                    # refused codec — deterministic, fatal to retry
                    if not self._send_err(conn, e, retryable=False):
                        return
                    continue
                except Exception as e:
                    # body failed to decode: bit flips / torn frame.
                    # The request never executed, so a resend is safe —
                    # typed retryable (the chaos corrupt path).
                    fault = CorruptFrame(f"{type(e).__name__}: {e}")
                    if not self._send_err(conn, fault, retryable=True):
                        return
                    continue
                decode_s = time.perf_counter() - t_dec
                if typ == MsgType.SHUTDOWN:
                    send_frame(conn, MsgType.OK, {})
                    self.shutdown()
                    return
                if typ == MsgType.BULK_BEGIN:
                    # windowed streamed ingest: a multi-frame
                    # conversation owned by this worker thread
                    if not self._handle_bulk(conn, payload):
                        return
                    continue
                if not self._dispatch_frame(conn, typ, codec_in, payload,
                                            decode_s=decode_s):
                    return

    def _send_reply(self, conn, typ, payload, codec=CODEC_MSGPACK) -> None:
        """Reply send with the same deadline discipline as mid-frame
        recv: the peer must DRAIN within frame_timeout_s or the send
        fails (socket.timeout → the caller drops the connection) — a
        client that stops reading can never wedge a handler thread in
        sendall. The idle-recv timeout (None) is restored after."""
        conn.settimeout(self.frame_timeout_s)
        try:
            send_frame(conn, typ, payload, codec, chaos=self._chaos)
        finally:
            conn.settimeout(None)

    def _send_err(self, conn, exc, retryable: Optional[bool] = None,
                  with_traceback: bool = False) -> bool:
        """ERR frame for ``exc``; False when the connection is dead.
        ``retryable`` rides the payload so clients classify without
        string-matching (errors.classify_remote)."""
        if retryable is None:
            retryable = bool(getattr(exc, "retryable", False))
        body = {"error": type(exc).__name__, "message": str(exc),
                "retryable": retryable}
        # scheduler backpressure details ride the frame so the client's
        # backoff can honor the server's own hint (the same field list
        # classify_remote rebuilds client-side)
        for field in BACKPRESSURE_FIELDS:
            value = getattr(exc, field, None)
            if value is not None:
                body[field] = value
        if with_traceback:
            body["traceback"] = traceback.format_exc(limit=20)
        try:
            self._send_reply(conn, MsgType.ERR, body)
            return True
        except OSError:
            return False

    def _dispatch_frame(self, conn, typ, codec_in, payload,
                        decode_s: float = 0.0) -> bool:
        """Execute one decoded request frame and send its reply. A
        frame carrying a client-minted query id opens a query-scoped
        trace first (``obs.trace``): the handler, the executor below
        it, staging and the device cache all report spans/counters
        into it, and the completed profile lands in this daemon's
        GET_TRACE ring — the ``-DPROFILING`` decomposition, per query,
        always on (``config.obs_enabled`` is the kill switch).

        Around the trace, the ACTIVE layer: every workload frame
        (``OBS_FRAMES`` excluded) ticks the request counters at
        OUTCOME time + the latency histogram the SLO engine evaluates;
        a frame carrying a client identity attributes its handler's
        resource use per (client, set); a traced query may capture an
        opt-in ``jax.profiler`` session; and a trace whose total
        exceeds ``obs_slow_query_s`` persists to the on-disk slowlog
        ring after it closes."""
        qid = payload.pop(QUERY_ID_KEY, None) \
            if isinstance(payload, dict) else None
        client = payload.pop(CLIENT_ID_KEY, None) \
            if isinstance(payload, dict) else None
        lane = payload.pop(LANE_KEY, None) \
            if isinstance(payload, dict) else None
        if isinstance(payload, dict) and SESSION_KEY in payload:
            # session-scoped frames admit through the reserved decode
            # lane unless the client pinned one explicitly — decode
            # loops and one-shot analytics get weighted fairness
            payload.pop(SESSION_KEY, None)
            if lane is None:
                lane = DECODE_LANE
        # introspection frames are EXCLUDED from the request counters
        # and latency histogram (t0=None): the SLOs those instruments
        # feed must measure the workload, not the monitoring of it —
        # a 10s HEALTH poll plus a per-query PUT_TRACE shipper would
        # otherwise flood the p99 sample ring with microsecond
        # dispatches and mask real slow queries
        t0 = None if typ in OBS_FRAMES else time.perf_counter()
        if qid is None or not self._obs_enabled:
            return self._dispatch_traced(conn, typ, codec_in,
                                         payload, None, client, t0,
                                         lane=lane)
        with obs.trace(str(qid), origin="server",
                       ring=self.trace_ring) as tr:
            if tr is not None:
                # the body decode finished before the trace could
                # open: back-date the trace so the decode span
                # occupies real timeline [0, decode_s] AHEAD of the
                # dispatch span (and total_s covers it) instead of
                # overlapping it
                tr.backdate(decode_s)
                tr.record("server.decode", decode_s, "serve",
                          start_s=0.0)
                tr.add("frame.decode_s", decode_s)
                if client is not None:
                    tr.annotate("client", str(client))
            with self._maybe_device_profile(tr):
                ok = self._dispatch_traced(conn, typ, codec_in,
                                           payload, str(qid), client,
                                           t0, lane=lane)
        if tr is not None:
            # the trace closed on context exit — total_s is final
            self._maybe_slowlog(tr)
        return ok

    @contextlib.contextmanager
    def _maybe_device_profile(self, tr):
        """Opt-in per-qid ``jax.profiler`` session
        (``config.obs_device_profile_dir``): the REAL device half of a
        traced query, captured into ``<dir>/<qid>`` for
        TensorBoard/XProf. One session at a time — a concurrent traced
        query skips (non-blocking acquire) rather than queueing the
        serve path behind the profiler; profiler failures annotate the
        trace and never fail the query."""
        if (tr is None or not self._device_profile_dir
                or not self._profiler_mu.acquire(blocking=False)):
            yield
            return
        sess = None
        try:
            try:
                from netsdb_tpu.utils.profiling import qid_profile_session

                sess = qid_profile_session(tr.qid,
                                           self._device_profile_dir)
                tr.annotate("device_profile", sess.__enter__())
            except Exception as e:  # noqa: BLE001 — annotated, not fatal
                tr.annotate("device_profile_error",
                            f"{type(e).__name__}: {e}")
                sess = None
            try:
                yield
            finally:
                if sess is not None:
                    try:
                        sess.__exit__(None, None, None)
                    except Exception as e:  # noqa: BLE001 — annotated
                        tr.annotate("device_profile_error",
                                    f"{type(e).__name__}: {e}")
        finally:
            self._profiler_mu.release()

    def _maybe_slowlog(self, tr) -> None:
        """Persist a just-closed slow trace to the on-disk ring (the
        structured slow-query log). Prefers the RINGED profile over
        ``tr.profile()``: a client section shipped before the ring
        push (TraceRing's pending buffer) is already folded into the
        ringed copy but absent from a fresh profile(). Never fails
        the request path."""
        try:
            # threshold gate FIRST: almost every traced request is
            # fast, and the ring find is an O(capacity) scan under
            # the ring mutex — don't pay it just to reject
            thr = self.slowlog.threshold_s
            if not thr or tr.total_s is None or tr.total_s < thr:
                return
            ringed = self.trace_ring.find(tr.qid)
            self.slowlog.maybe_record(ringed[-1] if ringed
                                      else tr.profile())
        except Exception as e:  # noqa: BLE001 — counted, never fatal
            obs.REGISTRY.counter("obs.slowlog_errors").inc()
            del e

    def _dispatch_traced(self, conn, typ, codec_in, payload, qid,
                         client=None, t0=None, lane=None) -> bool:
        """The dispatch body (trace context, if any, already
        installed). Returns False when the connection is dead. Mutating
        frames carrying an idempotency token are deduplicated here: a
        retry of a COMPLETED request replays the cached reply without
        re-running the handler — the at-most-once half of the client's
        retry contract.

        ``t0`` anchors the ``serve.request_s`` histogram (the p99
        SLO's feed): unary frames observe through the reply send,
        streaming frames observe TIME TO FIRST FRAME — a multi-GB scan
        drain rides the client's consumption rate, and folding tens of
        seconds of TCP backpressure into "request latency" would make
        the p99 objective breach on perfectly healthy bulk reads.
        ``t0`` is None for introspection frames (``OBS_FRAMES``) —
        they observe nothing and count nowhere."""
        observed = [False]

        def mark():
            if not observed[0] and t0 is not None:
                observed[0] = True
                obs.REGISTRY.histogram("serve.request_s").observe(
                    time.perf_counter() - t0)

        def done(ok):
            # availability counts BOTH sides at outcome time: ticking
            # serve.requests at dispatch start read every in-flight
            # request as a failure — one long EXECUTE in a low-QPS
            # window pushed good/total under the 0.999 target and
            # flapped breach events with zero real errors
            if t0 is None:
                return
            obs.REGISTRY.counter("serve.requests").inc()
            if ok:
                obs.REGISTRY.counter("serve.requests_ok").inc()

        token = payload.pop(IDEMPOTENCY_KEY, None) \
            if isinstance(payload, dict) else None
        # the sender's HA term (mirrored frames, handoff drains, log
        # replays): popped here so handlers never see it, fenced in
        # _execute_frame against the receiver's own term
        term = payload.pop(HA_TERM_KEY, None) \
            if isinstance(payload, dict) else None
        try:
            if token is not None:
                cached = self._idem.claim(token, wait_s=self.frame_timeout_s)
                if cached is not None:
                    reply_type, reply, codec = cached
                    self._send_reply(conn, reply_type, reply, codec)
                    mark()
                    done(True)
                    return True
            with obs.span(f"server.dispatch:{getattr(typ, 'name', typ)}",
                          "serve"):
                out = self._execute_frame(typ, payload, codec_in, token,
                                          qid=qid, client=client,
                                          lane=lane, term=term)
            if inspect.isgenerator(out):
                # streaming handler: each yielded (type, payload
                # [, codec]) goes out as its own frame; TCP
                # backpressure bounds server buffering to ONE
                # frame (the reference's page-by-page result
                # streaming, FrontendQueryTestServer.cc:785-890).
                # The contract: ends with STREAM_END, or ERR on
                # a mid-stream failure — either way the
                # connection stays frame-synchronized. Streams are
                # not idempotency-cached (mutating frames never
                # stream).
                for frame in out:
                    if len(frame) == 3:
                        f_type, f_payload, f_codec = frame
                    else:
                        (f_type, f_payload), f_codec = frame, CODEC_MSGPACK
                    self._send_reply(conn, f_type, f_payload, f_codec)
                    mark()  # first frame = the latency that matters
                mark()  # empty stream: observe at STREAM_END
                done(True)
                return True
            with obs.span("server.reply", "serve"):
                self._send_reply(conn, *out)
            mark()
            done(True)
            return True
        except BrokenPipeError:
            mark()
            done(False)  # died mid-reply: dispatched, not answered OK
            return False
        except Exception as e:  # handler errors go back as typed ERR
            mark()
            done(False)
            return self._send_err(conn, e, with_traceback=True)

    #: frame types eligible for identical-query coalescing: idempotent
    #: job launches whose reply reuse the idempotency-token cache
    #: already proves safe (serve/sched/coalesce.py)
    COALESCED_FRAMES = frozenset({MsgType.EXECUTE_COMPUTATIONS,
                                  MsgType.EXECUTE_PLAN})

    def _devcache_warm(self, scope: str):
        """The scheduler's cache probe: is ``scope`` ("db:set") warm in
        the device cache? Answers warm (= no gating) for a disabled
        cache AND for non-paged sets: resident sets never enter the
        devcache, so an affinity gate keyed on them could only
        serialize concurrent queries with no warm cache to wake into.
        Only a COLD PAGED set — the one whose first stream installs
        the run every later sibling replays — is worth queueing
        behind.

        With block-granular partial caching the answer is RANGE-aware
        (the AffinityGate's per-page-range keying): ``True`` when the
        set's block coverage is complete (a query over an
        already-warm prefix admits immediately — mere ``has_scope``
        would also read one resident block as "warm" and let every
        sibling race the gap installs), an ``int`` (the contiguous
        covered prefix's end row) when partially covered so only the
        cold-remainder installer serializes, ``False`` when cold."""
        cache = self.library.store.device_cache()
        if not cache.enabled:
            return True
        partial = getattr(cache, "partial", False)
        if partial:
            covered, total = cache.coverage(scope)
            if total is not None and 0 < total <= covered:
                return True  # fully resident: no gating
        elif cache.has_scope(scope):
            return True
        else:
            covered = 0
        db, _, set_name = scope.partition(":")
        try:
            storage = self.library.store.storage_of(
                SetIdentifier(db, set_name))
        except Exception as e:  # noqa: BLE001 — unknown set → ungated
            del e
            return True
        if storage != "paged":
            return True
        return int(covered) if covered > 0 else False

    def _refresh_pin_auto(self) -> None:
        """One pin-budget auto-sizing pass (config.device_cache_pin_
        auto, run on the scheduler-feedback cadence): the attribution
        ledger's hot-set table → ``feedback.pin_budget`` (pinned
        formula) → the devcache pin budget, annotated ``pin_auto`` in
        its stats section."""
        from netsdb_tpu.serve.sched import feedback as _feedback

        cache = self.library.store.device_cache()
        if not (cache.enabled and getattr(cache, "partial", False)):
            return
        cache.set_pin_budget(
            _feedback.pin_budget(obs.attrib.LEDGER.snapshot(),
                                 cache.budget_bytes),
            auto=True)

    def _execute_frame(self, typ, payload, codec_in, token, qid=None,
                       client=None, lane=None, term=None):
        """Run one request's handler with the idempotency-token
        lifecycle (the caller has already claimed ``token``). Returns a
        generator (streaming handlers) or the normalized ``(type,
        payload, codec)`` reply; on every exit path the token has been
        finished or aborted exactly once. Shared by the per-frame
        dispatch and the bulk-ingest COMMIT. ``qid`` (the client's
        query id, already popped) rides mirrored forwards so follower
        traces share the leader's id; ``client`` (the frame's client
        identity, already popped) likewise — and is installed for the
        handler's dynamic extent so every instrumented layer below
        attributes its resource use per (client, db:set). ``lane``
        (the frame's scheduler hint, already popped) installs the same
        way and steers the job's admission lane.

        EXECUTE frames additionally pass the scheduler's COALESCE
        point here — BEFORE mirroring and admission: a byte-identical
        in-flight execution absorbs this frame entirely (no slot, no
        mirror forward, no handler run) and its reply fans out under
        this frame's own token/trace; a waiter whose leader dies gets
        the typed retryable CoalesceAborted and this token is aborted,
        so the retry re-executes."""
        handler = self.handlers.get(typ)
        if client is not None or isinstance(payload, dict):
            scope = None
            if isinstance(payload, dict) and payload.get("db") \
                    and payload.get("set"):
                scope = f"{payload['db']}:{payload['set']}"
            obs.attrib.account("requests", 1, scope=scope, client=client)
        try:
            if handler is None:
                raise ProtocolError(f"no handler for {typ!r}")
            if self._ha is not None:
                if term is not None:
                    # peer-originated frame (mirror/drain/replay): a
                    # STALE term means a deposed leader's straggler —
                    # reject typed, never double-apply
                    self._ha.observe_term(term)
                elif typ in self.MIRRORED:
                    # client-originated mutation: only the leader
                    # accepts; the typed NotLeader carries the
                    # current leader's address for rediscovery
                    self._ha.check_client_write()

            def invoke():
                if self._follower_addrs and typ in self.MIRRORED:
                    return self._run_mirrored(typ, payload, codec_in,
                                              handler, token=token,
                                              qid=qid, client=client)
                return handler(payload)

            tok_reset = _idem_token_var.set(token)
            try:
                with obs.attrib.client_context(client), \
                        _sched.lane_context(lane):
                    if typ in self.COALESCED_FRAMES:
                        winfo: Dict[str, Any] = {}
                        out = self.sched.coalesced(typ, payload,
                                                   invoke, token=token,
                                                   waiter_info=winfo)
                    else:
                        winfo = None
                        out = invoke()
            finally:
                _idem_token_var.reset(tok_reset)
        except FollowerDegraded as e:
            # the LOCAL mutation applied; only the mirror failed.
            # Cache the local reply under the token so the client's
            # retry returns success instead of double-applying,
            # then surface the typed retryable error for THIS
            # attempt (the ambiguous-outcome contract).
            if token is not None:
                if e.local_result is not None:
                    self._idem.finish(
                        token, self._normalize_reply(e.local_result))
                else:
                    self._idem.abort(token)
            raise
        except BaseException:
            if token is not None:
                # transient or handler failure: nothing durable to
                # replay — release waiters so a retry re-executes
                self._idem.abort(token)
            raise
        if inspect.isgenerator(out):
            # streams are not idempotency-cached (mutating frames
            # never stream)
            if token is not None:
                self._idem.abort(token)
            return out
        result = self._normalize_reply(out)
        if token is not None:
            self._idem.finish(token, result)
            # coalesce WAITER absorbed by another flight: its token
            # finished HERE but followers only saw the leader's —
            # ship the alias so the waiter's post-failover retry
            # still dedupes (the PR 9 at-most-once gap)
            ltok = winfo.get("leader_token") if winfo else None
            if ltok and ltok != token and self._follower_addrs:
                self._send_token_alias(token, ltok)
        return result

    @staticmethod
    def _normalize_reply(out) -> Tuple[MsgType, Any, int]:
        if len(out) == 3:  # handler picked the reply codec
            return out[0], out[1], out[2]
        return out[0], out[1], CODEC_MSGPACK

    # --- windowed bulk ingest (BULK_BEGIN/CHUNK/COMMIT) ---------------

    #: ops that accept the streamed-ingest conversation; anything else
    #: in a BULK_BEGIN is a deterministic protocol violation
    BULK_OPS = frozenset({MsgType.SEND_DATA, MsgType.RESYNC_FOLLOWER})

    def _bulk_assembler(self, op: MsgType, meta: dict) -> "_BulkAssembler":
        if op == MsgType.RESYNC_FOLLOWER:
            return _BlobAssembler(meta)
        if meta.get("mode") == "table":
            return _TableAssembler(meta)
        return _ItemsAssembler(meta, self.allow_pickle)

    def _handle_bulk(self, conn, p) -> bool:
        """One streamed-ingest conversation: BEGIN (already decoded in
        ``p``) → N CHUNK frames, each acked AFTER it decodes so the
        client pipelines ``window`` chunks deep → COMMIT, which
        assembles the payload and dispatches it through the normal
        handler path (mirroring + ordering locks + idempotency all
        apply at commit — chunks decode OUTSIDE the per-set lock, the
        apply runs under it). Returns False when the connection must
        close (transport desync or a mid-stream fault: the chunk
        stream cannot be resynchronized, so the typed ERR is sent and
        the socket dropped — the client retries the whole conversation
        under its idempotency token)."""
        try:
            op = MsgType(int(p.get("op", -1)))
            if op not in self.BULK_OPS:
                raise ProtocolError(
                    f"op {p.get('op')!r} is not bulk-streamable")
            meta = dict(p.get("meta") or {})
        except (ProtocolError, ValueError) as e:
            return self._send_err(conn, e, retryable=False)
        token = p.get(IDEMPOTENCY_KEY)
        client = p.get(CLIENT_ID_KEY)  # one identity for the whole
        # conversation — the COMMIT's apply attributes under it
        if token is not None:
            try:
                cached = self._idem.claim(token, wait_s=self.frame_timeout_s)
            except Exception as e:  # RequestInFlight → typed retryable
                return self._send_err(conn, e)
            if cached is not None:
                # completed execution replay: the final reply goes out
                # INSTEAD of "go" — the client skips streaming entirely
                try:
                    self._send_reply(conn, *cached)
                    return True
                except OSError:
                    return False
        owned = token is not None
        try:
            try:
                asm = self._bulk_assembler(op, meta)
            except ProtocolError as e:
                # deterministic refusal (e.g. pickle chunks with
                # allow_pickle off): typed fatal ERR instead of "go";
                # the connection stays frame-synchronized
                return self._send_err(conn, e, retryable=False)
            if meta.get("pepoch") is not None or self.is_sharded(
                    meta.get("db"), meta.get("set")):
                # placement-epoch gate at BEGIN — a stale map must
                # reject before the client streams the payload, not
                # after (the COMMIT-time check below still guards the
                # race where the epoch moves mid-conversation)
                self._shard_route(meta.get("db"), meta.get("set"),
                                  meta.get("pepoch"), meta.get("slot"))
            if self._ha is not None and op in self.MIRRORED \
                    and HA_TERM_KEY not in (p or {}):
                # leadership gate at BEGIN, same rationale as the
                # epoch gate: a demoted daemon must bounce the client
                # BEFORE it streams gigabytes, not at COMMIT
                self._ha.check_client_write()
            self._send_reply(conn, MsgType.OK, {"go": True})
            total_in = 0
            while True:
                typ, codec_in, raw, segs = recv_frame_raw(
                    conn, chaos=self._chaos,
                    mid_frame_timeout=self.frame_timeout_s)
                total_in += len(raw) + sum(b.nbytes for b, _ in segs)
                if total_in > MAX_FRAME_BYTES:
                    # the streamed path keeps the single-frame sanity
                    # cap — one conversation must not balloon daemon
                    # RSS without bound before COMMIT validation
                    self._send_err(conn, ProtocolError(
                        f"bulk conversation exceeded the "
                        f"{MAX_FRAME_BYTES}-byte cap"), retryable=False)
                    return False
                try:
                    payload = decode_body(raw, codec_in, self.allow_pickle,
                                          segments=segs)
                except ProtocolError:
                    raise
                except Exception as e:
                    raise CorruptFrame(f"{type(e).__name__}: {e}") from e
                if typ == MsgType.BULK_CHUNK:
                    asm.add(payload)  # decode work, outside any set lock
                    self._send_reply(conn, MsgType.OK,
                                     {"ack": payload.get("seq")})
                elif typ == MsgType.BULK_COMMIT:
                    if asm.chunks != int(payload.get("chunks", -1)):
                        raise CorruptFrame(
                            f"ingest stream torn: committed "
                            f"{payload.get('chunks')} chunks, received "
                            f"{asm.chunks}")
                    final_payload, fwd_codec = asm.finish()
                    if meta.get("pepoch") is not None \
                            and isinstance(final_payload, dict):
                        # the routed conversation's epoch/slot ride to
                        # the apply (validated again there — COMMIT
                        # must reject a mid-stream membership change)
                        final_payload[PLACEMENT_EPOCH_KEY] = \
                            meta["pepoch"]
                        if meta.get("slot") is not None:
                            final_payload[SHARD_SLOT_KEY] = meta["slot"]
                    owned = False  # _execute_frame consumes the token
                    result = self._execute_frame(op, final_payload,
                                                 fwd_codec, token,
                                                 client=client)
                    self._send_reply(conn, *result)
                    return True
                else:
                    raise ProtocolError(
                        f"unexpected frame {typ!r} inside a bulk-ingest "
                        f"conversation")
        except BrokenPipeError:
            return False
        except (ProtocolError, ConnectionError, OSError):
            return False  # transport desync — client retries fresh
        except Exception as e:
            self._send_err(conn, e, with_traceback=True)
            return False  # chunk stream unsynchronizable past a fault
        finally:
            if owned:
                self._idem.abort(token)

    # --- multi-host mirroring (master → workers) ----------------------
    def _dial_follower(self, addr: str, timeout: Optional[float] = None):
        """One follower connection with mirror-path semantics: NO
        client-side retries (a mirror failure must surface immediately
        so the leader can evict + resync, not be papered over by a
        silent reconnect that breaks frame ordering). The dial +
        handshake is always bounded — a peer that accepts TCP and goes
        silent must fail the dial, not wedge the dialing thread;
        ``timeout`` additionally bounds steady-state replies (used by
        the resync RPC; mirror links leave it None because a mirrored
        EXECUTE may legitimately run for minutes, and the ack-timeout
        eviction already unsticks those)."""
        from netsdb_tpu.serve.client import RemoteClient, RetryPolicy

        return RemoteClient(addr, token=self.token,
                            retry=RetryPolicy(max_attempts=1),
                            chaos=self._follower_chaos,
                            timeout=timeout,
                            connect_timeout=self.handshake_timeout_s)

    def _ensure_followers(self, timeout_s: float = 30.0) -> None:
        """Dial any not-yet-connected follower, retrying while it comes
        up (bring-up order between master and workers is free — the
        deadline is MONOTONIC, so wall-clock jumps cannot break the
        retry window). Each follower gets a :class:`_FollowerLink` — a
        FIFO sender thread whose queue order IS the follower's frame
        order. A follower that never answers within the window is
        evicted into the degraded state (the reattach loop keeps
        trying) and the frame that needed it fails typed-retryable."""
        with self._followers_mu:
            undialed = [a for a in self._follower_addrs
                        if a not in self._links and a not in self._degraded]
        if not undialed:
            return
        for addr in undialed:
            deadline = deadline_after(timeout_s)
            while True:
                try:
                    fc = self._dial_follower(addr)
                    with self._followers_mu:
                        self._links[addr] = _FollowerLink(addr, fc)
                    break
                except OSError as e:
                    if seconds_left(deadline) <= 0:
                        self._evict_follower(
                            addr, f"unreachable after {timeout_s:.0f}s: {e}")
                        raise FollowerDegraded(
                            f"follower daemon {addr} unreachable after "
                            f"{timeout_s:.0f}s; evicted for background "
                            f"reattach: {e}") from e
                    time.sleep(0.3)

    def _evict_follower(self, addr: str, reason: str) -> None:
        """Move a follower out of the mirror set into the degraded
        state. The leader keeps serving reads/queries from its own
        store; a background reattach loop resyncs the follower from a
        leader checkpoint before readmitting it. Idempotent."""
        with self._followers_mu:
            link = self._links.pop(addr, None)
            if link is not None and link.acked_offset is not None:
                # the log-replay resume position: everything at or
                # before this END offset is applied on that follower
                self._follower_offsets[addr] = link.acked_offset
            self._degraded[addr] = reason
        if link is not None:
            link.close(abort=True)

    def follower_status(self) -> Dict[str, Any]:
        with self._followers_mu:
            out = {"active": sorted(self._links),
                   "degraded": dict(self._degraded)}
        out["mirror_dropped"] = int(
            obs.REGISTRY.counter("serve.mirror_dropped").value)
        return out

    # --- sharded worker pool (horizontal scale-out) -------------------
    def is_sharded(self, db: str, set_name: str) -> bool:
        """Placement probe: does this daemon coordinate a partitioned
        placement for (db, set)? Empty map → always False — the
        un-sharded paths never branch."""
        return self.placement.entry(db, set_name) is not None

    def shard_registration(self, db: str,
                           set_name: str) -> Optional[Dict[str, int]]:
        """Worker-side shard registration for (db, set), or None."""
        with self._shard_mu:
            reg = self._shard_sets.get((db, set_name))
            return dict(reg) if reg is not None else None

    def _register_shard(self, db: str, set_name: str, slot: int,
                        epoch: int) -> None:
        with self._shard_mu:
            self._shard_sets[(db, set_name)] = {"epoch": int(epoch),
                                                "slot": int(slot)}

    def _shard_route(self, db: Optional[str], set_name: Optional[str],
                     epoch, slot) -> str:
        """Classify one (possibly routed) mutating frame against this
        daemon's placement knowledge: ``"local"`` (apply here),
        ``"handoff"`` (buffer for a degraded slot), or a typed
        retryable :class:`PlacementStale` — the placement-epoch
        rejection. Validation happens BEFORE any execution, so a
        revised membership can never partially apply."""
        if not db or not set_name:
            return "local"
        entry = self.placement.entry(db, set_name)
        if entry is not None:  # this daemon coordinates the set
            current = entry["epoch"]
            if epoch is None:
                self._reject_stale(
                    f"set {db}:{set_name} is partitioned across a "
                    f"worker pool; fetch the placement map and route "
                    f"to the owning shards", current)
            if int(epoch) != current:
                self._reject_stale(
                    f"placement epoch rejected for {db}:{set_name}: "
                    f"frame rode epoch {epoch}, current is {current}",
                    current)
            if slot is None or not (0 <= int(slot)
                                    < len(entry["slots"])):
                self._reject_stale(
                    f"routed frame for {db}:{set_name} carries no "
                    f"valid shard slot", current)
            sl = entry["slots"][int(slot)]
            if sl["state"] == _placement.HANDOFF:
                return "handoff"
            if sl["addr"] == self.advertise_addr:
                if _rebalance.sealed(self, db, set_name):
                    raise ShardUnavailable(
                        f"slot {slot} of {db}:{set_name} is "
                        f"write-sealed for rebalancing; retry after "
                        f"the move commits", slot=int(slot),
                        epoch=current)
                return "local"
            self._reject_stale(
                f"slot {slot} of {db}:{set_name} is owned by "
                f"{sl['addr']}, not this daemon", current)
        reg = self.shard_registration(db, set_name)
        if reg is not None:  # this daemon holds one slot
            # the write-seal outranks the epoch check: a mid-move
            # source must answer retryable even to correctly-routed
            # frames — the tail drain after the seal is what makes
            # the copy's row count exact
            if _rebalance.sealed(self, db, set_name):
                raise ShardUnavailable(
                    f"shard slot of {db}:{set_name} is write-sealed "
                    f"for rebalancing; retry after the move commits",
                    slot=reg["slot"], epoch=reg["epoch"])
            if epoch is None or int(epoch) != reg["epoch"]:
                self._reject_stale(
                    f"placement epoch rejected for {db}:{set_name}: "
                    f"frame rode epoch {epoch}, shard registered "
                    f"{reg['epoch']}", reg["epoch"])
        elif epoch is not None \
                and _rebalance.tombstoned(self, db, set_name):
            # a committed move dropped this daemon's copy: a frame
            # still riding the old map must reject typed — applying
            # it into the cleared set would silently lose the row
            self._reject_stale(
                f"shard slot of {db}:{set_name} moved away from this "
                f"daemon; re-fetch the placement map", None)
        return "local"

    @staticmethod
    def _reject_stale(message: str, epoch) -> None:
        obs.REGISTRY.counter("shard.epoch_rejects").inc()
        raise PlacementStale(message, epoch=epoch)

    def _pool_health_loop(self) -> None:
        """Leader-side shard liveness: heartbeat every pool worker
        over a dedicated short-timeout connection, evict into the
        degraded (handoff) state after ``heartbeat_misses`` failures,
        and readmit — shard-scoped resync + handoff drain, never a
        whole-store snapshot — once the worker answers again."""
        from netsdb_tpu.serve.client import RemoteClient, RetryPolicy

        probes: Dict[str, Any] = {}
        misses: Dict[str, int] = {}
        while not self._stop.wait(self.heartbeat_interval_s):
            for addr in list(self._worker_addrs):
                try:
                    probe = probes.get(addr)
                    if probe is None:
                        probe = RemoteClient(
                            addr, token=self.token,
                            timeout=self.heartbeat_timeout_s,
                            retry=RetryPolicy(max_attempts=1))
                        probes[addr] = probe
                    probe.ping()
                    misses[addr] = 0
                    if self.shards.is_degraded(addr):
                        self._try_readmit_shard(addr)
                except Exception as e:  # noqa: BLE001 — counted below
                    probe = probes.pop(addr, None)
                    if probe is not None:
                        probe.close()
                    misses[addr] = misses.get(addr, 0) + 1
                    if misses[addr] >= self.heartbeat_misses \
                            and not self.shards.is_degraded(addr):
                        misses[addr] = 0
                        self._evict_shard(
                            addr, f"{self.heartbeat_misses} missed "
                                  f"heartbeats: {type(e).__name__}: {e}")
            if getattr(self.config, "rebalance", False):
                # liveness for the rebalance loop on pools with no
                # query traffic (the sched-feedback cadence only
                # fires on admissions): a cheap no-op unless the
                # detector's verdict or a pool change is pending
                try:
                    self.rebalancer.check()
                except Exception as e:  # noqa: BLE001 — a broken
                    del e              # planner must never kill the
                    pass               # heartbeat loop; skip the pass
        for probe in probes.values():
            probe.close()

    def _evict_shard(self, addr: str, reason: str) -> None:
        """Degrade one pool worker: its slots flip to handoff (epoch
        bump — in-flight stale routes reject typed), its ingest
        buffers at this leader until readmit, and every OTHER live
        worker learns the new epochs (``ShardPool.degrade`` pushes,
        best-effort). Idempotent. A membership change is also a
        rebalance trigger: the remaining LIVE members re-plan on the
        next skew check without waiting out the sustained windows."""
        self.shards.degrade(addr, reason)
        self.rebalancer.pool_changed()

    def _push_epochs(self, exclude: Tuple[str, ...] = (),
                     prune: bool = False) -> None:
        """Re-register CURRENT placement epochs on every live worker —
        an epoch bump is leader-local until this push, and a live
        worker still registered under the old epoch would reject every
        correctly-routed new-epoch frame. Best-effort per worker: a
        push failure leaves that worker answering typed-retryable
        (clients back off) until a later push lands.

        ``prune=True`` (the restart/promotion reconcile) additionally
        sends the push to EVERY pool worker — slotless ones get an
        empty list — with the prune marker: each worker drops (and
        tombstones + clears) registrations absent from its list. This
        finishes any slot move a dead leader committed but never got
        to drop: the persisted map is authoritative, the stale source
        copy must not keep applying old-epoch frames."""
        sets_by_addr: Dict[str, list] = {}
        keep_by_addr: Dict[str, list] = {}
        for db, s in self.placement.sets():
            entry = self.placement.entry(db, s)
            for i, sl in enumerate(entry["slots"]):
                addr = sl["addr"]
                if addr == self.advertise_addr or addr in exclude:
                    continue
                if sl["state"] != _placement.LIVE:
                    # A handoff slot still BELONGS to its degraded
                    # owner — the prune keep-list must cover it, or
                    # the reconcile would strip a worker that is
                    # merely awaiting readmit. Epochs are not
                    # re-registered for it here; that is readmit's
                    # job.
                    keep_by_addr.setdefault(addr, []).append(
                        {"db": db, "set": s})
                    continue
                sets_by_addr.setdefault(addr, []).append(
                    {"db": db, "set": s, "slot": i,
                     "epoch": entry["epoch"]})
        if prune:
            for addr in self._worker_addrs:
                if addr not in exclude:
                    sets_by_addr.setdefault(addr, [])
        for addr, sets in sets_by_addr.items():
            try:
                payload: Dict[str, Any] = {"sets": sets}
                if prune:
                    payload["prune"] = True
                    if keep_by_addr.get(addr):
                        payload["keep"] = keep_by_addr[addr]
                self.shards.peer_request(addr, MsgType.SHARD_RESYNC,
                                         payload)
            except Exception as e:  # noqa: BLE001 — best-effort push
                del e
                self.shards.drop_client(addr)

    def _try_readmit_shard(self, addr: str) -> bool:
        """Readmit one degraded shard: re-register its placement
        epochs (SHARD_RESYNC — required, a failure re-degrades), push
        the bumped epochs to the REST of the pool, then drain ONLY the
        shard's own buffered pages. The drain's per-batch idempotency
        tokens make a retried drain safe."""
        try:
            self.placement.readmit_addr(addr)
            sets = []
            for db, s in self.placement.sets_for_addr(addr):
                entry = self.placement.entry(db, s)
                for i, sl in enumerate(entry["slots"]):
                    if sl["addr"] == addr:
                        sets.append({"db": db, "set": s, "slot": i,
                                     "epoch": entry["epoch"]})
            if sets:
                self.shards.peer_request(addr, MsgType.SHARD_RESYNC,
                                         {"sets": sets})
                self._push_epochs(exclude=(addr,))
                self.shards.drain_handoff(addr)
            self.shards.clear_degraded(addr)
            obs.REGISTRY.counter("shard.readmits").inc()
            self._replicate_placement()
            return True
        except Exception as e:  # noqa: BLE001 — re-degraded, retried
            self.shards.degrade(addr, f"readmit failed: "
                                      f"{type(e).__name__}: {e}")
            return False

    # --- shard-pool handlers ------------------------------------------
    def _on_placement(self, p):
        """The placement map (the PLACEMENT frame a client's stale-map
        retry re-fetches)."""
        return MsgType.OK, self.placement.to_wire()

    def _on_subplan(self, p):
        """Shard side of scatter-gather: run one pushed subplan over
        this daemon's local pages. Admission happened at the
        coordinator (one client EXECUTE = one admission slot pool-
        wide); the shard's own devcache/staging/fusion state still
        applies — that is the per-shard payoff."""
        return MsgType.OK, _shard.execute_subplan(self, p), CODEC_PICKLE

    def _on_shuffle_put(self, p):
        """One inbound distributed-shuffle bucket (shard → shard)."""
        cols = p.get("cols")
        nbytes = sum(np.asarray(v).nbytes for v in (cols or {}).values())
        obs.REGISTRY.counter("shard.shuffle_parts").inc()
        if nbytes:
            obs.REGISTRY.counter("shard.shuffle_bytes").inc(nbytes)
        self._shuffle.put(p["sid"], p["side"], int(p["slot"]), cols,
                          p.get("dicts"))
        return MsgType.OK, {}

    def _on_shard_resync(self, p):
        """Leader → readmitted shard: re-register placement epochs for
        this daemon's slots (the metadata half of the shard-scoped
        resync; the data half is the handoff drain of ordinary routed
        SEND_DATA frames that follows). ``prune: true`` (the leader's
        restart/promotion reconcile) makes the list AUTHORITATIVE:
        registrations absent from it are dropped, tombstoned, and
        their local copies cleared — the worker-side completion of
        any slot move the map committed but a dead leader never got
        to drop."""
        count = 0
        for s in p.get("sets", ()):
            self._register_shard(s["db"], s["set"], s["slot"],
                                 s["epoch"])
            count += 1
        if p.get("prune"):
            keep = {(s["db"], s["set"]) for s in p.get("sets", ())}
            keep |= {(s["db"], s["set"]) for s in p.get("keep", ())}
            with self._shard_mu:
                stale = [k for k in self._shard_sets
                         if k not in keep]
                for k in stale:
                    del self._shard_sets[k]
                    self._reshard_seals.pop(k, None)
                    self._reshard_moved.add(k)
            for db, set_name in stale:
                try:
                    self.library.clear_set(db, set_name)
                except Exception as e:  # noqa: BLE001 — tombstoned
                    del e              # above; a clear failure only
                    pass               # leaves unreachable garbage
        return MsgType.OK, {"sets": count}

    def _on_reshard(self, p):
        """The RESHARD frame (serve/rebalance.py): worker ops run one
        leg of a slot move against this daemon's local state; admin
        ops (status / check / add_worker) drive the leader's
        campaign. Everything answers CODEC_PICKLE — partitions ride
        the reply."""
        op = p.get("op")
        if op == "status":
            return MsgType.OK, self.rebalancer.status(), CODEC_PICKLE
        if op == "view":
            return (MsgType.OK, self.rebalancer.placement_view(),
                    CODEC_PICKLE)
        if op == "check":
            moves = self.rebalancer.check(force=bool(p.get("force")))
            return MsgType.OK, {"moves": moves}, CODEC_PICKLE
        if op == "add_worker":
            return (MsgType.OK,
                    self.add_worker(p["addr"],
                                    campaign=bool(
                                        p.get("campaign", True))),
                    CODEC_PICKLE)
        return (MsgType.OK, _rebalance.handle_reshard(self, p),
                CODEC_PICKLE)

    def add_worker(self, addr: str,
                   campaign: bool = True) -> Dict[str, Any]:
        """Register one NEW pool worker on a live leader (the 5th
        daemon joining a running 4-daemon pool). The health loop
        starts heartbeating it immediately; the rebalancer treats the
        growth as a forced trigger — when ``config.rebalance`` is on,
        a move round runs synchronously and the reply carries its
        results, so callers (tests, the CLI, the bench's mid-run
        registration) observe the pool absorb the member.
        ``campaign=False`` registers only, leaving the move decision
        to a later pass (the advisor's measured commit-or-revert)."""
        addr = str(addr)
        if addr != self.advertise_addr \
                and addr not in self._worker_addrs:
            self._worker_addrs.append(addr)
        self._start_pool_threads()
        self.rebalancer.pool_changed()
        moves = None
        if campaign and getattr(self.config, "rebalance", False):
            moves = self.rebalancer.check()
        return {"workers": list(self._worker_addrs), "moves": moves}

    # --- follower health + graceful degradation -----------------------
    def _health_loop(self) -> None:
        """Leader-side liveness: heartbeat every active follower over a
        DEDICATED connection (never the ordered mirror link — a probe
        must not queue behind a big forward), evict after
        ``heartbeat_misses`` consecutive failures, and keep trying to
        reattach + resync degraded followers."""
        from netsdb_tpu.serve.client import RemoteClient, RetryPolicy

        misses: Dict[str, int] = {}
        probes: Dict[str, Any] = {}
        while not self._stop.wait(self.heartbeat_interval_s):
            with self._followers_mu:
                active = list(self._links)
                degraded = list(self._degraded)
            for addr in active:
                try:
                    probe = probes.get(addr)
                    if probe is None:
                        probe = RemoteClient(
                            addr, token=self.token,
                            timeout=self.heartbeat_timeout_s,
                            retry=RetryPolicy(max_attempts=1))
                        probes[addr] = probe
                    probe.ping()
                    misses[addr] = 0
                except Exception as e:  # noqa: BLE001 — counted, typed below
                    probe = probes.pop(addr, None)
                    if probe is not None:
                        probe.close()
                    misses[addr] = misses.get(addr, 0) + 1
                    if misses[addr] >= self.heartbeat_misses:
                        misses[addr] = 0
                        self._evict_follower(
                            addr, f"{self.heartbeat_misses} missed "
                                  f"heartbeats: {type(e).__name__}: {e}")
            for addr in degraded:
                if self._stop.is_set():
                    return
                self._try_reattach(addr)
        for probe in probes.values():
            probe.close()

    def _try_reattach(self, addr: str) -> bool:
        """Attempt to bring one degraded follower back: dial it, resync
        its store from a leader checkpoint, readmit it to the mirror
        set. Quietly returns False while the follower stays down. The
        resync connection is FULLY bounded (dial, handshake, reply) —
        the resync holds the leader's write path, so a follower that
        answers the dial and then hangs must fail the resync within
        ``resync_timeout_s``, never wedge the health thread (and with
        it every mutation) forever."""
        try:
            fc = self._dial_follower(addr, timeout=self.resync_timeout_s)
        except OSError:
            return False
        try:
            with self._followers_mu:
                offset = self._follower_offsets.get(addr)
            if self.mutlog is not None and offset is not None \
                    and offset <= self.mutlog.last_offset():
                # log replay: re-send only the frames this follower
                # missed since its last ack — minutes of divergence
                # costs kilobytes, not a whole-store snapshot
                self._resync_follower_log(addr, fc, offset)
            else:
                self._resync_follower(addr, fc)
            return True
        except Exception as e:  # noqa: BLE001 — recorded, retried later
            fc.close()
            with self._followers_mu:
                if addr in self._degraded:
                    self._degraded[addr] = (f"resync failed: "
                                            f"{type(e).__name__}: {e}")
            return False

    def _resync_follower(self, addr: str, fc) -> None:
        """Rebuild ``addr``'s store from a leader snapshot, then
        readmit it. The snapshot is taken under the exclusive frame
        order (and the collective lock), so no mutation can interleave
        between 'what the checkpoint holds' and 'first mirrored frame
        the readmitted follower sees' — the store-equality guarantee.
        Reads never take these locks: the leader keeps serving them
        throughout (degraded mode is only a write-path pause). Old
        snapshot steps are pruned after success — a flapping follower
        must not fill the leader's disk.

        The snapshot pickles ONCE, lands in the leader's local
        checkpoint dir (durability), and STREAMS to the follower in
        bounded frames over the wire (``RemoteClient.resync_follower``)
        — no shared-filesystem assumption: leader and follower may run
        with completely disjoint root dirs or on different hosts."""
        from netsdb_tpu.storage import checkpoint

        self._resync_idle.clear()
        self._order.acquire_write()
        try:
            with self._collective_lock:
                step = next(self._resync_seq)
                root = os.path.join(self.config.root_dir, "resync")
                blob = checkpoint.dumps_store(self._snapshot_state())
                checkpoint.save_store_bytes(root, blob, step)
                fc.resync_follower(blob, step)
                # the resync client carries resync_timeout_s on every
                # recv; the LINK must not (mirrored EXECUTEs may run
                # for minutes) — so the readmitted link gets a fresh
                # unbounded-reply connection
                fc.close()
                if self.mutlog is not None:
                    # the snapshot captures everything up to HERE in
                    # the log (we hold the exclusive order — no frame
                    # can append concurrently); a later eviction of
                    # this follower resumes replay from this offset
                    off = self.mutlog.last_offset()
                    with self._followers_mu:
                        self._follower_offsets[addr] = off
                    checkpoint.save_meta(root, step,
                                         {"mutlog_offset": off})
                link_client = self._dial_follower(addr)
                with self._followers_mu:
                    self._degraded.pop(addr, None)
                    link = self._links[addr] = _FollowerLink(
                        addr, link_client)
                if self._ha is not None \
                        and self._ha.role == _ha.LEADER:
                    # the readmitted follower may have missed epochs
                    # (or a whole term) — re-announce on its fresh link
                    link.submit(MsgType.HA_STATE,
                                self._ha_state_payload(), CODEC_MSGPACK)
                checkpoint.prune_steps(root, keep=1)
                self._idem.prune()  # same disk-bounding moment: old
                # persisted idempotency tokens go with old snapshots
        finally:
            self._order.release_write()
            self._resync_idle.set()

    def _resync_follower_log(self, addr: str, fc, offset: int) -> None:
        """Log-replay readmission (``ha_mutlog``): re-send every
        mutation-log frame past ``offset`` to the reattached follower,
        then readmit it — the snapshot's store-equality argument holds
        because the replay runs under the same exclusive frame order
        (nothing can append between 'replay bound captured' and 'link
        installed'). Each replayed frame carries a deterministic
        fallback idempotency token (``mutlog-<end>``) so a frame the
        follower DID apply before dying dedupes instead of
        double-applying, and the CURRENT term so a deposed leader's
        replay is rejected typed."""
        self._resync_idle.clear()
        self._order.acquire_write()
        try:
            with self._collective_lock:
                bound = self.mutlog.last_offset()
                for end, rec in self.mutlog.replay(offset):
                    if rec.get("op") == "alias":
                        fc._request(MsgType.TOKEN_ALIAS,
                                    {"alias": rec["alias"],
                                     "target": rec["target"]},
                                    CODEC_MSGPACK)
                        continue
                    if rec.get("op") != "frame":
                        continue
                    payload = dict(rec["payload"])
                    payload.setdefault(IDEMPOTENCY_KEY, f"mutlog-{end}")
                    if self._ha is not None:
                        payload[HA_TERM_KEY] = self._ha.term
                    fc._request(MsgType(rec["typ"]), payload,
                                rec.get("codec", CODEC_PICKLE))
                fc.close()
                link_client = self._dial_follower(addr)
                with self._followers_mu:
                    self._degraded.pop(addr, None)
                    self._follower_offsets[addr] = bound
                    link = self._links[addr] = _FollowerLink(
                        addr, link_client)
                if self._ha is not None \
                        and self._ha.role == _ha.LEADER:
                    link.submit(MsgType.HA_STATE,
                                self._ha_state_payload(), CODEC_MSGPACK)
        finally:
            self._order.release_write()
            self._resync_idle.set()

    def _snapshot_state(self) -> Dict[str, Any]:
        """The leader's replayable state: databases, registered types,
        and every set as host values. Paged relations snapshot as their
        host-assembled form (chunk tables / records) and re-page on the
        follower; a paged MATRIX — which by design never materializes
        densely (PAGED_MATMUL streams it) — snapshots as its ordered
        arena PAGE BLOCKS and replays page by page on the follower
        (``SetStore.restore_paged_matrix``), closing the PR 2 leftover
        where it resynced as an empty set."""
        from netsdb_tpu.core.blocked import BlockedTensor
        from netsdb_tpu.relational.outofcore import PagedColumns
        from netsdb_tpu.storage.paged import PagedObjects
        from netsdb_tpu.storage.store import _PagedMatrix

        cat = self.library.catalog
        types = []
        for t in cat.list_types():
            types.append({"type": t["type"],
                          "entry_point": t["entry_point"],
                          "source": cat.get_type_source(t["type"])})
        sets = []
        for ident in self.library.store.list_sets():
            meta = cat.get_set(ident.db, ident.set) or {}
            storage = self.library.store.storage_of(ident)
            entry: Dict[str, Any] = {
                "db": ident.db, "set": ident.set,
                "type_name": meta.get("type", "tensor"),
                "persistence": meta.get("persistence", "transient"),
                "storage": storage,
            }
            items = self.library.store.get_items(ident)
            if storage == "paged":
                if len(items) == 1 and isinstance(items[0], PagedColumns):
                    entry["kind"] = "paged-table"
                    entry["table"] = items[0].to_host_table()
                elif len(items) == 1 and isinstance(items[0], PagedObjects):
                    entry["kind"] = "paged-objects"
                    entry["items"] = list(items[0])
                elif len(items) == 1 and isinstance(items[0],
                                                    _PagedMatrix):
                    # paged MATRIX: snapshot its arena pages in order
                    # so the follower re-pages them block by block.
                    # Peak: ALL pages host-resident in the snapshot at
                    # once — the SAME whole-relation bound the
                    # paged-table branch above pays (to_host_table) and
                    # the one-blob resync wire format imposes anyway;
                    # a bounded page-streamed resync is the ROADMAP
                    # follow-on. The read lock pins the pages against a
                    # concurrent replace; the snapshot itself already
                    # holds the exclusive frame order.
                    pm = items[0]
                    ps = self.library.store.page_store()
                    with pm.rw.read():
                        blocks = [np.asarray(b) for _, b in
                                  ps.stream_blocks(f"{pm.ident}.mat",
                                                   prefetch=0)]
                        rb = int(ps.meta(f"{pm.ident}.mat")[1][0])
                    entry["kind"] = "paged-matrix"
                    entry["blocks"] = blocks
                    entry["row_block"] = rb
                else:
                    # unknown/empty paged content: recreate the (empty)
                    # set so the follower keeps accepting frames for it
                    entry["kind"] = "paged-empty"
            elif len(items) == 1 and isinstance(items[0], BlockedTensor):
                t = items[0]
                entry["kind"] = "tensor"
                entry["dense"] = np.asarray(t.to_dense())
                entry["block_shape"] = list(t.meta.block_shape)
            else:
                entry["kind"] = "objects"
                entry["items"] = list(items)
            sets.append(entry)
        return {"databases": cat.list_databases(), "types": types,
                "sets": sets}

    def _on_resync_follower(self, p):
        """Follower side: replace this daemon's store with the leader's
        snapshot. The primary form is ``snapshot_blob`` — the pickled
        snapshot assembled from the wire-streamed bulk conversation
        (no shared filesystem: the blob never touches this daemon's
        disk); ``path`` remains as the legacy shared-fs form. Either
        way the restore executes pickle — the codec-1 trust boundary,
        so it requires allow_pickle (trusted-cluster control planes
        only)."""
        if not self.allow_pickle:
            raise ProtocolError(
                "RESYNC_FOLLOWER refused: snapshot restore executes "
                "pickle and this daemon has allow_pickle off")
        from netsdb_tpu.storage import checkpoint

        if "snapshot_blob" in p:
            snap = checkpoint.loads_store(p["snapshot_blob"])
            self.last_resync_mode = "wire"
        else:
            snap = checkpoint.load_store(p["path"], p.get("step"))
            self.last_resync_mode = "path"
        for ident in list(self.library.store.list_sets()):
            self.library.remove_set(ident.db, ident.set)
        for db in snap["databases"]:
            self.library.create_database(db)
        for t in snap.get("types", []):
            self.library.register_type(t["type"], t["entry_point"],
                                       source=t.get("source"))
        restored = 0
        for entry in snap["sets"]:
            self.library.create_set(entry["db"], entry["set"],
                                    type_name=entry["type_name"],
                                    persistence=entry["persistence"],
                                    storage=entry.get("storage", "memory"))
            kind = entry["kind"]
            if kind == "tensor":
                self.library.send_matrix(entry["db"], entry["set"],
                                         entry["dense"],
                                         tuple(entry["block_shape"]))
            elif kind == "paged-table":
                # host chunk table re-pages through the ingest path
                self.library.send_table(entry["db"], entry["set"],
                                        entry["table"])
            elif kind == "paged-matrix":
                # leader arena pages replay page by page — the matrix
                # never materializes densely on this side either
                self.library.store.restore_paged_matrix(
                    SetIdentifier(entry["db"], entry["set"]),
                    entry["blocks"], int(entry.get("row_block") or 1))
            elif kind == "paged-empty":
                pass  # set exists; content streams in on next ingest
            elif entry["items"]:
                # verbatim replay (items are already post-ingest form;
                # send_data would re-columnarize "objects" sets)
                self.library.store.add_data(
                    SetIdentifier(entry["db"], entry["set"]),
                    list(entry["items"]))
            restored += 1
        # the whole store was just replaced wholesale: every remove/
        # re-ingest above already bumped its set's version, but the
        # explicit clear returns the dead device blocks to the budget
        # NOW (the resync invalidation hook the cache contract names)
        self.library.store.device_cache().clear()
        return MsgType.OK, {"restored_sets": restored}

    #: mirrored frames scoped to ONE (db, set) target — these serialize
    #: per set (and hold the RW order shared) in replicated-daemon
    #: topologies; everything else mirrored is multi-set and holds the
    #: RW order exclusively (ordering model in ``__init__``)
    SET_SCOPED_FRAMES = frozenset({
        MsgType.CREATE_SET, MsgType.REMOVE_SET, MsgType.CLEAR_SET,
        MsgType.SEND_DATA, MsgType.SEND_MATRIX, MsgType.LOAD_SET,
        # GENERATE rides the set-scoped lane keyed (model db, sid):
        # concurrent SESSIONS mirror-execute in parallel (and so can
        # coalesce into one padded batch), while one session's steps
        # stay serialized — per-session FIFO to every follower
        MsgType.GENERATE,
    })

    def _set_lock(self, db: str, set_name: str) -> TrackedLock:
        with self._set_locks_mu:
            return self._set_locks.setdefault(
                (db, set_name),
                TrackedLock("ServeController._set_locks[]"))

    def _run_mirrored(self, typ, payload, codec, handler, token=None,
                      qid=None, client=None):
        """Execute one mutating/job frame on EVERY process, holding the
        frame's ORDERING lock across both the follower enqueue and the
        local handler (see the ordering model in ``__init__`` — the
        lock choice is what keeps master execution order equal to
        follower receipt order for conflicting frames). Forwarding
        itself still overlaps local execution (the processes rendezvous
        inside XLA). A follower failure after local success EVICTS the
        follower into the degraded state (background resync reattaches
        it from a leader checkpoint) and surfaces as the typed
        retryable ``FollowerDegraded`` — the idempotent retry then
        returns the locally-applied result instead of double-applying
        (this replaces the old raise-and-diverge split-brain error)."""
        import jax

        if not self._resync_idle.wait(self.resync_grace_s):
            # a resync holds the write path; shed typed-retryable
            # instead of queueing unboundedly behind it
            raise FollowerDegraded(
                f"follower resync in progress (> {self.resync_grace_s}s); "
                f"retry shortly")
        if jax.process_count() > 1:
            # true SPMD: one total order for everything mirrored
            with self._collective_lock:
                return self._mirror_once(typ, payload, codec, handler,
                                         token, qid, client)
        if typ in self.SET_SCOPED_FRAMES and "db" in payload \
                and "set" in payload:
            self._order.acquire_read()
            try:
                with self._set_lock(payload["db"], payload["set"]):
                    return self._mirror_once(typ, payload, codec, handler,
                                             token, qid, client)
            finally:
                self._order.release_read()
        self._order.acquire_write()
        try:
            return self._mirror_once(typ, payload, codec, handler, token,
                                     qid, client)
        finally:
            self._order.release_write()

    def _mirror_once(self, typ, payload, codec, handler, token=None,
                     qid=None, client=None):
        # forward the CLIENT's idempotency token (popped before
        # dispatch) so followers dedupe too: if the local handler fails
        # retryably AFTER the forward (e.g. AdmissionFull), the
        # client's retry re-forwards the frame — without the shared
        # token each follower would apply it twice and diverge.
        # The query id rides along for the same reason traces exist:
        # one logical query's spans must join up across every daemon
        # that executed it (GET_TRACE merges them by qid) — and the
        # client identity likewise, so follower-side attribution books
        # the same tenant the leader does.
        fwd = payload
        lane = _sched.current_lane()  # the frame's hint, if any —
        # followers admit their mirrored copy through the same lane
        if token is not None or qid is not None or client is not None \
                or lane is not None or self._ha is not None:
            fwd = dict(payload)
            if token is not None:
                fwd[IDEMPOTENCY_KEY] = token
            if qid is not None:
                fwd[QUERY_ID_KEY] = qid
            if client is not None:
                fwd[CLIENT_ID_KEY] = client
            if lane is not None:
                fwd[LANE_KEY] = lane
            if self._ha is not None:
                # every mirrored frame is fenced by the sender's term:
                # a follower that adopted a newer leader rejects this
                # straggler typed instead of double-applying it
                fwd[HA_TERM_KEY] = self._ha.term
        with self._mirror_lock:  # short: dial + ordered enqueue only
            self._ensure_followers()
            offset = None
            if self.mutlog is not None:
                # append INSIDE the enqueue lock: log order == every
                # FIFO link's frame order, so "replay from offset N"
                # reconstructs exactly the stream a follower missed
                offset = self.mutlog.append(
                    {"op": "frame", "typ": int(typ), "codec": codec,
                     "payload": fwd})
            with self._followers_mu:
                pending = [(addr, link.submit(typ, fwd, codec,
                                              offset=offset))
                           for addr, link in self._links.items()]
        try:
            out = handler(payload)
        finally:
            failures, deposed = self._collect_mirror_failures(pending)
        if deposed is not None:
            # a follower answered NotLeader: it adopted a NEWER term —
            # this daemon was deposed while the frame was in flight.
            # Step down (keeping the follower: its link is healthy and
            # the new leader owns resyncing it) and bounce the client
            # to the real leader. The locally-applied copy is private
            # divergence — wiped when this daemon rejoins as a
            # follower and resyncs; the client's retry executes on
            # the real leader, exactly once in authoritative history.
            addr, exc = deposed
            self._ha.step_down(getattr(exc, "term", None),
                               getattr(exc, "leader_addr", None))
            raise NotLeader(
                f"this daemon was deposed mid-mirror ({addr} rejected "
                f"the frame: {exc}); retry against the current leader",
                leader_addr=getattr(exc, "leader_addr", None),
                term=self._ha.term)
        if failures:
            exc = FollowerDegraded(
                "mirror failed; follower(s) evicted for resync: "
                + "; ".join(f"{a}: {m}" for a, m in failures))
            exc.local_result = out  # applied here — retry must not redo
            raise exc
        return out

    def _collect_mirror_failures(self, pending) -> Tuple[list, Any]:
        """Wait (bounded) for every follower ack; evict the ones that
        errored or hung. ONE shared deadline covers the whole frame —
        three hung followers cost one timeout, not three stacked. The
        ack-timeout eviction aborts the link's socket, so its drain
        thread unblocks — a hung follower can never wedge the leader's
        handler thread.

        Returns ``(failures, deposed)``: ``deposed`` is ``(addr,
        NotLeaderError)`` when a follower rejected the frame because
        it follows a NEWER term — that is a fencing verdict on THIS
        daemon, not a follower fault, so the follower is NOT
        evicted."""
        deadline = (deadline_after(self.mirror_ack_timeout_s)
                    if self.mirror_ack_timeout_s is not None else None)
        failures = []
        deposed = None
        for addr, rec in pending:
            left = (max(0.0, seconds_left(deadline))
                    if deadline is not None else None)
            if not rec["done"].wait(left):
                failures.append(
                    (addr, f"no mirror ack within the frame's "
                           f"{self.mirror_ack_timeout_s}s budget"))
                self._evict_follower(
                    addr, f"mirror ack timeout "
                          f"({self.mirror_ack_timeout_s}s)")
            elif rec.get("error"):
                exc = rec.get("exc")
                if self._ha is not None \
                        and isinstance(exc, NotLeaderError):
                    if deposed is None:
                        deposed = (addr, exc)
                    continue
                failures.append((addr, rec["error"]))
                self._evict_follower(addr, rec["error"])
        return failures, deposed

    # --- job bookkeeping ----------------------------------------------
    def _run_job(self, job_name: str, fn: Callable[[], Any],
                 scopes=()) -> Any:
        """Admit + run one job under the query scheduler. Admission is
        lane-keyed (the frame's LANE_KEY hint, else its client
        identity, else the default lane) and bounded: a saturated lane
        refuses typed-retryable (LaneSaturated on quota, AdmissionFull
        with the lane's retry_after_s hint on timeout) instead of
        parking the handler thread forever. ``scopes`` ("db:set" scan
        leaves) then pass the cache-aware affinity gate: siblings of a
        cold-set installer wait (bounded) and wake into the warm
        device cache instead of racing cold streams."""
        job_id = next(self._job_seq)
        # "submitted" is a display timestamp (list_jobs), never compared
        # against a deadline — the one sanctioned wall-clock read
        rec = {"id": job_id, "name": job_name, "status": "queued",
               "submitted": wall_now(), "elapsed": None, "lane": None}
        with self._jobs_lock:
            self._jobs[job_id] = rec
            # bounded history so a long-lived daemon cannot grow this
            while len(self._jobs) > 1024:
                self._jobs.pop(next(iter(self._jobs)))
        lane = _sched.current_lane() or obs.attrib.current_client()
        try:
            with obs.span("server.sched.admit", "serve"):
                ticket = self.sched.acquire(
                    lane, timeout_s=self.admission_timeout_s)
        except (AdmissionFull, LaneSaturated):
            rec["status"] = "rejected"
            raise
        rec["status"] = "running"
        rec["lane"] = ticket.lane
        tr = obs.current_trace()
        if tr is not None:
            tr.annotate("sched.lane", ticket.lane)
        t0 = time.perf_counter()
        try:
            with self.sched.affinity(scopes):
                with obs.span(f"server.job:{job_name}", "job"):
                    out = fn()
            rec["status"] = "done"
            return out
        except Exception:
            rec["status"] = "failed"
            raise
        finally:
            rec["elapsed"] = time.perf_counter() - t0
            self.sched.release(ticket)

    # --- handlers -----------------------------------------------------
    def _on_ping(self, p) -> Tuple[MsgType, Any]:
        with self._jobs_lock:
            done = sum(1 for j in self._jobs.values() if j["status"] == "done")
        out = {"uptime": time.monotonic() - self._started,
               "jobs_done": done,
               "sets": len(self.library.store.list_sets())}
        if self._follower_addrs:
            out["followers"] = self.follower_status()
        if self._ha is not None:
            # the probe doubles as leader discovery: a follower's ping
            # reply names who IT believes leads, and the HA monitor's
            # liveness check reads the role straight off this
            out["ha"] = self._ha.snapshot()
        return MsgType.OK, out

    def _on_ha_state(self, p):
        """Leader → follower state announcement (term, leader address,
        placement map) — shipped through the ordered mirror links on
        every epoch bump and on arming, so a promoted follower already
        HOLDS the routing map the instant it wins an election."""
        if self._ha is None:
            return MsgType.OK, {"armed": False}
        self._ha.adopt_leader(p.get("leader"), int(p.get("term") or 0))
        placement = p.get("placement")
        if placement:
            self._ha.store_placement(placement)
        return MsgType.OK, self._ha.snapshot()

    def _on_token_alias(self, p):
        """Leader → follower: finish a coalesce WAITER's idempotency
        token with its leader-token's cached reply (the frame rides
        the same FIFO link as the mirrored execution, so the target is
        already cached when this lands)."""
        ok = self._idem.alias(str(p["alias"]), str(p["target"]))
        return MsgType.OK, {"aliased": bool(ok)}

    def _on_create_database(self, p):
        self.library.create_database(p["db"])
        return MsgType.OK, {}

    @staticmethod
    def _shard_mode(placement_arg) -> Tuple[Optional[str], Optional[str]]:
        """(mode, key) when ``placement`` asks for pool sharding —
        the string forms ``"hash"``/``"range"`` or ``{"shard": mode,
        "key": col}`` — else (None, None): mesh Placement metas and
        plain sets flow through untouched."""
        if isinstance(placement_arg, str) \
                and placement_arg in ("hash", "range"):
            return placement_arg, None
        if isinstance(placement_arg, dict) and placement_arg.get("shard"):
            return str(placement_arg["shard"]), placement_arg.get("key")
        return None, None

    def _create_local_set(self, p) -> None:
        self.library.create_set(
            p["db"], p["set"], type_name=p.get("type_name", "tensor"),
            persistence=p.get("persistence", "transient"),
            eviction=p.get("eviction", "lru"),
            partition_lambda=p.get("partition_lambda"),
            placement=None,
            storage=p.get("storage", "memory"))

    def _on_create_set(self, p):
        shard_info = p.get("__shard__")
        if shard_info is not None:
            # worker side of a sharded create: one local slot set plus
            # the epoch registration routed frames validate against
            # (create_database is idempotent — workers need the db
            # even though only the leader saw CREATE_DATABASE)
            self.library.create_database(p["db"])
            self._create_local_set(p)
            self._register_shard(p["db"], p["set"],
                                 shard_info["slot"],
                                 shard_info["epoch"])
            return MsgType.OK, {}
        if p.get("placement") == "mirror":
            # the explicit spelling of the default replication mode:
            # full copy on every follower, nothing sharded
            p = {**p, "placement": None}
        mode, key = self._shard_mode(p.get("placement"))
        if mode is not None:
            # leader side: this daemon is slot 0; every pool worker
            # gets one slot. A degraded pool refuses typed BEFORE any
            # mutation — registering a dead worker's slot as live
            # would turn every later routed frame into a raw
            # connection error instead of the typed story.
            degraded = self.shards.degraded()
            if degraded:
                raise ShardUnavailable(
                    f"cannot create partitioned set "
                    f"{p['db']}:{p['set']}: pool worker(s) "
                    f"{sorted(degraded)} are degraded; retry after "
                    f"readmit")
            self._create_local_set(p)
            addrs = [self.advertise_addr] + list(self._worker_addrs)
            entry = self.placement.create(p["db"], p["set"], addrs,
                                          mode=mode, key=key)
            fwd = {k: v for k, v in p.items() if k != "placement"}
            try:
                for i, addr in enumerate(addrs[1:], start=1):
                    self.shards.peer_request(
                        addr, MsgType.CREATE_SET,
                        {**fwd, "__shard__": {"slot": i,
                                              "epoch": entry["epoch"]}})
            except Exception as e:
                # a worker died mid-create: unregister the half-born
                # entry (the local set stays — harmless, and a retry
                # recreates over it) and surface typed retryable
                self.placement.remove(p["db"], p["set"])
                raise ShardUnavailable(
                    f"partitioned create of {p['db']}:{p['set']} "
                    f"failed mid-fanout ({type(e).__name__}: {e}); "
                    f"placement rolled back — retry") from e
            self._replicate_placement()
            return MsgType.OK, {"placement": entry}
        self.library.create_set(
            p["db"], p["set"], type_name=p.get("type_name", "tensor"),
            persistence=p.get("persistence", "transient"),
            eviction=p.get("eviction", "lru"),
            partition_lambda=p.get("partition_lambda"),
            placement=p.get("placement"),  # Placement.to_meta dict
            storage=p.get("storage", "memory"))
        return MsgType.OK, {}

    def _fanout_sharded_ddl(self, typ, p) -> bool:
        """Forward one DDL frame to every worker slot of a sharded
        set. DDL is all-or-nothing like the partial merges: a
        degraded slot REFUSES typed-retryable (a clear/remove that
        skipped an unreachable shard would leave it holding pages
        every other slot deleted — divergence at readmit), and a
        forward failure raises. True when the set was sharded."""
        entry = self.placement.entry(p["db"], p["set"])
        if entry is None:
            return False
        for i, sl in enumerate(entry["slots"]):
            if sl["state"] != _placement.LIVE:
                raise ShardUnavailable(
                    f"slot {i} of {p['db']}:{p['set']} ({sl['addr']}) "
                    f"is degraded; pool-wide DDL refused rather than "
                    f"diverge the absent shard — retry after readmit",
                    slot=i, epoch=entry["epoch"])
        for sl in entry["slots"]:
            if sl["addr"] != self.advertise_addr:
                self.shards.peer_request(sl["addr"], typ,
                                         {"db": p["db"],
                                          "set": p["set"]})
        return True

    def _on_remove_set(self, p):
        if self._fanout_sharded_ddl(MsgType.REMOVE_SET, p):
            self.placement.remove(p["db"], p["set"])
            self._replicate_placement()
        # bytes-accounting hygiene: any buffered handoff for the set
        # dies with it (unreachable once the placement entry is gone)
        self.shards.purge_handoff(p["db"], p["set"])
        with self._shard_mu:
            self._shard_sets.pop((p["db"], p["set"]), None)
        self.library.remove_set(p["db"], p["set"])
        return MsgType.OK, {}

    def _on_clear_set(self, p):
        if self._fanout_sharded_ddl(MsgType.CLEAR_SET, p):
            self.shards.purge_handoff(p["db"], p["set"])
        self.library.clear_set(p["db"], p["set"])
        return MsgType.OK, {}

    def _on_set_exists(self, p):
        return MsgType.OK, {"exists": self.library.set_exists(p["db"], p["set"])}

    def _on_list_sets(self, p):
        return MsgType.OK, {"sets": [list(i) for i in self.library.store.list_sets()]}

    def _on_register_type(self, p):
        self.library.register_type(p["type_name"], p["entry_point"],
                                   source=p.get("source"))
        return MsgType.OK, {}

    def _resolve_registered(self, name_or_entry: str) -> Any:
        """Resolve a registry value: a registered type name goes through
        the catalog (picking up shipped source for modules the daemon
        doesn't have installed); anything else is a raw entry point."""
        entry = self.library.catalog.get_type(name_or_entry)
        if entry is not None:
            return resolve_entry_point(
                entry, self.library.catalog.get_type_source(name_or_entry))
        return resolve_entry_point(name_or_entry)

    def _on_send_data(self, p):
        epoch = p.pop(PLACEMENT_EPOCH_KEY, None)
        slot = p.pop(SHARD_SLOT_KEY, None)
        route = self._shard_route(p.get("db"), p.get("set"), epoch, slot)
        if route == "handoff":
            # the slot's shard is away: buffer EXACTLY this slot's
            # batch at the leader; the readmit drain ships it (and
            # only it) back — the shard-scoped resync. The drain rides
            # the CLIENT's idempotency token: if the shard already
            # applied this batch before the eviction (reply lost), its
            # cache dedupes the drained copy instead of doubling it.
            items = p.get("items")
            count = int(getattr(items, "num_rows", None)
                        or (len(items) if hasattr(items, "__len__")
                            else 0))
            self.shards.handoff_put(p["db"], p["set"], int(slot),
                                    _idem_token_var.get(), p)
            return MsgType.OK, {"count": count, "handoff": True}
        # objects arrive via the pickle codec (whole payload is a dict)
        if p.get("as_table"):
            # rows → one dictionary-encoded ColumnTable, sharded by the
            # set's placement (dispatcher page-building + partitioning);
            # append=True adds the batch instead of replacing
            t = self.library.send_table(p["db"], p["set"], p["items"],
                                        date_cols=p.get("date_cols", ()),
                                        append=bool(p.get("append")))
            return MsgType.OK, {"count": t.num_rows,
                                "columns": sorted(t.cols)}
        self.library.send_data(p["db"], p["set"], p["items"])
        return MsgType.OK, {"count": len(p["items"])}

    def _on_send_matrix(self, p):
        # a batch-partitioned TENSOR set (the model-serving input
        # shape) takes routed frames exactly like SEND_DATA: the
        # client splits rows by the placement's range slices and each
        # slot daemon ingests its contiguous slice as the local
        # partition. An unrouted frame against a sharded set gets
        # _shard_route's typed placement rejection.
        epoch = p.pop(PLACEMENT_EPOCH_KEY, None)
        slot = p.pop(SHARD_SLOT_KEY, None)
        route = self._shard_route(p.get("db"), p.get("set"), epoch, slot)
        if route == "handoff":
            # matrix slices are not handoff-buffered (a scoring batch
            # is transient, unlike durable table rows): refuse typed
            # retryable — the client re-routes after readmit
            raise ShardUnavailable(
                f"slot {slot} of {p['db']}:{p['set']} is degraded; "
                f"matrix ingest refused — retry after readmit",
                slot=slot, epoch=epoch)
        dense, block_shape = tensor_from_wire(p["tensor"])
        t = self.library.send_matrix(p["db"], p["set"], dense, block_shape)
        if t is None:
            # storage="paged" set: the matrix went into the arena, not
            # HBM — reply from the ingested array (there is no blocked
            # tensor to describe)
            return MsgType.OK, {"shape": list(dense.shape),
                                "dtype": str(np.asarray(dense).dtype),
                                "block_shape": None}
        return MsgType.OK, {"shape": list(t.shape), "dtype": str(t.dtype),
                            "block_shape": list(t.meta.block_shape)}

    def _on_paged_matmul(self, p):
        """stored @ rhs with the stored matrix streamed from the arena
        page by page — the daemon-side consumption path for paged
        TENSOR sets (whose GET_TENSOR deliberately raises)."""
        out = self.library.paged_matmul(p["db"], p["set"],
                                        np.asarray(p["rhs"]))
        return MsgType.OK, {"data": np.asarray(out)}

    def _on_get_tensor(self, p):
        t = self.library.get_tensor(p["db"], p["set"])
        # mesh-spanning placed tensors assemble via follower shards
        t = self._fetch_global(p["db"], p["set"], t)
        dense = np.asarray(t.to_dense())
        return MsgType.OK, {"data": dense,
                            "block_shape": list(t.meta.block_shape)}

    # --- multi-host reads of placed sets -----------------------------
    # A mesh-spanning jax.Array cannot be np.asarray'd on one process.
    # Reads therefore assemble the GLOBAL value host-side: the master
    # fills from its own addressable shards and asks each follower
    # daemon for its local shards over the serve protocol (LOCAL_SHARDS
    # frames) — the reference streaming each node's local pages to the
    # frontend (FrontendQueryTestServer.cc:785-890). Reads never enter
    # the SPMD program: no collectives, no frame-ordering constraints.

    @staticmethod
    def _item_leaves(item) -> Optional[Dict[str, Any]]:
        """Named jax.Array leaves of a stored item (None = host object)."""
        import jax

        from netsdb_tpu.core.blocked import BlockedTensor
        from netsdb_tpu.relational.table import ColumnTable

        if isinstance(item, ColumnTable):
            leaves = dict(item.cols)
            if item.valid is not None:
                leaves["__valid__"] = item.valid
            return leaves
        if isinstance(item, BlockedTensor):
            return {"data": item.data}
        if isinstance(item, jax.Array):
            return {"value": item}
        return None

    @staticmethod
    def _rebuild_item(item, arrays: Dict[str, np.ndarray]):
        from netsdb_tpu.core.blocked import BlockedTensor
        from netsdb_tpu.relational.table import ColumnTable

        if isinstance(item, ColumnTable):
            valid = arrays.pop("__valid__", None)
            return ColumnTable(arrays, dict(item.dicts), valid)
        if isinstance(item, BlockedTensor):
            return BlockedTensor(arrays["data"], item.meta)
        return arrays["value"]

    @staticmethod
    def _shard_ranges(shard, shape):
        return [[s.start or 0, s.stop if s.stop is not None else dim]
                for s, dim in zip(shard.index, shape)]

    def _on_local_shards(self, p):
        """Follower side: this process's addressable shards of one
        stored item's arrays, as (index ranges, raw buffer) pairs."""
        item = self._single_item(p["db"], p["set"])
        leaves = self._item_leaves(item)
        if leaves is None:
            return MsgType.OK, {"leaves": None}
        out = {}
        for name, arr in leaves.items():
            out[name] = [
                {"idx": self._shard_ranges(s, arr.shape),
                 "data": np.asarray(s.data)}
                for s in arr.addressable_shards]
        return MsgType.OK, {"leaves": out,
                            "shapes": {n: list(a.shape)
                                       for n, a in leaves.items()}}

    def _single_item(self, db: str, set_name: str):
        items = self.library.store.get_items(SetIdentifier(db, set_name))
        if len(items) != 1:
            raise ValueError(f"set {db}:{set_name} holds {len(items)} "
                             f"items; shard assembly expects 1")
        return items[0]

    def _fetch_global(self, db: str, set_name: str, item):
        """Item with every mesh-spanning array replaced by its full
        host value (local shards + follower LOCAL_SHARDS)."""
        import jax

        leaves = self._item_leaves(item)
        if leaves is None or all(
                (not isinstance(a, jax.Array)) or a.is_fully_addressable
                for a in leaves.values()):
            return item
        if self._single_item(db, set_name) is not item:
            raise ValueError(
                f"set {db}:{set_name}: shard assembly of mesh-spanning "
                f"arrays supports single-item sets only")
        from netsdb_tpu.serve.protocol import CODEC_MSGPACK

        # the WHOLE assembly — master-local shard copy AND follower
        # fetches — runs under the collective lock, which every
        # spanning mutation (EXECUTE_*/SEND_* in multi-process mode)
        # also holds: without it, a concurrent replacement could tear
        # the result into pre-mutation master halves + post-mutation
        # follower halves
        with self._collective_lock:
            # re-read under the lock: the set may have been replaced
            # while we waited
            item = self._single_item(db, set_name)
            leaves = self._item_leaves(item)
            out: Dict[str, np.ndarray] = {}
            covered: Dict[str, np.ndarray] = {}
            for name, arr in leaves.items():
                buf = np.empty(arr.shape, arr.dtype)
                cov = np.zeros(arr.shape, np.bool_)
                for s in arr.addressable_shards:
                    idx = tuple(slice(a, b) for a, b
                                in self._shard_ranges(s, arr.shape))
                    buf[idx] = np.asarray(s.data)
                    cov[idx] = True
                out[name] = buf
                covered[name] = cov
            with self._mirror_lock:
                self._ensure_followers()
                with self._followers_mu:
                    recs = [(addr, link.submit(MsgType.LOCAL_SHARDS,
                                               {"db": db, "set": set_name},
                                               CODEC_MSGPACK))
                            for addr, link in self._links.items()]
            # same deadline discipline as the mutation mirror: a
            # follower that hangs serving LOCAL_SHARDS (heartbeats may
            # still pass — the daemon is alive, one handler is stuck)
            # is evicted at the shared ack deadline and the read fails
            # TYPED-retryable, never wedging this handler thread
            failures = self._collect_mirror_failures(recs)
            if failures:
                raise FollowerDegraded(
                    "follower shard fetch failed; evicted for resync: "
                    + "; ".join(f"{a}: {m}" for a, m in failures))
            for _addr, rec in recs:
                for name, shards in (rec["reply"]["leaves"] or {}).items():
                    for sh in shards:
                        idx = tuple(slice(a, b) for a, b in sh["idx"])
                        out[name][idx] = sh["data"]
                        covered[name][idx] = True
            missing = [n for n, c in covered.items() if not c.all()]
            if missing:
                # e.g. a client reading through a WORKER daemon (no
                # follower links): returning np.empty garbage would be
                # silent corruption — reads of spanning sets must go to
                # the daemon that knows every holder
                raise RuntimeError(
                    f"set {db}:{set_name}: cannot assemble mesh-spanning "
                    f"columns {missing} — this daemon's local + follower "
                    f"shards do not cover the arrays (read through the "
                    f"master daemon)")
        return self._rebuild_item(item, out)

    def _scan_items(self, db: str, set_name: str):
        """Set scan for the wire: a paged set's PagedColumns handle is
        process-local (it wraps the native arena), so it ships as its
        HOST-assembled table (numpy columns — the device never sees a
        set that was paged because it does not fit; the STREAMED scan
        ships it page by page instead), and mesh-spanning placed items
        assemble their global value first (``_fetch_global``) — clients
        wanting summaries only should use ANALYZE_SET instead."""
        entry = self.placement.entry(db, set_name)
        if entry is not None:
            # sharded set: chain every slot's scan in slot order — the
            # leader's own partition streams locally, worker partitions
            # stream over their pool connections (bounded frames)
            for i, sl in enumerate(entry["slots"]):
                if sl["state"] != _placement.LIVE:
                    raise ShardUnavailable(
                        f"slot {i} of {db}:{set_name} ({sl['addr']}) "
                        f"is degraded; scan refused rather than return "
                        f"a partial set", slot=i, epoch=entry["epoch"])
            for sl in entry["slots"]:
                if sl["addr"] == self.advertise_addr:
                    yield from self._scan_items_local(db, set_name)
                else:
                    client = self.shards.client(sl["addr"])
                    with contextlib.closing(
                            client.scan_stream(db, set_name)) as items:
                        yield from items
            return
        yield from self._scan_items_local(db, set_name)

    def _scan_items_local(self, db: str, set_name: str):
        from netsdb_tpu.relational.outofcore import PagedColumns
        from netsdb_tpu.storage.paged import PagedObjects
        from netsdb_tpu.storage.store import _PagedMatrix

        for item in self.library.get_set_iterator(db, set_name):
            if isinstance(item, PagedColumns):
                yield item.to_host_table()
            elif isinstance(item, PagedObjects):
                # record pages stream as records (the handle is
                # process-local; in the STREAMED scan these pack into
                # adaptive bounded frames like any object items).
                # closing(): the record generator holds the relation's
                # read lock — a client abandoning the scan mid-stream
                # (this generator is then closed, not exhausted) must
                # release it NOW, not when GC finds the frame
                with contextlib.closing(iter(item)) as records:
                    yield from records
            elif isinstance(item, _PagedMatrix):
                # the handle is process-local (it wraps the native
                # arena + a lock); the matrix itself deliberately never
                # materializes — consume it with PAGED_MATMUL
                raise ValueError(
                    f"set {db}:{set_name} holds a PAGED matrix — it "
                    f"streams (PAGED_MATMUL) and cannot be scanned "
                    f"over the wire")
            else:
                yield self._fetch_global(db, set_name, item)

    def _on_scan_set(self, p):
        from netsdb_tpu.serve.protocol import CODEC_PICKLE

        items = list(self._scan_items(p["db"], p["set"]))
        # host objects are arbitrary Python → pickle codec on the reply
        return MsgType.OK, {"items": items}, CODEC_PICKLE

    @staticmethod
    def _stream_paged(pc):
        """One host-side compact chunk table per frame, straight off
        the arena stream — the paged relation never materializes on
        the device or as one wire blob."""
        import contextlib
        import pickle

        def gen():
            seq = 0
            with contextlib.closing(
                    pc.stream_host_tables(prefetch=2)) as chunks:
                for tbl in chunks:
                    blob = pickle.dumps([tbl],
                                        protocol=pickle.HIGHEST_PROTOCOL)
                    yield MsgType.STREAM_ITEM, {"seq": seq,
                                                "batch": blob,
                                                "paged_chunk": True}
                    seq += 1
            yield MsgType.STREAM_END, {"frames": seq, "items": seq}

        return gen()

    def _on_scan_set_stream(self, p):
        """Streamed scan: items go out in frames of ~``max_frame_bytes``
        of pickled payload each — the server never materializes the
        whole set's wire form, and TCP backpressure holds buffering to
        one frame (ref FrontendQueryTestServer.cc:785-890 paging results
        to the client page by page).

        Each frame is ONE pickled list of items (per-item pickling
        measured 11× slower at 50k small rows). The items-per-frame
        count adapts to the observed bytes-per-item of the previous
        frame (growth capped at 4×/frame), so a frame overshoots the
        budget only while item sizes are growing and re-converges on
        the next frame — bounded memory, amortized serialization.

        A PAGED set streams its pages directly: one host-side compact
        chunk table per frame straight off the arena stream — the
        relation never materializes on the device OR as one wire blob
        (the reference streaming each node's local pages to the client
        page by page, ``FrontendQueryTestServer.cc:785-890``)."""
        import pickle

        from netsdb_tpu.relational.outofcore import PagedColumns

        budget = int(p.get("max_frame_bytes") or (4 << 20))
        # cheap storage peek — listing a big (possibly spilled)
        # non-paged set's items here would double-iterate it
        pc = None
        store = getattr(self.library, "store", None)
        if store is not None \
                and not self.is_sharded(p["db"], p["set"]):
            # a SHARDED set must take the generic path — _scan_items
            # chains every slot; the paged fast-path below would
            # stream only this daemon's local partition
            from netsdb_tpu.storage.store import SetIdentifier

            ident = SetIdentifier(p["db"], p["set"])
            if store.storage_of(ident) == "paged":
                items = store.get_items(ident)
                if len(items) == 1 and isinstance(items[0],
                                                  PagedColumns):
                    pc = items[0]
        if pc is not None:
            return self._stream_paged(pc)

        def stream():
            seq = 0
            total = 0
            # target starts at 1: the FIRST frame must not pack an
            # unmeasured batch (32 × 20 MB items would be a ~640 MB
            # frame — the exact both-ends spike streaming exists to
            # remove); the 4×/frame growth reaches steady state in a
            # handful of frames
            target = 1
            batch: list = []
            for item in self._scan_items(p["db"], p["set"]):
                batch.append(item)
                if len(batch) < target:
                    continue
                blob = pickle.dumps(batch,
                                    protocol=pickle.HIGHEST_PROTOCOL)
                yield MsgType.STREAM_ITEM, {"seq": seq, "batch": blob}
                seq += 1
                total += len(batch)
                per_item = max(len(blob) // len(batch), 1)
                target = max(1, min(budget // per_item, 4 * target))
                batch = []
            if batch:
                yield MsgType.STREAM_ITEM, {
                    "seq": seq,
                    "batch": pickle.dumps(batch,
                                          protocol=pickle.HIGHEST_PROTOCOL)}
                seq += 1
                total += len(batch)
            yield MsgType.STREAM_END, {"frames": seq, "items": total}

        return stream()

    def _on_get_tensor_chunked(self, p):
        """Chunked tensor pull: one meta frame, then the dense buffer in
        ``chunk_bytes`` slices, then STREAM_END. Bounds the *transfer*
        buffering to one chunk on each side (vs. a single frame holding
        the full payload twice); the dense host materialization itself
        is one copy, as in `_on_get_tensor`."""
        t = self.library.get_tensor(p["db"], p["set"])
        t = self._fetch_global(p["db"], p["set"], t)
        dense = np.ascontiguousarray(np.asarray(t.to_dense()))
        chunk = int(p.get("chunk_bytes") or (8 << 20))
        view = memoryview(dense).cast("B")
        nbytes = view.nbytes

        def stream():
            yield MsgType.STREAM_ITEM, {
                "seq": 0, "meta": {
                    "shape": list(dense.shape), "dtype": dense.dtype.str,
                    "block_shape": list(t.meta.block_shape),
                    "nbytes": nbytes,
                    "nchunks": max(1, -(-nbytes // chunk))}}
            seq = 1
            for off in range(0, max(nbytes, 1), chunk):
                # uint8 view over the dense buffer: the chunk rides as
                # an out-of-band segment — no per-chunk byte copy
                yield MsgType.STREAM_ITEM, {
                    "seq": seq,
                    "b": np.frombuffer(view[off:off + chunk], np.uint8)}
                seq += 1
            yield MsgType.STREAM_END, {"frames": seq}

        return stream()

    def _on_dedup_resident(self, p):
        """Pool shared blocks across resident model weight sets so
        fine-tuned variants share HBM (``Client.dedup_resident``) — the
        serve-time dedup flow (``SharedTensorBlockSet.h:25``)."""
        report = self.library.dedup_resident(
            [tuple(s) for s in p["sets"]], bands=int(p.get("bands", 16)),
            seed=int(p.get("seed", 0)))
        return MsgType.OK, report

    def _on_add_shared_mapping(self, p):
        self.library.add_shared_mapping(
            p["private_db"], p["private_set"], p["shared_db"], p["shared_set"],
            p.get("mapping"))
        return MsgType.OK, {}

    def _on_flush_data(self, p):
        self.library.flush_data()
        return MsgType.OK, {}

    def _on_load_set(self, p):
        self.library.store.load_set(SetIdentifier(p["db"], p["set"]))
        return MsgType.OK, {}

    @staticmethod
    def _sync_results(results: Dict[SetIdentifier, Any]) -> None:
        """Barrier on tensor results: the OK reply must mean the value
        exists, not that XLA enqueued it. A scalar reduce+pull is the
        only sync that holds over the controller↔device tunnel
        (block_until_ready returns early there)."""
        import jax.numpy as jnp

        from netsdb_tpu.core.blocked import BlockedTensor
        from netsdb_tpu.relational.table import ColumnTable

        for val in results.values():
            if isinstance(val, BlockedTensor):
                float(jnp.sum(val.data))
            elif isinstance(val, ColumnTable):
                float(jnp.sum(next(iter(val.cols.values()))
                              .astype(jnp.float32)))

    def _result_summaries(self, results: Dict[SetIdentifier, Any]) -> dict:
        from netsdb_tpu.core.blocked import BlockedTensor
        from netsdb_tpu.relational.table import ColumnTable

        out = {}
        for ident, val in results.items():
            if isinstance(val, BlockedTensor):
                out[str(ident)] = {"kind": "tensor", "shape": list(val.shape),
                                   "dtype": str(val.dtype)}
            elif isinstance(val, ColumnTable):
                out[str(ident)] = {"kind": "table", "rows": val.num_rows,
                                   "columns": sorted(val.cols)}
            elif isinstance(val, dict):
                out[str(ident)] = {"kind": "map", "count": len(val)}
            else:
                out[str(ident)] = {"kind": "objects",
                                   "count": len(list(val))}
        return out

    def _on_execute_computations(self, p):
        """Body (pickle codec): {sinks: [WriteSet...], job_name}. The
        DAG's callables were cloudpickled by the client — the analogue of
        ``executeComputations`` shipping serialized Computation objects
        whose code the worker loads from registered .so files.

        ``explain: true`` runs the job with per-operator recording
        FORCED (``obs.operators.explain_capture``) and round-trips the
        annotated plan tree in the reply — EXPLAIN ANALYZE over the
        wire; the same tree also rides the query's GET_TRACE profile
        when the frame carried a qid."""
        sinks = p["sinks"]
        job_name = p.get("job_name", "remote-job")
        if self._scatter_touched(sinks):
            return self._execute_scatter(p, job_name, sinks)

        def run():
            results = self.library.execute_computations(
                *sinks, job_name=job_name,
                materialize=p.get("materialize", True))
            if p.get("sync", True):
                self._sync_results(results)
            return results

        return self._execute_with_explain(
            p, job_name, run,
            scopes=_sched.sets_touched(MsgType.EXECUTE_COMPUTATIONS, p))

    def _scatter_touched(self, sinks) -> bool:
        """Does this DAG scan any set this daemon coordinates a
        partitioned placement for? Empty map (every non-pool daemon)
        short-circuits — the local path never pays a walk."""
        if not len(self.placement):
            return False
        from netsdb_tpu.plan import scatter

        return bool(scatter.sharded_scan_sets(sinks, self.is_sharded))

    def _execute_scatter(self, p, job_name, sinks):
        """Coordinator path for queries over partitioned sets: admit
        ONE job (admission/lanes/affinity at the coordinator — one
        client EXECUTE is one pool-wide execution), scatter subplans
        to every shard slot, merge partials all-or-nothing, reply with
        the same summary shape the local path produces. ``explain``
        replies carry the coordinator slot's tree as ``operators``
        (rendered exactly like a local EXPLAIN) plus the full
        per-shard forest as ``shard_operators`` — every node annotated
        with the daemon that executed its region."""
        explain = bool(p.get("explain"))
        tr = obs.current_trace()
        # mirror the local path's default: a traced query records its
        # operator tree when obs_explain is on, explicit explain or
        # not — so GET_TRACE shows the distributed region forest for
        # every traced scatter query, not only EXPLAIN requests
        collect = explain or (tr is not None and getattr(
            self.config, "obs_explain", True))
        qid = tr.qid if tr is not None else None
        client = obs.attrib.current_client()
        holder: Dict[str, Any] = {}

        def run():
            results, shard_ops = self.shards.scatter_execute(
                sinks, job_name,
                materialize=p.get("materialize", True),
                explain=collect, qid=qid, client_id=client)
            if p.get("sync", True):
                self._sync_results(results)
            holder["ops"] = shard_ops
            return results

        scopes = _sched.sets_touched(MsgType.EXECUTE_COMPUTATIONS,
                                     {"sinks": sinks})
        results = self._run_job(job_name, run, scopes=scopes)
        out: Dict[str, Any] = {"results": self._result_summaries(results)}
        ops = holder.get("ops") or {}
        if explain:
            local = ops.get(self.advertise_addr)
            if local is not None:
                out["operators"] = local
            out["shard_operators"] = ops
        if collect and tr is not None and ops:
            # the distributed region forest rides the query's own
            # trace — GET_TRACE shows coordinator regions AND every
            # shard's region forest under ONE qid
            tr.attach_section("shard_operators", ops)
        return MsgType.OK, out

    def _execute_with_explain(self, p, job_name, run, scopes=()):
        """Shared EXECUTE tail: run the job (under an explain capture
        when asked) and shape the reply. ``scopes`` are the plan's
        scan-leaf sets — the affinity gate's key."""
        if p.get("explain"):
            with obs.operators.explain_capture() as cap:
                results = self._run_job(job_name, run, scopes=scopes)
            out = {"results": self._result_summaries(results)}
            if cap.get("operators") is not None:
                out["operators"] = cap["operators"]
            return MsgType.OK, out
        results = self._run_job(job_name, run, scopes=scopes)
        return MsgType.OK, {"results": self._result_summaries(results)}

    def _on_execute_plan(self, p):
        """Body (msgpack): {plan: text, registry: {label: entry_point or
        {kwargs..., fn: entry_point}}, job_name}. Pickle-free remote
        execution: labels rebind to *registered* entry points, the
        TCAP-text path (``ComputePlan.cc:20-56`` reparsing TCAP at the
        worker and binding against registered types)."""
        from netsdb_tpu.plan.parser import parse_plan

        registry: Dict[str, Any] = {}
        for label, spec in (p.get("registry") or {}).items():
            if isinstance(spec, str):
                registry[label] = self._resolve_registered(spec)
            elif isinstance(spec, dict):
                kw = dict(spec)
                for k, v in list(kw.items()):
                    if isinstance(v, str) and ":" in v:
                        kw[k] = self._resolve_registered(v)
                registry[label] = kw
            else:
                raise ProtocolError(
                    f"registry entry for {label!r} must be an entry-point "
                    f"string or kwargs dict")
        sinks = parse_plan(p["plan"]).to_computations(registry)
        job_name = p.get("job_name", "remote-plan")
        if self._scatter_touched(sinks):
            return self._execute_scatter(p, job_name, sinks)

        def run():
            results = self.library.execute_computations(
                *sinks, job_name=job_name,
                materialize=p.get("materialize", True))
            if p.get("sync", True):
                self._sync_results(results)
            return results

        return self._execute_with_explain(
            p, job_name, run,
            scopes=_sched.sets_touched(MsgType.EXECUTE_PLAN, p))

    def _on_list_jobs(self, p):
        with self._jobs_lock:
            return MsgType.OK, {"jobs": [dict(j) for j in self._jobs.values()]}

    def _fanout_read(self, typ, payload) -> Dict[str, Any]:
        """Best-effort read fan-out to every ACTIVE follower over its
        ordered link (stats/trace collection — the leader-merges-
        follower-sections leg of COLLECT_STATS and GET_TRACE). One
        shared deadline covers all followers; a follower that can't
        answer in time reports ``{"error": ...}`` instead of being
        evicted — liveness stays the health loop's job, a slow stats
        read must never degrade the mirror set."""
        with self._followers_mu:
            links = dict(self._links)
        if not links:
            return {}
        recs = [(addr, link.submit(typ, payload, CODEC_MSGPACK))
                for addr, link in links.items()]
        deadline = deadline_after(self.frame_timeout_s)
        out: Dict[str, Any] = {}
        for addr, rec in recs:
            if not rec["done"].wait(max(0.0, seconds_left(deadline))):
                out[addr] = {"error": f"no reply within "
                                      f"{self.frame_timeout_s}s"}
            elif rec.get("error"):
                out[addr] = {"error": rec["error"]}
            else:
                out[addr] = rec["reply"]
        return out

    def _on_collect_stats(self, p):
        # device_cache: the cross-query device-resident block cache's
        # hit/miss/evict/bytes counters (storage/devcache.py) — the
        # serve STATUS view of the warm-EXECUTE path.
        # metrics: the central registry snapshot (obs/metrics.py) —
        # compile stats, staging, devcache aggregates, serve counters
        # and span-time histograms in ONE section.
        out = {"sets": self.library.collect_stats(),
               "cache": self.library.store.stats.as_dict(),
               "device_cache": self.library.store.device_cache().stats(),
               "metrics": obs.REGISTRY.snapshot(),
               # the stateful-serving section: open sessions, batcher
               # occupancy, arena revive counters, decode program/
               # trace counts, multi-model residency attribution
               "sessions": self.sessions.stats()}
        if self._follower_addrs:
            # the mirror section: active/degraded links plus the
            # silently-dropped-frame count (satellite of the HA work —
            # an abort-closed link's queued frames now surface here)
            out["mirror"] = self.follower_status()
        if self._ha is not None:
            out["ha"] = self._ha.snapshot()
        if not p.get("local_only"):
            followers = self._fanout_read(MsgType.COLLECT_STATS,
                                          {"local_only": True})
            if followers:
                out["followers"] = followers
            shards = self.shards.fanout(MsgType.COLLECT_STATS,
                                        {"local_only": True})
            if shards:
                # per-shard sections, same best-effort merge contract
                # as the follower fan-out (a slow shard reports an
                # error entry, never gets evicted by a stats read)
                out["shards"] = shards
        return MsgType.OK, out

    # --- stateful serving (serve/sessions.py) -------------------------
    def _on_session_open(self, p):
        """SESSION_OPEN: ``op`` sub-dispatch — ``open`` (client),
        ``adopt``/``spill``/``handoff`` (daemon→daemon), ``lookup``/
        ``move`` (routing/rebalance). Mirrored: followers re-derive
        the session table from the replayed stream."""
        return self.sessions.handle_open(p)

    def _on_generate(self, p):
        """GENERATE: one decode step, sticky to the session's owner
        (typed retryable ``SessionMoved`` elsewhere), coalesced into
        a padded batch with every concurrent session of the model."""
        return self.sessions.handle_generate(p)

    def _on_session_close(self, p):
        """SESSION_CLOSE: drop state everywhere (devcache + arena +
        table), forwarding to a worker owner. Idempotent."""
        return self.sessions.handle_close(p)

    def _on_put_trace(self, p):
        """Client half of a traced query arriving after its reply: the
        RemoteClient ships its send/wait/hedge span profile once the
        logical request completes, and it merges into the qid's ringed
        profile as the ``client`` section — GET_TRACE then returns one
        end-to-end client→leader→follower decomposition. Best-effort
        by design (an unmatched qid — ring already rotated — is
        counted, not an error)."""
        prof = p.get("profile")
        if not isinstance(prof, dict):
            raise ProtocolError("PUT_TRACE needs a profile dict")
        qid = str(p.get("qid") or prof.get("qid") or "")
        merged = slow = False
        if qid and self._obs_enabled:
            merged = self.trace_ring.merge_section(qid, "client", prof)
            try:
                # a slow query persisted its profile when the trace
                # closed — before this section could exist; rewrite it
                slow = self.slowlog.merge_section(qid, "client", prof)
            except Exception as e:  # noqa: BLE001 — counted, never fatal
                obs.REGISTRY.counter("obs.slowlog_errors").inc()
                del e
        obs.REGISTRY.counter(
            "obs.put_trace.merged" if merged
            else "obs.put_trace.unmatched").inc()
        return MsgType.OK, {"merged": merged, "slowlog_merged": slow}

    def _on_health(self, p):
        """The SLO/health readout: every objective evaluated with
        multi-window burn rates (obs/slo.py), recent breach/recovery
        events, and the slowlog summary. On a leader, follower
        sections merge exactly like COLLECT_STATS — best-effort over
        the ordered links, a slow follower reports an error entry and
        is NEVER evicted by a health read."""
        out = {"objectives": self.slo.evaluate(),
               "events": self.slo.events(),
               "slowlog": self.slowlog.summary(),
               "followers_status": self.follower_status()
               if self._follower_addrs else None}
        if not p.get("local_only"):
            followers = self._fanout_read(MsgType.HEALTH,
                                          {"local_only": True})
            if followers:
                out["followers"] = followers
            shards = self.shards.fanout(MsgType.HEALTH,
                                        {"local_only": True})
            if shards:
                out["shards"] = shards
        if self._worker_addrs:
            out["pool"] = {"workers": list(self._worker_addrs),
                           "degraded": self.shards.degraded(),
                           "placement_epoch":
                               self.placement.to_wire()["epoch"]}
        return MsgType.OK, out

    def _on_get_trace(self, p):
        """The last N completed query profiles from this daemon's ring.
        On a leader, each profile additionally carries the follower
        sections that share its query id (``followers``: addr →
        profiles) — mirrored EXECUTEs forward the qid, so one logical
        query decomposes across every daemon that ran it.
        ``slow: true`` reads the persisted slow-query ring
        (``<root>/slowlog/``) instead of the in-memory one."""
        n = p.get("last")
        qid = p.get("qid")
        if p.get("slow"):
            # qid filter BEFORE the last-N truncation (the in-memory
            # path's semantics): a persisted slow query must stay
            # findable by id even after N newer outliers landed
            profiles = self.slowlog.entries()
            if qid:
                profiles = [pr for pr in profiles
                            if pr.get("qid") == str(qid)]
            if n:
                profiles = profiles[-int(n):]
            return MsgType.OK, {"profiles": profiles,
                                "enabled": self._obs_enabled,
                                "slowlog": self.slowlog.summary()}
        if qid:
            profiles = self.trace_ring.find(str(qid))
        else:
            profiles = self.trace_ring.last(int(n) if n else None)
        out: Dict[str, Any] = {"profiles": profiles,
                               "enabled": self._obs_enabled}

        def _merge_sections(profs, replies, section):
            merged = []
            for prof in profs:
                sections = {
                    addr: [fp for fp in reply.get("profiles", ())
                           if fp.get("qid") == prof.get("qid")]
                    for addr, reply in replies.items()
                    if "error" not in reply}
                sections = {a: s for a, s in sections.items() if s}
                if sections:
                    prof = {**prof, section: sections}
                merged.append(prof)
            return merged

        if not p.get("local_only"):
            freplies = self._fanout_read(
                MsgType.GET_TRACE, {"local_only": True, "qid": qid,
                                    "last": n})
            if freplies:
                out["profiles"] = _merge_sections(out["profiles"],
                                                  freplies, "followers")
                out["followers"] = freplies
            sreplies = self.shards.fanout(
                MsgType.GET_TRACE, {"local_only": True, "qid": qid,
                                    "last": n})
            if sreplies:
                # per-shard trace sections: a scatter-gather query's
                # subplans ran on the shards UNDER THE SAME qid, so
                # one logical query decomposes across the whole pool
                out["profiles"] = _merge_sections(out["profiles"],
                                                  sreplies, "shards")
                out["shards"] = sreplies
        return MsgType.OK, out

    def _on_get_metrics(self, p):
        """Continuous telemetry export. Two forms:

        * ``format="openmetrics"`` — the Prometheus text exposition
          (``obs/export.py``): stable catalogued family names,
          ``client``/``set`` labels from the attribution ledger, and —
          on a leader — every follower's samples merged under a
          ``follower`` label. The scrape endpoint's payload.
        * default (structured) — the registry snapshot plus the
          telemetry history's summary and derived rates (QPS, staged
          MB/s, hit-rate trend over ``window_s``), the feed ``cli obs
          --top`` refreshes from.

        Either way a reading is taken first, so a poller gets deltas
        exactly as fresh as its own cadence even when the snapshot
        thread is disabled."""
        from netsdb_tpu.obs import export as _export

        self.history.observe()
        snapshot = obs.REGISTRY.snapshot()
        followers: Dict[str, Any] = {}
        if not p.get("local_only"):
            followers = self._fanout_read(MsgType.GET_METRICS,
                                          {"local_only": True})
        if p.get("format") == "openmetrics":
            text = _export.to_openmetrics(
                snapshot,
                followers={a: (r.get("metrics") if isinstance(r, dict)
                               else {"error": "bad reply"})
                           for a, r in followers.items()})
            return MsgType.OK, {"format": "openmetrics", "text": text}
        window = p.get("window_s")
        out: Dict[str, Any] = {
            "metrics": snapshot,
            "history": self.history.summary(),
            "deltas": self.history.deltas(
                float(window) if window else None)}
        if followers:
            out["followers"] = followers
        return MsgType.OK, out

    def _on_analyze_set(self, p):
        """Planner statistics computed where the data lives — the
        summaries ship, the table stays (ref StorageCollectStats,
        ``PangeaStorageServer.h:48``). ColumnStats flatten to 4-int
        rows; dictionaries are lists of strings (msgpack-safe). A
        mesh-spanning placed table assembles its global columns first
        (stats need every host's rows)."""
        from netsdb_tpu.client import table_info
        from netsdb_tpu.relational.table import ColumnTable

        if self.is_sharded(p.get("db"), p.get("set")) \
                and not p.get("local_only"):
            return MsgType.OK, self._analyze_sharded(p["db"], p["set"])
        items = self.library.store.get_items(
            SetIdentifier(p["db"], p["set"]))
        if len(items) == 1 and isinstance(items[0], ColumnTable):
            info = table_info(
                self._fetch_global(p["db"], p["set"], items[0]))
        else:
            info = self.library.analyze_set(p["db"], p["set"])
        return MsgType.OK, {
            "num_rows": int(info["num_rows"]),
            "dicts": {k: list(v) for k, v in info["dicts"].items()},
            "stats": {k: [s.n_rows, s.min_val, s.max_val, s.n_distinct]
                      for k, s in info["stats"].items()}}

    def _analyze_sharded(self, db: str, set_name: str) -> Dict[str, Any]:
        """ANALYZE_SET fan-out over a partitioned set: every LIVE slot
        analyzes its local pages, the coordinator merges the summaries
        — rows sum, per-column [n_rows, min, max, n_distinct] merge by
        sum/min/max, dictionaries union in slot order. ``n_distinct``
        merges as the max over shards: a shard-local distinct count
        never exceeds the global one, so the merged figure is the
        tightest lower bound the summaries can give (exact when the
        partition key correlates with the column — range ingest keeps
        runs together). Degraded slots refuse, like scatter-gather:
        stats covering a subset of shards would silently mis-cost every
        plan built on them."""
        entry = self.placement.entry(db, set_name)
        parts: List[Tuple[int, Dict[str, Any]]] = []
        payload = {"db": db, "set": set_name, "local_only": True}
        for i, sl in enumerate(entry["slots"]):
            if sl["state"] != _placement.LIVE:
                raise ShardUnavailable(
                    f"slot {i} of {db}:{set_name} ({sl['addr']}) is "
                    f"degraded; partial statistics would mis-cost "
                    f"every plan — retry after readmit",
                    slot=i, epoch=entry["epoch"])
            if sl["addr"] == self.advertise_addr:
                _typ, rep = self._on_analyze_set(dict(payload))
            else:
                rep = self.shards.peer_request(
                    sl["addr"], MsgType.ANALYZE_SET, payload)
            parts.append((i, rep))
        merged_rows = 0
        dicts: Dict[str, List[Any]] = {}
        stats: Dict[str, List[Any]] = {}
        for _i, rep in parts:
            merged_rows += int(rep.get("num_rows") or 0)
            for k, vals in (rep.get("dicts") or {}).items():
                seen = dicts.setdefault(k, [])
                known = set(seen)
                for v in vals:
                    if v not in known:
                        seen.append(v)
                        known.add(v)
            for k, row in (rep.get("stats") or {}).items():
                n, lo, hi, nd = row
                cur = stats.get(k)
                if cur is None:
                    stats[k] = [int(n), lo, hi, int(nd)]
                else:
                    cur[0] += int(n)
                    if lo is not None:
                        cur[1] = lo if cur[1] is None else min(cur[1], lo)
                    if hi is not None:
                        cur[2] = hi if cur[2] is None else max(cur[2], hi)
                    cur[3] = max(cur[3], int(nd))
        obs.REGISTRY.counter("shard.analyze_fanouts").inc()
        return {"num_rows": merged_rows, "dicts": dicts, "stats": stats,
                "sharded": len(parts)}


def run_daemon(config: Configuration, host: str = "127.0.0.1",
               port: int = 8108, token: Optional[str] = None,
               max_jobs: Optional[int] = None,
               followers: Optional[list] = None,
               workers: Optional[list] = None,
               ha_peers: Optional[list] = None) -> int:
    """Start a daemon and block until shutdown — shared by the CLI
    ``serve`` subcommand and :func:`main`. ``followers``: worker-daemon
    addresses for multi-host fan-out (one per other jax.distributed
    process; call ``parallel.distributed.initialize_cluster`` first).
    ``workers``: shard-daemon addresses forming this leader's
    partitioned pool (horizontal scale-out — plain daemons, no
    jax.distributed requirement). ``ha_peers``: the ordered succession
    list arming automatic failover (index 0 = initial leader; pass the
    SAME list to every daemon in the pool)."""
    from netsdb_tpu.utils.profiling import get_logger

    ctl = ServeController(config, host=host, port=port, token=token,
                          max_jobs=max_jobs, followers=followers,
                          workers=workers, ha_peers=ha_peers)
    bound = ctl.start()
    get_logger("netsdb_tpu.serve", level="INFO").info(
        "netsdb_tpu serving on %s:%s", host, bound)
    ctl.serve_forever()
    return 0


def main(argv=None) -> int:
    """``python -m netsdb_tpu.serve.server`` — standalone daemon entry
    (the CLI's ``serve`` subcommand wraps this)."""
    import argparse

    ap = argparse.ArgumentParser(prog="netsdb-tpu-serve")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8108)
    ap.add_argument("--root", default=None, help="database root dir")
    ap.add_argument("--token", default=None, help="shared auth token")
    ap.add_argument("--max-jobs", type=int, default=None)
    ap.add_argument("--followers", default=None,
                    help="comma-separated worker daemon addresses for "
                         "multi-host fan-out (jax.distributed must be "
                         "initialized in every process)")
    ap.add_argument("--workers", default=None,
                    help="comma-separated shard daemon addresses "
                         "forming this leader's partitioned worker "
                         "pool (horizontal scale-out)")
    ap.add_argument("--ha-peers", default=None,
                    help="comma-separated ORDERED succession list for "
                         "automatic failover (index 0 = initial "
                         "leader; pass the same list to every daemon)")
    args = ap.parse_args(argv)
    config = Configuration(root_dir=args.root) if args.root else DEFAULT_CONFIG
    followers = ([a.strip() for a in args.followers.split(",") if a.strip()]
                 if args.followers else None)
    workers = ([a.strip() for a in args.workers.split(",") if a.strip()]
               if args.workers else None)
    ha_peers = ([a.strip() for a in args.ha_peers.split(",") if a.strip()]
                if args.ha_peers else None)
    return run_daemon(config, host=args.host, port=args.port,
                      token=args.token, max_jobs=args.max_jobs,
                      followers=followers, workers=workers,
                      ha_peers=ha_peers)


if __name__ == "__main__":
    raise SystemExit(main())
