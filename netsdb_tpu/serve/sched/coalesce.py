"""Identical-query coalescing — single-flight EXECUTE frames.

N concurrent byte-identical idempotent ``EXECUTE_COMPUTATIONS`` /
``EXECUTE_PLAN`` frames used to race N cold streams through one arena;
the idempotency-token cache already proves reply REUSE is safe for
these frames (a retry replays the cached reply verbatim), so running
the execution more than once concurrently buys nothing and thrashes
the device cache. This table collapses them: the first frame with a
given fingerprint becomes the *leader* and executes normally
(mirroring, ordering locks, admission — all of it); every concurrent
duplicate becomes a *waiter* that parks on the leader's completion
event and fans the leader's reply out under its OWN query id, trace
and idempotency token (each waiter's dispatch opened its own trace;
the coalesce decision is annotated into it with the leader's qid so
GET_TRACE joins the fan-out).

Failure contract (``tests/test_sched.py`` chaos coverage): a waiter
whose leader dies mid-run gets the typed retryable
:class:`~netsdb_tpu.serve.errors.CoalesceAborted` — never a wrong or
half-written reply — and nothing ran under the waiter's token, so its
retry re-executes from scratch (the dead flight is gone from the
table before the event fires).

The fingerprint is computed by ``policy.frame_fingerprint`` over the
decoded payload AFTER the per-request metadata (qid, client id,
idempotency token, lane hint) was popped — "byte-identical" means
identical in every byte the execution can observe.

Failover scope: the mirror hop forwards the coalesce LEADER's token;
each waiter's token is finished in the leader daemon's reply cache
AND shipped to followers as a TOKEN_ALIAS frame mapping it onto the
leader token's cached reply (``run``'s ``token``/``waiter_info``
plumbing surfaces the leader token to the serve layer, which emits
the alias after the mirrored execution acked). A waiter client's
retry against a PROMOTED follower therefore still dedupes —
at-most-once survives the failover edge instead of degrading to
at-least-once-same-result (the PR 9 gap, now closed).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

from netsdb_tpu import obs
from netsdb_tpu.serve.errors import CoalesceAborted
from netsdb_tpu.utils.locks import TrackedLock


class _Flight:
    __slots__ = ("done", "result", "error", "leader_qid",
                 "leader_token", "waiters", "t0")

    def __init__(self, leader_qid: Optional[str],
                 leader_token: Optional[str] = None):
        self.done = threading.Event()
        self.result: Any = None
        self.error: Optional[BaseException] = None
        self.leader_qid = leader_qid
        # the leader request's idempotency token — what a waiter's
        # token aliases to across the mirror hop (TOKEN_ALIAS)
        self.leader_token = leader_token
        self.waiters = 0
        self.t0 = time.perf_counter()


class CoalesceTable:
    """fingerprint → in-flight execution; single-flight semantics.

    ``done_ttl_s``/``done_max`` arm the COMPLETED-fingerprint cache: a
    byte-identical EXECUTE arriving just after its coalesce leader
    finished (the near-miss the in-flight table cannot catch) still
    hits — the retained reply is served under the late waiter's own
    qid/token, counted as ``sched.coalesce_late_hits``.  The window is
    deliberately tight and doubly bounded (TTL + entry count, oldest
    evicted): correctness rests on the same idempotency argument as
    coalescing itself — these frames replay verbatim under a retried
    token — but a long retention would serve ever-staler reads, so the
    TTL caps the staleness exactly like a retry of a just-completed
    request would experience.  ``done_ttl_s=0`` disables retention
    (PR 9 behavior)."""

    def __init__(self, done_ttl_s: float = 0.0, done_max: int = 32):
        self._mu = TrackedLock("sched.CoalesceTable._mu")
        self._inflight: Dict[str, _Flight] = {}
        self._done_ttl_s = float(done_ttl_s or 0.0)
        self._done_max = int(done_max)
        # fingerprint → (result, finished_at, leader_token);
        # LRU-ordered, TTL-pruned on every touch (monotonic clock —
        # the serve discipline)
        self._done: "OrderedDict[str, Tuple[Any, float, Optional[str]]]" \
            = OrderedDict()

    def _prune_done(self, now: float) -> None:
        """Drop expired/overflow entries (caller holds ``_mu``)."""
        ttl = self._done_ttl_s
        while self._done:
            _k, (_v, t, _tok) = next(iter(self._done.items()))
            if now - t <= ttl and len(self._done) <= self._done_max:
                break
            self._done.popitem(last=False)

    def _retain(self, key: str, result: Any,
                leader_token: Optional[str] = None) -> None:
        """Record a leader's completed reply for the late-hit window
        (no-op when retention is disabled)."""
        if self._done_ttl_s <= 0:
            return
        now = time.monotonic()
        with self._mu:
            self._done[key] = (result, now, leader_token)
            self._done.move_to_end(key)
            self._prune_done(now)

    def done_entries(self) -> int:
        """Live completed-fingerprint entries (observability probe)."""
        with self._mu:
            self._prune_done(time.monotonic())
            return len(self._done)

    def waiters(self, key: str) -> int:
        """How many requests are currently coalesced behind ``key``'s
        leader (0 when nothing is in flight) — test/observability
        probe."""
        with self._mu:
            fl = self._inflight.get(key)
            return fl.waiters if fl is not None else 0

    def run(self, key: str, fn: Callable[[], Any],
            wait_s: Optional[float],
            token: Optional[str] = None,
            waiter_info: Optional[Dict[str, Any]] = None) -> Any:
        """Single-flight ``fn`` under ``key``. The leader runs ``fn``
        OUTSIDE the table lock; waiters park on its event (bounded by
        ``wait_s``) and return the leader's result verbatim. Leader
        exceptions propagate unchanged to the leader and surface to
        every waiter as the typed retryable :class:`CoalesceAborted`.

        ``token`` is THIS request's idempotency token; the leader's is
        stashed on the flight (and the retained late-hit entry).
        ``waiter_info`` (a caller-owned dict) gets
        ``waiter_info["leader_token"]`` filled when this request was
        absorbed by another flight — the serve layer then ships a
        TOKEN_ALIAS frame so the waiter's token dedupes on followers
        across a failover, not just here."""
        tr = obs.current_trace()
        with self._mu:
            if self._done_ttl_s > 0:
                # prune on EVERY run, not just retention touches: a
                # retained large reply must not outlive its TTL by
                # more than the daemon's idle gap between any two
                # coalescable requests
                self._prune_done(time.monotonic())
            fl = self._inflight.get(key)
            if fl is None and self._done_ttl_s > 0:
                # the near-miss window: an identical frame whose
                # leader JUST finished replays the retained reply
                # under this request's own qid/token
                hit = self._done.get(key)
                if hit is not None:
                    result, t_done, ltok = hit
                    if time.monotonic() - t_done <= self._done_ttl_s:
                        self._done.move_to_end(key)
                        obs.REGISTRY.counter(
                            "sched.coalesce_late_hits").inc()
                        if tr is not None:
                            tr.annotate("sched.coalesce_late_hit", key[:16])
                            tr.add("sched.coalesce_late_hits")
                        if waiter_info is not None and ltok is not None:
                            waiter_info["leader_token"] = ltok
                        return result
                    self._done.pop(key, None)
            if fl is None:
                fl = self._inflight[key] = _Flight(
                    tr.qid if tr is not None else None,
                    leader_token=token)
                leader = True
            elif wait_s is not None \
                    and time.perf_counter() - fl.t0 >= wait_s:
                # the in-flight leader has already outlived the wait
                # bound: parking behind it can only time out (and a
                # waiter that ALREADY timed out would retry straight
                # back into the same flight, failing every attempt of
                # a request that would succeed solo) — run this one
                # uncoalesced instead
                fl = None
                leader = False
            else:
                fl.waiters += 1
                leader = False
        if fl is None:
            return fn()
        if leader:
            try:
                out = fn()
            except BaseException as e:
                fl.error = e
                raise
            else:
                fl.result = out
                self._retain(key, out, leader_token=fl.leader_token)
                return out
            finally:
                # the flight leaves the table BEFORE the event fires:
                # a waiter released by a FAILED leader retries into a
                # fresh execution, never onto the same dead flight
                with self._mu:
                    self._inflight.pop(key, None)
                fl.done.set()
        # waiter path
        obs.REGISTRY.counter("sched.coalesce_hits").inc()
        if tr is not None:
            tr.annotate("sched.coalesced_into", fl.leader_qid or "?")
            tr.add("sched.coalesce_hits")
        with obs.span("server.sched.coalesce_wait", "serve"):
            completed = fl.done.wait(wait_s)
        if not completed:
            with self._mu:
                fl.waiters -= 1  # departed — keep the probe honest
            obs.REGISTRY.counter("sched.coalesce_failures").inc()
            raise CoalesceAborted(
                f"coalesced leader {fl.leader_qid or '?'} still "
                f"executing after {wait_s}s — this request never ran; "
                f"a retry will execute solo (over-age flights are "
                f"not re-joined)")
        if fl.error is not None:
            obs.REGISTRY.counter("sched.coalesce_failures").inc()
            raise CoalesceAborted(
                f"coalesced leader {fl.leader_qid or '?'} failed "
                f"({type(fl.error).__name__}: {fl.error}) — this "
                f"request never ran; retry re-executes")
        if waiter_info is not None and fl.leader_token is not None:
            waiter_info["leader_token"] = fl.leader_token
        return fl.result
