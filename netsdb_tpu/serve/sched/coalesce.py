"""Identical-query coalescing — single-flight EXECUTE frames.

N concurrent byte-identical idempotent ``EXECUTE_COMPUTATIONS`` /
``EXECUTE_PLAN`` frames used to race N cold streams through one arena;
the idempotency-token cache already proves reply REUSE is safe for
these frames (a retry replays the cached reply verbatim), so running
the execution more than once concurrently buys nothing and thrashes
the device cache. This table collapses them: the first frame with a
given fingerprint becomes the *leader* and executes normally
(mirroring, ordering locks, admission — all of it); every concurrent
duplicate becomes a *waiter* that parks on the leader's completion
event and fans the leader's reply out under its OWN query id, trace
and idempotency token (each waiter's dispatch opened its own trace;
the coalesce decision is annotated into it with the leader's qid so
GET_TRACE joins the fan-out).

Failure contract (``tests/test_sched.py`` chaos coverage): a waiter
whose leader dies mid-run gets the typed retryable
:class:`~netsdb_tpu.serve.errors.CoalesceAborted` — never a wrong or
half-written reply — and nothing ran under the waiter's token, so its
retry re-executes from scratch (the dead flight is gone from the
table before the event fires).

The fingerprint is computed by ``policy.frame_fingerprint`` over the
decoded payload AFTER the per-request metadata (qid, client id,
idempotency token, lane hint) was popped — "byte-identical" means
identical in every byte the execution can observe.

Failover scope note: a WAITER's idempotency token is finished in the
LEADER DAEMON's reply cache only — the mirror hop forwards the
coalesce leader's token, not the N−1 waiter tokens (they would need a
token-alias frame; ROADMAP follow-on). After a leader-daemon loss, a
waiter client's retry against the promoted follower therefore
re-executes instead of replaying — safe by the same argument that
makes coalescing sound at all (these frames are idempotent: same
sinks, same values), but at-most-once degrades to
at-least-once-same-result across that one failover edge.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from netsdb_tpu import obs
from netsdb_tpu.serve.errors import CoalesceAborted
from netsdb_tpu.utils.locks import TrackedLock


class _Flight:
    __slots__ = ("done", "result", "error", "leader_qid", "waiters",
                 "t0")

    def __init__(self, leader_qid: Optional[str]):
        self.done = threading.Event()
        self.result: Any = None
        self.error: Optional[BaseException] = None
        self.leader_qid = leader_qid
        self.waiters = 0
        self.t0 = time.perf_counter()


class CoalesceTable:
    """fingerprint → in-flight execution; single-flight semantics."""

    def __init__(self):
        self._mu = TrackedLock("sched.CoalesceTable._mu")
        self._inflight: Dict[str, _Flight] = {}

    def waiters(self, key: str) -> int:
        """How many requests are currently coalesced behind ``key``'s
        leader (0 when nothing is in flight) — test/observability
        probe."""
        with self._mu:
            fl = self._inflight.get(key)
            return fl.waiters if fl is not None else 0

    def run(self, key: str, fn: Callable[[], Any],
            wait_s: Optional[float]) -> Any:
        """Single-flight ``fn`` under ``key``. The leader runs ``fn``
        OUTSIDE the table lock; waiters park on its event (bounded by
        ``wait_s``) and return the leader's result verbatim. Leader
        exceptions propagate unchanged to the leader and surface to
        every waiter as the typed retryable :class:`CoalesceAborted`."""
        tr = obs.current_trace()
        with self._mu:
            fl = self._inflight.get(key)
            if fl is None:
                fl = self._inflight[key] = _Flight(
                    tr.qid if tr is not None else None)
                leader = True
            elif wait_s is not None \
                    and time.perf_counter() - fl.t0 >= wait_s:
                # the in-flight leader has already outlived the wait
                # bound: parking behind it can only time out (and a
                # waiter that ALREADY timed out would retry straight
                # back into the same flight, failing every attempt of
                # a request that would succeed solo) — run this one
                # uncoalesced instead
                fl = None
                leader = False
            else:
                fl.waiters += 1
                leader = False
        if fl is None:
            return fn()
        if leader:
            try:
                out = fn()
            except BaseException as e:
                fl.error = e
                raise
            else:
                fl.result = out
                return out
            finally:
                # the flight leaves the table BEFORE the event fires:
                # a waiter released by a FAILED leader retries into a
                # fresh execution, never onto the same dead flight
                with self._mu:
                    self._inflight.pop(key, None)
                fl.done.set()
        # waiter path
        obs.REGISTRY.counter("sched.coalesce_hits").inc()
        if tr is not None:
            tr.annotate("sched.coalesced_into", fl.leader_qid or "?")
            tr.add("sched.coalesce_hits")
        with obs.span("server.sched.coalesce_wait", "serve"):
            completed = fl.done.wait(wait_s)
        if not completed:
            with self._mu:
                fl.waiters -= 1  # departed — keep the probe honest
            obs.REGISTRY.counter("sched.coalesce_failures").inc()
            raise CoalesceAborted(
                f"coalesced leader {fl.leader_qid or '?'} still "
                f"executing after {wait_s}s — this request never ran; "
                f"a retry will execute solo (over-age flights are "
                f"not re-joined)")
        if fl.error is not None:
            obs.REGISTRY.counter("sched.coalesce_failures").inc()
            raise CoalesceAborted(
                f"coalesced leader {fl.leader_qid or '?'} failed "
                f"({type(fl.error).__name__}: {fl.error}) — this "
                f"request never ran; retry re-executes")
        return fl.result
