"""The session/decode lane shape — coalescing GENERATE into batches.

One-shot analytics coalesce by FINGERPRINT (``policy.frame_fingerprint``:
identical queries share one execution). Decode traffic inverts the
shape: concurrent ``GENERATE`` frames are all DIFFERENT (each advances
its own session) yet want to share one padded step program dispatch —
coalescing by MODEL, not by identity. :class:`DecodeBatcher` is that
lane: the first arrival for a model becomes the batch leader, lingers
one small window for peers, then drains up to ``max_batch`` waiters
into a single ``run_batch`` call (``models/decode.step_batch`` under
the serve handler), fanning each session's own result back to its
waiter. The leader keeps draining while work is queued — the
``sched.coalesced`` leader/waiter discipline, reshaped for
batch-of-distinct-work.

Two structural guarantees the chaos tests lean on:

* **At most one occurrence of a session per batch** — a retried or
  pipelined duplicate stays queued for the NEXT batch, so one batch
  can never double-advance a session's state.
* **Exceptions fan out** — a failed batch rejects every waiter in it
  with the original fault; nothing blocks forever on a dead leader
  (the leader runs the batch on its own request thread).

Frames carrying ``protocol.SESSION_KEY`` admit through the reserved
:data:`DECODE_LANE` of the lane scheduler (unless the client named an
explicit lane), so decode loops and one-shot analytics get weighted
fairness instead of FIFO interleaving.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from netsdb_tpu.utils.locks import TrackedLock

#: the scheduler lane session-scoped frames admit through when the
#: client named none — reserved for interactive decode so a busy
#: analytics lane can't starve sessions (and vice versa).
DECODE_LANE = "decode"


class _Waiter:
    __slots__ = ("sid", "req", "done", "result", "error")

    def __init__(self, sid: str, req: Any):
        self.sid = sid
        self.req = req
        self.done = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None


class DecodeBatcher:
    """Per-model batch coalescing for concurrent decode steps.

    ``run_batch(db, reqs) -> results`` executes one padded step over
    the batch (index-aligned results). ``submit`` blocks the calling
    handler thread until its session's result (or fault) is ready.
    """

    def __init__(self, run_batch: Callable[[str, List[Any]], List[Any]],
                 max_batch: int = 8, window_s: float = 0.003):
        self._run = run_batch
        self.max_batch = max(1, int(max_batch))
        self.window_s = float(window_s)
        self._mu = TrackedLock("DecodeBatcher._mu")
        self._cv = threading.Condition(self._mu)
        self._pending: Dict[str, List[_Waiter]] = {}
        self._leader: Dict[str, bool] = {}
        self._stats = {"batches": 0, "coalesced": 0, "max_occupancy": 0}

    def submit(self, db: str, sid: str, req: Any) -> Any:
        """Enqueue one session's step; returns its result. The first
        waiter of an idle model becomes the leader and drains the
        queue batch by batch; everyone else parks on their event."""
        w = _Waiter(sid, req)
        with self._mu:
            q = self._pending.setdefault(db, [])
            q.append(w)
            lead = not self._leader.get(db, False)
            if lead:
                self._leader[db] = True
            else:
                self._cv.notify_all()
        if lead:
            self._drain(db)
        w.done.wait()
        if w.error is not None:
            raise w.error
        return w.result

    def _drain(self, db: str) -> None:
        # Leadership ends ONLY under ``_mu`` in the same critical
        # section that observed an empty queue — a waiter therefore
        # either enqueues before that check (this leader batches it)
        # or after the flag clears (it becomes the next leader).
        # Anything else loses a wakeup: waiters park on their own
        # event, not the condition variable.
        try:
            while True:
                deadline = time.monotonic() + self.window_s
                with self._mu:
                    while (len(self._pending.get(db, ()))
                           < self.max_batch):
                        left = deadline - time.monotonic()
                        if left <= 0:
                            break
                        self._cv.wait(left)
                    batch = self._take_locked(db)
                    if not batch:
                        self._leader[db] = False
                        return
                try:
                    results = self._run(db, [w.req for w in batch])
                    if len(results) != len(batch):
                        raise RuntimeError(
                            f"decode batch returned {len(results)} "
                            f"results for {len(batch)} requests")
                    for w, r in zip(batch, results):
                        # a per-request fault (e.g. one session moved
                        # out from under the batch) fails ONLY its own
                        # waiter; the rest of the batch keeps its
                        # results
                        if isinstance(r, BaseException):
                            w.error = r
                        else:
                            w.result = r
                except BaseException as e:  # noqa: BLE001 — fan out
                    for w in batch:
                        w.error = e
                finally:
                    for w in batch:
                        w.done.set()
        except BaseException as e:  # leader thread dying: fail the
            with self._mu:          # parked waiters, don't strand them
                self._leader[db] = False
                orphans = self._pending.pop(db, [])
            for w in orphans:
                w.error = e
                w.done.set()
            raise

    def _take_locked(self, db: str) -> List[_Waiter]:
        """Up to ``max_batch`` waiters, AT MOST ONE PER SESSION —
        duplicates (a pipelined retry) wait for the next batch so a
        single dispatch can never double-step a session."""
        q = self._pending.get(db, [])
        batch: List[_Waiter] = []
        seen = set()
        rest: List[_Waiter] = []
        for w in q:
            if len(batch) < self.max_batch and w.sid not in seen:
                batch.append(w)
                seen.add(w.sid)
            else:
                rest.append(w)
        if rest:
            self._pending[db] = rest
        else:
            self._pending.pop(db, None)
        if batch:
            self._stats["batches"] += 1
            self._stats["coalesced"] += len(batch)
            if len(batch) > self._stats["max_occupancy"]:
                self._stats["max_occupancy"] = len(batch)
        return batch

    def snapshot(self) -> Dict[str, Any]:
        with self._mu:
            out = dict(self._stats)
            out["pending"] = sum(len(v)
                                 for v in self._pending.values())
        return out
