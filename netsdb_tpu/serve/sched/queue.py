"""Per-client priority lanes: weighted-deficit admission with aging,
quotas and typed backpressure.

The reference's ``QuerySchedulerServer`` keeps ONE job queue and parks
every submitted job on it; our old serve layer kept one bounded
semaphore. Both are first-come: a chatty tenant monopolizes the
controller and a saturated queue answers everyone with the same
blanket refusal. This module replaces the semaphore with *lanes*:

* every request is admitted through a lane keyed by the frame's
  scheduler hint (``protocol.LANE_KEY``) or its client identity
  (``CLIENT_ID_KEY``) — per-tenant queues with zero client changes;
* free slots are granted to the non-empty lane with the lowest
  *virtual time* (``served / weight``) — weighted fair queueing over
  admission counts, so a weight-10 lane gets ~10× the admissions of a
  weight-1 lane under saturation, never 100%;
* **aging** bounds starvation deterministically: every
  ``aging_every``-th grant goes to the lane whose head waiter has
  waited longest, regardless of weights — a saturated low-priority
  lane admits within a bounded number of high-priority admissions
  (the property ``tests/test_sched.py`` pins);
* **quotas** refuse per-lane, typed: a lane already holding
  ``quota`` queued waiters rejects with :class:`LaneSaturated` — a
  DISTINCT retryable error from :class:`AdmissionFull`, carrying the
  lane's observed queue depth and a ``retry_after_s`` hint computed
  from the lane's queue-wait histogram (the PR 5 registry feed), so
  the client backs off for a server-measured interval instead of
  blind exponential jitter.

Locking: one tracked mutex (``sched.LaneScheduler._mu`` — born into
the audited hierarchy, ``docs/ANALYSIS.md``) guards the lane table;
each waiter parks OUTSIDE it on its own event, so a grant wakes
exactly the granted thread (no O(queued) spurious-wakeup convoy per
release). Grants happen under the lock in ``_pump_locked``.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, Optional

from netsdb_tpu import obs
from netsdb_tpu.serve.errors import AdmissionFull, LaneSaturated
from netsdb_tpu.utils.locks import TrackedLock
from netsdb_tpu.utils.timing import deadline_after, seconds_left

#: lane used when a frame carries neither a lane hint nor a client id
DEFAULT_LANE = "default"

#: bound on distinct lanes (a client fabricating lane names cannot grow
#: daemon memory without bound — extras fold into the default lane)
MAX_LANES = 256


class _Lane:
    __slots__ = ("name", "weight", "q", "served", "wait_hist")

    def __init__(self, name: str, weight: float):
        self.name = name
        self.weight = max(float(weight), 1e-6)
        self.q: "deque[_Waiter]" = deque()
        self.served = 0
        # per-lane queue-wait distribution: the retry_after_s hint and
        # the `sched` collector section read it; the process-wide
        # `sched.queue_wait_s` registry histogram gets the same
        # observations
        self.wait_hist = obs.Histogram(max_samples=128)


class _Waiter:
    # per-waiter event, not a shared condition: a grant wakes exactly
    # the granted thread — no O(queued) spurious-wakeup convoy on
    # every release of a saturated daemon
    __slots__ = ("t0", "granted", "ev")

    def __init__(self, t0: float):
        self.t0 = t0
        self.granted = False
        self.ev = threading.Event()


class AdmissionTicket:
    """Proof of admission — hand it back to :meth:`LaneScheduler.
    release` exactly once."""

    __slots__ = ("lane", "waited_s")

    def __init__(self, lane: str, waited_s: float):
        self.lane = lane
        self.waited_s = waited_s


class LaneScheduler:
    """Weighted-deficit lane admission over ``slots`` concurrent
    executions (the ``max_jobs`` bound the semaphore used to hold)."""

    def __init__(self, slots: int,
                 lanes: Optional[Dict[str, float]] = None,
                 quota: int = 0, aging_every: int = 8):
        self._mu = TrackedLock("sched.LaneScheduler._mu")
        self._free = max(int(slots), 1)
        self.slots = self._free
        self._quota = max(int(quota or 0), 0)
        self._aging_every = max(int(aging_every or 0), 0)
        self._grants_since_aged = 0
        self._weights = {str(k): float(v)
                         for k, v in (lanes or {}).items()}
        # lane names the OPERATOR configured — the feedback reseed
        # never overrides an explicit weight
        self.reserved_lanes = frozenset(self._weights)
        # per-lane quota overrides (feedback-seeded); lanes not listed
        # keep the global _quota
        self._lane_quotas: Dict[str, int] = {}
        # SLO load-shed override: lane -> the pre-shed quota override
        # (None = the lane had no override; restore deletes the entry).
        # At most one lane is shed at a time.
        self._shed: Dict[str, Optional[int]] = {}
        self._lanes: "OrderedDict[str, _Lane]" = OrderedDict()
        self._depth = 0

    def reseed(self, weights: Dict[str, float],
               quotas: Optional[Dict[str, int]] = None) -> None:
        """Apply feedback-derived lane weights (and per-lane quota
        overrides). Existing lanes keep their served counts — only the
        weight moves, so the WFQ share shifts without resetting
        virtual time; reserved (operator-configured) lanes are never
        touched."""
        with self._mu:
            for name, w in (weights or {}).items():
                if name in self.reserved_lanes:
                    continue
                self._weights[name] = max(float(w), 1e-6)
                lane = self._lanes.get(name)
                if lane is not None:
                    lane.weight = max(float(w), 1e-6)
            for name, q in (quotas or {}).items():
                if name in self.reserved_lanes:
                    continue
                if name in self._shed:
                    # the lane is under a shed override: reseed the
                    # REMEMBERED quota so unshed restores the fresh
                    # value, never a pre-reseed stale one
                    self._shed[name] = max(int(q), 1)
                else:
                    self._lane_quotas[name] = max(int(q), 1)

    def _quota_for_locked(self, name: str) -> int:
        return self._lane_quotas.get(name, self._quota)

    def shed(self, lane: str, factor: float,
             min_quota: int = 1) -> Optional[int]:
        """Apply the SLO load-shed quota override to ``lane``:
        ``quota × factor`` (floored at ``min_quota``), remembering the
        pre-shed state for :meth:`unshed`. Returns the shed quota, or
        None when there is nothing to shed (no effective quota, lane
        already shed, or reserved). The override halves QUEUEING
        capacity only — admitted work is never cancelled."""
        name = str(lane)
        with self._mu:
            if name in self._shed or name in self.reserved_lanes:
                return None
            current = self._quota_for_locked(name)
            if current <= 0:  # unbounded lanes have no quota to halve
                return None
            shed_q = max(int(current * factor), int(min_quota))
            if shed_q >= current:
                return None  # already at the floor
            self._shed[name] = self._lane_quotas.get(name)
            self._lane_quotas[name] = shed_q
            return shed_q

    def unshed(self) -> list:
        """Lift every load-shed quota override (the first breach-free
        check restores full capacity). Returns the lane names
        restored."""
        with self._mu:
            restored = []
            for name, prev in self._shed.items():
                if prev is None:
                    self._lane_quotas.pop(name, None)
                else:
                    self._lane_quotas[name] = prev
                restored.append(name)
            self._shed.clear()
            return restored

    def shed_lanes(self) -> list:
        """Lane names currently under a shed override (introspection)."""
        with self._mu:
            return sorted(self._shed)

    # --- lane bookkeeping --------------------------------------------
    def _lane_locked(self, name: str) -> _Lane:
        lane = self._lanes.get(name)
        if lane is not None:
            return lane
        if len(self._lanes) >= MAX_LANES and name not in self._weights:
            # fabricated-lane overflow folds into the default lane
            name = DEFAULT_LANE
            lane = self._lanes.get(name)
            if lane is not None:
                return lane
        lane = _Lane(name, self._weights.get(name, 1.0))
        if self._lanes:
            # standard WFQ join rule: a new lane enters at the CURRENT
            # minimum virtual time, not zero — otherwise a tenant
            # joining a long-lived daemon would monopolize grants
            # until its served count caught up with everyone else's
            min_vt = min(ln.served / ln.weight
                         for ln in self._lanes.values())
            lane.served = min_vt * lane.weight
        self._lanes[name] = lane
        return lane

    def retry_after_s(self, lane_name: str) -> Optional[float]:
        """The scheduler's backoff hint for one lane: the observed
        queue-wait median (None until the lane has admitted anything —
        the client then falls back to its exponential policy)."""
        with self._mu:
            lane = self._lanes.get(str(lane_name))
        if lane is None:
            return None
        return lane.wait_hist.quantile(0.5)

    # --- admission ----------------------------------------------------
    def acquire(self, lane_name: Optional[str],
                timeout_s: float) -> AdmissionTicket:
        """Park on ``lane_name`` until granted a slot. Raises
        :class:`LaneSaturated` immediately when the lane's quota is
        full, :class:`AdmissionFull` (with the lane's ``retry_after_s``
        hint) when no grant lands within ``timeout_s``."""
        name = str(lane_name) if lane_name else DEFAULT_LANE
        t0 = time.perf_counter()
        deadline = deadline_after(timeout_s)
        with self._mu:
            lane = self._lane_locked(name)
            quota = self._quota_for_locked(lane.name)
            if quota and len(lane.q) >= quota:
                depth = len(lane.q)
                obs.REGISTRY.counter("sched.quota_rejects").inc()
                raise LaneSaturated(
                    f"lane {lane.name!r} quota full ({depth} queued, "
                    f"quota {quota}) — per-tenant backoff",
                    lane=lane.name, queue_depth=depth,
                    retry_after_s=lane.wait_hist.quantile(0.5))
            if not lane.q:
                # empty -> non-empty: re-sync a RE-ACTIVATING lane's
                # virtual time to the active minimum (WFQ). A bursty
                # tenant that idled while others accumulated served
                # counts must not return with a stale low vtime and
                # monopolize grants until it "catches up".
                active = [ln for ln in self._lanes.values() if ln.q]
                if active:
                    min_vt = min(ln.served / ln.weight
                                 for ln in active)
                    lane.served = max(lane.served,
                                      min_vt * lane.weight)
            w = _Waiter(t0)
            lane.q.append(w)
            self._depth += 1
            obs.REGISTRY.gauge("sched.queue_depth").set(self._depth)
            self._pump_locked()
        # park OUTSIDE the lock on this waiter's own event: only the
        # granted thread ever wakes
        if not w.ev.wait(max(seconds_left(deadline), 0.0)):
            with self._mu:
                if not w.granted:
                    # still queued (the grant/timeout race re-checks
                    # under the lock — a grant that landed after the
                    # wait timed out is kept, never dropped)
                    lane.q.remove(w)
                    self._depth -= 1
                    obs.REGISTRY.gauge("sched.queue_depth").set(
                        self._depth)
                    obs.REGISTRY.counter("sched.timeouts").inc()
                    raise AdmissionFull(
                        f"no admission slot in lane {lane.name!r} "
                        f"within {timeout_s}s ({len(lane.q)} still "
                        f"queued) — back off and retry",
                        retry_after_s=lane.wait_hist.quantile(0.5),
                        queue_depth=len(lane.q), lane=lane.name)
        waited = time.perf_counter() - t0
        with self._mu:
            lane.wait_hist.observe(waited)
        obs.REGISTRY.counter("sched.admits").inc()
        obs.REGISTRY.histogram("sched.queue_wait_s").observe(waited)
        return AdmissionTicket(lane.name, waited)

    def release(self, ticket: AdmissionTicket) -> None:
        del ticket  # identity is not needed; slots are fungible
        with self._mu:
            self._free += 1
            self._pump_locked()

    # --- the policy ---------------------------------------------------
    def _pick_locked(self) -> Optional[_Lane]:
        nonempty = [ln for ln in self._lanes.values() if ln.q]
        if not nonempty:
            return None
        if (self._aging_every
                and self._grants_since_aged >= self._aging_every
                and len(nonempty) > 1):
            # aging turn: longest-waiting head wins regardless of
            # weights — the deterministic starvation bound
            self._grants_since_aged = 0
            lane = min(nonempty, key=lambda ln: ln.q[0].t0)
            obs.REGISTRY.counter("sched.aged_grants").inc()
            return lane
        # weighted deficit: lowest virtual time (served/weight) first;
        # name breaks ties deterministically
        return min(nonempty,
                   key=lambda ln: (ln.served / ln.weight, ln.name))

    def _pump_locked(self) -> None:
        granted = False
        while self._free > 0:
            lane = self._pick_locked()
            if lane is None:
                break
            w = lane.q.popleft()
            w.granted = True
            lane.served += 1
            self._free -= 1
            self._depth -= 1
            self._grants_since_aged += 1
            granted = True
            w.ev.set()  # wake exactly the granted waiter
        if granted:
            obs.REGISTRY.gauge("sched.queue_depth").set(self._depth)

    # --- introspection ------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """The ``sched`` collector section: msgpack-safe lane table the
        COLLECT_STATS frame (and ``cli obs --sched``) ships."""
        with self._mu:
            return {
                "slots": self.slots,
                "free_slots": self._free,
                "queued": self._depth,
                "quota": self._quota,
                "lane_quotas": dict(self._lane_quotas),
                "shed_lanes": sorted(self._shed),
                "aging_every": self._aging_every,
                "lanes": {
                    name: {"weight": ln.weight, "depth": len(ln.q),
                           "served": ln.served,
                           "wait": ln.wait_hist.summary()}
                    for name, ln in self._lanes.items()},
            }
