"""Scheduler feedback loop: seed lane weights/quotas from observed
behavior instead of the static ``sched_lanes`` table.

The PR 9 scheduler admits per-client lanes under operator-configured
weights; the PR 6/7 observability layer already measures exactly what
those weights should encode — per-(client, set) resource volumes in
the attribution ledger (``obs/attrib.py``) and per-operator cost rows
in the OperatorLedger (``obs/operators.py``). This module closes the
loop (the ROADMAP carry-over): a deterministic, **pinned** formula
turning those ledgers into lane weights, re-applied every
``sched_feedback_every`` admissions when ``config.sched_feedback`` is
on.

The formula (every constant is part of the test contract):

1. ``sec_per_chunk`` — the OperatorLedger's global mean wall-seconds
   per executed chunk (its cost rows supply the *conversion* from
   attributed volumes to seconds; ``DEFAULT_SEC_PER_CHUNK`` when the
   ledger is cold).
2. For every client with at least ``MIN_REQUESTS`` attributed
   requests: ``rate = (chunks × sec_per_chunk) / requests`` — the
   client's historical cost per request.
3. ``weight = clamp(median_rate / rate, 0.25, 4.0)`` — lanes whose
   requests are LIGHTER than the median earn proportionally more
   weight (up to 4×), heavy lanes proportionally less (down to ¼×).
   A zero-cost lane takes the upper clamp. Lanes the operator listed
   in ``sched_lanes`` are never reseeded — explicit configuration
   outranks inference.
4. With a global ``sched_lane_quota`` configured, per-lane quotas
   scale the same way: ``quota = max(1, round(global × weight))`` —
   light lanes may queue deeper, heavy lanes saturate sooner.

Weights only reshape the WFQ share; aging still bounds starvation
deterministically, so a mis-seeded lane degrades to slower admission,
never to none.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

#: minimum attributed requests before a client's rate is trusted
MIN_REQUESTS = 8
#: weight clamp — inference may shift shares 16× end to end, no more
CLAMP = (0.25, 4.0)
#: seconds per executor chunk when the OperatorLedger is cold
DEFAULT_SEC_PER_CHUNK = 1e-3
#: SLO load shedding (``config.sched_slo_shed``): when an objective
#: breaches on ALL its windows, the heaviest non-reserved lane's quota
#: is multiplied by SHED_FACTOR (floored at SHED_MIN_QUOTA) until the
#: first breach-free check restores it. Both constants are part of the
#: pinned test contract, like the weight formula above.
SHED_FACTOR = 0.5
SHED_MIN_QUOTA = 1
#: pin-budget auto-sizing (``config.device_cache_pin_auto``): the
#: hottest scope's attributed staged bytes become the hot-prefix pin
#: budget ONLY when that scope carries at least PIN_HOT_SHARE of all
#: attributed staged bytes, and never more than PIN_FRACTION of the
#: device-cache budget. Both constants are pinned test contract.
PIN_HOT_SHARE = 0.25
PIN_FRACTION = 0.5


def pin_budget(attrib_snapshot: Dict[str, Dict[str, Dict[str, float]]],
               cache_budget: int) -> int:
    """The auto-derived ``device_cache_pin_bytes`` (pinned formula).

    The attribution ledger's hot-set table — per-scope staged bytes
    summed over every client (``anon`` included, the ``overflow``
    fold-in bucket and the scope-free ``*`` row skipped) — names the
    HOTTEST scope. Its observed staged bytes (a ceiling on the bytes
    worth pinning: re-stages only inflate it, and the cap bounds the
    damage) become the pin budget when the scope carries at least
    ``PIN_HOT_SHARE`` of all attributed staged bytes; otherwise 0 —
    no set is hot enough to deserve eviction immunity."""
    by_scope: Dict[str, float] = {}
    for client, scopes in (attrib_snapshot or {}).items():
        if client == "overflow":
            continue
        for scope, metrics in scopes.items():
            if scope == "*":
                continue
            by_scope[scope] = by_scope.get(scope, 0.0) + float(
                metrics.get("staged_bytes") or 0.0)
    total = sum(by_scope.values())
    if total <= 0:
        return 0
    hot_bytes = max(by_scope.values())
    if hot_bytes / total < PIN_HOT_SHARE:
        return 0
    return int(min(hot_bytes, PIN_FRACTION * max(int(cache_budget), 0)))


def sec_per_chunk(op_snapshot: Dict[str, Dict[str, Dict[str, float]]]
                  ) -> float:
    """Global mean wall-seconds per chunk over every OperatorLedger
    row (the volume→seconds conversion)."""
    wall = chunks = 0.0
    for labels in (op_snapshot or {}).values():
        for row in labels.values():
            wall += float(row.get("wall_s") or 0.0)
            chunks += float(row.get("chunks") or 0.0)
    if chunks <= 0 or wall <= 0:
        return DEFAULT_SEC_PER_CHUNK
    return wall / chunks


def seed_lanes(attrib_snapshot: Dict[str, Dict[str, Dict[str, float]]],
               op_snapshot: Dict[str, Dict[str, Dict[str, float]]],
               base_quota: int = 0,
               reserved: Optional[set] = None,
               ) -> Tuple[Dict[str, float], Dict[str, int]]:
    """(weights, quotas) per the documented formula. ``reserved``
    lanes (statically configured) are skipped. Empty dicts when no
    client clears MIN_REQUESTS — the scheduler then keeps running on
    its current table."""
    spc = sec_per_chunk(op_snapshot)
    rates: Dict[str, float] = {}
    for client, scopes in (attrib_snapshot or {}).items():
        if client == "overflow":
            continue  # the ledger's fold-in bucket is not a lane
        if client == "anon":
            # unattributed requests are ADMITTED on the default lane
            # but ATTRIBUTED under "anon" — seed the lane they
            # actually queue on
            client = "default"
        if reserved and client in reserved:
            continue
        requests = chunks = 0.0
        for metrics in scopes.values():
            requests += float(metrics.get("requests") or 0.0)
            chunks += float(metrics.get("executor.chunks")
                            or metrics.get("chunks") or 0.0)
        if requests < MIN_REQUESTS:
            continue
        rates[client] = (chunks * spc) / requests
    if not rates:
        return {}, {}
    ordered = sorted(rates.values())
    median = ordered[len(ordered) // 2]
    lo, hi = CLAMP
    weights: Dict[str, float] = {}
    quotas: Dict[str, int] = {}
    for client, rate in rates.items():
        if rate <= 0 or median <= 0:
            w = hi
        else:
            w = min(max(median / rate, lo), hi)
        weights[client] = round(w, 6)
        if base_quota > 0:
            quotas[client] = max(1, round(base_quota * w))
    return weights, quotas


def pick_shed_lane(lane_snapshot: Dict[str, Dict[str, float]],
                   reserved: Optional[set] = None) -> Optional[str]:
    """The lane SLO load shedding targets: the HEAVIEST non-reserved
    lane — most admissions (the wait histogram's exact ``count`` is
    one tick per grant; the WFQ ``served`` number is join-adjusted
    virtual time and would misrank late joiners), queue depth breaking
    ties (deepest first), then name for determinism. None when every
    lane is reserved or the table is empty — explicit operator
    configuration outranks shedding, like it outranks the weight
    reseed."""
    best = None
    for name, row in (lane_snapshot or {}).items():
        if reserved and name in reserved:
            continue
        admissions = float((row.get("wait") or {}).get("count")
                           or row.get("served") or 0.0)
        key = (admissions, float(row.get("depth") or 0.0))
        if best is None or key > best[1] \
                or (key == best[1] and name < best[0]):
            best = (name, key)
    return best[0] if best else None
