"""Serve-side query scheduler — the policy-driven admission layer.

netsDB's master schedules TCAP JobStages onto workers with a job queue
as the central control point (``QuerySchedulerServer``); our serve
layer admitted jobs through a bare bounded semaphore. This package is
the replacement control point, three policies composed:

* **lanes** (``queue.py``) — per-client priority lanes with weights,
  deficit scheduling, deterministic anti-starvation aging, per-lane
  quotas and typed backpressure (``LaneSaturated`` vs
  ``AdmissionFull``, both carrying a server-computed ``retry_after_s``
  from the lane's queue-wait histogram);
* **coalescing** (``coalesce.py``) — byte-identical idempotent
  EXECUTE frames single-flight into one execution fanned out to every
  waiter under its own qid/trace/token;
* **affinity** (``policy.py``) — queries keyed by the placed sets
  they scan; siblings of a cold-set installer queue behind it and
  wake into the warm device cache.

Decisions are observable end to end: ``sched.*`` metrics in the PR 5
registry (catalogued in ``docs/METRICS.md``, scraped via
OpenMetrics), a ``sched`` collector section in COLLECT_STATS
(rendered by ``cli obs --sched``), and per-query trace annotations +
``server.sched.*`` spans in GET_TRACE profiles.
"""

from __future__ import annotations

import contextlib
import contextvars
from typing import Any, Dict, Iterable, Optional

from netsdb_tpu import obs
from netsdb_tpu.serve.sched.coalesce import CoalesceTable
from netsdb_tpu.serve.sched.policy import (  # noqa: F401 — re-exported
    AffinityGate,
    frame_fingerprint,
    sets_touched,
)
from netsdb_tpu.serve.sched.queue import (  # noqa: F401 — re-exported
    DEFAULT_LANE,
    AdmissionTicket,
    LaneScheduler,
)

#: the dispatch-extent lane hint (LANE_KEY popped off the frame) — the
#: same zero-plumbing propagation the client identity uses
_lane_var: "contextvars.ContextVar[Optional[str]]" = \
    contextvars.ContextVar("netsdb_sched_lane", default=None)


def current_lane() -> Optional[str]:
    return _lane_var.get()


@contextlib.contextmanager
def lane_context(lane: Optional[str]):
    """Install the frame's lane hint for the handler's dynamic extent
    (None installs nothing — mirrored/nested execution keeps the outer
    hint)."""
    if lane is None:
        yield
        return
    token = _lane_var.set(str(lane))
    try:
        yield
    finally:
        _lane_var.reset(token)


class QueryScheduler:
    """The facade ``ServeController`` drives: lanes + coalescing +
    affinity behind one object, exported as the registry's ``sched``
    collector section."""

    def __init__(self, slots: int,
                 lanes: Optional[Dict[str, float]] = None,
                 quota: int = 0, aging_every: int = 8,
                 coalesce: bool = True, affinity: bool = True,
                 affinity_wait_s: float = 30.0,
                 coalesce_wait_s: Optional[float] = 300.0,
                 coalesce_done_ttl_s: float = 0.0,
                 coalesce_done_max: int = 32,
                 cache_probe=None,
                 feedback: bool = False, feedback_every: int = 64,
                 slo_source=None, pin_auto=None, rebalance_cb=None):
        from netsdb_tpu.utils.locks import TrackedLock

        self.lanes = LaneScheduler(slots, lanes=lanes, quota=quota,
                                   aging_every=aging_every)
        # feedback loop (serve/sched/feedback.py): reseed lane weights
        # and per-lane quotas from the attribution + operator ledgers
        # every `feedback_every` admissions (opt-in)
        self.feedback_enabled = bool(feedback)
        # SLO burn-rate load shedding (config.sched_slo_shed):
        # ``slo_source()`` returns the objective names currently
        # breached on ALL windows; any breach halves the heaviest
        # non-reserved lane's quota (feedback.SHED_FACTOR, pinned)
        # until the first breach-free check. Shares the feedback
        # cadence and background thread.
        self.shed_enabled = slo_source is not None
        self._slo_source = slo_source
        # pin-budget auto-sizing (config.device_cache_pin_auto): a
        # no-arg callable re-deriving the devcache hot-prefix pin
        # budget from the attribution ledger's hot-set table
        # (feedback.pin_budget), run on the same cadence/thread
        self._pin_auto_cb = pin_auto
        # live shard rebalancing (config.rebalance): a no-arg callable
        # running one skew-detector pass (serve/rebalance.py) on the
        # same cadence/thread — the "sched-feedback cadence" the
        # self-rebalancing loop rides
        self._rebalance_cb = rebalance_cb
        self._feedback_every = max(int(feedback_every or 0), 1)
        self._base_quota = max(int(quota or 0), 0)
        self._fb_mu = TrackedLock("sched.QueryScheduler._fb_mu")
        self._fb_count = 0
        self._fb_running = False
        self.coalesce_enabled = bool(coalesce)
        self.coalesce_wait_s = coalesce_wait_s
        self._coalesce = CoalesceTable(
            done_ttl_s=coalesce_done_ttl_s, done_max=coalesce_done_max)
        self.affinity_enabled = bool(affinity) \
            and cache_probe is not None
        self._affinity = AffinityGate(cache_probe or (lambda s: True),
                                      wait_s=affinity_wait_s)
        obs.REGISTRY.register_collector("sched", self.snapshot)

    # --- lanes --------------------------------------------------------
    def acquire(self, lane: Optional[str],
                timeout_s: float) -> AdmissionTicket:
        if self.feedback_enabled or self.shed_enabled \
                or self._pin_auto_cb is not None \
                or self._rebalance_cb is not None:
            self._maybe_feedback()
        return self.lanes.acquire(lane, timeout_s)

    def _maybe_feedback(self) -> None:
        import threading

        with self._fb_mu:
            self._fb_count += 1
            due = (self._fb_count % self._feedback_every == 0
                   and not self._fb_running)
            if due:
                self._fb_running = True
        if due:
            # OFF the admission hot path: the two-ledger snapshot +
            # reseed must not become a periodic latency spike in the
            # very p99 the scheduler exists to protect
            threading.Thread(target=self._feedback_bg,
                             daemon=True,
                             name="netsdb-sched-feedback").start()

    def _feedback_bg(self) -> None:
        try:
            if self.feedback_enabled:
                self.refresh_feedback()
            if self.shed_enabled:
                self.refresh_shed()
            if self._pin_auto_cb is not None:
                try:
                    self._pin_auto_cb()
                except Exception as e:  # noqa: BLE001 — a broken pin
                    del e               # probe must never wedge
                    pass                # admission; skip the pass
            if self._rebalance_cb is not None:
                try:
                    self._rebalance_cb()
                except Exception as e:  # noqa: BLE001 — a broken skew
                    del e               # check must never wedge
                    pass                # admission; skip the pass
        finally:
            with self._fb_mu:
                self._fb_running = False

    def refresh_shed(self):
        """One SLO load-shedding check (serve/sched/feedback.py's
        pinned formula): any objective breached on all windows →
        halve the heaviest non-reserved lane's quota and tick
        ``sched.shed_events``; no breach → lift every shed override.
        Returns the lane shed this check (None otherwise) — for
        tests/tooling."""
        from netsdb_tpu.serve.sched import feedback as _feedback

        try:
            breached = list(self._slo_source() or ())
        except Exception as e:  # noqa: BLE001 — a broken probe must
            del e              # never wedge admission; skip the check
            return None
        if not breached:
            self.lanes.unshed()
            return None
        if self.lanes.shed_lanes():
            return None  # one shed at a time; wait for recovery
        snap = self.lanes.snapshot()
        lane = _feedback.pick_shed_lane(snap.get("lanes", {}),
                                        reserved=self.lanes.reserved_lanes)
        if lane is None:
            return None
        shed_q = self.lanes.shed(lane, _feedback.SHED_FACTOR,
                                 _feedback.SHED_MIN_QUOTA)
        if shed_q is None:
            return None
        obs.REGISTRY.counter("sched.shed_events").inc()
        return lane

    def refresh_feedback(self):
        """Recompute lane weights/quotas from the attribution +
        operator ledgers (serve/sched/feedback.py's pinned formula)
        and apply them. Returns (weights, quotas) for tests/tooling;
        empty when no lane cleared the evidence floor."""
        from netsdb_tpu.serve.sched import feedback as _feedback

        weights, quotas = _feedback.seed_lanes(
            obs.attrib.LEDGER.snapshot(),
            obs.operators.LEDGER.snapshot(),
            base_quota=self._base_quota,
            reserved=self.lanes.reserved_lanes)
        if weights:
            self.lanes.reseed(weights, quotas)
            obs.REGISTRY.counter("sched.feedback_reseeds").inc()
        return weights, quotas

    def release(self, ticket: AdmissionTicket) -> None:
        self.lanes.release(ticket)

    def retry_after_s(self, lane: str) -> Optional[float]:
        return self.lanes.retry_after_s(lane)

    # --- coalescing ---------------------------------------------------
    def coalesced(self, typ: Any, payload: Any, fn,
                  token: Optional[str] = None,
                  waiter_info: Optional[Dict[str, Any]] = None) -> Any:
        """Single-flight ``fn`` when the frame fingerprints (and
        coalescing is on); otherwise just run it. ``token`` /
        ``waiter_info`` ride through to
        :meth:`~netsdb_tpu.serve.sched.coalesce.CoalesceTable.run` —
        the token-alias plumbing that keeps waiter idempotency tokens
        replayable across the mirror hop."""
        if not self.coalesce_enabled:
            return fn()
        key = frame_fingerprint(typ, payload)
        if key is None:
            return fn()
        return self._coalesce.run(key, fn, self.coalesce_wait_s,
                                  token=token, waiter_info=waiter_info)

    def coalesce_waiters(self, typ: Any, payload: Any) -> int:
        """Waiters currently parked behind this frame's fingerprint
        (test/observability probe)."""
        key = frame_fingerprint(typ, payload)
        return self._coalesce.waiters(key) if key else 0

    # --- affinity -----------------------------------------------------
    def affinity(self, scopes: Iterable[str]):
        """Context manager gating one execution on the hot-set
        installer policy (no-op when disabled or scope-free)."""
        if not self.affinity_enabled or not scopes:
            return contextlib.nullcontext()
        return self._affinity.admit(scopes)

    # --- introspection ------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        out = self.lanes.snapshot()
        out["coalesce_enabled"] = self.coalesce_enabled
        out["affinity_enabled"] = self.affinity_enabled
        return out
