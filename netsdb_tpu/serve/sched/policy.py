"""Scheduling policy inputs: plan fingerprints, touched-set
extraction, and the cache-aware hot-set affinity gate.

Queries are keyed two ways (the tentpole's "set/plan-keyed queues"):

* the **plan fingerprint** (:func:`frame_fingerprint`) — a canonical
  digest of the decoded EXECUTE payload after every per-request
  metadata key (qid/client/token/lane) was popped. Byte-identical
  frames from different clients digest identically; the coalesce
  table single-flights on it.
* the **placed sets touched** (:func:`sets_touched`) — the
  ``db:set`` scopes the plan's SCAN leaves stream from. The affinity
  gate keys on the subset that is COLD in the device cache: when an
  installer is already streaming those sets, sibling queries (same
  sets, different plans — the ones coalescing can't collapse) queue
  behind it and wake into the warm devcache instead of racing cold
  streams through one arena. The wait is bounded and purely a
  thrash-avoidance window — correctness never depends on it (an
  installer that fails releases the gate; siblings then stream cold
  themselves).
"""

from __future__ import annotations

import contextlib
import hashlib
import re
import threading
from typing import Any, Callable, Dict, FrozenSet, Iterable, Optional

from netsdb_tpu import obs
from netsdb_tpu.utils.locks import TrackedLock
from netsdb_tpu.utils.timing import deadline_after, seconds_left

#: SCAN leaves of a textual plan — the to_plan_string / parse_plan
#: surface form (plan/computations.ScanSet.__repr__)
_SCAN_RE = re.compile(r"SCAN\(\s*'([^']*)'\s*,\s*'([^']*)'\s*\)")


def frame_fingerprint(typ: Any, payload: Any) -> Optional[str]:
    """Canonical digest of one decoded EXECUTE frame (metadata keys
    already popped by the dispatch). Uses cloudpickle when present
    (EXECUTE_COMPUTATIONS payloads hold callables plain pickle
    refuses); identical wire bytes decode to isomorphic object graphs,
    which re-serialize identically within one process. None on any
    serialization trouble — the frame then simply doesn't coalesce
    (a safe fallback, never a correctness hazard)."""
    try:
        try:
            import cloudpickle as _pickler
        except ImportError:
            import pickle as _pickler
        blob = _pickler.dumps((int(typ), payload))
    except Exception as e:  # noqa: BLE001 — unfingerprintable → solo run
        del e
        return None
    return hashlib.sha256(blob).hexdigest()


def _dag_scan_sets(sinks: Iterable[Any]) -> FrozenSet[str]:
    from netsdb_tpu.plan.computations import ScanSet

    out = set()
    seen = set()
    stack = list(sinks or ())
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        if isinstance(node, ScanSet):
            out.add(f"{node.db}:{node.set_name}")
        stack.extend(getattr(node, "inputs", ()) or ())
    return frozenset(out)


def sets_touched(typ: Any, payload: Any) -> FrozenSet[str]:
    """``db:set`` scopes an EXECUTE frame's plan streams FROM (scan
    leaves; write targets are outputs and don't key affinity). Empty
    on anything unparseable — the query then runs ungated."""
    from netsdb_tpu.serve.protocol import MsgType

    try:
        if typ == MsgType.EXECUTE_PLAN:
            plan = payload.get("plan") or ""
            return frozenset(f"{db}:{s}"
                             for db, s in _SCAN_RE.findall(str(plan)))
        if typ == MsgType.EXECUTE_COMPUTATIONS:
            return _dag_scan_sets(payload.get("sinks") or ())
    except Exception as e:  # noqa: BLE001 — ungated is always safe
        del e
    return frozenset()


class AffinityGate:
    """Cold-set single-installer gate, keyed per PAGE RANGE.

    ``cache_probe(scope)`` answers three ways (the partial-run cache's
    coverage probe, ``ServeController._devcache_warm``):

    * ``True`` — warm (fully resident / ungated): admit immediately.
      With block-granular caching this is what a query over an
      already-warm set gets even though earlier streams installed it
      piecemeal — full coverage, zero gating.
    * ``False`` — cold from row 0: classic single-installer gating.
    * an ``int`` — partially covered: the contiguous resident prefix
      ends at that row, so only the COLD REMAINDER ``[covered, end)``
      needs installing. The query still serializes as that
      remainder's gap installer (two gap installers racing the same
      remainder is exactly the cold-stream thrash the gate exists to
      prevent), but the gate's key records the remainder start — a
      sibling arriving after the gap landed probes warm and admits
      without ever touching the gate.

    Queries whose cold/remainder key matches an in-progress installer
    wait (bounded) for its completion and then run into the warm
    cache."""

    def __init__(self, cache_probe: Callable[[str], Any],
                 wait_s: float = 30.0):
        self._mu = TrackedLock("sched.AffinityGate._mu")
        # scope -> the installer's completion event. Membership is
        # PER SCOPE, not per cold-set key: a query whose cold sets
        # merely OVERLAP an in-progress installer's must still wait
        # (two "installers" sharing one cold set would race exactly
        # the cold streams the gate exists to prevent). The remainder
        # start of the current installer rides alongside for
        # introspection/annotation.
        self._installing: Dict[str, threading.Event] = {}
        self._remainder: Dict[str, int] = {}
        self._probe = cache_probe
        self.wait_s = float(wait_s)

    @contextlib.contextmanager
    def admit(self, scopes: Iterable[str]):
        # remainder-aware cold map: scope -> first cold row (0 = fully
        # cold; >0 = the resident prefix ends there and only the gap
        # serializes)
        cold: Dict[str, int] = {}
        for s in (scopes or ()):
            p = self._probe(s)
            if p is True:
                continue
            cold[s] = 0 if p is False else max(int(p), 0)
        if not cold:
            yield
            return
        tr = obs.current_trace()
        with self._mu:
            busy = {self._installing[s] for s in cold
                    if s in self._installing}
            # become the installer for every cold scope NOT already
            # covered — a query overlapping an in-progress install
            # still owns its uncovered remainder, so a third query on
            # that remainder queues behind THIS one instead of racing
            # a second cold stream
            mine = [s for s in cold if s not in self._installing]
            ev = None
            if mine:
                ev = threading.Event()
                for s in mine:
                    self._installing[s] = ev
                    self._remainder[s] = cold[s]
        if mine:
            obs.REGISTRY.counter("sched.affinity_installs").inc()
            if tr is not None:
                tr.annotate("sched.affinity",
                            "install" if not busy else "install+wait")
                # which ranges this installer owns: row 0 for a fully
                # cold set, the warm prefix's end for a gap install
                tr.annotate("sched.affinity_remainder",
                            {s: cold[s] for s in mine})
        if busy:
            obs.REGISTRY.counter("sched.affinity_hits").inc()
            if tr is not None:
                if not mine:
                    tr.annotate("sched.affinity", "wait")
                tr.add("sched.affinity_hits")
            deadline = deadline_after(self.wait_s)  # ONE bound, all evs
            with obs.span("server.sched.affinity_wait", "serve"):
                for busy_ev in busy:
                    left = seconds_left(deadline)
                    if left <= 0 or not busy_ev.wait(left):
                        break  # bounded: proceed past a slow installer
        try:
            yield
        finally:
            if ev is not None:
                # success or failure, the gate opens: siblings proceed
                # (into a warm cache on success, cold on failure)
                with self._mu:
                    for s in mine:
                        if self._installing.get(s) is ev:
                            del self._installing[s]
                            self._remainder.pop(s, None)
                ev.set()
