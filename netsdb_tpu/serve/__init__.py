"""Resident service layer — the PDBServer/PDBClient pair, TPU-shaped.

The reference is a long-running shared service: ``PDBServer`` listens on
ports dispatching typed-object frames to registered handlers
(``src/pdbServer/headers/PDBServer.h:39-152``), ``PDBClient`` talks to it
over TCP (``src/mainClient/headers/PDBClient.h:28-295``), the master runs
forever (``src/mainServer/source/MasterMain.cc:64-96``) and model weight
sets stay loaded while many clients run queries.

Here one daemon process is the single JAX controller owning the TPU: it
holds the :class:`~netsdb_tpu.storage.store.SetStore` (device-resident
weight tensors), the catalog, and the compiled-plan cache, and serves
concurrent clients over a typed-frame TCP protocol
(:mod:`netsdb_tpu.serve.protocol`). Clients are thin — they never touch
JAX; tensors cross the wire as raw dense buffers.
"""

from netsdb_tpu.serve.client import RemoteClient, RemoteError, RemoteTensor
from netsdb_tpu.serve.server import ServeController

__all__ = ["RemoteClient", "RemoteError", "RemoteTensor", "ServeController"]
