"""Typed-frame wire protocol — the PDBCommunicator role.

The reference frames every message as ``8-byte size + TYPEID-tagged
Record<T> bytes`` over blocking TCP/Unix sockets and validates the
TYPEID on receive (``src/communication/headers/PDBCommunicator.h:27-80``).
Here a frame is::

    !HBIQ  header = magic(u16) | codec(u8) | msg_type(u32) | body_len(u64)

followed by ``body_len`` body bytes. Control bodies are msgpack (codec
0); computation DAGs — which carry Python callables, the analogue of the
reference shipping serialized Computation objects whose code lives in
registered .so files — are cloudpickle (codec 1).

**Out-of-band tensor framing (codec 2, wire format v3).** Dense tensor
payloads used to ride *inside* the msgpack body as ``bin`` fields —
which cost one ``tobytes()`` copy on send, one concatenated-body copy,
and a read-only ``frombuffer`` view on receive. A codec-2 frame instead
carries only metadata + buffer descriptors in the msgpack body, and the
raw ndarray bytes ride AFTER the body as separate segments::

    !HBIQ header (body_len = msgpack body only)
    !I    segment count
    n ×  !QI  per-segment (nbytes u64, checksum u32)
    body bytes (msgpack; arrays are {"__ndseg__": idx, "d": dtype, "s": shape})
    seg0 bytes … segN bytes  (raw C-contiguous ndarray buffers)

The sender gathers header/table/body/segments with ``socket.sendmsg``
over ``memoryview``s — the tensor bytes are never copied host-side —
and the receiver lands each segment in its own writable buffer fed
straight to ``np.frombuffer``. Any ``send_frame`` with the msgpack
codec upgrades to codec 2 automatically when the payload holds arrays
above :data:`OOB_MIN_BYTES`; frames without such arrays stay codec 0,
byte-identical to v2. A per-segment checksum (``segment_checksum`` — a vectorized
sum/xor fold at memory speed) makes in-segment corruption
detectable (msgpack's framing no longer covers those bytes), surfacing
as the retryable CorruptFrame family. This also lifts msgpack's 4 GiB
``bin`` cap off single tensors.

Peers handshake :data:`PROTO_VERSION` inside HELLO and refuse
mixed-version connections with a typed ``ProtocolVersionError`` — a v2
peer cannot misparse a segment table as body bytes.

Security note: codec 1 executes code on deserialization, exactly like
the reference's ``registerType`` shipping .so binaries that the server
``dlopen``s. The same boundary applies to REGISTER_TYPE frames carrying
module ``source`` (the .so-bytes analogue, executed daemon-side on
first EXECUTE_PLAN bind — ``server.resolve_entry_point``). The serve
layer is a trusted-cluster control plane; an optional shared token
(HELLO handshake) gates connections.
"""

from __future__ import annotations

import socket
import struct
import time
from enum import IntEnum
from typing import Any, List, Optional, Sequence, Tuple

import msgpack
import numpy as np

MAGIC = 0x4E54  # "NT"
_HEADER = struct.Struct("!HBIQ")
MAX_FRAME_BYTES = 1 << 34  # 16 GiB sanity cap on a single frame

#: wire-format version, exchanged in the HELLO handshake. v3 added
#: out-of-band tensor segments (codec 2) and the BULK_* streamed-ingest
#: conversation; mixed-version peers are refused with a typed error.
PROTO_VERSION = 3

CODEC_MSGPACK = 0
CODEC_PICKLE = 1
#: msgpack body + out-of-band raw-buffer segments (see module docstring)
CODEC_MSGPACK_OOB = 2

#: arrays at or above this ride out-of-band; smaller ones stay inline
#: (a segment costs a 12-byte table entry + an iovec slot — not worth it
#: for tiny arrays).
OOB_MIN_BYTES = 1 << 10
_SEG_COUNT = struct.Struct("!I")
_SEG_ENTRY = struct.Struct("!QI")  # nbytes(u64) | checksum(u32)
MAX_SEGMENTS = 4096
#: iovecs per sendmsg call — comfortably under any platform IOV_MAX
_IOV_BATCH = 64


class MsgType(IntEnum):
    """Frame type ids — the reference's handler-map TYPEIDs
    (``PDBServer::registerHandler``). Grouped like its message families
    (Cat*, Storage*, DistributedStorage*, ExecuteComputation, ...)."""

    # session
    HELLO = 1
    OK = 2
    ERR = 3
    PING = 4
    SHUTDOWN = 5
    # streamed replies (ref: FrontendQueryTestServer paging results back
    # page-by-page, FrontendQueryTestServer.cc:785-890): a streaming
    # request is answered by N STREAM_ITEM frames then one STREAM_END;
    # an ERR frame aborts the stream
    STREAM_ITEM = 6
    STREAM_END = 7
    # catalog / DDL (ref Cat* + DistributedStorageAddSet family)
    CREATE_DATABASE = 10
    CREATE_SET = 11
    REMOVE_SET = 12
    CLEAR_SET = 13
    SET_EXISTS = 14
    LIST_SETS = 15
    REGISTER_TYPE = 16
    # data path (ref DispatcherAddData / StorageAddData / SetScan)
    SEND_DATA = 20
    SEND_MATRIX = 21
    GET_TENSOR = 22
    SCAN_SET = 23
    ADD_SHARED_MAPPING = 24
    FLUSH_DATA = 25
    LOAD_SET = 26
    # streamed data path: bounded-memory scan / chunked tensor pull
    SCAN_SET_STREAM = 27
    GET_TENSOR_CHUNKED = 28
    # serve-time model dedup: pool shared blocks across resident models
    DEDUP_RESIDENT = 29
    # query execution (ref ExecuteComputation)
    EXECUTE_COMPUTATIONS = 30
    EXECUTE_PLAN = 31
    LIST_JOBS = 32
    # stats (ref StorageCollectStats)
    COLLECT_STATS = 40
    # planner statistics computed where the data lives: per-column
    # summaries + dictionaries of one stored relation, so DAG builders
    # (suite_sink_for) never pull tables from a daemon (ref
    # StorageCollectStats → Statistics, PangeaStorageServer.h:48)
    ANALYZE_SET = 41
    # query-scoped observability: the last N completed query trace
    # profiles from the daemon's ring buffer (obs/trace.TraceRing);
    # the leader merges follower sections by query id
    GET_TRACE = 44
    # the CLIENT ships its side of a traced query (send/wait/hedge
    # spans) to the daemon after the reply lands; the daemon merges it
    # into the qid's ringed profile, so GET_TRACE returns ONE
    # end-to-end client->leader->follower decomposition. Best-effort:
    # a lost PUT_TRACE costs a client section, never the query.
    PUT_TRACE = 45
    # SLO/health readout (obs/slo.py): evaluated objectives with
    # multi-window burn rates + breach events + slowlog summary;
    # the leader merges follower sections like COLLECT_STATS
    HEALTH = 46
    # continuous telemetry export (obs/history.py + obs/export.py):
    # format=openmetrics returns the Prometheus text exposition of the
    # central registry (stable catalogued names, client/set labels
    # from the attribution ledger, leader-merged follower samples);
    # the default structured form carries the registry snapshot plus
    # the history ring's derived rates (QPS, staged MB/s, hit-rate
    # trends) that `cli obs --top` refreshes from
    GET_METRICS = 47
    # multi-host reads: a master assembling a mesh-spanning array asks
    # each follower for ITS addressable shards (index ranges + bytes) —
    # the reference streaming each node's local pages to the frontend
    # (FrontendQueryTestServer.cc:785-890); reads never enter the SPMD
    # program, so no collective/ordering hazards
    LOCAL_SHARDS = 42
    # streamed compute over a paged TENSOR set: stored @ rhs with the
    # stored matrix paged through the device (larger-than-HBM weights
    # behind the daemon; ref pipelines over pinned weight pages)
    PAGED_MATMUL = 43
    # fault tolerance: a leader tells an evicted follower to rebuild
    # its store from a checkpoint snapshot (storage/checkpoint.py
    # save_store/load_store) before being readmitted to the mirror set
    RESYNC_FOLLOWER = 50
    # windowed bulk ingest (the dispatcher-striped ingest role): BEGIN
    # opens a streamed conversation for one mutating op (SEND_DATA /
    # RESYNC_FOLLOWER), CHUNK frames carry bounded slices of the
    # payload back-to-back under a depth-W ack window (not
    # stop-and-wait), COMMIT assembles + applies under the target op's
    # ordering locks. The server decodes chunks OUTSIDE the per-set
    # lock and applies under it.
    BULK_BEGIN = 60
    BULK_CHUNK = 61
    BULK_COMMIT = 62
    # --- horizontal scale-out (sharded worker pool) -------------------
    # the leader's versioned placement map: which daemon owns which
    # shard slot of each hash/range-partitioned set. Shipped in the v3
    # handshake when the pool holds sharded sets, re-fetched by clients
    # on a PlacementStale rejection (the stale-map retry loop).
    PLACEMENT = 70
    # coordinator → shard: execute one pushed subplan (Scan→Filter/
    # Apply→Aggregate region, a partial fold, or one leg of a
    # distributed shuffle join) over the shard's LOCAL pages and reply
    # with the bounded partial the coordinator merges — the reference's
    # master scheduling JobStages onto workers over their local
    # partitions (QuerySchedulerServer.cc:216-330).
    SUBPLAN = 71
    # shard → shard: one hash bucket of a distributed shuffle (the
    # grace-hash partition step run across daemons). Column buffers
    # ride as out-of-band segments — no tobytes copies on the shuffle
    # path, same zero-copy framing as BULK table chunks.
    SHUFFLE_PUT = 72
    # leader → readmitted shard: re-register the shard's placement
    # epochs ahead of the handoff drain (the shard-scoped resync — a
    # readmitted shard receives only its OWN buffered pages, never a
    # whole-store snapshot like RESYNC_FOLLOWER)
    SHARD_RESYNC = 73
    # --- multi-host HA (leader election + failover) -------------------
    # leader → follower: the authoritative HA record — current term,
    # leader address and the placement map's wire form, shipped on
    # every placement-epoch bump (and at resync/promotion) so a
    # freshly promoted follower serves routed ingest from its
    # REPLICATED map immediately instead of starting empty.
    HA_STATE = 74
    # leader → follower: alias one idempotency token to another's
    # cached reply. The coalesce path executes ONE leader token but
    # finishes every waiter's token locally; this frame ships the
    # waiter→leader mapping across the mirror hop, so a waiter client
    # retrying a coalesced EXECUTE against the PROMOTED follower still
    # dedupes instead of re-executing (the PR 9 failover-scope gap).
    TOKEN_ALIAS = 75
    # live shard rebalancing (serve/rebalance.py): one frame, an "op"
    # field dispatches the sub-protocol. Worker-side ops run one leg of
    # a slot move (prepare the destination's local set, seal the source
    # registration behind a TTL, count rows, drop the source copy — the
    # bulk copy itself rides plain SEND_DATA frames with the epoch keys,
    # the drain_handoff idiom); leader-side ops are the admin plane
    # (status, plan, run a bounded round, register a new pool member).
    # Epoch-bumped all-or-nothing per move: the source keeps serving
    # until the destination acks and the new epoch commits.
    RESHARD = 76
    # --- stateful interactive serving (serve/sessions.py) -------------
    # open one decode session against a deployed model: the leader
    # assigns an OWNER daemon (sticky for every later GENERATE), seeds
    # the session's recurrent/KV state, and records the session in the
    # replicated session table. One frame, an "op" field dispatches the
    # sub-protocol (open / lookup / adopt / spill) — the RESHARD idiom:
    # lookup is the client's re-route probe after SessionMoved, adopt
    # installs a packed state at a new owner on relocation, spill is a
    # worker pushing an evicted session's state to the leader's arena
    # so owner death never loses it.
    SESSION_OPEN = 77
    # one decode step (or a short run of steps) against an open
    # session's resident state. Routed STICKY to the owning daemon;
    # concurrent GENERATEs for the same model coalesce into one padded
    # batched step program on the owner. Mutating (the state advances),
    # so idempotency tokens fence retries — a replayed step returns the
    # cached reply instead of advancing the state twice.
    GENERATE = 78
    # close one session: drop its devcache/arena state everywhere and
    # remove it from the replicated table. Idempotent by construction.
    SESSION_CLOSE = 79


#: payload key carrying the client-generated idempotency token on
#: mutating frames. The server caches the completed reply per token, so
#: a retry after an ambiguous failure (reply lost mid-wire) returns the
#: first execution's result instead of double-applying the mutation.
IDEMPOTENCY_KEY = "__idem__"

#: payload key carrying the client-minted query id (obs/trace.py) on
#: traced frames. The server pops it before dispatch, opens a
#: query-scoped trace under it, and re-attaches it to mirrored
#: forwards — so one logical query's spans join up across the client,
#: the leader and every follower (queryable via GET_TRACE).
QUERY_ID_KEY = "__qid__"

#: payload key carrying the client identity (an operator-chosen string,
#: e.g. a tenant or service name) on every frame a RemoteClient built
#: with ``client_id=...`` sends. The server pops it before dispatch and
#: installs it for the handler's dynamic extent
#: (``obs/attrib.client_context``), so staged bytes, device-cache
#: traffic and executor chunk counts aggregate per (client, db:set) —
#: the accounting the multi-tenant scheduler admits against. Mirrored
#: forwards re-attach it so followers attribute the same way.
CLIENT_ID_KEY = "__client__"

#: OPTIONAL payload key carrying a scheduler lane hint (a priority
#: class name, e.g. "interactive"/"batch"). The server pops it before
#: dispatch and admits the frame's job through that lane of the query
#: scheduler (``serve/sched/``); absent, the lane defaults to the
#: frame's client identity — per-client lanes with no client change.
#: Lane WEIGHTS are server configuration (``config.sched_lanes``): a
#: client can only name a lane, never grant itself priority the
#: operator didn't configure.
LANE_KEY = "__lane__"

#: payload key carrying the placement-map epoch on frames ROUTED to a
#: shard slot of a partitioned set (ingest the client aimed at an
#: owning daemon, coordinator→shard subplans). The receiving daemon
#: validates it against the epoch it was registered under; a mismatch
#: is the typed retryable ``PlacementStale`` — the client/coordinator
#: refreshes the map and re-routes instead of applying against a
#: membership the leader already revised (the partial/doubled-merge
#: hazard the epoch exists to close).
PLACEMENT_EPOCH_KEY = "__pepoch__"

#: payload key carrying the sender's HA TERM on every leader-
#: originated frame (mirrored forwards, handoff drains, resync) in an
#: HA-armed topology. The receiver validates it against the term it
#: knows: a HIGHER term is adopted (a new leader was elected), a STALE
#: term is the deposed-leader straggler — rejected with the typed
#: retryable ``NotLeader`` naming both terms, never applied. Routed
#: frames carry this alongside ``PLACEMENT_EPOCH_KEY`` — the
#: ``(term, epoch)`` fencing pair. Absent in non-HA topologies, so
#: every existing frame stays byte-identical.
HA_TERM_KEY = "__term__"

#: payload key carrying the target shard SLOT index on routed ingest.
#: A slot in handoff state routes to the LEADER with this key intact:
#: the leader buffers the batch for the degraded shard and drains it
#: on readmit (the shard-scoped resync).
SHARD_SLOT_KEY = "__slot__"

#: payload key carrying the session id on session-scoped frames
#: (GENERATE / SESSION_CLOSE). The server pops it before dispatch and
#: admits the frame through the reserved decode lane of the query
#: scheduler — the session lane shape: one lane for every interactive
#: decode step, sticky to the owner daemon, so batch coalescing sees
#: all concurrent sessions of a model in one place and one-shot
#: analytics never starve behind a decode loop (or vice versa).
SESSION_KEY = "__session__"

#: frame types that mutate daemon state or launch jobs — the set the
#: client attaches idempotency tokens to before retrying. Reads are
#: naturally idempotent and retried bare. (BULK_BEGIN carries its
#: logical op's token explicitly — the whole conversation is one
#: logical mutation.)
MUTATING_TYPES = frozenset({
    MsgType.CREATE_DATABASE, MsgType.CREATE_SET, MsgType.REMOVE_SET,
    MsgType.CLEAR_SET, MsgType.REGISTER_TYPE, MsgType.SEND_DATA,
    MsgType.SEND_MATRIX, MsgType.ADD_SHARED_MAPPING, MsgType.FLUSH_DATA,
    MsgType.LOAD_SET, MsgType.EXECUTE_COMPUTATIONS, MsgType.EXECUTE_PLAN,
    MsgType.DEDUP_RESIDENT, MsgType.RESYNC_FOLLOWER, MsgType.BULK_BEGIN,
    MsgType.SESSION_OPEN, MsgType.GENERATE, MsgType.SESSION_CLOSE,
})


class ProtocolError(ConnectionError):
    pass


_MASK64 = 0xFFFFFFFFFFFFFFFF


def _mix64(v: int) -> int:
    """splitmix64 finalizer — full avalanche, so a single-bit change in
    the input flips ~half the output bits (plain sum^xor folds let
    top-bit flips cancel between the two reductions)."""
    v &= _MASK64
    v ^= v >> 33
    v = (v * 0xFF51AFD7ED558CCD) & _MASK64
    v ^= v >> 29
    v = (v * 0xC4CEB9FE1A85EC53) & _MASK64
    v ^= v >> 32
    return v


def segment_checksum(mv) -> int:
    """32-bit integrity checksum of an out-of-band segment, computed at
    memory speed: numpy u64 sum + xor reductions over the buffer (full
    coverage — every byte participates in both), each avalanched
    through splitmix64 before folding. ~2.5× faster than zlib.adler32
    on commodity hosts, which matters because the checksum is the only
    full pass the zero-copy path makes over the tensor bytes. Verified
    against 3k-trial single-bit-flip fuzzing (0 misses)."""
    n = mv.nbytes if isinstance(mv, memoryview) else len(mv)
    mv = memoryview(mv)
    main = n - (n & 7)
    s = x = 0
    if main:
        a = np.frombuffer(mv[:main], np.uint64)
        s = int(np.add.reduce(a, dtype=np.uint64))
        x = int(np.bitwise_xor.reduce(a))
    if n & 7:
        tail = int.from_bytes(mv[main:], "little")
        s = (s + tail) & _MASK64
        x ^= tail
    # asymmetric combine: s passes through TWO mixes, x one — a
    # symmetric mix(s)^mix(x^n) collides whenever the (s, x^n) pair
    # swaps (e.g. the low-bit flip of a 1-byte segment)
    acc = _mix64(_mix64(s) ^ x ^ n)
    return (acc ^ (acc >> 32)) & 0xFFFFFFFF


class _OOBPacker:
    """msgpack ``default`` hook that diverts big ndarrays out-of-band.

    Arrays ≥ :data:`OOB_MIN_BYTES` become ``{"__ndseg__": idx, ...}``
    descriptors; their buffers are collected as ``memoryview``s in
    :attr:`segments` (NO byte copy — ``ascontiguousarray`` is a no-op
    on already-contiguous input, the overwhelmingly common case).
    Smaller arrays inline as before (one small copy)."""

    __slots__ = ("segments",)

    def __init__(self):
        self.segments: List[memoryview] = []

    def __call__(self, obj: Any):
        if isinstance(obj, np.ndarray):
            a = np.ascontiguousarray(obj)
            if a.nbytes >= OOB_MIN_BYTES and not a.dtype.hasobject \
                    and len(self.segments) < MAX_SEGMENTS:
                self.segments.append(memoryview(a).cast("B"))
                return {"__ndseg__": len(self.segments) - 1,
                        "d": a.dtype.str, "s": list(a.shape)}
            return {"__nd__": True, "d": a.dtype.str, "s": list(a.shape),
                    "b": bytes(a.data)}
        if isinstance(obj, (np.integer,)):
            return int(obj)
        if isinstance(obj, (np.floating,)):
            return float(obj)
        raise TypeError(f"cannot serialize {type(obj)!r} over the wire; "
                        f"wrap host objects in a pickled job instead")


def _pack_default(obj: Any):
    """msgpack hook for the inline-only (codec 0) encoder."""
    if isinstance(obj, np.ndarray):
        a = np.ascontiguousarray(obj)
        return {"__nd__": True, "d": a.dtype.str, "s": list(a.shape),
                "b": bytes(a.data)}
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    raise TypeError(f"cannot serialize {type(obj)!r} over the wire; "
                    f"wrap host objects in a pickled job instead")


def _inline_array(obj: dict) -> np.ndarray:
    """Inline ``__nd__`` dict → WRITABLE ndarray. ``bytearray(...)``
    copies the (small — big arrays ride out-of-band) buffer so the
    result owns writable memory; ``np.frombuffer`` over msgpack's
    ``bytes`` would be read-only."""
    buf = bytearray(obj["b"])
    return np.frombuffer(buf, dtype=np.dtype(obj["d"])).reshape(obj["s"])


def _unpack_hook(obj):
    if isinstance(obj, dict) and obj.get("__nd__"):
        return _inline_array(obj)
    return obj


def _make_oob_hook(segments: Sequence[Any]):
    """Unpack hook resolving ``__ndseg__`` descriptors to zero-copy,
    WRITABLE arrays over the received segment buffers (bytearrays —
    ``np.frombuffer`` inherits their writability)."""

    def hook(obj):
        if isinstance(obj, dict):
            if "__ndseg__" in obj:
                idx = obj["__ndseg__"]
                return np.frombuffer(
                    segments[idx], dtype=np.dtype(obj["d"])
                ).reshape(obj["s"])
            if obj.get("__nd__"):
                return _inline_array(obj)
        return obj

    return hook


def encode_body(payload: Any, codec: int = CODEC_MSGPACK) -> bytes:
    if codec == CODEC_MSGPACK:
        return msgpack.packb(payload, use_bin_type=True,
                             default=_pack_default)
    if codec == CODEC_PICKLE:
        import cloudpickle

        return cloudpickle.dumps(payload)
    raise ProtocolError(f"unknown codec {codec}")


def encode_body_oob(payload: Any) -> Tuple[bytes, List[memoryview]]:
    """msgpack body + out-of-band segment list (codec 2 when the list
    is non-empty, codec 0 otherwise). The segments are ``memoryview``s
    over the payload's own array buffers — zero copies."""
    packer = _OOBPacker()
    body = msgpack.packb(payload, use_bin_type=True, default=packer)
    return body, packer.segments


def decode_body(body: Any, codec: int, allow_pickle: bool,
                segments: Optional[Sequence[Tuple[Any, int]]] = None) -> Any:
    """``segments``: the (buffer, checksum) pairs read after a codec-2
    body. Checksums are verified HERE (not in the transport read) so a
    flipped segment byte surfaces as a decode failure — the typed
    retryable CorruptFrame path — with the connection still
    frame-synchronized, never a torn read."""
    if codec == CODEC_MSGPACK_OOB:
        bufs = []
        for i, (buf, crc) in enumerate(segments or ()):
            if segment_checksum(buf) != crc:
                raise ValueError(
                    f"out-of-band segment {i} checksum mismatch "
                    f"(bit flip on the wire)")
            bufs.append(buf)
        return msgpack.unpackb(body, raw=False,
                               object_hook=_make_oob_hook(bufs),
                               strict_map_key=False)
    if codec == CODEC_MSGPACK:
        return msgpack.unpackb(body, raw=False, object_hook=_unpack_hook,
                               strict_map_key=False)
    if codec == CODEC_PICKLE:
        if not allow_pickle:
            raise ProtocolError(
                "pickled frame refused: this endpoint has allow_pickle "
                "off (enable it only on trusted-cluster control planes)")
        import pickle

        return pickle.loads(body)
    raise ProtocolError(f"unknown codec {codec}")


def _pack_segtable(segments: Sequence[memoryview]) -> bytes:
    out = bytearray(_SEG_COUNT.size + len(segments) * _SEG_ENTRY.size)
    _SEG_COUNT.pack_into(out, 0, len(segments))
    off = _SEG_COUNT.size
    for mv in segments:
        _SEG_ENTRY.pack_into(out, off, mv.nbytes, segment_checksum(mv))
        off += _SEG_ENTRY.size
    return bytes(out)


def _sendmsg_all(sock: socket.socket, parts: Sequence[Any]) -> None:
    """ONE vectored send for header + segment table + body + segments
    (scatter-gather: the kernel walks the iovecs, no host-side
    concatenation, and header + small bodies never split across TCP
    segments under TCP_NODELAY). Handles partial sends and batches
    iovecs below IOV_MAX; falls back to sendall where sendmsg is
    unavailable."""
    views = []
    for p in parts:
        v = p if isinstance(p, memoryview) else memoryview(p)
        v = v.cast("B") if v.format != "B" or v.ndim != 1 else v
        if v.nbytes:
            views.append(v)
    if not views:
        return
    if not hasattr(sock, "sendmsg"):
        for v in views:
            sock.sendall(v)
        return
    while views:
        sent = sock.sendmsg(views[:_IOV_BATCH])
        while sent:
            head = views[0]
            if sent >= head.nbytes:
                sent -= head.nbytes
                views.pop(0)
            else:
                views[0] = head[sent:]
                sent = 0


def send_frame(sock: socket.socket, msg_type: int, payload: Any,
               codec: int = CODEC_MSGPACK, chaos=None) -> None:
    """``chaos``: optional :class:`~netsdb_tpu.serve.chaos.ChaosInjector`
    that may drop/delay/corrupt/truncate this frame (tests only; the
    production path pays one ``is None`` check).

    The msgpack codec auto-upgrades to codec 2 (out-of-band segments)
    when the payload holds arrays ≥ :data:`OOB_MIN_BYTES`; everything
    goes out as one vectored ``sendmsg`` either way."""
    segments: List[memoryview] = []
    if codec in (CODEC_MSGPACK, CODEC_MSGPACK_OOB):
        # a caller echoing a RECEIVED frame's wire codec may pass
        # codec 2 — the payload is a decoded dict again, so re-encode
        # through the OOB path (the mirror-forward case: a big-tensor
        # frame arrives as codec 2 and must forward losslessly)
        body, segments = encode_body_oob(payload)
        wire_codec = CODEC_MSGPACK_OOB if segments else CODEC_MSGPACK
    else:
        body = encode_body(payload, codec)
        wire_codec = codec
    header = _HEADER.pack(MAGIC, wire_codec, int(msg_type), len(body))
    segtable = _pack_segtable(segments) if segments else b""
    if chaos is not None:
        header, segtable, body, segments = chaos.on_send(
            sock, int(msg_type), header, body,
            segtable=segtable, segments=segments)
    _sendmsg_all(sock, [header, segtable, body, *segments])


def _recv_exact(sock: socket.socket, n: int,
                mid_timeout: Optional[float] = None,
                started: bool = False) -> memoryview:
    """Read exactly ``n`` bytes. ``mid_timeout`` is a CUMULATIVE
    deadline on finishing the read once it has started (``started=True``
    means the frame is already mid-flight, so the clock runs from byte
    0): an idle connection may block indefinitely awaiting the next
    frame, but once bytes flow the remainder must land within the
    budget — a peer trickling one byte per near-timeout gap cannot hold
    the thread past the deadline. Expiry raises
    :class:`ProtocolError`, never a bare socket.timeout."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    old_timeout: Any = False  # sentinel: False = not overridden
    deadline = None
    try:
        if started and mid_timeout is not None:
            old_timeout = sock.gettimeout()
            deadline = time.monotonic() + mid_timeout
        while got < n:
            if deadline is not None:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise ProtocolError(
                        f"peer stalled mid-frame ({n - got} of {n} bytes "
                        f"still missing after {mid_timeout}s)")
                sock.settimeout(left)
            try:
                r = sock.recv_into(view[got:], n - got)
            except socket.timeout:
                if old_timeout is False:
                    raise  # the caller's own socket timeout, not ours
                raise ProtocolError(
                    f"peer stalled mid-frame (> {mid_timeout}s)")
            if r == 0:
                raise ProtocolError("peer closed mid-frame")
            got += r
            if got < n and mid_timeout is not None and old_timeout is False:
                # first bytes landed — the frame has started; bound the
                # remainder with one shared deadline
                old_timeout = sock.gettimeout()
                deadline = time.monotonic() + mid_timeout
    finally:
        if old_timeout is not False:
            sock.settimeout(old_timeout)
    return memoryview(buf)


def recv_frame_raw(sock: socket.socket, chaos=None,
                   mid_frame_timeout: Optional[float] = None,
                   ) -> Tuple[MsgType, int, bytes, List[Tuple[Any, int]]]:
    """Receive one frame without decoding — servers decode separately so
    a refused codec becomes an ERR reply, not a dropped connection.
    Returns ``(type, codec, body, segments)``; ``segments`` is the
    codec-2 out-of-band list of (writable buffer, expected checksum)
    pairs, empty for other codecs — each segment lands in its own
    buffer via ``recv_into`` (no reassembly copy) and checksum
    verification is deferred to :func:`decode_body`.

    ``mid_frame_timeout`` is the deadline-discipline knob: waiting for
    a frame to START may block (idle persistent connection), but once
    the first header byte lands the rest of header + body + segments
    must arrive within the timeout or the read fails typed (server
    worker threads pass this so a hung peer can never wedge a handler
    thread)."""
    if chaos is not None:
        chaos.on_recv(sock)
    header = _recv_exact(sock, _HEADER.size, mid_timeout=mid_frame_timeout)
    magic, codec, msg_type, body_len = _HEADER.unpack(header)
    if magic != MAGIC:
        raise ProtocolError(f"bad frame magic {magic:#x}")
    if body_len > MAX_FRAME_BYTES:
        raise ProtocolError(f"frame of {body_len} bytes exceeds cap")
    # ONE budget for everything after the header: each follow-up read
    # gets only the REMAINING time, so a codec-2 frame with thousands
    # of segments cannot stretch the deadline to nsegs × timeout (a
    # peer dribbling one segment per near-timeout gap would otherwise
    # hold a handler thread for hours)
    deadline = (time.monotonic() + mid_frame_timeout
                if mid_frame_timeout is not None else None)

    def budget() -> Optional[float]:
        if deadline is None:
            return None
        rem = deadline - time.monotonic()
        if rem <= 0:
            raise ProtocolError(
                f"peer stalled mid-frame (frame budget of "
                f"{mid_frame_timeout}s spent)")
        return rem

    seg_meta: List[Tuple[int, int]] = []
    if codec == CODEC_MSGPACK_OOB:
        cnt = _recv_exact(sock, _SEG_COUNT.size,
                          mid_timeout=budget(), started=True)
        (nsegs,) = _SEG_COUNT.unpack(cnt)
        if nsegs > MAX_SEGMENTS:
            raise ProtocolError(f"frame carries {nsegs} segments "
                                f"(cap {MAX_SEGMENTS})")
        table = _recv_exact(sock, nsegs * _SEG_ENTRY.size,
                            mid_timeout=budget(), started=True)
        seg_meta = [_SEG_ENTRY.unpack_from(table, i * _SEG_ENTRY.size)
                    for i in range(nsegs)]
        total = body_len + sum(n for n, _ in seg_meta)
        if total > MAX_FRAME_BYTES:
            raise ProtocolError(f"frame of {total} bytes exceeds cap")
    body = _recv_exact(sock, body_len, mid_timeout=budget(),
                       started=True)
    segments = [(_recv_exact(sock, n, mid_timeout=budget(),
                             started=True), crc)
                for n, crc in seg_meta]
    try:
        typ = MsgType(msg_type)
    except ValueError:
        # unknown type ids stay raw ints: the server answers them with a
        # "no handler" ERR instead of dropping the connection
        typ = msg_type
    return typ, codec, bytes(body), segments


def recv_frame(sock: socket.socket, allow_pickle: bool = False,
               chaos=None, mid_frame_timeout: Optional[float] = None,
               ) -> Tuple[MsgType, Any]:
    msg_type, codec, body, segments = recv_frame_raw(
        sock, chaos=chaos, mid_frame_timeout=mid_frame_timeout)
    return msg_type, decode_body(body, codec, allow_pickle,
                                 segments=segments)


# --- tensor wire form -------------------------------------------------

def tensor_to_wire(dense: np.ndarray, block_shape=None) -> dict:
    """Dense tensor → wire dict. The device-side blocking/placement is
    the server's job; the wire carries the raw dense buffer once (as an
    out-of-band segment — never ``tobytes()``-copied)."""
    return {"data": np.ascontiguousarray(dense),
            "block_shape": list(block_shape) if block_shape else None}


def tensor_from_wire(obj: dict) -> Tuple[np.ndarray, Any]:
    """Wire dict → (dense, block_shape). The array arrives WRITABLE:
    out-of-band segments decode over their own received buffers, inline
    arrays are copied into owned memory (see ``_inline_array``)."""
    data = obj["data"]
    bs = obj.get("block_shape")
    return data, (tuple(bs) if bs else None)
