"""Typed-frame wire protocol — the PDBCommunicator role.

The reference frames every message as ``8-byte size + TYPEID-tagged
Record<T> bytes`` over blocking TCP/Unix sockets and validates the
TYPEID on receive (``src/communication/headers/PDBCommunicator.h:27-80``).
Here a frame is::

    !HBIQ  header = magic(u16) | codec(u8) | msg_type(u32) | body_len(u64)

followed by ``body_len`` body bytes. Control bodies are msgpack (codec
0); computation DAGs — which carry Python callables, the analogue of the
reference shipping serialized Computation objects whose code lives in
registered .so files — are cloudpickle (codec 1). Dense tensor payloads
ride inside msgpack ``bin`` fields (raw buffer + dtype/shape header), so
bulk data never round-trips through pickle.

Security note: codec 1 executes code on deserialization, exactly like
the reference's ``registerType`` shipping .so binaries that the server
``dlopen``s. The same boundary applies to REGISTER_TYPE frames carrying
module ``source`` (the .so-bytes analogue, executed daemon-side on
first EXECUTE_PLAN bind — ``server.resolve_entry_point``). The serve
layer is a trusted-cluster control plane; an optional shared token
(HELLO handshake) gates connections.
"""

from __future__ import annotations

import socket
import struct
import time
from enum import IntEnum
from typing import Any, Optional, Tuple

import msgpack
import numpy as np

MAGIC = 0x4E54  # "NT"
_HEADER = struct.Struct("!HBIQ")
MAX_FRAME_BYTES = 1 << 34  # 16 GiB sanity cap on a single frame

CODEC_MSGPACK = 0
CODEC_PICKLE = 1


class MsgType(IntEnum):
    """Frame type ids — the reference's handler-map TYPEIDs
    (``PDBServer::registerHandler``). Grouped like its message families
    (Cat*, Storage*, DistributedStorage*, ExecuteComputation, ...)."""

    # session
    HELLO = 1
    OK = 2
    ERR = 3
    PING = 4
    SHUTDOWN = 5
    # streamed replies (ref: FrontendQueryTestServer paging results back
    # page-by-page, FrontendQueryTestServer.cc:785-890): a streaming
    # request is answered by N STREAM_ITEM frames then one STREAM_END;
    # an ERR frame aborts the stream
    STREAM_ITEM = 6
    STREAM_END = 7
    # catalog / DDL (ref Cat* + DistributedStorageAddSet family)
    CREATE_DATABASE = 10
    CREATE_SET = 11
    REMOVE_SET = 12
    CLEAR_SET = 13
    SET_EXISTS = 14
    LIST_SETS = 15
    REGISTER_TYPE = 16
    # data path (ref DispatcherAddData / StorageAddData / SetScan)
    SEND_DATA = 20
    SEND_MATRIX = 21
    GET_TENSOR = 22
    SCAN_SET = 23
    ADD_SHARED_MAPPING = 24
    FLUSH_DATA = 25
    LOAD_SET = 26
    # streamed data path: bounded-memory scan / chunked tensor pull
    SCAN_SET_STREAM = 27
    GET_TENSOR_CHUNKED = 28
    # serve-time model dedup: pool shared blocks across resident models
    DEDUP_RESIDENT = 29
    # query execution (ref ExecuteComputation)
    EXECUTE_COMPUTATIONS = 30
    EXECUTE_PLAN = 31
    LIST_JOBS = 32
    # stats (ref StorageCollectStats)
    COLLECT_STATS = 40
    # planner statistics computed where the data lives: per-column
    # summaries + dictionaries of one stored relation, so DAG builders
    # (suite_sink_for) never pull tables from a daemon (ref
    # StorageCollectStats → Statistics, PangeaStorageServer.h:48)
    ANALYZE_SET = 41
    # multi-host reads: a master assembling a mesh-spanning array asks
    # each follower for ITS addressable shards (index ranges + bytes) —
    # the reference streaming each node's local pages to the frontend
    # (FrontendQueryTestServer.cc:785-890); reads never enter the SPMD
    # program, so no collective/ordering hazards
    LOCAL_SHARDS = 42
    # streamed compute over a paged TENSOR set: stored @ rhs with the
    # stored matrix paged through the device (larger-than-HBM weights
    # behind the daemon; ref pipelines over pinned weight pages)
    PAGED_MATMUL = 43
    # fault tolerance: a leader tells an evicted follower to rebuild
    # its store from a checkpoint snapshot (storage/checkpoint.py
    # save_store/load_store) before being readmitted to the mirror set
    RESYNC_FOLLOWER = 50


#: payload key carrying the client-generated idempotency token on
#: mutating frames. The server caches the completed reply per token, so
#: a retry after an ambiguous failure (reply lost mid-wire) returns the
#: first execution's result instead of double-applying the mutation.
IDEMPOTENCY_KEY = "__idem__"

#: frame types that mutate daemon state or launch jobs — the set the
#: client attaches idempotency tokens to before retrying. Reads are
#: naturally idempotent and retried bare.
MUTATING_TYPES = frozenset({
    MsgType.CREATE_DATABASE, MsgType.CREATE_SET, MsgType.REMOVE_SET,
    MsgType.CLEAR_SET, MsgType.REGISTER_TYPE, MsgType.SEND_DATA,
    MsgType.SEND_MATRIX, MsgType.ADD_SHARED_MAPPING, MsgType.FLUSH_DATA,
    MsgType.LOAD_SET, MsgType.EXECUTE_COMPUTATIONS, MsgType.EXECUTE_PLAN,
    MsgType.DEDUP_RESIDENT, MsgType.RESYNC_FOLLOWER,
})


class ProtocolError(ConnectionError):
    pass


def _pack_default(obj: Any):
    """msgpack hook: numpy arrays ride as raw buffers."""
    if isinstance(obj, np.ndarray):
        a = np.ascontiguousarray(obj)
        return {"__nd__": True, "d": a.dtype.str, "s": list(a.shape),
                "b": a.tobytes()}
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    raise TypeError(f"cannot serialize {type(obj)!r} over the wire; "
                    f"wrap host objects in a pickled job instead")


def _unpack_hook(obj):
    if isinstance(obj, dict) and obj.get("__nd__"):
        return np.frombuffer(obj["b"], dtype=np.dtype(obj["d"])).reshape(
            obj["s"])
    return obj


def encode_body(payload: Any, codec: int = CODEC_MSGPACK) -> bytes:
    if codec == CODEC_MSGPACK:
        return msgpack.packb(payload, use_bin_type=True,
                             default=_pack_default)
    if codec == CODEC_PICKLE:
        import cloudpickle

        return cloudpickle.dumps(payload)
    raise ProtocolError(f"unknown codec {codec}")


def decode_body(body: bytes, codec: int, allow_pickle: bool) -> Any:
    if codec == CODEC_MSGPACK:
        return msgpack.unpackb(body, raw=False, object_hook=_unpack_hook,
                               strict_map_key=False)
    if codec == CODEC_PICKLE:
        if not allow_pickle:
            raise ProtocolError(
                "pickled frame refused: this endpoint has allow_pickle "
                "off (enable it only on trusted-cluster control planes)")
        import pickle

        return pickle.loads(body)
    raise ProtocolError(f"unknown codec {codec}")


def send_frame(sock: socket.socket, msg_type: int, payload: Any,
               codec: int = CODEC_MSGPACK, chaos=None) -> None:
    """``chaos``: optional :class:`~netsdb_tpu.serve.chaos.ChaosInjector`
    that may drop/delay/corrupt/truncate this frame (tests only; the
    production path pays one ``is None`` check)."""
    body = encode_body(payload, codec)
    header = _HEADER.pack(MAGIC, codec, int(msg_type), len(body))
    if chaos is not None:
        header, body = chaos.on_send(sock, int(msg_type), header, body)
    sock.sendall(header)
    sock.sendall(body)


def _recv_exact(sock: socket.socket, n: int,
                mid_timeout: Optional[float] = None,
                started: bool = False) -> memoryview:
    """Read exactly ``n`` bytes. ``mid_timeout`` is a CUMULATIVE
    deadline on finishing the read once it has started (``started=True``
    means the frame is already mid-flight, so the clock runs from byte
    0): an idle connection may block indefinitely awaiting the next
    frame, but once bytes flow the remainder must land within the
    budget — a peer trickling one byte per near-timeout gap cannot hold
    the thread past the deadline. Expiry raises
    :class:`ProtocolError`, never a bare socket.timeout."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    old_timeout: Any = False  # sentinel: False = not overridden
    deadline = None
    try:
        if started and mid_timeout is not None:
            old_timeout = sock.gettimeout()
            deadline = time.monotonic() + mid_timeout
        while got < n:
            if deadline is not None:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise ProtocolError(
                        f"peer stalled mid-frame ({n - got} of {n} bytes "
                        f"still missing after {mid_timeout}s)")
                sock.settimeout(left)
            try:
                r = sock.recv_into(view[got:], n - got)
            except socket.timeout:
                if old_timeout is False:
                    raise  # the caller's own socket timeout, not ours
                raise ProtocolError(
                    f"peer stalled mid-frame (> {mid_timeout}s)")
            if r == 0:
                raise ProtocolError("peer closed mid-frame")
            got += r
            if got < n and mid_timeout is not None and old_timeout is False:
                # first bytes landed — the frame has started; bound the
                # remainder with one shared deadline
                old_timeout = sock.gettimeout()
                deadline = time.monotonic() + mid_timeout
    finally:
        if old_timeout is not False:
            sock.settimeout(old_timeout)
    return memoryview(buf)


def recv_frame_raw(sock: socket.socket, chaos=None,
                   mid_frame_timeout: Optional[float] = None,
                   ) -> Tuple[MsgType, int, bytes]:
    """Receive one frame without decoding — servers decode separately so
    a refused codec becomes an ERR reply, not a dropped connection.

    ``mid_frame_timeout`` is the deadline-discipline knob: waiting for
    a frame to START may block (idle persistent connection), but once
    the first header byte lands the rest of header + body must arrive
    within the timeout or the read fails typed (server worker threads
    pass this so a hung peer can never wedge a handler thread)."""
    if chaos is not None:
        chaos.on_recv(sock)
    header = _recv_exact(sock, _HEADER.size, mid_timeout=mid_frame_timeout)
    magic, codec, msg_type, body_len = _HEADER.unpack(header)
    if magic != MAGIC:
        raise ProtocolError(f"bad frame magic {magic:#x}")
    if body_len > MAX_FRAME_BYTES:
        raise ProtocolError(f"frame of {body_len} bytes exceeds cap")
    body = _recv_exact(sock, body_len, mid_timeout=mid_frame_timeout,
                       started=True)
    try:
        typ = MsgType(msg_type)
    except ValueError:
        # unknown type ids stay raw ints: the server answers them with a
        # "no handler" ERR instead of dropping the connection
        typ = msg_type
    return typ, codec, bytes(body)


def recv_frame(sock: socket.socket, allow_pickle: bool = False,
               chaos=None, mid_frame_timeout: Optional[float] = None,
               ) -> Tuple[MsgType, Any]:
    msg_type, codec, body = recv_frame_raw(
        sock, chaos=chaos, mid_frame_timeout=mid_frame_timeout)
    return msg_type, decode_body(body, codec, allow_pickle)


# --- tensor wire form -------------------------------------------------

def tensor_to_wire(dense: np.ndarray, block_shape=None) -> dict:
    """Dense tensor → wire dict. The device-side blocking/placement is
    the server's job; the wire carries the raw dense buffer once."""
    return {"data": np.ascontiguousarray(dense),
            "block_shape": list(block_shape) if block_shape else None}


def tensor_from_wire(obj: dict) -> Tuple[np.ndarray, Any]:
    data = obj["data"]
    bs = obj.get("block_shape")
    return data, (tuple(bs) if bs else None)
