"""Typed-frame wire protocol — the PDBCommunicator role.

The reference frames every message as ``8-byte size + TYPEID-tagged
Record<T> bytes`` over blocking TCP/Unix sockets and validates the
TYPEID on receive (``src/communication/headers/PDBCommunicator.h:27-80``).
Here a frame is::

    !HBIQ  header = magic(u16) | codec(u8) | msg_type(u32) | body_len(u64)

followed by ``body_len`` body bytes. Control bodies are msgpack (codec
0); computation DAGs — which carry Python callables, the analogue of the
reference shipping serialized Computation objects whose code lives in
registered .so files — are cloudpickle (codec 1). Dense tensor payloads
ride inside msgpack ``bin`` fields (raw buffer + dtype/shape header), so
bulk data never round-trips through pickle.

Security note: codec 1 executes code on deserialization, exactly like
the reference's ``registerType`` shipping .so binaries that the server
``dlopen``s. The same boundary applies to REGISTER_TYPE frames carrying
module ``source`` (the .so-bytes analogue, executed daemon-side on
first EXECUTE_PLAN bind — ``server.resolve_entry_point``). The serve
layer is a trusted-cluster control plane; an optional shared token
(HELLO handshake) gates connections.
"""

from __future__ import annotations

import socket
import struct
from enum import IntEnum
from typing import Any, Tuple

import msgpack
import numpy as np

MAGIC = 0x4E54  # "NT"
_HEADER = struct.Struct("!HBIQ")
MAX_FRAME_BYTES = 1 << 34  # 16 GiB sanity cap on a single frame

CODEC_MSGPACK = 0
CODEC_PICKLE = 1


class MsgType(IntEnum):
    """Frame type ids — the reference's handler-map TYPEIDs
    (``PDBServer::registerHandler``). Grouped like its message families
    (Cat*, Storage*, DistributedStorage*, ExecuteComputation, ...)."""

    # session
    HELLO = 1
    OK = 2
    ERR = 3
    PING = 4
    SHUTDOWN = 5
    # streamed replies (ref: FrontendQueryTestServer paging results back
    # page-by-page, FrontendQueryTestServer.cc:785-890): a streaming
    # request is answered by N STREAM_ITEM frames then one STREAM_END;
    # an ERR frame aborts the stream
    STREAM_ITEM = 6
    STREAM_END = 7
    # catalog / DDL (ref Cat* + DistributedStorageAddSet family)
    CREATE_DATABASE = 10
    CREATE_SET = 11
    REMOVE_SET = 12
    CLEAR_SET = 13
    SET_EXISTS = 14
    LIST_SETS = 15
    REGISTER_TYPE = 16
    # data path (ref DispatcherAddData / StorageAddData / SetScan)
    SEND_DATA = 20
    SEND_MATRIX = 21
    GET_TENSOR = 22
    SCAN_SET = 23
    ADD_SHARED_MAPPING = 24
    FLUSH_DATA = 25
    LOAD_SET = 26
    # streamed data path: bounded-memory scan / chunked tensor pull
    SCAN_SET_STREAM = 27
    GET_TENSOR_CHUNKED = 28
    # serve-time model dedup: pool shared blocks across resident models
    DEDUP_RESIDENT = 29
    # query execution (ref ExecuteComputation)
    EXECUTE_COMPUTATIONS = 30
    EXECUTE_PLAN = 31
    LIST_JOBS = 32
    # stats (ref StorageCollectStats)
    COLLECT_STATS = 40
    # planner statistics computed where the data lives: per-column
    # summaries + dictionaries of one stored relation, so DAG builders
    # (suite_sink_for) never pull tables from a daemon (ref
    # StorageCollectStats → Statistics, PangeaStorageServer.h:48)
    ANALYZE_SET = 41
    # multi-host reads: a master assembling a mesh-spanning array asks
    # each follower for ITS addressable shards (index ranges + bytes) —
    # the reference streaming each node's local pages to the frontend
    # (FrontendQueryTestServer.cc:785-890); reads never enter the SPMD
    # program, so no collective/ordering hazards
    LOCAL_SHARDS = 42
    # streamed compute over a paged TENSOR set: stored @ rhs with the
    # stored matrix paged through the device (larger-than-HBM weights
    # behind the daemon; ref pipelines over pinned weight pages)
    PAGED_MATMUL = 43


class ProtocolError(ConnectionError):
    pass


def _pack_default(obj: Any):
    """msgpack hook: numpy arrays ride as raw buffers."""
    if isinstance(obj, np.ndarray):
        a = np.ascontiguousarray(obj)
        return {"__nd__": True, "d": a.dtype.str, "s": list(a.shape),
                "b": a.tobytes()}
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    raise TypeError(f"cannot serialize {type(obj)!r} over the wire; "
                    f"wrap host objects in a pickled job instead")


def _unpack_hook(obj):
    if isinstance(obj, dict) and obj.get("__nd__"):
        return np.frombuffer(obj["b"], dtype=np.dtype(obj["d"])).reshape(
            obj["s"])
    return obj


def encode_body(payload: Any, codec: int = CODEC_MSGPACK) -> bytes:
    if codec == CODEC_MSGPACK:
        return msgpack.packb(payload, use_bin_type=True,
                             default=_pack_default)
    if codec == CODEC_PICKLE:
        import cloudpickle

        return cloudpickle.dumps(payload)
    raise ProtocolError(f"unknown codec {codec}")


def decode_body(body: bytes, codec: int, allow_pickle: bool) -> Any:
    if codec == CODEC_MSGPACK:
        return msgpack.unpackb(body, raw=False, object_hook=_unpack_hook,
                               strict_map_key=False)
    if codec == CODEC_PICKLE:
        if not allow_pickle:
            raise ProtocolError(
                "pickled frame refused: this endpoint has allow_pickle "
                "off (enable it only on trusted-cluster control planes)")
        import pickle

        return pickle.loads(body)
    raise ProtocolError(f"unknown codec {codec}")


def send_frame(sock: socket.socket, msg_type: int, payload: Any,
               codec: int = CODEC_MSGPACK) -> None:
    body = encode_body(payload, codec)
    sock.sendall(_HEADER.pack(MAGIC, codec, int(msg_type), len(body)))
    sock.sendall(body)


def _recv_exact(sock: socket.socket, n: int) -> memoryview:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ProtocolError("peer closed mid-frame")
        got += r
    return memoryview(buf)


def recv_frame_raw(sock: socket.socket) -> Tuple[MsgType, int, bytes]:
    """Receive one frame without decoding — servers decode separately so
    a refused codec becomes an ERR reply, not a dropped connection."""
    header = _recv_exact(sock, _HEADER.size)
    magic, codec, msg_type, body_len = _HEADER.unpack(header)
    if magic != MAGIC:
        raise ProtocolError(f"bad frame magic {magic:#x}")
    if body_len > MAX_FRAME_BYTES:
        raise ProtocolError(f"frame of {body_len} bytes exceeds cap")
    body = _recv_exact(sock, body_len)
    try:
        typ = MsgType(msg_type)
    except ValueError:
        # unknown type ids stay raw ints: the server answers them with a
        # "no handler" ERR instead of dropping the connection
        typ = msg_type
    return typ, codec, bytes(body)


def recv_frame(sock: socket.socket,
               allow_pickle: bool = False) -> Tuple[MsgType, Any]:
    msg_type, codec, body = recv_frame_raw(sock)
    return msg_type, decode_body(body, codec, allow_pickle)


# --- tensor wire form -------------------------------------------------

def tensor_to_wire(dense: np.ndarray, block_shape=None) -> dict:
    """Dense tensor → wire dict. The device-side blocking/placement is
    the server's job; the wire carries the raw dense buffer once."""
    return {"data": np.ascontiguousarray(dense),
            "block_shape": list(block_shape) if block_shape else None}


def tensor_from_wire(obj: dict) -> Tuple[np.ndarray, Any]:
    data = obj["data"]
    bs = obj.get("block_shape")
    return data, (tuple(bs) if bs else None)
