"""Scatter-gather execution engine for the sharded worker pool.

The coordinator half (:class:`ShardPool`, owned by the leader) maps a
query's :class:`~netsdb_tpu.plan.scatter.ScatterSpec` onto the pool:
one SUBPLAN per shard slot (the leader executes its own slot
in-process — it IS slot 0 of every set it placed), bounded partials
collected under one shared deadline, merged in slot order, the merged
result materialized into the coordinator's store exactly like a local
execution — so reads of the output set need no new wire surface.

The shard half (:func:`execute_subplan`) runs a shipped subplan
through the daemon's OWN executor over its local pages: staging, the
device cache, scheduler affinity state and PR 10's fusion regions all
apply per shard with zero new code — a shard executes its region
program over local pages and ships only the bounded partial back
(the *Large Scale Distributed Linear Algebra With TPUs* shape: each
worker computes over only its panel, the coordinator merges bounded
partials).

The distributed shuffle (``shuffle_join`` specs) runs shard→shard:
every slot hash-partitions both local join sides by the key's
splitmix64 mix and ships bucket *j* to slot *j* as a SHUFFLE_PUT
whose column buffers ride out-of-band v3 segments (no ``tobytes``
copies anywhere on the path); each slot folds its own bucket and the
coordinator merges outputs with the fold's declared ``merge`` — the
grace-hash partition step run across daemons instead of arena spill
partitions.

Failure discipline: partials are merged ALL-or-nothing. Any slot
failing (connection loss, epoch mismatch, deadline) discards every
partial, evicts unreachable shards from placement (epoch bump) and
surfaces the typed retryable ``ShardUnavailable``/``PlacementStale``
to the client — never a partial or doubled merge.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from netsdb_tpu import obs
from netsdb_tpu.serve import placement as _placement
from netsdb_tpu.serve.errors import PlacementStale, ShardUnavailable
from netsdb_tpu.serve.protocol import (
    CLIENT_ID_KEY,
    CODEC_MSGPACK,
    CODEC_PICKLE,
    HA_TERM_KEY,
    IDEMPOTENCY_KEY,
    PLACEMENT_EPOCH_KEY,
    QUERY_ID_KEY,
    SHARD_SLOT_KEY,
    MsgType,
)
from netsdb_tpu.utils.locks import TrackedLock
from netsdb_tpu.utils.timing import deadline_after, seconds_left

_shuffle_ids = itertools.count(1)


def _np_tree(value: Any) -> Any:
    """Fold state → numpy pytree for the wire (device arrays must not
    ride a pickle frame)."""
    import jax

    return jax.tree_util.tree_map(np.asarray, value)


def local_table(ctl, db: str, set_name: str):
    """This daemon's local partition of a table set as ONE host
    ColumnTable (paged relations assemble off the arena; resident
    relations compact their validity). None when the set holds no
    table — an empty shard's legitimate state."""
    from netsdb_tpu.relational.outofcore import PagedColumns
    from netsdb_tpu.relational.table import ColumnTable
    from netsdb_tpu.storage.store import SetIdentifier

    items = ctl.library.store.get_items(SetIdentifier(db, set_name))
    for item in items:
        if isinstance(item, PagedColumns):
            return item.to_host_table()
        if isinstance(item, ColumnTable):
            return item.compact() if item.valid is not None else item
    return None


def local_schema(ctl, db: str, set_name: str) -> Tuple[Dict, int]:
    """(dicts, num_rows) of this daemon's local partition — the schema
    surface a scatterable fold's coordinator-side finalize may read."""
    from netsdb_tpu.relational.outofcore import PagedColumns
    from netsdb_tpu.relational.table import ColumnTable
    from netsdb_tpu.storage.store import SetIdentifier

    items = ctl.library.store.get_items(SetIdentifier(db, set_name))
    for item in items:
        if isinstance(item, (PagedColumns, ColumnTable)):
            return dict(item.dicts), int(item.num_rows)
    return {}, 0


class ShuffleInbox:
    """Bounded store of inbound distributed-shuffle buckets, keyed by
    (shuffle id, side, sender slot). Senders may retry — a duplicate
    put overwrites its own key (byte-identical content), so the
    receiving leg can never double-count a bucket. Entries a leg never
    claims are pruned by TTL on later puts."""

    def __init__(self, max_bytes: int = 1 << 30, ttl_s: float = 600.0):
        self._mu = TrackedLock("serve.ShuffleInbox._mu")
        self._cv = threading.Condition(self._mu)
        self._entries: Dict[str, Dict[str, Any]] = {}
        self._bytes = 0
        self._max_bytes = int(max_bytes)
        self._ttl_s = float(ttl_s)

    @staticmethod
    def _size(cols: Optional[Dict[str, np.ndarray]]) -> int:
        return sum(np.asarray(v).nbytes for v in (cols or {}).values())

    def put(self, sid: str, side: str, slot: int,
            cols: Optional[Dict[str, np.ndarray]],
            dicts: Optional[Dict] = None) -> None:
        nbytes = self._size(cols)
        with self._cv:
            self._prune_locked()
            entry = self._entries.setdefault(
                sid, {"sides": {}, "bytes": 0, "t": time.monotonic()})
            old = entry["sides"].get(side, {}).get(slot)
            # the cap judges the NET delta: a duplicate put (sender
            # retry) overwrites its own byte-identical key, so it must
            # never be refused against bytes it is about to replace
            old_bytes = self._size(old[0]) if old is not None else 0
            if self._bytes - old_bytes + nbytes > self._max_bytes:
                raise ShardUnavailable(
                    f"shuffle inbox over its {self._max_bytes}-byte "
                    f"bound; retry shortly")
            if old is not None:
                entry["bytes"] -= old_bytes
                self._bytes -= old_bytes
            entry["sides"].setdefault(side, {})[slot] = (cols, dicts)
            entry["bytes"] += nbytes
            self._bytes += nbytes
            self._cv.notify_all()

    def wait(self, sid: str, sides: Dict[str, int],
             timeout_s: float) -> Dict[str, Dict[int, Tuple]]:
        """Block until ``sid`` holds ``sides[side]`` buckets per side
        (or raise typed retryable on timeout), then POP the entry."""
        if not sides or all(n <= 0 for n in sides.values()):
            return {}  # single-slot pool: nothing to exchange
        deadline = deadline_after(timeout_s)
        with self._cv:
            while True:
                entry = self._entries.get(sid)
                if entry is not None and all(
                        len(entry["sides"].get(side, {})) >= n
                        for side, n in sides.items()):
                    self._entries.pop(sid)
                    self._bytes -= entry["bytes"]
                    return entry["sides"]
                left = seconds_left(deadline)
                if left <= 0 or not self._cv.wait(left):
                    # re-check ONCE under the lock before failing:
                    # the final bucket's put may have landed (and
                    # notified) in the same instant the wait timed out
                    entry = self._entries.get(sid)
                    if entry is not None and all(
                            len(entry["sides"].get(side, {})) >= n
                            for side, n in sides.items()):
                        continue
                    got = {s: len((entry or {}).get("sides", {})
                                  .get(s, {})) for s in sides}
                    raise ShardUnavailable(
                        f"distributed shuffle {sid} incomplete after "
                        f"{timeout_s}s (received {got}, expected "
                        f"{sides}) — a peer shard is unreachable")

    def _prune_locked(self) -> None:
        cutoff = time.monotonic() - self._ttl_s
        for sid in [s for s, e in self._entries.items()
                    if e["t"] < cutoff]:
            self._bytes -= self._entries[sid]["bytes"]
            self._entries.pop(sid)


# --- shard-side subplan execution ------------------------------------

def check_epochs(ctl, epochs: Dict[str, int]) -> None:
    """Validate a routed frame's placement epochs against what this
    daemon was registered under (worker: ``_shard_sets``; leader: its
    own placement map). A mismatch is the typed retryable
    placement-epoch rejection — the frame is refused WHOLE before any
    execution, so a revised membership can never partially apply."""
    for scope, epoch in (epochs or {}).items():
        db, _, set_name = scope.partition(":")
        current = None
        reg = ctl.shard_registration(db, set_name)
        if reg is not None:
            current = reg["epoch"]
        else:
            entry = ctl.placement.entry(db, set_name)
            if entry is not None:
                current = entry["epoch"]
        if current is None or int(epoch) != int(current):
            obs.REGISTRY.counter("shard.epoch_rejects").inc()
            raise PlacementStale(
                f"placement epoch rejected for {scope}: frame rode "
                f"epoch {epoch}, daemon registered "
                f"{current if current is not None else 'none'}",
                epoch=current)


def execute_subplan(ctl, p: dict) -> dict:
    """One shard's leg of a scatter-gather execution (also run
    in-process by the coordinator for its own slot). Returns the
    bounded partial the coordinator merges, plus the leg's compiled-
    program delta (``compile_stats`` misses/traces across the run —
    the distributed-compilation proof the one-program tests pin;
    process-global, so only meaningful on a quiesced daemon)."""
    from netsdb_tpu.plan import executor as _executor

    obs.REGISTRY.counter("shard.subplans").inc()
    check_epochs(ctl, p.get("epochs"))
    kind = p["kind"]
    if kind == "shuffle_join":
        return _execute_shuffle_leg(ctl, p)
    explain = bool(p.get("explain"))

    def run():
        results = ctl.library.execute_computations(
            *p["sinks"], job_name=f"{p.get('job_name', 'scatter')}@shard",
            materialize=False)
        return next(iter(results.values()))

    before = _executor.compile_stats()
    with obs.span("server.shard.subplan", "serve"):
        if explain:
            with obs.operators.explain_capture() as cap:
                value = run()
            tree = cap.get("operators")
        else:
            value = run()
            tree = None
    after = _executor.compile_stats()
    out: Dict[str, Any] = {
        "compile": {
            "programs": after["misses"] - before["misses"],
            "traces": after["traces"] - before["traces"],
        },
    }
    if kind in ("fold_state", "multi_fold"):
        db, set_name = p["scan"]
        dicts, rows = local_schema(ctl, db, set_name)
        out.update(state=_np_tree(value), dicts=dicts, rows=rows)
    elif kind == "tensor_chain":
        # the local-batch output rides the wire dense and UNPADDED
        # (to_dense strips block padding) — the coordinator's concat
        # must see true batch extents, not bucket-padded ones. Item
        # lists (the conv2d shape: one tensor per input image) ship
        # as per-item host arrays.
        from netsdb_tpu.core.blocked import BlockedTensor

        def _host(v):
            if isinstance(v, BlockedTensor):
                v = v.to_dense()
            return np.asarray(v)

        out["tensor"] = [_host(v) for v in value] \
            if isinstance(value, (list, tuple)) else _host(value)
    else:  # group_partial — the dict IS the partial
        out["groups"] = value
    if tree is not None:
        out["operators"] = tree
    return out


def _partition_cols(table, key: str, nslots: int,
                    columns: Optional[Tuple[str, ...]] = None
                    ) -> List[Optional[Dict[str, np.ndarray]]]:
    """Hash-partition one table's rows by ``key`` into per-slot column
    dicts (splitmix64 mix — the same rule ingest-time hash placement
    uses, so the two agree). ``columns`` projects the carried columns
    (the fold's declared probe columns + the key), cutting shuffle
    bytes the way the arena grace partitioner already does."""
    if table is None:
        return [None] * nslots
    names = list(table.cols)
    if columns:
        keep = set(columns) | {key}
        names = [n for n in names if n in keep]
    cols = {n: np.asarray(table.cols[n]) for n in names}
    slot_ids = _placement.hash_slot_ids(cols[key], nslots)
    out: List[Optional[Dict[str, np.ndarray]]] = []
    for j in range(nslots):
        idx = np.nonzero(slot_ids == j)[0]
        out.append({n: v[idx] for n, v in cols.items()})
    return out


def _execute_shuffle_leg(ctl, p: dict) -> dict:
    """One slot's leg of the distributed shuffle join: partition both
    local sides, exchange buckets with every peer slot, fold the own
    bucket, return the partial output."""
    from netsdb_tpu.relational.table import ColumnTable

    fold = p["fold"]
    slot = int(p["slot"])
    addrs = list(p["addrs"])
    nslots = len(addrs)
    sid = p["sid"]
    sides = (("probe", tuple(p["probe"]), fold.probe_key,
              tuple(fold.probe_columns) if fold.probe_columns else None),
             ("build", tuple(p["build"]), fold.build_key, None))
    own: Dict[str, Tuple] = {}
    dicts_by_side: Dict[str, Dict] = {}
    with obs.span("server.shard.shuffle", "serve"):
        for side, (db, set_name), key, columns in sides:
            table = local_table(ctl, db, set_name)
            dicts_by_side[side] = dict(table.dicts) if table is not None \
                else {}
            buckets = _partition_cols(table, key, nslots, columns)
            for j in range(nslots):
                if j == slot:
                    own[side] = (buckets[j], dicts_by_side[side])
                    continue
                payload = {"sid": sid, "side": side, "slot": slot,
                           "cols": buckets[j],
                           "dicts": dicts_by_side[side]}
                # data connection: the peer's CONTROL connection is
                # busy carrying its own in-flight SUBPLAN
                ctl.shards.data_client(addrs[j])._request(
                    MsgType.SHUFFLE_PUT, payload, CODEC_MSGPACK)
        inbound = ctl._shuffle.wait(
            sid, {side: nslots - 1 for side, *_ in sides} if nslots > 1
            else {},
            float(p.get("shuffle_timeout_s") or 120.0))

    tables: Dict[str, Any] = {}
    for side, _ident, key, _cols in sides:
        parts: List[Dict[str, np.ndarray]] = []
        dicts = dict(dicts_by_side.get(side) or {})
        for j in range(nslots):
            if j == slot:
                cols = own[side][0]
            else:
                cols, peer_dicts = inbound.get(side, {}).get(
                    j, (None, None))
                for name, vocab in (peer_dicts or {}).items():
                    if name in dicts and list(dicts[name]) \
                            != list(vocab):
                        # concatenating RAW code columns is only sound
                        # when every shard encoded under the SAME
                        # dictionary; divergent vocabularies (possible
                        # under multi-batch hash ingest where a batch
                        # skipped a slot) would silently decode codes
                        # through the wrong vocab — refuse loudly
                        raise ValueError(
                            f"distributed shuffle: shard {j}'s "
                            f"dictionary for column {name!r} diverges "
                            f"from shard {slot}'s; re-ingest the set "
                            f"with aligned dictionaries")
                    dicts.setdefault(name, vocab)
            if cols is not None and cols:
                parts.append(cols)
        if not parts:
            tables[side] = None
            continue
        names = list(parts[0])
        tables[side] = ColumnTable(
            {n: np.concatenate([np.asarray(c[n]) for c in parts])
             for n in names}, dicts, None)
    if tables["probe"] is None or tables["build"] is None:
        # a legitimately empty bucket: the fold still needs SOME table
        # shape — report the empty partial and let the merge skip it
        return {"table": None}
    t0 = time.perf_counter()
    with obs.span("server.shard.subplan", "serve"):
        out = fold.whole(tables["probe"], tables["build"])
    reply: Dict[str, Any] = {"table": out}
    if p.get("explain"):
        # the shuffle leg runs outside the executor (no per-node
        # recorder) — report a one-node tree so the per-shard EXPLAIN
        # forest stays complete: kind, wall, probe/build row counts
        wall = time.perf_counter() - t0
        reply["operators"] = {
            "job": p.get("job_name", "scatter"), "mode": "shuffle",
            "total_wall_s": wall,
            "nodes": [{
                "id": 0, "kind": "ShuffleJoin",
                "label": f"{fold.probe_key}={fold.build_key}",
                "inputs": [], "wall_s": wall,
                "rows_in": int(tables["probe"].num_rows),
                "rows_out": int(getattr(out, "num_rows", 0) or 0),
                "counters": {}}]}
    return reply


# --- results materialization (the executor's rule, shared) -----------

def materialize_result(store, ident, out) -> None:
    """Write one merged scatter result into the coordinator's store
    exactly the way ``plan/executor.py`` materializes a sink — reads
    of the output set then behave identically to a local execution."""
    import jax

    from netsdb_tpu.core.blocked import BlockedTensor
    from netsdb_tpu.relational.table import ColumnTable

    store.create_set(ident)
    if isinstance(out, BlockedTensor):
        store.put_tensor(ident, out)
    elif isinstance(out, (ColumnTable, jax.Array)):
        store.clear_set(ident)
        store.add_data(ident, [out])
    elif isinstance(out, dict):
        store.clear_set(ident)
        store.add_data(ident, list(out.items()))
    else:
        store.clear_set(ident)
        store.add_data(ident, list(out))


def _annotate_shard(tree: Any, addr: str) -> Any:
    """Mark every node of one shard's EXPLAIN tree with the daemon
    that executed it (the pushed-region annotation). Operator trees
    carry their nodes as a flat ``nodes`` list (obs/operators.py), so
    that list is what gets stamped — recursing only into ``children``
    keys used to stamp nothing but the root."""
    if isinstance(tree, dict):
        out = dict(tree)
        if isinstance(out.get("nodes"), list):
            out["nodes"] = [dict(n, shard=addr) if isinstance(n, dict)
                            else n for n in out["nodes"]]
        out["shard"] = addr
        return out
    if isinstance(tree, list):
        return [_annotate_shard(t, addr) for t in tree]
    return tree


class ShardPool:
    """Per-controller pool state: cached connections to shard peers,
    the leader's handoff buffers for degraded slots, and the
    coordinator entry point. Workers carry one too (empty worker list)
    purely as the peer-connection cache the distributed shuffle
    dials through."""

    def __init__(self, ctl, handoff_max_bytes: int = 256 << 20,
                 spill=None):
        self.ctl = ctl
        self._mu = TrackedLock("serve.ShardPool._mu")
        self._clients: Dict[str, Any] = {}
        self._degraded: Dict[str, str] = {}
        # (db, set, slot) → [(token, payload)] ingest buffered while
        # the slot's shard is away; drained — only these pages, never
        # a whole-store snapshot — on readmit
        self._handoff: Dict[Tuple[str, str, int], List[Tuple[str, dict]]] \
            = {}
        self._handoff_bytes = 0
        self._handoff_max = int(handoff_max_bytes)
        # the buffer's disk shadow (storage/mutlog.py, config.ha_mutlog):
        # every put/drain/purge appends a record under _mu, so a leader
        # restart replays the buffer via load_spill() instead of losing
        # buffered routed ingest. None keeps the buffer memory-only.
        self._spill = spill

    # --- connections --------------------------------------------------
    def client(self, addr: str):
        """Cached pool connection (mirror-path semantics: no silent
        client-side retries — a failure must surface so the
        coordinator can evict + refuse typed)."""
        from netsdb_tpu.serve.client import RemoteClient, RetryPolicy

        with self._mu:
            c = self._clients.get(addr)
        if c is not None:
            return c
        dial = addr.partition(":")[2] if addr.startswith("data:") \
            else addr
        c = RemoteClient(dial, token=self.ctl.token,
                         retry=RetryPolicy(max_attempts=1),
                         timeout=self.ctl.mirror_ack_timeout_s,
                         connect_timeout=self.ctl.handshake_timeout_s)
        with self._mu:
            other = self._clients.setdefault(addr, c)
        if other is not c:
            c.close()
        return other

    def data_client(self, addr: str):
        """Separate connection pool for SHUFFLE_PUT traffic. The
        control connection to a shard is OCCUPIED for the whole
        in-flight SUBPLAN (one request per connection), and a shuffle
        leg must push buckets to that same shard WHILE its subplan
        runs — sharing the connection would deadlock the exchange
        (bucket waits for subplan reply, subplan waits for bucket)."""
        return self.client(f"data:{addr}")

    def fresh_client(self, addr: str):
        """UNCACHED connection for one in-flight subplan. Subplans do
        not share the pooled control connection: (a) concurrent
        scatter queries would serialize per shard behind its one
        connection lock, and (b) the scatter deadline unsticks a slow
        slot by force-closing its socket — which must kill exactly
        THAT query's request, never a concurrent healthy query that
        happened to share the connection (whose failure would then
        evict a healthy shard). The caller owns close()."""
        from netsdb_tpu.serve.client import RemoteClient, RetryPolicy

        return RemoteClient(addr, token=self.ctl.token,
                            retry=RetryPolicy(max_attempts=1),
                            timeout=self.ctl.mirror_ack_timeout_s,
                            connect_timeout=self.ctl.handshake_timeout_s)

    def drop_client(self, addr: str) -> None:
        for key in (addr, f"data:{addr}"):
            with self._mu:
                c = self._clients.pop(key, None)
            if c is not None:
                c._force_close()

    def peer_request(self, addr: str, typ, payload,
                     codec: int = CODEC_MSGPACK):
        return self.client(addr)._request(typ, payload, codec)

    def close(self) -> None:
        with self._mu:
            clients = list(self._clients.values())
            self._clients.clear()
        for c in clients:
            c.close()

    # --- degraded bookkeeping ----------------------------------------
    def _forget_session_weights(self, addr: str) -> None:
        """A member left (or came back) — the session manager's
        weights-already-shipped record for it is stale: a restarted
        worker no longer holds the models, and a weight-less adopt
        there fails register_model."""
        sessions = getattr(self.ctl, "sessions", None)
        if sessions is not None:
            sessions.forget_owner(addr)

    def degrade(self, addr: str, reason: str) -> None:
        with self._mu:
            fresh = addr not in self._degraded
            self._degraded[addr] = reason
        if fresh:
            obs.REGISTRY.counter("shard.evictions").inc()
        self._forget_session_weights(addr)
        changed = self.ctl.placement.degrade_addr(addr)
        self.drop_client(addr)
        if changed:
            # the bump is leader-local until the surviving workers
            # re-register under it (best-effort push)
            self.ctl._push_epochs(exclude=(addr,))
        # every membership change replicates (and persists, under
        # ha_mutlog) the map — a follower promoted mid-outage must
        # already know which slots are in handoff
        self.ctl._replicate_placement()

    def note_degraded(self, addr: str, reason: str) -> None:
        """Record-only degrade (durable restart): the placement map
        ALREADY holds the slot in handoff state — re-running the full
        :meth:`degrade` would bump epochs a second time and invalidate
        every client map for nothing. The pool health loop sees the
        entry and runs the normal readmit + drain."""
        with self._mu:
            self._degraded.setdefault(addr, reason)
        self._forget_session_weights(addr)

    def is_degraded(self, addr: str) -> bool:
        with self._mu:
            return addr in self._degraded

    def clear_degraded(self, addr: str) -> None:
        with self._mu:
            self._degraded.pop(addr, None)
        self._forget_session_weights(addr)

    def degraded(self) -> Dict[str, str]:
        with self._mu:
            return dict(self._degraded)

    # --- handoff (the shard-scoped resync buffer) ---------------------
    @staticmethod
    def _payload_bytes(p: dict) -> int:
        items = p.get("items")
        if hasattr(items, "cols"):
            return int(sum(np.asarray(v).nbytes
                           for v in items.cols.values()))
        try:
            return 256 * len(items)
        except TypeError:
            return 1 << 20

    def handoff_put(self, db: str, set_name: str, slot: int,
                    token: Optional[str], payload: dict) -> None:
        import uuid

        # the batch drains under the CLIENT's idempotency token when
        # the frame carried one (a shard that already applied the
        # original then dedupes the drained copy); otherwise a DRAIN
        # token minted here keeps a retried drain itself at-most-once
        token = token or uuid.uuid4().hex
        nbytes = self._payload_bytes(payload)
        rec = (token, dict(payload))
        key = (db, set_name, slot)
        with self._mu:
            if self._handoff_bytes + nbytes > self._handoff_max:
                raise ShardUnavailable(
                    f"handoff buffer for degraded shard slot {slot} is "
                    f"full ({self._handoff_max} bytes); retry later",
                    slot=slot)
            self._handoff.setdefault(key, []).append(rec)
            self._handoff_bytes += nbytes
            if self._spill is not None:
                # appended under _mu: spill-record order == buffer
                # order, so load_spill() reconstructs exact FIFO state
                self._spill.append({"op": "put", "key": list(key),
                                    "token": token,
                                    "payload": dict(payload)})
        # close the buffer-vs-readmit race: if the slot flipped LIVE
        # while this frame was in flight, the readmit drain may
        # already have run — a batch inserted after its final sweep
        # would otherwise strand in the buffer forever. Re-check and,
        # when the slot is no longer in handoff, pull the batch back
        # out and reject typed (the client re-routes to the live
        # shard); if the drain already shipped it, it was delivered.
        entry = self.ctl.placement.entry(db, set_name)
        sl = (entry["slots"][slot]
              if entry is not None and slot < len(entry["slots"])
              else None)
        if sl is None or sl["state"] != _placement.HANDOFF:
            with self._mu:
                cur = self._handoff.get(key, [])
                if rec in cur:
                    cur.remove(rec)
                    self._handoff_bytes -= nbytes
                    if not cur:
                        self._handoff.pop(key, None)
                    if self._spill is not None:
                        self._spill.append({"op": "unput",
                                            "key": list(key),
                                            "token": token})
                    raise PlacementStale(
                        f"slot {slot} of {db}:{set_name} readmitted "
                        f"mid-buffer; re-route to the live shard",
                        epoch=entry["epoch"] if entry else None)
            return  # drained concurrently — delivered, not buffered
        obs.REGISTRY.counter("shard.handoff_batches").inc()

    def handoff_pending(self, addr: str) -> int:
        """Buffered batches destined for ``addr``'s slots (test and
        readmit-drain probe)."""
        count = 0
        for db, set_name in self.ctl.placement.sets_for_addr(addr):
            entry = self.ctl.placement.entry(db, set_name)
            for i, s in enumerate(entry["slots"]):
                if s["addr"] != addr:
                    continue
                with self._mu:
                    count += len(self._handoff.get((db, set_name, i),
                                                   ()))
        return count

    def purge_handoff(self, db: str, set_name: str) -> int:
        """Drop every buffered handoff batch of one set (REMOVE/CLEAR
        — the pages it would have delivered no longer exist). Returns
        the batch count dropped; keeps the byte accounting exact."""
        dropped = 0
        with self._mu:
            for key in [k for k in self._handoff
                        if k[0] == db and k[1] == set_name]:
                gone = self._handoff.pop(key)
                dropped += len(gone)
                self._handoff_bytes -= sum(self._payload_bytes(p)
                                           for _, p in gone)
            if dropped and self._spill is not None:
                self._spill.append({"op": "purge", "db": db,
                                    "set": set_name})
        return dropped

    def drain_handoff(self, addr: str) -> int:
        """Ship a readmitted shard exactly its own buffered pages (the
        shard-scoped resync — contrast RESYNC_FOLLOWER's whole-store
        snapshot). Buffered idempotency tokens ride along, so a drain
        retried after a mid-drain failure can never double-apply.
        Batches are removed from the buffer only AFTER they shipped,
        exactly the ones that shipped — a batch buffered concurrently
        (a frame classified handoff just before the epoch flipped) is
        picked up by the drain loop's next round, never dropped.

        Device-cache coherence: drained batches land on the shard
        through the ordinary SEND_DATA mutators, so ``SetStore._touch``
        logs each one as an APPEND-TAIL dirty range — under partial-run
        caching the shard's pre-buffered cached blocks stay resident
        and only the drained tail re-stages (pinned by
        tests/test_devcache_partial.py)."""
        drained = 0
        for db, set_name in self.ctl.placement.sets_for_addr(addr):
            entry = self.ctl.placement.entry(db, set_name)
            for i, s in enumerate(entry["slots"]):
                if s["addr"] != addr:
                    continue
                key = (db, set_name, i)
                while True:
                    with self._mu:
                        batches = list(self._handoff.get(key, ()))
                    if not batches:
                        break
                    for token, payload in batches:
                        fwd = dict(payload)
                        fwd[PLACEMENT_EPOCH_KEY] = entry["epoch"]
                        fwd[SHARD_SLOT_KEY] = i
                        if token:
                            fwd[IDEMPOTENCY_KEY] = token
                        if getattr(self.ctl, "_ha", None) is not None:
                            # drains are peer frames: a shard that
                            # adopted a newer leader must fence a
                            # deposed leader's drain, same as mirrors
                            fwd[HA_TERM_KEY] = self.ctl._ha.term
                        self.peer_request(addr, MsgType.SEND_DATA,
                                          fwd, CODEC_PICKLE)
                        drained += 1
                    with self._mu:
                        cur = self._handoff.get(key, [])
                        # the sent batches are the FIFO prefix; drop
                        # exactly them, keep any concurrent arrivals
                        rest = cur[len(batches):]
                        self._handoff_bytes -= sum(
                            self._payload_bytes(p)
                            for _, p in cur[:len(batches)])
                        if rest:
                            self._handoff[key] = rest
                        else:
                            self._handoff.pop(key, None)
                        if self._spill is not None:
                            self._spill.append(
                                {"op": "drain", "key": list(key),
                                 "n": len(batches)})
                            if not self._handoff:
                                # buffer fully empty: the spill's
                                # history is dead weight — truncate so
                                # it never grows without bound
                                self._spill.truncate()
        if drained:
            obs.REGISTRY.counter("shard.handoff_drained").inc(drained)
        return drained

    def load_spill(self) -> int:
        """Rebuild the handoff buffer from the spill log (leader
        restart under ``ha_mutlog``): replay put/unput/drain/purge in
        order — the surviving suffix is exactly what was buffered and
        undelivered when the daemon died. Returns the pending batch
        count."""
        if self._spill is None:
            return 0
        with self._mu:
            self._handoff.clear()
            self._handoff_bytes = 0
            for _end, rec in self._spill.replay():
                op = rec.get("op")
                if op == "put":
                    key = tuple(rec["key"])
                    self._handoff.setdefault(key, []).append(
                        (rec.get("token"), rec["payload"]))
                elif op == "unput":
                    key = tuple(rec["key"])
                    cur = self._handoff.get(key, [])
                    for j in range(len(cur) - 1, -1, -1):
                        if cur[j][0] == rec.get("token"):
                            cur.pop(j)
                            break
                    if not cur:
                        self._handoff.pop(key, None)
                elif op == "drain":
                    key = tuple(rec["key"])
                    cur = self._handoff.get(key, [])
                    rest = cur[int(rec.get("n") or 0):]
                    if rest:
                        self._handoff[key] = rest
                    else:
                        self._handoff.pop(key, None)
                elif op == "purge":
                    for key in [k for k in self._handoff
                                if k[0] == rec.get("db")
                                and k[1] == rec.get("set")]:
                        self._handoff.pop(key)
            self._handoff_bytes = sum(
                self._payload_bytes(p)
                for batches in self._handoff.values()
                for _, p in batches)
            return sum(len(b) for b in self._handoff.values())

    # --- read fan-out (stats/trace/health shard sections) -------------
    def fanout(self, typ, payload) -> Dict[str, Any]:
        """Best-effort read fan-out to every worker — the shard twin
        of the follower ``_fanout_read`` merge: one shared deadline, a
        slow shard reports an error entry and is NEVER evicted by a
        stats read."""
        addrs = list(self.ctl._worker_addrs)
        if not addrs:
            return {}
        out: Dict[str, Any] = {}
        deadline = deadline_after(self.ctl.frame_timeout_s)
        threads = []

        def ask(addr):
            try:
                out[addr] = self.peer_request(addr, typ, payload)
            except Exception as e:  # noqa: BLE001 — best-effort section
                out[addr] = {"error": f"{type(e).__name__}: {e}"}

        for addr in addrs:
            t = threading.Thread(target=ask, args=(addr,), daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(max(0.0, seconds_left(deadline)))
        for addr in addrs:
            out.setdefault(addr, {"error": "no reply within "
                                           f"{self.ctl.frame_timeout_s}s"})
        return out

    # --- the coordinator ----------------------------------------------
    def scatter_execute(self, sinks: List[Any], job_name: str,
                        materialize: bool = True,
                        explain: bool = False,
                        qid: Optional[str] = None,
                        client_id: Optional[str] = None):
        """Execute one sink DAG over the pool: analyze, fan out, merge
        all-or-nothing, materialize. Returns ``(results, shard_ops)``
        — ``shard_ops`` is the per-shard EXPLAIN forest (None unless
        ``explain``)."""
        from netsdb_tpu.plan import scatter
        from netsdb_tpu.storage.store import SetIdentifier

        ctl = self.ctl
        spec = scatter.analyze_sinks(sinks, ctl.is_sharded)
        if spec is None:
            touched = scatter.sharded_scan_sets(sinks, ctl.is_sharded)
            raise ValueError(
                f"query scans partitioned set(s) "
                f"{[f'{d}:{s}' for d, s in touched]} in a shape "
                f"scatter-gather cannot push (supported: single-pass "
                f"folds declaring state_merge, dict group-bys with "
                f"combine, grace-hash joins with declared keys+merge, "
                f"layer chains with a sink scatter_gather "
                f"declaration); "
                f"a partitioned set's pages live only on its shards, "
                f"so there is no local fallback")
        entries = {}
        for db, s in spec.scan_sets:
            entry = ctl.placement.entry(db, s)
            entries[(db, s)] = entry
            for i, sl in enumerate(entry["slots"]):
                if sl["state"] != _placement.LIVE:
                    raise ShardUnavailable(
                        f"shard slot {i} of {db}:{s} ({sl['addr']}) is "
                        f"degraded; scatter-gather refuses rather than "
                        f"merge a partial result", slot=i,
                        epoch=entry["epoch"])
        first = entries[spec.scan_sets[0]]
        addrs = [sl["addr"] for sl in first["slots"]]
        for (db, s), e in entries.items():
            if [sl["addr"] for sl in e["slots"]] != addrs:
                raise ValueError(
                    f"sets {spec.scan_sets} are not co-placed on one "
                    f"pool; cross-pool scatter is unsupported")
        epochs = {f"{db}:{s}": e["epoch"] for (db, s), e in
                  entries.items()}
        payload: Dict[str, Any] = {
            "kind": spec.kind, "job_name": job_name,
            "explain": bool(explain), "epochs": epochs,
        }
        if spec.kind == "shuffle_join":
            payload.update(
                sid=f"{ctl.advertise_addr}#{next(_shuffle_ids)}",
                addrs=addrs, probe=list(spec.probe),
                build=list(spec.build), fold=spec.fold,
                shuffle_timeout_s=min(
                    ctl.mirror_ack_timeout_s or 120.0, 120.0))
        elif spec.kind == "multi_fold":
            # the fan ships as ONE subplan per shard: a single scan,
            # one combined tuple-state fold, one partial sink
            payload["sinks"] = [scatter.multi_partial_sink(spec)]
            payload["scan"] = [spec.scan_sets[0][0],
                               spec.scan_sets[0][1]]
        else:
            psink = scatter.partial_sink(spec)
            payload["sinks"] = [psink]
            if spec.kind == "fold_state":
                payload["scan"] = [spec.scan_sets[0][0],
                                   spec.scan_sets[0][1]]
        obs.REGISTRY.counter("shard.scatter_queries").inc()

        replies: List[Optional[dict]] = [None] * len(addrs)
        failures: List[Tuple[int, str, BaseException]] = []
        conns: Dict[int, Any] = {}  # this query's OWN connections

        def run_slot(i: int, addr: str) -> None:
            p = dict(payload)
            if spec.kind == "shuffle_join":
                p["slot"] = i
            try:
                if addr == ctl.advertise_addr:
                    replies[i] = execute_subplan(ctl, p)
                    return
                if qid is not None:
                    p[QUERY_ID_KEY] = qid
                if client_id is not None:
                    p[CLIENT_ID_KEY] = client_id
                sc = self.fresh_client(addr)
                conns[i] = sc
                try:
                    replies[i] = sc._request(MsgType.SUBPLAN, p,
                                             CODEC_PICKLE)
                finally:
                    sc.close()
            except BaseException as e:  # noqa: BLE001 — typed below
                failures.append((i, addr, e))

        threads = []
        local = None
        for i, addr in enumerate(addrs):
            if addr == ctl.advertise_addr:
                local = (i, addr)
                continue
            t = threading.Thread(target=run_slot, args=(i, addr),
                                 daemon=True,
                                 name=f"netsdb-scatter-{i}")
            t.start()
            threads.append((i, addr, t))
        if local is not None:
            run_slot(*local)
        deadline = deadline_after(ctl.mirror_ack_timeout_s or 300.0)
        for i, addr, t in threads:
            t.join(max(0.0, seconds_left(deadline)))
            if t.is_alive():
                failures.append((i, addr, TimeoutError(
                    f"no subplan reply within the "
                    f"{ctl.mirror_ack_timeout_s}s budget")))
                # force-close THIS query's own connection — unblocks
                # the parked thread without touching any concurrent
                # query's traffic to the same shard
                sc = conns.get(i)
                if sc is not None:
                    sc._force_close()
        if failures:
            self._raise_scatter_failure(spec, entries, failures)
        return self._merge(spec, entries, addrs, replies, materialize,
                           explain, job_name)

    def _raise_scatter_failure(self, spec, entries, failures) -> None:
        """ALL partials are discarded; unreachable shards evict
        (epoch bump — in-flight stale routes now reject typed)."""
        from netsdb_tpu.serve.errors import (
            PlacementStaleError,
            RemoteError,
            ShardUnavailableError,
        )

        parts = []
        fatal: Optional[BaseException] = None
        stale = 0
        for i, addr, e in failures:
            parts.append(f"slot {i} ({addr}): {type(e).__name__}: {e}")
            if isinstance(e, PlacementStaleError):
                stale += 1  # membership moved; the shard is healthy
            elif isinstance(e, ShardUnavailableError):
                # an ANSWERED capacity refusal (e.g. a peer's shuffle
                # inbox over budget) — the refusing daemon is alive
                # and so is this one; evicting the SENDER for the
                # receiver's backpressure would churn pool membership
                # on transient load. Surface retryable, evict nobody.
                pass
            elif isinstance(e, RemoteError) and not e.retryable:
                # the shard ANSWERED with a deterministic refusal —
                # the query is wrong, not the pool; don't evict
                fatal = fatal or e
            else:
                # transport loss / timeout / retryable fault: the
                # shard is unreachable or unhealthy — evict it so the
                # map (and every in-flight stale route) moves on
                self.degrade(addr, f"subplan failed: "
                                   f"{type(e).__name__}: {e}")
        if fatal is not None:
            raise fatal
        if stale == len(failures):
            raise PlacementStale(
                "scatter-gather raced a placement change; partials "
                "discarded — retry re-routes against the current map: "
                + "; ".join(parts))
        raise ShardUnavailable(
            "scatter-gather failed; partials discarded (never merged): "
            + "; ".join(parts))

    def _merge(self, spec, entries, addrs, replies, materialize,
               explain, job_name="scatter"):
        from netsdb_tpu.plan import scatter
        from netsdb_tpu.storage.store import SetIdentifier

        obs.REGISTRY.counter("shard.partials_merged").inc(len(replies))
        shard_ops = None
        if explain:
            shard_ops = {
                addrs[i]: _annotate_shard(r["operators"], addrs[i])
                for i, r in enumerate(replies)
                if r and r.get("operators") is not None}
        if spec.kind in ("fold_state", "multi_fold"):
            states = [r["state"] for r in replies]
            dicts: Dict[str, list] = {}
            rows = 0
            for r in replies:
                for k, v in (r.get("dicts") or {}).items():
                    if k in dicts and list(dicts[k]) != list(v):
                        # per-shard group codes were accumulated under
                        # divergent vocabularies — a merged finalize
                        # would decode them wrong; refuse loudly
                        raise ValueError(
                            f"scatter merge: shard dictionaries for "
                            f"column {k!r} diverge; re-ingest the set "
                            f"with aligned dictionaries")
                    dicts.setdefault(k, v)
                rows += int(r.get("rows") or 0)
            if spec.kind == "multi_fold":
                fold = scatter.MultiFoldMerge(spec.components)
                label = "multi::" + "+".join(
                    (getattr(c.node, "label", "") or c.node.op_kind)
                    for c in spec.components)
                traceable = all(getattr(c.node, "traceable", True)
                                for c in spec.components)
            else:
                fold = spec.fold
                label = getattr(spec.node, "label", "") \
                    or spec.node.op_kind
                traceable = bool(getattr(spec.node, "traceable", True))
            cfg = getattr(self.ctl, "config", None)
            if getattr(cfg, "plan_fusion", True) and \
                    getattr(cfg, "fusion_mapper", "optimal") \
                    == "optimal":
                # the coordinator's merge + finalize as ONE compiled
                # program — plan_fusion=off and greedy keep the eager
                # per-shard merge byte-for-byte (the rollback arms)
                value = scatter.merge_fold_states_compiled(
                    fold, states, dicts, rows, job_name, label,
                    traceable=traceable)
            else:
                value = scatter.merge_fold_states(fold, states, dicts,
                                                  rows)
        elif spec.kind == "group_partial":
            value = scatter.merge_group_dicts(
                spec.node, [r["groups"] for r in replies])
        elif spec.kind == "tensor_chain":
            value = scatter.merge_tensor_chain(
                spec.gather, [r["tensor"] for r in replies])
        else:
            tables = [r["table"] for r in replies
                      if r.get("table") is not None]
            if not tables:
                raise ValueError(
                    "distributed shuffle produced no partials (both "
                    "join sides empty on every shard)")
            value = scatter.merge_join_outputs(spec.fold, tables)
        if spec.kind == "multi_fold":
            # split the merged tuple back into per-sink results —
            # sink order, exactly as running the components separately
            results: Dict[Any, Any] = {}
            for c, v in zip(spec.components, value):
                ident = SetIdentifier(c.sink.db, c.sink.set_name)
                if materialize:
                    materialize_result(self.ctl.library.store, ident, v)
                results[ident] = v
            return results, shard_ops
        ident = SetIdentifier(spec.sink.db, spec.sink.set_name)
        if materialize:
            materialize_result(self.ctl.library.store, ident, value)
        return {ident: value}, shard_ops
