"""Leader-owned placement map for the sharded worker pool.

netsDB's real topology is master/worker *partitioned* storage: the
master plans TCAP into JobStages that are scheduled across workers
over 64 MB pages (``QuerySchedulerServer.cc:216-330``), so adding a
node buys capacity, not a copy. The serve layer's mirror pool (every
follower holds a full replica) keeps that role for redundancy; THIS
module is the capacity half: a set created with ``placement="hash"``
(or ``"range"``) partitions its pages across a pool of daemons, and
the leader owns the authoritative, **versioned** map of which daemon
holds which shard slot.

The map is:

* shipped to clients inside the v3 handshake (the HELLO reply gains a
  ``placement`` section — only when sharded sets exist, so the
  un-sharded handshake stays byte-identical) and re-fetched over the
  ``PLACEMENT`` frame;
* **epoch-versioned** per set: every membership change (a shard
  evicted into handoff, a readmit) bumps the set's epoch. Routed
  frames carry the sender's epoch (``protocol.PLACEMENT_EPOCH_KEY``)
  and a receiver whose registration disagrees rejects with the typed
  retryable ``PlacementStale`` — the stale-map retry loop. An epoch
  mismatch can therefore never partially apply an ingest or merge
  partials computed against two different memberships;
* slot-stable: eviction flips a slot's state to ``handoff`` (ingest
  for it buffers at the leader; scatter-gather refuses typed) instead
  of re-assigning its hash space — a readmitted shard gets exactly
  its own buffered pages back, never a rebalance.

Routing is deterministic and shared by client and server:
``range`` mode splits each batch into contiguous row ranges
(even spread, zero hashing cost — the default); ``hash`` mode routes
rows by a splitmix64-mixed key column so equal keys co-locate
(ingest-time co-partitioning for key-local work).
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from netsdb_tpu.utils.locks import TrackedLock

_MASK64 = np.uint64(0xFFFFFFFFFFFFFFFF)

#: slot states: ``live`` (the shard daemon owns the slot) and
#: ``handoff`` (degraded — the leader buffers the slot's ingest and
#: drains it on readmit; queries refuse typed while any slot is here)
LIVE = "live"
HANDOFF = "handoff"


def mix64_array(values: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer over an integer column — the
    same full-avalanche mix the wire checksum and the grace-hash
    partitioner use, so ingest-time hash placement and the distributed
    shuffle agree on what "hash of key" means."""
    with np.errstate(over="ignore"):
        v = values.astype(np.uint64)
        v ^= v >> np.uint64(33)
        v *= np.uint64(0xFF51AFD7ED558CCD)
        v ^= v >> np.uint64(29)
        v *= np.uint64(0xC4CEB9FE1A85EC53)
        v ^= v >> np.uint64(32)
    return v


def hash_slot_ids(key_col: np.ndarray, nslots: int) -> np.ndarray:
    """Row → owning slot for hash placement (int key columns)."""
    return (mix64_array(np.asarray(key_col)) % np.uint64(nslots)).astype(
        np.int64)


def item_slot(item: Any, nslots: int) -> int:
    """Stable slot for one opaque object row (hash mode over object
    sets): digest of the pickled item — content-stable across
    processes, unlike ``hash()``."""
    import pickle

    blob = pickle.dumps(item, protocol=pickle.HIGHEST_PROTOCOL)
    return int.from_bytes(hashlib.blake2s(blob, digest_size=8).digest(),
                          "little") % nslots


def range_slices(nrows: int, nslots: int) -> List[Tuple[int, int]]:
    """Contiguous even split of one batch's rows across slots (range
    mode). Deterministic: slot i gets rows [i*n/k, (i+1)*n/k)."""
    out = []
    for i in range(nslots):
        start = (nrows * i) // nslots
        stop = (nrows * (i + 1)) // nslots
        out.append((start, stop))
    return out


def split_table(table, entry: Dict[str, Any]):
    """One ColumnTable batch → per-slot row-slice tables (numpy views
    in range mode — zero copies; one fancy-index gather per slot in
    hash mode). Returns ``[(slot_index, sub_table)]`` with empty slots
    omitted. Shared by the routing client and the leader's handoff
    drain so the two can never partition differently."""
    from netsdb_tpu.relational.table import ColumnTable

    nslots = len(entry["slots"])
    if table.valid is not None:
        table = table.compact()
    cols = {k: np.asarray(v) for k, v in table.cols.items()}
    nrows = table.num_rows
    out = []
    if entry.get("mode") == "hash" and entry.get("key") \
            and entry["key"] not in cols:
        # silently range-splitting here would break the set's key
        # co-location contract batch by batch — refuse loudly
        raise ValueError(
            f"hash-placed set declares key {entry['key']!r} but this "
            f"batch carries columns {sorted(cols)}")
    if entry.get("mode") == "hash" and entry.get("key") in cols:
        slot_ids = hash_slot_ids(cols[entry["key"]], nslots)
        for i in range(nslots):
            idx = np.nonzero(slot_ids == i)[0]
            if idx.size:
                out.append((i, ColumnTable(
                    {k: v[idx] for k, v in cols.items()},
                    dict(table.dicts), None)))
        return out
    for i, (start, stop) in enumerate(range_slices(nrows, nslots)):
        if stop > start:
            out.append((i, ColumnTable(
                {k: v[start:stop] for k, v in cols.items()},
                dict(table.dicts), None)))
    return out


def split_items(items: list, entry: Dict[str, Any]):
    """One object-row batch → per-slot sublists (same contract as
    :func:`split_table`)."""
    nslots = len(entry["slots"])
    buckets: List[list] = [[] for _ in range(nslots)]
    if entry.get("mode") == "hash":
        key = entry.get("key")
        if key and items and all(isinstance(it, dict) and key in it
                                 for it in items):
            # one vectorized hash over the whole batch (the per-item
            # pipeline below costs an array construction + five u64
            # ops PER ROW — ruinous on the routed-ingest hot path)
            slot_ids = hash_slot_ids(
                np.asarray([it[key] for it in items]), nslots)
            for item, slot in zip(items, slot_ids):
                buckets[int(slot)].append(item)
            return [(i, b) for i, b in enumerate(buckets) if b]
        for item in items:
            if key and isinstance(item, dict) and key in item:
                slot = int(hash_slot_ids(
                    np.asarray([item[key]]), nslots)[0])
            else:
                slot = item_slot(item, nslots)
            buckets[slot].append(item)
    else:
        for i, (start, stop) in enumerate(range_slices(len(items),
                                                       nslots)):
            buckets[i] = items[start:stop]
    return [(i, b) for i, b in enumerate(buckets) if b]


class PlacementMap:
    """The leader's authoritative set → shard-slot table. All methods
    are thread-safe; readers get deep-enough copies (slot dicts are
    rebuilt) so no caller ever mutates shared state."""

    def __init__(self):
        self._mu = TrackedLock("serve.PlacementMap._mu")
        self._entries: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._epoch = 0

    # --- registration -------------------------------------------------
    def create(self, db: str, set_name: str, addrs: List[str],
               mode: str = "range",
               key: Optional[str] = None) -> Dict[str, Any]:
        if mode not in ("hash", "range"):
            raise ValueError(f"placement mode must be 'hash' or "
                             f"'range', got {mode!r}")
        with self._mu:
            self._epoch += 1
            entry = {"mode": mode, "key": key, "epoch": self._epoch,
                     "slots": [{"addr": a, "state": LIVE}
                               for a in addrs]}
            self._entries[(db, set_name)] = entry
            return self._copy(entry)

    def remove(self, db: str, set_name: str) -> None:
        with self._mu:
            self._entries.pop((db, set_name), None)

    # --- reads --------------------------------------------------------
    @staticmethod
    def _copy(entry: Dict[str, Any]) -> Dict[str, Any]:
        return {"mode": entry["mode"], "key": entry["key"],
                "epoch": entry["epoch"],
                "slots": [dict(s) for s in entry["slots"]]}

    def entry(self, db: str, set_name: str) -> Optional[Dict[str, Any]]:
        with self._mu:
            e = self._entries.get((db, set_name))
            return self._copy(e) if e is not None else None

    def sets(self) -> List[Tuple[str, str]]:
        with self._mu:
            return sorted(self._entries)

    def __len__(self) -> int:
        with self._mu:
            return len(self._entries)

    def sets_for_addr(self, addr: str) -> List[Tuple[str, str]]:
        """Every (db, set) with a slot on ``addr`` — the readmit
        drain's work list."""
        with self._mu:
            return sorted(k for k, e in self._entries.items()
                          if any(s["addr"] == addr for s in e["slots"]))

    # --- membership changes (each bumps affected epochs) --------------
    def _flip(self, addr: str, state: str) -> List[Tuple[str, str]]:
        changed = []
        with self._mu:
            for ident, e in self._entries.items():
                hit = False
                for s in e["slots"]:
                    if s["addr"] == addr and s["state"] != state:
                        s["state"] = state
                        hit = True
                if hit:
                    self._epoch += 1
                    e["epoch"] = self._epoch
                    changed.append(ident)
        return changed

    def degrade_addr(self, addr: str) -> List[Tuple[str, str]]:
        """Evict one shard daemon: its slots flip to handoff, every
        affected set's epoch bumps (in-flight frames routed under the
        old epoch now reject typed)."""
        return self._flip(addr, HANDOFF)

    def readmit_addr(self, addr: str) -> List[Tuple[str, str]]:
        """Readmit one shard daemon after its handoff drained."""
        return self._flip(addr, LIVE)

    def rebind_addr(self, old: str, new: str) -> List[Tuple[str, str]]:
        """Rewrite every slot owned by ``old`` to ``new`` (state LIVE)
        and bump the affected sets' epochs — the promotion step: the
        new leader inherited the old leader's slot DATA through the
        mirror stream, so it takes over the slot identity too. The
        epoch bump is what keeps re-pointing cheap and safe: a client
        still routing under the old map gets exactly one typed
        ``PlacementStale``, refreshes, and re-routes — no discovery
        scan, no partial application."""
        changed = []
        with self._mu:
            for ident, e in self._entries.items():
                hit = False
                for s in e["slots"]:
                    if s["addr"] == old:
                        s["addr"] = new
                        s["state"] = LIVE
                        hit = True
                if hit:
                    self._epoch += 1
                    e["epoch"] = self._epoch
                    changed.append(ident)
        return changed

    def move_slot(self, db: str, set_name: str, slot: int,
                  new_addr: str) -> Optional[Dict[str, Any]]:
        """Re-own ONE shard slot — the rebalance commit point. The
        slot's addr is rewritten to ``new_addr`` (state LIVE) and the
        set's epoch bumps, so every frame routed under the old epoch —
        including ingest still aimed at the sealed source — rejects
        with the typed retryable ``PlacementStale`` and re-routes to
        the new owner. Slot COUNT never changes (slot-stable routing:
        ``% nslots`` hash spaces are untouched); only ownership moves.
        Returns the updated entry copy, or ``None`` if the set or the
        slot index does not exist (the move aborts typed upstream)."""
        with self._mu:
            e = self._entries.get((db, set_name))
            if e is None or not (0 <= slot < len(e["slots"])):
                return None
            e["slots"][slot]["addr"] = new_addr
            e["slots"][slot]["state"] = LIVE
            self._epoch += 1
            e["epoch"] = self._epoch
            return self._copy(e)

    # --- wire form ----------------------------------------------------
    def to_wire(self) -> Dict[str, Any]:
        with self._mu:
            return {"epoch": self._epoch,
                    "sets": {f"{db}:{s}": self._copy(e)
                             for (db, s), e in self._entries.items()}}

    def restore(self, wire: Dict[str, Any]) -> int:
        """Install a map previously captured by :meth:`to_wire` —
        the replicated-map half of failover (a freshly promoted
        leader) and of a durable leader restart. Epochs are preserved
        EXACTLY: per-set epochs and the global counter resume where
        the map left off, so routed frames from clients holding the
        old leader's map validate against the same numbers (the
        promotion's ``rebind_addr`` then bumps only the sets whose
        slots actually moved). Returns the restored set count."""
        sets = (wire or {}).get("sets") or {}
        with self._mu:
            self._entries = {}
            for key, entry in sets.items():
                db, _, set_name = key.partition(":")
                self._entries[(db, set_name)] = {
                    "mode": entry["mode"], "key": entry.get("key"),
                    "epoch": int(entry["epoch"]),
                    "slots": [dict(s) for s in entry["slots"]]}
            self._epoch = max(
                [int((wire or {}).get("epoch") or 0)]
                + [e["epoch"] for e in self._entries.values()])
            return len(self._entries)

    @staticmethod
    def entry_from_wire(wire: Dict[str, Any], db: str,
                        set_name: str) -> Optional[Dict[str, Any]]:
        """Client-side read of one set's entry out of a shipped map."""
        if not wire:
            return None
        return (wire.get("sets") or {}).get(f"{db}:{set_name}")
